"""Device kernels: spec -> jitted query function.

The TPU execution of the reference's per-segment operator chain
(``Filter -> DocIdSet -> Projection -> Transform -> Aggregate``, SURVEY.md
section 3.1 hot loop): instead of streaming 10k-doc blocks through iterators,
the whole segment is evaluated as fixed-shape masked vector ops that XLA
fuses into a few HBM passes:

- filter tree  -> boolean doc mask (vector compares / LUT gathers)
- projection   -> dictId gathers (``dictvals[fwd]``)
- aggregation  -> masked reductions; group-by via composed keys +
                  ``jax.ops.segment_sum`` scatter-adds (the fixed-shape
                  analogue of DictionaryBasedGroupKeyGenerator + GroupByResultHolder)

One kernel is built per *spec* (query structure + static sizes) and cached;
literal values arrive as device arrays so repeated query shapes skip
retracing entirely.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

POS_INF = float("inf")
NEG_INF = float("-inf")

# accumulator dtypes, chosen per aggregation at plan time from column stats
# (plan._acc_dtype): capacity-sized math runs narrow (v5e has no native
# f64/i64 units), partials widen to i64/f64 at kernel output so cross-segment
# merging is exact
_ACC = {"i32": jnp.int32, "i64": jnp.int64,
        "f32": jnp.float32, "f64": jnp.float64}

# None = backend-keyed (batched on TPU, split on CPU); tests override to
# exercise the batched branch on the CPU oracle
FORCE_BATCH_SCATTERS = None


def _acc_info(acc: str):
    """(dtype, widened dtype, min-neutral, max-neutral) for an acc tag."""
    dt = _ACC[acc]
    if acc in ("i32", "i64"):
        info = jnp.iinfo(dt)
        return dt, jnp.int64, info.max, info.min
    return dt, jnp.float64, POS_INF, NEG_INF


class _ParamCursor:
    """Walks the flat params tuple in the same order the planner wrote it."""

    def __init__(self, params):
        self.params = params
        self.i = 0

    def take(self):
        p = self.params[self.i]
        self.i += 1
        return p

    def finish(self):
        """Assert full consumption at kernel-build end — the runtime
        mirror of the lint protocol family, catching dynamically-built
        specs the static model can't prove. Trace-time only (``i`` is a
        plain int), so the check costs nothing per launch."""
        if self.i != len(self.params):
            raise AssertionError(
                f"param cursor finished at {self.i} of "
                f"{len(self.params)} params — pack/unpack drift between "
                f"plan.py and the kernel consumers")


# --------------------------------------------------------------------------
# filter mask emission
# --------------------------------------------------------------------------

def _emit_filter(spec: Tuple, cols: Dict[str, Dict[str, jnp.ndarray]],
                 pc: _ParamCursor, capacity: int) -> jnp.ndarray:
    op = spec[0]
    if op == "true":
        return jnp.ones(capacity, dtype=bool)
    if op == "false":
        return jnp.zeros(capacity, dtype=bool)
    if op == "validdocs":
        # upsert valid-doc snapshot [capacity] (plan.py injects the param)
        return pc.take()
    if op == "and":
        m = _emit_filter(spec[1][0], cols, pc, capacity)
        for s in spec[1][1:]:
            m = m & _emit_filter(s, cols, pc, capacity)
        return m
    if op == "or":
        m = _emit_filter(spec[1][0], cols, pc, capacity)
        for s in spec[1][1:]:
            m = m | _emit_filter(s, cols, pc, capacity)
        return m
    if op == "not":
        return ~_emit_filter(spec[1][0], cols, pc, capacity)

    col = spec[1]
    c = cols[col]

    # ---- dictionary SV strategies ----
    if op == "eq":
        return c["fwd"] == pc.take()
    if op == "neq":
        return c["fwd"] != pc.take()
    if op == "range":
        iv = pc.take()
        return (c["fwd"] >= iv[0]) & (c["fwd"] <= iv[1])
    if op == "lut":
        return pc.take()[c["fwd"]]

    # ---- dictionary MV strategies (ANY-value-matches semantics) ----
    if op.startswith("mv_"):
        mv, cnt = c["mv"], c["mvcount"]
        entry_valid = (jnp.arange(mv.shape[1], dtype=jnp.int32)[None, :]
                       < cnt[:, None])
        sub = op[3:]
        if sub == "eq":
            hit = mv == pc.take()
        elif sub == "neq":
            hit = mv != pc.take()
        elif sub == "range":
            iv = pc.take()
            hit = (mv >= iv[0]) & (mv <= iv[1])
        else:  # lut
            hit = pc.take()[mv]
        return (hit & entry_valid).any(axis=-1)

    # ---- raw-value strategies ----
    if op == "veq":
        return c["fwd"] == pc.take()
    if op == "vneq":
        return c["fwd"] != pc.take()
    if op == "vrange":
        lo, hi = pc.take(), pc.take()
        lo_inc, hi_inc = spec[2], spec[3]
        m = (c["fwd"] >= lo) if lo_inc else (c["fwd"] > lo)
        m &= (c["fwd"] <= hi) if hi_inc else (c["fwd"] < hi)
        return m
    if op in ("vin", "vnotin"):
        vals = pc.take()
        m = (c["fwd"][:, None] == vals[None, :]).any(axis=-1)
        return ~m if op == "vnotin" else m

    # ---- null strategies ----
    if op == "isnull":
        return c["null"]
    if op == "isnotnull":
        return ~c["null"]

    raise AssertionError(f"unknown filter op {op!r}")


# --------------------------------------------------------------------------
# value expression emission
# --------------------------------------------------------------------------

def _emit_value(vspec: Tuple, cols, pc: _ParamCursor,
                compute_dt=jnp.float32) -> jnp.ndarray:
    op = vspec[0]
    if op == "lit":
        return pc.take()
    if op == "col":
        _, name, has_dict = vspec
        c = cols[name]
        if has_dict:
            return c["dictvals"][c["fwd"]]
        return c["fwd"]
    if op == "fn":
        _, name, args = vspec
        vals = [_emit_value(a, cols, pc, compute_dt) for a in args]
        a = vals[0].astype(compute_dt) if hasattr(vals[0], "astype") else vals[0]
        b = vals[1].astype(compute_dt) if hasattr(vals[1], "astype") else vals[1]
        if name == "plus":
            return a + b
        if name == "minus":
            return a - b
        if name == "times":
            return a * b
        if name == "divide":
            return a / b
        if name == "mod":
            return a % b
        if name == "floordiv":
            return jnp.floor_divide(a, b)
    raise AssertionError(f"unknown value op {vspec!r}")


# --------------------------------------------------------------------------
# kernel factory
# --------------------------------------------------------------------------

def build_kernel_body(spec: Tuple, capacity_override: int = 0,
                      sparse_k: int = 0, sparse_rung: str = "cond"):
    """spec = (filter_spec, agg_specs, group_specs, num_groups, capacity)
    -> unjitted fn(cols, params, num_docs, doc_offset) -> dict of partials.

    ``doc_offset`` is the global doc index of local row 0 — nonzero when the
    doc dimension is sharded over a mesh axis (the sharded combine path
    evaluates each device's sub-range of the scan; ref: the doc-dimension
    "context parallelism" mapping, SURVEY.md §5). ``capacity_override``
    replaces the spec's capacity with the per-shard local capacity.
    ``sparse_k`` > 0 switches the group-by path to sparse grouping over K
    compact slots; ``sparse_rung`` picks how:

    - "cond" (per-segment default): hash-aggregate, with an in-kernel
      ``lax.cond`` falling back to the sort rung when the table overflows;
    - "hash": hash rung only — the ``"rung"`` output flags overflow and the
      caller must discard the (garbage) leaves and rerun the sort body.
      The sharded combine needs this split because a cond UNDER vmap
      lowers to select (both branches always execute, paying the sort);
    - "sort": the sort/compaction rung only.
    """
    filter_spec, agg_specs, group_specs, num_groups, capacity = spec
    if capacity_override:
        capacity = capacity_override

    def kernel(cols, params, num_docs, doc_offset):
        pc = _ParamCursor(params)
        mask = _emit_filter(filter_spec, cols, pc, capacity)
        valid = (jnp.arange(capacity, dtype=jnp.int32) + doc_offset) < num_docs
        mask = mask & valid

        if not group_specs:
            out: Dict[str, Any] = {
                "num_matched": mask.sum(dtype=jnp.int32).astype(jnp.int64)}
            for i, aspec in enumerate(agg_specs):
                out[f"agg{i}"] = _emit_scalar_agg(aspec, cols, pc, mask)
            pc.finish()
            return out

        # ---- group-by path ----
        strides = pc.take()           # [g] int32
        _bases = pc.take()            # [g] int64 (host uses for decode; keys
        #                               subtract base on device — nonzero for
        #                               graw/gexpr and for filter-narrowed
        #                               gdict columns, see plan.py)
        keys = jnp.zeros(capacity, dtype=jnp.int32)
        for gi, (strat, payload) in enumerate(group_specs):
            if strat == "gdict":
                k = cols[payload]["fwd"] - _bases[gi].astype(jnp.int32)
            elif strat == "graw":  # value-space key
                k = (cols[payload]["fwd"] - _bases[gi]).astype(jnp.int32)
            else:  # gexpr: bounded integral expression, key = value - lo
                v = _emit_value(payload, cols, pc, jnp.int64)
                k = (v - _bases[gi]).astype(jnp.int32)
            keys = keys + k * strides[gi]
        if sparse_k:
            return _emit_grouped_rung(agg_specs, cols, pc, mask, keys,
                                      num_groups, sparse_k, capacity,
                                      sparse_rung)
        seg_ids = jnp.where(mask, keys, num_groups)  # overflow bucket
        out = _emit_grouped_all(agg_specs, cols, pc, mask, seg_ids,
                                num_groups)
        pc.finish()
        return out

    return kernel


def compact_from_sorted(sk: jnp.ndarray, K: int):
    """Shared compaction core for BOTH sparse-grouping paths (the
    per-segment kernel here and the cross-device merge in
    parallel/combine.py): ``sk`` = ascending keys with _SENTINEL_KEY fill.
    Returns (first, n_live, uniq): first-occurrence flags over sk, the live
    distinct-key count, and the first K live keys (SENT-filled past
    n_live)."""
    SENT = jnp.int32(_SENTINEL_KEY)
    valid = sk != SENT
    first = valid & jnp.concatenate(
        [jnp.ones((1,), dtype=bool), sk[1:] != sk[:-1]])
    n_live = first.sum(dtype=jnp.int32)
    pos = jnp.nonzero(first, size=K, fill_value=sk.shape[0] - 1)[0]
    live = jnp.arange(K, dtype=jnp.int32) < jnp.minimum(n_live, K)
    uniq = jnp.where(live, sk[pos], SENT)
    return first, n_live, uniq


def _emit_grouped_sparse(agg_specs, cols, pc, mask, keys, num_groups, K):
    """Sort/compaction-based grouping for LARGE composed key spaces — the
    device rung of the reference's cardinality ladder past dense array
    holders (DictionaryBasedGroupKeyGenerator.java:62): sort the masked
    keys, compact the live groups into K slots, scatter aggregates over
    [K+1] instead of [num_groups+1]. The output is ALREADY compact
    ("ck" = sorted live composed keys, "compact_n" = live count); more
    than K live groups reports compact_n > K so the decode falls back to
    the host path instead of truncating."""
    SENT = jnp.int32(_SENTINEL_KEY)
    mk = jnp.where(mask, keys, SENT)
    sk = jnp.sort(mk)
    first, n_live, uniq = compact_from_sorted(sk, K)
    live = uniq != SENT
    # doc -> slot rank via a dense key-space LUT: ONE gather per doc (a
    # searchsorted would cost log2(K) gather passes on TPU). Fill slots
    # park at the LUT's overflow cell.
    lut = jnp.full((num_groups + 1,), jnp.int32(K))
    park = jnp.where(live, uniq, num_groups)
    lut = lut.at[park].set(
        jnp.where(live, jnp.arange(K, dtype=jnp.int32), K))
    rank = lut[jnp.clip(keys, 0, num_groups - 1)]
    seg_ids = jnp.where(mask, rank, K)
    out = _emit_grouped_all(agg_specs, cols, pc, mask, seg_ids, K)
    out["ck"] = uniq
    out["compact_n"] = n_live
    return out


# --------------------------------------------------------------------------
# hash-aggregation rung: the device ladder step BETWEEN the dense
# segment_sum rung and the sort-based sparse rung. Selective queries whose
# composed key space is huge but whose LIVE rows are few (SSB Q3.2/Q3.3
# shape: a few thousand matches against a 2^19 key space) pay the sort rung
# an n*log(n) over ALL docs; here the live docs are compacted to a fixed
# window and their keys scatter-minned into an open-addressing table, so
# cost scales with live rows. Overflow (too many live docs, probe failure,
# or more live groups than K) falls back to the sort rung — in-kernel via
# lax.cond on the per-segment path, at the device level on the sharded
# path (see build_kernel_body's sparse_rung).
# --------------------------------------------------------------------------

# open-addressing table: 2^15 slots, 4x the compact output K so the load
# factor for K-bounded group sets stays low enough that the bounded probe
# chain below almost never overflows
_HASH_BITS = 15
HASH_TABLE_SLOTS = 1 << _HASH_BITS
# linear-probe passes unrolled at trace time; each pass is one scatter-min
# + one gather over the live window
HASH_PROBES = 4
# live-doc window: more matched docs than this -> sort rung
HASH_LIVE_DOCS = 1 << 16
# Knuth multiplicative hash (2^32 / phi)
_HASH_MULT = 2654435761

# per-column arrays with a leading capacity dim (gathered down to the live
# window); everything else (dictvals) is shared
_CAPACITY_KEYS = ("fwd", "null", "mv", "mvcount")


def _compact_positions(mask: jnp.ndarray, L: int):
    """(pos, n) — ascending doc positions of the first L masked docs (the
    ascending order keeps per-group accumulation in doc order, so hash-rung
    sums are bit-exact with the sort rung's) and the total masked count.
    cumsum-scatter, not jnp.nonzero: this must stay cheap under vmap."""
    capacity = mask.shape[0]
    r = jnp.cumsum(mask.astype(jnp.int32)) - 1
    n = jnp.where(capacity > 0, r[-1] + 1, 0)
    tgt = jnp.where(mask & (r < L), r, L)
    pos = jnp.zeros(L + 1, dtype=jnp.int32).at[tgt].set(
        jnp.arange(capacity, dtype=jnp.int32), mode="drop")[:L]
    return pos, n


def _hash_probe(mask, keys, K, capacity):
    """Place masked composed keys into the open-addressing table.

    Returns (overflow, pos, mask_live, seg_ids, ck, n_live): ``pos`` indexes
    the live-doc window, ``seg_ids`` [L] maps each live doc to its compact
    group slot (K = parked), ``ck`` the K live keys in slot order
    (SENT-filled), ``n_live`` the live group count. ``overflow`` means the
    hash results are unusable and the sort rung must serve."""
    SENT = jnp.int32(_SENTINEL_KEY)
    H = HASH_TABLE_SLOTS
    L = min(capacity, HASH_LIVE_DOCS)

    pos, n_docs = _compact_positions(mask, L)
    mask_live = jnp.arange(L, dtype=jnp.int32) < jnp.minimum(n_docs, L)
    mk = jnp.where(mask_live, keys[pos], SENT)

    h = ((mk.astype(jnp.uint32) * jnp.uint32(_HASH_MULT))
         >> jnp.uint32(32 - _HASH_BITS)).astype(jnp.int32)
    slot = jnp.where(mask_live, h, H)      # fill docs park at slot H
    placed = ~mask_live
    table = jnp.full(H + 1, SENT, dtype=jnp.int32)
    for p in range(HASH_PROBES):
        if p:
            slot = jnp.where(placed, slot, (slot + 1) & (H - 1))
        put = jnp.where(placed, H, slot)
        # scatter-min claims the slot for the smallest competing key; docs
        # whose key won (or was already there) are placed, the rest probe on
        table = table.at[put].min(jnp.where(placed, SENT, mk))
        placed = placed | (table[put] == mk)
    # a later pass can STEAL a claimed slot (scatter-min lowers it with a
    # smaller key while the earlier claimant has already stopped probing) —
    # re-validate every claim against the final table; stolen claims count
    # as overflow so the sort rung serves instead of merging two groups
    placed = placed & (table[jnp.where(mask_live, slot, H)] == mk)

    live_tab = table[:H] != SENT
    n_live = live_tab.sum(dtype=jnp.int32)
    overflow = ((n_docs > L) | (mask_live & ~placed).any() | (n_live > K))

    # slot -> compact rank (cumsum, no scatter); park slot H -> K
    rk = jnp.cumsum(live_tab.astype(jnp.int32)) - 1
    rank = jnp.where(live_tab, jnp.minimum(rk, K), K)
    rank_ext = jnp.concatenate(
        [rank, jnp.full((1,), K, dtype=jnp.int32)])
    seg_ids = jnp.where(placed & mask_live, rank_ext[slot], K)

    # first K live slots -> compact keys (slot order, not sorted — the
    # decode and the cross-shard merge are both order-agnostic)
    stgt = jnp.where(live_tab & (rk < K), rk, K)
    spos = jnp.zeros(K + 1, dtype=jnp.int32).at[stgt].set(
        jnp.arange(H, dtype=jnp.int32), mode="drop")[:K]
    livek = jnp.arange(K, dtype=jnp.int32) < jnp.minimum(n_live, K)
    ck = jnp.where(livek, table[spos], SENT)
    return overflow, pos, mask_live, seg_ids, ck, n_live


def _hash_finish(agg_specs, cols, pc, probe, K):
    """Aggregate over the live-doc window: every capacity-sized column is
    gathered down to [L] first, so the scatter work scales with live rows."""
    _, pos, mask_live, seg_ids, ck, n_live = probe
    cols_live = {name: {k: (v[pos] if k in _CAPACITY_KEYS else v)
                        for k, v in tree.items()}
                 for name, tree in cols.items()}
    out = _emit_grouped_all(agg_specs, cols_live, pc, mask_live, seg_ids, K)
    out["ck"] = ck
    out["compact_n"] = n_live
    return out


def _emit_grouped_rung(agg_specs, cols, pc, mask, keys, num_groups, K,
                       capacity, rung):
    """Sparse-grouping dispatch: hash rung with sort fallback (see
    build_kernel_body docstring for the rung modes). The ``"rung"`` output
    leaf is 0 when the hash table served, 1 when the sort rung ran (or, in
    "hash" mode, when it MUST run)."""
    if rung == "sort":
        out = _emit_grouped_sparse(agg_specs, cols, pc, mask, keys,
                                   num_groups, K)
        pc.finish()
        out["rung"] = jnp.ones((), dtype=jnp.int32)
        return out
    probe = _hash_probe(mask, keys, K, capacity)
    overflow = probe[0]
    if rung == "hash":
        out = _hash_finish(agg_specs, cols, pc, probe, K)
        pc.finish()
        out["rung"] = overflow.astype(jnp.int32)
        return out
    # "cond": both branches re-walk the agg params from the same cursor
    # position with their own cursors (one traced consumption each);
    # the OUTER cursor deliberately stays at ``start`` — each branch
    # copy asserts full consumption instead
    start = pc.i

    def _hash_branch(_):
        pc2 = _ParamCursor(pc.params)
        pc2.i = start
        out = _hash_finish(agg_specs, cols, pc2, probe, K)
        pc2.finish()
        return out

    def _sort_branch(_):
        pc2 = _ParamCursor(pc.params)
        pc2.i = start
        out = _emit_grouped_sparse(agg_specs, cols, pc2, mask, keys,
                                   num_groups, K)
        pc2.finish()
        return out

    out = jax.lax.cond(overflow, _sort_branch, _hash_branch, None)
    out["rung"] = overflow.astype(jnp.int32)
    return out


def _emit_grouped_all(agg_specs, cols, pc, mask, seg_ids, num_groups):
    """All grouped aggregations + presence through BATCHED scatters: leaves
    sharing (reduce op, accumulator dtype) stack into one [N, k] array and
    reduce with a single segment_sum/min/max — scatters are the expensive
    op on TPU, and a 6-aggregation query otherwise issues 8+ of them.
    Param-cursor order is preserved (vectors are built in agg order; only
    the scatters are deferred)."""
    n = num_groups + 1
    # (op, dtype-str) -> list of [N] vectors to reduce together
    buckets: Dict[Tuple[str, str], List] = {}

    def enqueue(op: str, vec, post):
        b = buckets.setdefault((op, str(vec.dtype)), [])
        b.append(vec)
        return (op, str(vec.dtype), len(b) - 1, post)

    # presence / COUNT(*) / AVG counts are all the SAME masked count —
    # enqueue one column and share the ref (duplicate columns in a scatter
    # are not CSE'd away)
    count_ref = enqueue("sum", mask.astype(jnp.int32),
                        lambda r: r.astype(jnp.int64))
    refs: Dict[str, Any] = {}
    refs["presence"] = count_ref

    out: Dict[str, Any] = {}
    for i, aspec in enumerate(agg_specs):
        key = f"agg{i}"
        if aspec[0] == "distinctcounthll":
            # composed (group, bucket) id space: its own scatter
            _, colname, log2m = aspec
            m = 1 << log2m
            fwd = cols[colname]["fwd"]
            bucket = pc.take()[fwd]
            rank = pc.take()[fwd]
            ids = seg_ids * m + bucket
            regs = jax.ops.segment_max(jnp.where(mask, rank, 0), ids,
                                       num_segments=n * m)
            out[key] = jnp.maximum(regs[:num_groups * m], 0)
            continue
        base, mv, vals, dt, wide, min_n, max_n = _masked_values(
            aspec, cols, pc, mask)
        zero = jnp.zeros((), dtype=dt)
        if base == "count":
            refs[key] = count_ref
            continue
        fv = vals if vals.ndim else jnp.full(mask.shape[0], vals, dtype=dt)
        if base == "sum":
            refs[key] = enqueue("sum", jnp.where(mask, fv, zero),
                                lambda r, w=wide: r.astype(w))
        elif base == "min":
            refs[key] = enqueue(
                "min", jnp.where(mask, fv, min_n),
                lambda r: r.astype(jnp.float64))
        elif base == "max":
            refs[key] = enqueue(
                "max", jnp.where(mask, fv, max_n),
                lambda r: r.astype(jnp.float64))
        elif base == "avg":
            refs[key] = [
                enqueue("sum", jnp.where(mask, fv, zero),
                        lambda r, w=wide: r.astype(w)),
                count_ref]
        elif base == "minmaxrange":
            refs[key] = [
                enqueue("min", jnp.where(mask, fv, min_n),
                        lambda r: r.astype(jnp.float64)),
                enqueue("max", jnp.where(mask, fv, max_n),
                        lambda r: r.astype(jnp.float64))]
        else:
            raise AssertionError(f"agg {base} has no device grouped kernel")

    # one scatter per (op, dtype) bucket on TPU: the scatter's minor dim
    # pads to 128 lanes either way, so k stacked leaves cost ~one leaf.
    # CPU lowers separate 1-D scatters faster — keep them split there.
    # (FORCE_BATCH_SCATTERS overrides for tests of the batched branch.)
    batch = (FORCE_BATCH_SCATTERS if FORCE_BATCH_SCATTERS is not None
             else jax.default_backend() not in ("cpu",))
    reduced: Dict[Tuple[str, str], List] = {}
    scatter = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
               "max": jax.ops.segment_max}
    for (op, dts), vecs in buckets.items():
        if batch and len(vecs) > 1:
            data = jnp.stack(vecs, axis=1)  # [N, k]
            r = scatter[op](data, seg_ids, num_segments=n)[:num_groups]
            reduced[(op, dts)] = [r[:, j] for j in range(len(vecs))]
        else:
            reduced[(op, dts)] = [
                scatter[op](v, seg_ids, num_segments=n)[:num_groups]
                for v in vecs]

    def resolve(ref):
        op, dts, idx, post = ref
        return post(reduced[(op, dts)][idx])

    for key, ref in refs.items():
        if key in out:
            continue
        # multi-leaf states (avg, minmaxrange) ride as LISTS of refs;
        # single refs are 4-tuples
        out[key] = (tuple(resolve(r) for r in ref)
                    if isinstance(ref, list) else resolve(ref))
    return out


def build_kernel(spec: Tuple):
    """Single-segment entry: jitted fn(cols, params, num_docs) -> packed
    f64 output vector (ONE device array -> one D2H fetch per query; see
    output_layout)."""
    body = build_kernel_body(spec, sparse_k=sparse_mode(spec))

    def kernel(cols, params, num_docs):
        return pack_outputs(body(cols, params, num_docs, jnp.int32(0)), spec)

    return jax.jit(kernel)


# --------------------------------------------------------------------------
# packed output: every kernel output leaf concatenated into ONE f64 vector.
#
# The serving path talks to the TPU through a high-latency tunnel where every
# host<->device transfer is a roundtrip; fetching each output leaf separately
# (presence + N agg leaves + seg stats) made decode latency-bound, not
# compute-bound (round-3 profile: a 6-agg group-by spent ~4x the kernel time
# in sequential small D2H fetches). f64 keeps counts and i32-ranged sums
# exact to 2^53; SUM finalizes as double anyway (ref: the reference
# aggregates SUM in double, AggregationFunctionType SUM -> DOUBLE).
#
# SPARSE COMPACTION: dense group-by outputs scale with the PADDED key space
# (SSB Q4.3: 2^20 slots for ~800 real groups -> megabytes over the tunnel
# per query). At >= COMPACT_MIN_GROUPS the pack switches to a compact
# layout — device-side ``nonzero(presence, size=K)`` + gathers — so D2H
# scales with actual groups (the fixed-shape analogue of the reference's
# DictionaryBasedGroupKeyGenerator cardinality ladder switching from dense
# arrays to maps). More than K live groups raises PlanError at decode and
# the executor falls back to the host path (full results, never truncation).
# --------------------------------------------------------------------------

COMPACT_MIN_GROUPS = 8192
COMPACT_K = 8192

# past this key-space size the kernel switches from dense scatter slots to
# SORT-BASED SPARSE GROUPING (_emit_grouped_sparse): the device analogue of
# the reference's cardinality ladder stepping off dense array-based group-key
# holders onto maps (DictionaryBasedGroupKeyGenerator.java:62,
# InstancePlanMakerImplV2.java:67-84 numGroupsLimit)
SPARSE_MIN_GROUPS = 1 << 15
# composed keys never reach this value (MAX_DEVICE_GROUPS < 2^31)
_SENTINEL_KEY = (1 << 31) - 1


def sparse_mode(spec: Tuple) -> int:
    """0 = dense grouping; else the compact K for sort-based sparse
    grouping. Shares compact_mode's K so the packed output layout is
    identical either way."""
    _, agg_specs, group_specs, num_groups, _ = spec
    if not group_specs or num_groups < SPARSE_MIN_GROUPS:
        return 0
    if any(a[0] in ("distinctcount", "distinctcounthll") for a in agg_specs):
        return 0
    return min(COMPACT_K, num_groups)


def compact_mode(spec: Tuple) -> int:
    """0 = dense; else the compact K for this spec. distinctcount/HLL
    leaves carry their own [cardinality]/[G*m] shapes and stay dense."""
    _, agg_specs, group_specs, num_groups, _ = spec
    if not group_specs or num_groups < COMPACT_MIN_GROUPS:
        return 0
    if any(a[0] in ("distinctcount", "distinctcounthll") for a in agg_specs):
        return 0
    return min(COMPACT_K, num_groups)

def output_layout(spec: Tuple, num_seg: int = 0) -> List[Tuple[str, int]]:
    """[(key, size)] slices of the packed vector, in pack order. Key
    ``aggI.J`` is leaf J of a multi-leaf aggregation state (avg, minmaxrange).
    ``num_seg > 0`` appends the sharded combine's per-segment matched-doc
    counts. In compact mode, grouped leaves shrink to K gathered entries
    prefixed by the live-group count and their group indices."""
    _, agg_specs, group_specs, num_groups, _ = spec
    K = compact_mode(spec)
    if K:
        num_groups = K
    reducers = partial_reduce_ops(spec)
    entries: List[Tuple[str, int]] = []
    if K:
        entries.append(("compact_n", 1))
        entries.append(("compact_idx", K))
        entries.append(("presence", K))
    elif group_specs:
        entries.append(("presence", num_groups))
    else:
        entries.append(("num_matched", 1))
    for i, aspec in enumerate(agg_specs):
        if aspec[0] == "distinctcount":
            entries.append((f"agg{i}", aspec[2]))  # [cardinality] presence
            continue
        if aspec[0] == "distinctcounthll":
            m = 1 << aspec[2]
            entries.append((f"agg{i}", (num_groups or 1) * m))
            continue
        nleaves = len(reducers[f"agg{i}"])
        size = num_groups if group_specs else 1
        if nleaves == 1:
            entries.append((f"agg{i}", size))
        else:
            entries.extend((f"agg{i}.{j}", size) for j in range(nleaves))
    if sparse_mode(spec):
        # which sparse rung actually served (0 = hash table, 1 = sort
        # fallback): bench/stats surface this per query
        entries.append(("rung", 1))
    if num_seg:
        entries.append(("seg_matched", num_seg))
    return entries


def pack_outputs(out: Dict[str, Any], spec: Tuple) -> jnp.ndarray:
    """Flatten the kernel output tree into one f64 vector (device side).
    Sparse-grouped trees (``"ck"`` present) arrive ALREADY compact — their
    unique composed keys go out as compact_idx directly (a composed key IS
    the dense group index, so the decode is identical); dense trees past
    the compact threshold get gathered down to their live slots here."""
    num_seg = out["seg_matched"].shape[0] if "seg_matched" in out else 0
    K = compact_mode(spec)
    idx = None
    gat = None
    if K:
        if "ck" in out:
            n = out["compact_n"]
            idx = out["ck"]
        else:
            presence = out["presence"]
            # fill 0 is safe: positions >= n are ignored by the decode
            gat = jnp.nonzero(presence > 0, size=K, fill_value=0)[0]
            idx = gat
            n = (presence > 0).sum(dtype=jnp.int32)
    parts = []
    for key, _ in output_layout(spec, num_seg):
        if key == "compact_n":
            leaf = n
        elif key == "compact_idx":
            leaf = idx
        elif "." in key:
            k, j = key.split(".")
            leaf = out[k][int(j)]
            if gat is not None:
                leaf = jnp.asarray(leaf)[gat]
        else:
            leaf = out[key]
            if gat is not None and key != "seg_matched":
                leaf = jnp.asarray(leaf)[gat]
        parts.append(jnp.asarray(leaf, dtype=jnp.float64).reshape(-1))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def unpack_outputs(packed, spec: Tuple, num_seg: int = 0) -> Dict[str, Any]:
    """Packed f64 vector (host numpy) -> the kernel output tree the decode
    helpers consume. Scalar leaves come back as python-indexable scalars,
    vector leaves (grouped/presence/seg_matched) as arrays. Compact-mode
    leaves are scattered back into dense [num_groups] arrays host-side
    (cheap zeros; the expensive part was shipping them over the tunnel)."""
    import numpy as np

    packed = np.asarray(packed)
    grouped = bool(spec[2])
    num_groups = spec[3]
    K = compact_mode(spec)
    dc = {f"agg{i}" for i, a in enumerate(spec[1])
          if a[0] in ("distinctcount", "distinctcounthll")}
    out: Dict[str, Any] = {}
    multi: Dict[str, Dict[int, Any]] = {}
    off = 0
    n = 0
    idx = None

    def expand(leaf):
        if idx is None:
            return leaf
        dense = np.zeros(num_groups, dtype=leaf.dtype)
        dense[idx] = leaf[:n]
        return dense

    for key, size in output_layout(spec, num_seg):
        leaf = packed[off:off + size]
        off += size
        if key == "compact_n":
            n = int(leaf[0])
            if n > K:
                from pinot_tpu.engine.plan import PlanError

                raise PlanError(
                    f"{n} live groups exceed the compact cap {K} "
                    f"-> host path serves the full result")
            continue
        if key == "compact_idx":
            idx = leaf[:n].astype(np.int64)
            continue
        if "." in key:
            k, j = key.split(".")
            multi.setdefault(k, {})[int(j)] = \
                expand(leaf) if grouped else leaf[0]
            continue
        if key == "num_matched":
            out[key] = leaf[0]
        elif key == "rung":
            out[key] = int(leaf[0])
        elif key == "seg_matched":
            out[key] = leaf
        elif grouped or key in dc:
            out[key] = expand(leaf)
        else:
            out[key] = leaf[0]
    for k, leaves in multi.items():
        out[k] = tuple(leaves[j] for j in sorted(leaves))
    return out


def partial_reduce_ops(spec: Tuple) -> Dict[str, Tuple[str, ...]]:
    """Per-output-leaf merge op ('sum'|'min'|'max') for combining partials
    across segments/devices — the state algebra of the combine phase
    (ref: BaseCombineOperator merge + AggregationFunction.merge)."""
    _, agg_specs, group_specs, _, _ = spec
    ops: Dict[str, Tuple[str, ...]] = {}
    if group_specs:
        ops["presence"] = ("sum",)
    else:
        ops["num_matched"] = ("sum",)
    for i, aspec in enumerate(agg_specs):
        base = aspec[0]
        ops[f"agg{i}"] = {
            "count": ("sum",),
            "sum": ("sum",),
            "min": ("min",),
            "max": ("max",),
            "avg": ("sum", "sum"),
            "minmaxrange": ("min", "max"),
            "distinctcount": ("max",),
            "distinctcounthll": ("max",),  # register merge = pmax
        }[base]
    return ops


def _masked_values(aspec, cols, pc, mask):
    base, mv, vspec, acc = aspec[0], aspec[1], aspec[2], aspec[3]
    dt, wide, min_neutral, max_neutral = _acc_info(acc)
    # MV values are read inside the MV branch (dense mv + counts), not here
    vals = (_emit_value(vspec, cols, pc, dt)
            if (vspec is not None and not mv) else None)
    if vals is not None and hasattr(vals, "astype"):
        vals = vals.astype(dt)
    return base, mv, vals, dt, wide, min_neutral, max_neutral


def _count32(mask):
    """Per-segment doc counts always fit i32; widen for exact merging."""
    return mask.sum(dtype=jnp.int32).astype(jnp.int64)


def _emit_scalar_agg(aspec, cols, pc, mask):
    if aspec[0] == "distinctcount":
        _, colname, card = aspec
        fwd = cols[colname]["fwd"]
        presence = jnp.zeros(card, dtype=jnp.int32).at[fwd].max(
            mask.astype(jnp.int32), mode="drop")
        return presence  # [card] 0/1; host maps present dictIds -> values
    if aspec[0] == "distinctcounthll":
        # HLL register update as masked scatter-max over precomputed
        # per-dictId (bucket, rank) LUTs (utils/hll.register_updates)
        _, colname, log2m = aspec
        m = 1 << log2m
        fwd = cols[colname]["fwd"]
        bucket = pc.take()[fwd]
        rank = pc.take()[fwd]
        regs = jax.ops.segment_max(jnp.where(mask, rank, 0), bucket,
                                   num_segments=m)
        return jnp.maximum(regs, 0)  # untouched buckets -> 0, not int-min
    base, mv, vals, dt, wide, min_n, max_n = _masked_values(
        aspec, cols, pc, mask)
    zero = jnp.zeros((), dtype=dt)

    if mv:
        c = cols[aspec[2][1]]
        mvv, cnt = c["dictvals"][c["mv"]], c["mvcount"]
        entry = (jnp.arange(c["mv"].shape[1], dtype=jnp.int32)[None, :]
                 < cnt[:, None]) & mask[:, None]
        fv = mvv.astype(dt)
        any_entry = entry.any()
        if base == "count":
            # acc sized at plan time for capacity*max_mv total entries
            return jnp.where(mask, cnt, 0).sum(dtype=dt).astype(jnp.int64)
        if base == "sum":
            return jnp.where(entry, fv, zero).sum().astype(wide)
        if base == "min":
            v = jnp.where(entry, fv, min_n).min().astype(jnp.float64)
            return jnp.where(any_entry, v, POS_INF)
        if base == "max":
            v = jnp.where(entry, fv, max_n).max().astype(jnp.float64)
            return jnp.where(any_entry, v, NEG_INF)
        if base == "avg":
            return (jnp.where(entry, fv, zero).sum().astype(wide),
                    entry.sum(dtype=jnp.int32).astype(jnp.int64))
        raise AssertionError(f"MV agg {base} has no device kernel")

    if base == "count":
        return _count32(mask)
    fv = vals if vals.ndim else jnp.full(mask.shape[0], vals, dtype=dt)
    any_match = mask.any()
    if base == "sum":
        return jnp.where(mask, fv, zero).sum().astype(wide)
    if base == "min":
        v = jnp.where(mask, fv, min_n).min().astype(jnp.float64)
        return jnp.where(any_match, v, POS_INF)
    if base == "max":
        v = jnp.where(mask, fv, max_n).max().astype(jnp.float64)
        return jnp.where(any_match, v, NEG_INF)
    if base == "avg":
        return (jnp.where(mask, fv, zero).sum().astype(wide), _count32(mask))
    if base == "minmaxrange":
        lo = jnp.where(mask, fv, min_n).min().astype(jnp.float64)
        hi = jnp.where(mask, fv, max_n).max().astype(jnp.float64)
        return (jnp.where(any_match, lo, POS_INF),
                jnp.where(any_match, hi, NEG_INF))
    raise AssertionError(f"agg {base} has no device scalar kernel")


class KernelCache:
    """spec -> jitted kernel (the engine's plan cache)."""

    def __init__(self):
        self._cache: Dict[Tuple, Any] = {}

    def get(self, spec: Tuple):
        k = self._cache.get(spec)
        if k is None:
            k = build_kernel(spec)
            self._cache[spec] = k
        return k

    def __len__(self) -> int:
        return len(self._cache)
