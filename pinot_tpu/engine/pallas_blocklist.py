"""Per-shape pallas blocklist: which plan specs must not build a fused
kernel, and WHY.

Both executors used to hold a bare ``set`` of ``plan.spec`` values whose
pallas kernel failed to lower/run; every blocked shape then declined with
the one generic ``pallas_shape_blocked`` reason, and a process restart
forgot everything a dying chip had taught it. This class keeps the
``add``/``in`` surface those call sites use and adds:

- a **reason per shape**: runtime failures store ``pallas_shape_blocked``
  (the pre-existing ledger contract); the kernel preflight
  (tools/preflight.py) seeds predicted-fail shapes with their
  ``pallas_preflight_<rule>`` code, so the decline explains which
  lowering constraint the shape violates;
- **disk persistence** (``pinot.server.query.pallas.blocklist.path``):
  every add writes through, and a new executor reloads the file — the
  blocklist survives the process that learned it;
- a **snapshot** for ``GET /debug/pallas``.

Specs are plain nested tuples of str/int/bool (``SegmentPlan.spec``), so
they round-trip exactly through ``repr``/``ast.literal_eval``.
"""

from __future__ import annotations

import ast
import json
import logging
import os
import threading

from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

# the reason recorded for shapes blocked by a runtime lowering/run failure
RUNTIME_BLOCK_REASON = "pallas_shape_blocked"


class PallasBlocklist:
    """Thread-safe ``{plan spec -> decline reason}`` with optional
    write-through persistence. Drop-in for the old ``set``: ``add``,
    ``in``, ``len`` keep their shapes (``add`` without a reason records
    the runtime-failure code)."""

    def __init__(self, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._specs: Dict[Tuple, str] = {}  # guarded-by: _lock
        self._path = path or None
        if self._path:
            self._load()

    # -- set surface --------------------------------------------------------
    def add(self, spec: Tuple, reason: str = RUNTIME_BLOCK_REASON) -> None:
        with self._lock:
            self._specs[spec] = reason
            entries = self._entries_locked()
        self._persist(entries)

    def __contains__(self, spec: Tuple) -> bool:
        with self._lock:
            return spec in self._specs

    def __len__(self) -> int:
        with self._lock:
            return len(self._specs)

    # -- reasons ------------------------------------------------------------
    def reason_for(self, spec: Tuple,
                   default: str = RUNTIME_BLOCK_REASON) -> str:
        """The reason a blocked shape's decline should record — the
        preflight rule code for seeded shapes, ``pallas_shape_blocked``
        for runtime failures."""
        with self._lock:
            return self._specs.get(spec, default)

    def snapshot(self) -> List[Dict[str, Any]]:
        """``GET /debug/pallas`` body rows (spec repr is the stable,
        re-loadable key)."""
        with self._lock:
            return [{"spec": repr(s), "reason": r}
                    for s, r in self._specs.items()]

    # -- persistence --------------------------------------------------------
    def _entries_locked(self) -> List[Dict[str, str]]:
        return [{"spec": repr(s), "reason": r}
                for s, r in self._specs.items()]

    def _persist(self, entries: List[Dict[str, str]]) -> None:
        if not self._path:
            return
        tmp = f"{self._path}.tmp"
        try:
            d = os.path.dirname(self._path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"entries": entries}, f, indent=1)
            os.replace(tmp, self._path)
        except OSError:
            # persistence is best-effort: an unwritable path must not
            # take down the serving path that just learned a bad shape
            log.exception("pallas blocklist persist failed: %s", self._path)

    def _load(self) -> None:
        try:
            with open(self._path, encoding="utf-8") as f:
                data = json.load(f)
        except FileNotFoundError:
            return
        except (OSError, ValueError):
            log.exception("pallas blocklist unreadable: %s", self._path)
            return
        for e in data.get("entries", []):
            try:
                spec = ast.literal_eval(e["spec"])
            except (KeyError, ValueError, SyntaxError):
                log.warning("pallas blocklist entry skipped: %r", e)
                continue
            with self._lock:
                self._specs[spec] = e.get("reason", RUNTIME_BLOCK_REASON)
