"""Per-segment plan: QueryContext + segment metadata -> kernel spec + params.

Re-design of the reference's plan maker + predicate evaluators
(``InstancePlanMakerImplV2.makeSegmentPlanNode:227``,
``operator/filter/predicate/*``): the *spec* is a hashable structural
description of the computation (filter tree shape, predicate strategies,
aggregation set, group-by layout) that keys the kernel cache; the *params*
are the runtime values (dictId intervals, LUTs, literals, group strides)
passed as device arrays so queries differing only in literals reuse the
compiled kernel.

Predicate translation exploits sorted dictionaries: EQ/RANGE become dictId
compares, IN/REGEXP become a boolean LUT over the dictionary gathered on
device (the vectorized analogue of dictId-set predicate evaluators).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.engine.aggregates import AggDef, agg_value_expr, resolve_agg
from pinot_tpu.engine.errors import QueryError, UnsupportedQueryError
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    FilterOp,
    Function,
    Identifier,
    Literal,
    Predicate,
    PredicateType,
)
from pinot_tpu.segment.immutable import DataSource, ImmutableSegment
from pinot_tpu.spi.data import DataType

# group-by scatter limit: beyond this the composed key space is too large for
# dense device arrays and execution falls back to the host path
# (the reference's analogue knob: numGroupsLimit, InstancePlanMakerImplV2.java:67)
MAX_DEVICE_GROUPS = 1 << 21

_I32_MAX = np.iinfo(np.int32).max

_ARITH_OPS = {"plus", "minus", "times", "divide", "mod", "floordiv"}

# Epoch-arithmetic transforms compile to exact device integer ops (the
# device equivalents of the reference's vectorized datetime transform
# functions, operator/transform/function/DateTimeConversionTransformFunction
# et al. — fixed-width units only; calendar units stay host-evaluated).
# Unit widths come from the host function registry so the oracle and the
# device rewrite share one source of truth.
from pinot_tpu.query.functions import TIME_UNIT_MS as _UNIT_MS
from pinot_tpu.query.functions import TRUNC_UNIT_MS as _TRUNC_MS

_TIME_DIV = {
    "toepochseconds": _UNIT_MS["SECONDS"],
    "toepochminutes": _UNIT_MS["MINUTES"],
    "toepochhours": _UNIT_MS["HOURS"],
    "toepochdays": _UNIT_MS["DAYS"]}
_TIME_MUL = {
    "fromepochseconds": _UNIT_MS["SECONDS"],
    "fromepochminutes": _UNIT_MS["MINUTES"],
    "fromepochhours": _UNIT_MS["HOURS"],
    "fromepochdays": _UNIT_MS["DAYS"]}


def _device_transform_rewrite(e: Function) -> Optional[Expr]:
    """Time transform -> equivalent plus/minus/times/mod/floordiv tree, or
    None when the function isn't device-expressible. Rewrites happen at
    PLAN time only, so response column names keep the user's expression."""
    n = e.name
    if n in _TIME_DIV and len(e.args) == 1:
        return Function("floordiv", (e.args[0], Literal(_TIME_DIV[n])))
    if n in _TIME_MUL and len(e.args) == 1:
        return Function("times", (e.args[0], Literal(_TIME_MUL[n])))
    if (n == "datetrunc" and len(e.args) == 2
            and isinstance(e.args[0], Literal)):
        q = _TRUNC_MS.get(str(e.args[0].value).lower())
        if q == 1:
            return e.args[1]
        if q:
            # trunc(v, q) = v - (v mod q): exact for negatives too (floor
            # semantics match the host datetrunc's floordiv-multiply)
            return Function("minus", (e.args[1],
                                      Function("mod",
                                               (e.args[1], Literal(q)))))
        return None
    if (n == "timeconvert" and len(e.args) == 3
            and all(isinstance(a, Literal) for a in e.args[1:])):
        ma = _UNIT_MS.get(str(e.args[1].value).upper())
        mb = _UNIT_MS.get(str(e.args[2].value).upper())
        if ma is None or mb is None:
            return None
        inner: Expr = e.args[0] if ma == 1 else \
            Function("times", (e.args[0], Literal(ma)))
        return inner if mb == 1 else \
            Function("floordiv", (inner, Literal(mb)))
    return None


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


@dataclass
class SegmentPlan:
    """The executable plan for one (query, segment) pair."""

    spec: Tuple              # hashable kernel-cache key (incl. static sizes)
    params: List[np.ndarray]  # runtime arrays, kernel consumes in order
    columns: List[str]       # columns to stage
    # (strategy, column | gexpr base) per group expr (decode reads these)
    group_defs: List[Tuple[str, Any]]
    group_cards: List[int]   # per group col: size of its key space
    group_strides: Optional[np.ndarray]  # row-major key strides (decode uses)
    num_groups: int          # padded total group count (0 = not group-by)
    agg_defs: List[AggDef]
    # per group col: key-space offset the kernel subtracts (0 unless the
    # column is graw/gexpr or its dictId range was filter-narrowed)
    group_bases: List[int] = field(default_factory=list)


class PlanError(UnsupportedQueryError):
    """Query shape the device kernels don't cover -> host fallback.

    Every PlanError carries a machine-readable ``reason_code`` for the
    path-decision ledger (common/tracing.py): pass ``reason=`` at the
    raise site or rely on the message classifier — either way a decline
    is never ``unknown`` (the bench gates on that)."""

    def __init__(self, message: str, reason: Optional[str] = None):
        super().__init__(message)
        self._reason = reason

    @property
    def reason_code(self) -> str:
        if self._reason is not None:
            return self._reason
        from pinot_tpu.common.tracing import classify_decline

        self._reason = classify_decline(str(self))
        return self._reason


# --------------------------------------------------------------------------
# star-tree node plan: the pre-aggregation rung of the device ladder
# --------------------------------------------------------------------------

# pseudo-column namespace for star-tree node arrays: the kernel spec reads
# these keys out of the staged node-column tree (engine/staging.py
# startree_nodes), never a segment forward index
def startree_dim_key(col: str) -> str:
    return f"stdim:{col}"


def startree_metric_key(fn: str, col: str) -> str:
    return f"stmetric:{fn}__{col}"


@dataclass
class StarTreePlan:
    """Executable device plan over one star-tree's node arrays.

    The spec is a regular kernel spec (same ops, same param protocol, same
    cache) whose capacity is the padded SELECTED-record count — the kernel
    aggregates a gathered node slice, so the dense/hash group-by rungs and
    the packed-output machinery apply unchanged. ``agg_map`` records how
    the rewritten pre-agg leaves reassemble into the ORIGINAL aggregation
    states (count -> sum of the count column, avg -> sum+count pair)."""

    spec: Tuple
    params: List[np.ndarray]
    columns: List[str]            # pseudo node-column keys the kernel reads
    group_cols: List[str]         # real dimension names (key decode)
    group_cards: List[int]
    group_bases: List[int]
    group_strides: Optional[np.ndarray]
    num_groups: int
    agg_map: List[Tuple[str, List[int]]]  # (base, rewritten leaf indexes)


def plan_star_tree(ctx, segment, tree, matches: Dict[str, Any],
                   num_selected: int) -> StarTreePlan:
    """Star-tree device eligibility + spec build. ``matches`` carries the
    per-dimension dictId matches ``startree_exec.resolve_matches`` already
    translated (the fit check in ``pick_star_tree`` has passed). Reuses the
    PR-1 dictId-narrowing idea: a predicated group dimension's key range
    shrinks to its match bounds, so selective Q2.x shapes land on the dense
    rung outright. Raises PlanError when the node slice can't ride the
    device kernels (the host walker serves instead)."""
    from pinot_tpu.engine.startree_exec import _pairs_needed
    from pinot_tpu.segment.startree import match_bounds

    aggs = [resolve_agg(f) for f in ctx.aggregations]
    params: List[np.ndarray] = []
    columns: List[str] = []

    group_cols: List[str] = []
    group_specs: List[Tuple] = []
    group_cards: List[int] = []
    group_bases: List[int] = []
    num_groups = 0
    if ctx.group_by:
        for e in ctx.group_by:
            # pick_star_tree guarantees Identifier group exprs on tree dims
            col = e.name
            cm = segment.metadata.column(col)
            lo, hi = 0, cm.cardinality - 1
            if col in matches:
                mlo, mhi = match_bounds(matches[col])
                lo, hi = max(lo, mlo), min(hi, mhi)
                if lo > hi:
                    lo, hi = 0, 0  # unsatisfiable: 1-slot key space
            group_cols.append(col)
            group_cards.append(hi - lo + 1)
            group_bases.append(lo)
            key = startree_dim_key(col)
            group_specs.append(("gdict", key))
            if key not in columns:
                columns.append(key)
        total = 1
        for c in group_cards:
            total *= c
            if total > MAX_DEVICE_GROUPS:
                raise PlanError("star-tree group key space too large "
                                "-> host walker")
        num_groups = _next_pow2(total)
        strides = np.ones(len(group_cards), dtype=np.int32)
        for i in range(len(group_cards) - 2, -1, -1):
            strides[i] = strides[i + 1] * group_cards[i + 1]
        params.append(strides)
        params.append(np.asarray(group_bases, dtype=np.int64))
    else:
        strides = None

    # rewrite aggregations onto the pre-aggregated metric columns: COUNT
    # becomes SUM over the count column, AVG splits into SUM+COUNT leaves
    # reassembled at decode (ref: StarTreeGroupByExecutor reading
    # AggregationFunctionColumnPair columns instead of raw values)
    agg_specs: List[Tuple] = []
    agg_map: List[Tuple[str, List[int]]] = []

    def leaf(fn: str, col: str) -> int:
        key = startree_metric_key(fn, col)
        acc = "i64" if fn == "count" else "f64"
        op = "sum" if fn in ("count", "sum") else fn
        agg_specs.append((op, False, ("col", key, False), acc))
        if key not in columns:
            columns.append(key)
        return len(agg_specs) - 1

    for agg, fn in zip(aggs, ctx.aggregations):
        pairs = _pairs_needed(agg, fn)
        if pairs is None:  # pick_star_tree admitted it; stay defensive
            raise PlanError(f"aggregation {agg.name} has no pre-agg pairs")
        if agg.base == "avg":
            (sfn, scol), (cfn, ccol) = pairs
            agg_map.append(("avg", [leaf(sfn, scol), leaf(cfn, ccol)]))
        else:
            (pfn, pcol), = pairs
            agg_map.append((agg.base, [leaf(pfn, pcol)]))

    capacity = max(128, _next_pow2(max(1, num_selected)))
    spec = (("true",), tuple(agg_specs), tuple(group_specs), num_groups,
            capacity)
    expected = expected_param_count(spec)
    if len(params) != expected:
        raise AssertionError(
            f"star-tree param pack/unpack drift: packed {len(params)} but "
            f"the spec consumes {expected} (spec={spec[:3]!r})")
    return StarTreePlan(spec=spec, params=params, columns=columns,
                        group_cols=group_cols, group_cards=group_cards,
                        group_bases=group_bases, group_strides=strides,
                        num_groups=num_groups, agg_map=agg_map)


def plan_segment(ctx: QueryContext, segment: ImmutableSegment) -> SegmentPlan:
    if getattr(segment, "is_mutable", False):
        # consuming segments are host-resident (unsorted dictionaries, live
        # append) — served by the host engine until sealed (SURVEY.md §7)
        raise PlanError("mutable segment -> host path")
    params: List[np.ndarray] = []
    columns: List[str] = []

    filter_spec = _compile_filter(ctx.filter, segment, params, columns)
    # collected BEFORE the validdocs placeholder shifts the param slots
    dict_ranges = (_conjunctive_dict_ranges(filter_spec, params)
                   if ctx.group_by else {})

    if getattr(segment, "valid_doc_ids", None) is not None:
        # upsert-managed: AND a point-in-time snapshot of the live valid-doc
        # bitmap into the filter (the validDocIds contract,
        # ref: IndexSegment.getValidDocIds ANDed into every filter). The
        # param rides FIRST, before the filter's params, as a PLACEHOLDER:
        # the executor substitutes the version-cached device mask (or a
        # fresh host snapshot for unversioned bitmaps) at run time, so the
        # O(capacity) copy isn't paid when the cache will win anyway.
        params.insert(0, None)
        filter_spec = ("and", (("validdocs",), filter_spec))

    agg_defs = [resolve_agg(f) for f in ctx.aggregations]

    group_specs: List[Tuple] = []
    group_defs: List[Tuple[str, Any]] = []
    group_cards: List[int] = []
    group_bases: List[int] = []
    pending_gexpr: List[Tuple[int, Expr]] = []
    num_groups = 0
    if ctx.group_by:
        for e in ctx.group_by:
            strat, payload, card, base = _group_strategy(e, segment,
                                                         dict_ranges)
            group_cards.append(card)
            group_bases.append(base)
            if strat == "gexpr":
                # compiled AFTER strides/bases so the kernel's param-cursor
                # order (strides, bases, then key-expression literals)
                # matches the order the params list is built in
                group_specs.append(None)
                group_defs.append((strat, base))  # decode adds base back
                pending_gexpr.append((len(group_specs) - 1, e))
            else:
                group_specs.append((strat, payload))
                group_defs.append((strat, payload))
                if payload not in columns:
                    columns.append(payload)
        total = 1
        for c in group_cards:
            total *= c
            if total > MAX_DEVICE_GROUPS:
                raise PlanError(
                    f"group key space {total}+ exceeds device limit")
        num_groups = _next_pow2(total)
        # strides (row-major over group columns) + value-base offsets;
        # the executor's key decode reuses these exact strides
        strides = np.ones(len(group_cards), dtype=np.int32)
        for i in range(len(group_cards) - 2, -1, -1):
            strides[i] = strides[i + 1] * group_cards[i + 1]
        params.append(strides)
        params.append(np.asarray(group_bases, dtype=np.int64))
        for idx, e in pending_gexpr:
            group_specs[idx] = (
                "gexpr", _compile_value(e, segment, params, columns))
        grouped = True
    else:
        strides = None
        grouped = False

    agg_specs: List[Tuple] = []
    for agg, fn in zip(agg_defs, ctx.aggregations):
        ok = agg.device_grouped if grouped else agg.device_scalar
        if not ok:
            raise PlanError(f"aggregation {agg.name} not device-supported "
                            f"{'grouped' if grouped else 'scalar'}")
        vexpr = agg_value_expr(fn)
        if agg.base == "distinctcounthll" and not agg.mv:
            # device HLL: per-dictId (bucket, rank) LUTs precomputed from
            # the dictionary's hashes; register update = masked scatter-max
            # (ref: DistinctCountHLLAggregationFunction; utils/hll.py)
            from pinot_tpu.utils.hll import DEFAULT_LOG2M

            if not isinstance(vexpr, Identifier) or vexpr.name.startswith("$"):
                raise PlanError("DISTINCTCOUNTHLL argument must be a column")
            cm = segment.metadata.column(vexpr.name)
            if not (cm.has_dictionary and cm.single_value):
                raise PlanError("DISTINCTCOUNTHLL needs an SV dict column")
            m = 1 << DEFAULT_LOG2M
            if num_groups and (num_groups + 1) * m > (1 << 23):
                raise PlanError("grouped HLL register space too large")
            d = segment.data_source(vexpr.name).dictionary
            bucket, rank = d.hll_register_luts(DEFAULT_LOG2M)
            params.append(bucket)
            params.append(rank)
            agg_specs.append(("distinctcounthll", vexpr.name, DEFAULT_LOG2M))
            if vexpr.name not in columns:
                columns.append(vexpr.name)
            continue
        if agg.base == "distinctcount" and not agg.mv:
            # checked before value compilation: the presence-bitmap kernel
            # reads dictIds directly, so non-numeric (string) columns are
            # fine here even though they have no device value expression
            if not isinstance(vexpr, Identifier) or vexpr.name.startswith("$"):
                raise PlanError("DISTINCTCOUNT argument must be a column")
            cm = segment.metadata.column(vexpr.name)
            if not cm.has_dictionary:
                raise PlanError("DISTINCTCOUNT on raw column -> host")
            if not cm.single_value:
                raise PlanError("DISTINCTCOUNT on MV column -> host")
            if cm.cardinality > (1 << 20):
                # the presence vector is [cardinality]: past ~1M ids the
                # D2H outweighs the scan (use DISTINCTCOUNTHLL there, like
                # the reference recommends at scale)
                raise PlanError("DISTINCTCOUNT cardinality too large -> host")
            agg_specs.append(("distinctcount", vexpr.name, cm.cardinality))
            if vexpr.name not in columns:
                columns.append(vexpr.name)
            continue
        fanout = 1
        if vexpr is None:
            vspec = None
        elif agg.mv:
            if not isinstance(vexpr, Identifier) or vexpr.name.startswith("$"):
                raise PlanError("MV aggregation argument must be a column")
            cm = segment.metadata.column(vexpr.name)
            if cm.single_value or not cm.data_type.is_numeric:
                raise PlanError(f"{agg.name} needs a numeric MV column")
            vspec = ("colmv", vexpr.name)
            fanout = max(1, cm.max_num_multi_values)
            if vexpr.name not in columns:
                columns.append(vexpr.name)
        else:
            vspec = _compile_value(vexpr, segment, params, columns)
        acc = _acc_dtype(agg.base, vexpr, segment, fanout)
        agg_specs.append((agg.base, agg.mv, vspec, acc))

    spec = (filter_spec, tuple(agg_specs), tuple(group_specs), num_groups,
            segment.padded_capacity)
    expected = expected_param_count(spec)
    if len(params) != expected:
        raise AssertionError(
            f"param pack/unpack drift: packed {len(params)} params but the "
            f"spec consumes {expected} — plan.py and the kernel param "
            f"tables disagree (spec={spec[:3]!r})")
    return SegmentPlan(spec=spec, params=params, columns=columns,
                       group_defs=group_defs, group_cards=group_cards,
                       group_strides=strides, num_groups=num_groups,
                       agg_defs=agg_defs, group_bases=group_bases)


# --------------------------------------------------------------------------
# accumulator narrowing (v5e-shaped kernels: f64/i64 are emulated on TPU, so
# capacity-sized accumulation runs in i32/f32 whenever column stats bound the
# values; partials are widened to i64/f64 at kernel output for exact
# cross-segment merging)
# --------------------------------------------------------------------------

def _value_kind(e: Expr, segment: ImmutableSegment):
    """('int', max_abs|None) when the expression is integral on device,
    ('float', None) otherwise. Integer bounds propagate through
    plus/minus/times/mod/floordiv (and the epoch-transform rewrites) so
    expression aggregations like ``sum(lo_extendedprice * lo_discount)``
    or ``sum(toEpochDays(ts))`` accumulate EXACTLY in i32/i64 instead of
    drifting in f32; true division stays float."""
    if isinstance(e, Literal):
        if isinstance(e.value, bool) or isinstance(e.value, int):
            return ("int", abs(int(e.value)))
        return ("float", None)
    if isinstance(e, Identifier):
        cm = segment.metadata.column(e.name)
        if cm.data_type.is_integral:
            if cm.min_value is None or cm.max_value is None:
                return ("int", None)
            return ("int", max(abs(int(cm.min_value)),
                               abs(int(cm.max_value))))
        return ("float", None)
    if isinstance(e, Function):
        rewritten = _device_transform_rewrite(e)
        if rewritten is not None:
            return _value_kind(rewritten, segment)
        if (e.name in ("plus", "minus", "times", "mod", "floordiv")
                and len(e.args) == 2):
            kinds = [_value_kind(a, segment) for a in e.args]
            if all(k[0] == "int" for k in kinds):
                (_, la), (_, ra) = kinds
                if e.name == "mod":
                    # |a mod b| < |b| under floor semantics
                    return ("int", ra)
                if e.name == "floordiv":
                    # |a // b| <= |a| for integral |b| >= 1
                    return ("int", la)
                if la is None or ra is None:
                    return ("int", None)
                return ("int", la * ra if e.name == "times" else la + ra)
    return ("float", None)


def _acc_dtype(base: str, vexpr: Optional[Expr], segment: ImmutableSegment,
               fanout: int = 1) -> str:
    """``fanout`` is the MV entries-per-doc bound (1 for SV): MV sums/counts
    accumulate up to capacity*fanout terms, not capacity."""
    if vexpr is None:  # count(*): docs per segment always fit i32
        return "i32"
    if base == "count":
        # count(col) counts docs (SV) or total MV entries (fanout > 1)
        return ("i32" if segment.padded_capacity * fanout <= _I32_MAX
                else "i64")
    kind, max_abs = _value_kind(vexpr, segment)
    if kind == "float":
        return "f32"
    if base in ("min", "max", "minmaxrange"):
        return "i32" if (max_abs is not None and max_abs <= _I32_MAX) else "i64"
    # sum/avg: the whole-segment sum must fit the accumulator exactly
    if (max_abs is not None
            and max_abs * segment.padded_capacity * fanout <= _I32_MAX):
        return "i32"
    return "i64"


# --------------------------------------------------------------------------
# filter-aware dictId narrowing: predicates in the filter's top-level AND
# conjunction bound the dictIds any LIVE doc can carry in those columns, so
# a group column under such a predicate needs only the narrowed key range —
# the composed key space of selective queries (SSB Q3.3/Q3.4/Q4.3 shape)
# drops below the sparse threshold and takes the dense (often Pallas-
# eligible) rung outright. The reference narrows the same way by feeding
# filtered dictId sets to DictionaryBasedGroupKeyGenerator (SURVEY §2.4).
# --------------------------------------------------------------------------

# params consumed per compiled filter op (must mirror kernels._emit_filter)
_FILTER_PARAMS = {
    "true": 0, "false": 0, "validdocs": 1, "isnull": 0, "isnotnull": 0,
    "eq": 1, "neq": 1, "range": 1, "lut": 1,
    "mv_eq": 1, "mv_neq": 1, "mv_range": 1, "mv_lut": 1,
    "veq": 1, "vneq": 1, "vrange": 2, "vin": 1, "vnotin": 1,
}

# params consumed per compiled value op (must mirror kernels._emit_value;
# "fn" is structural — its args carry the params, like and/or/not in the
# filter tree). "colmv" is absent deliberately: MV values never route
# through _emit_value (the MV branch reads dense mv + counts, 0 params).
_VALUE_PARAMS = {"lit": 1, "col": 0, "fn": 0}


def _count_value_params(vspec: Optional[Tuple]) -> int:
    if vspec is None or vspec[0] == "colmv":
        return 0
    n = _VALUE_PARAMS[vspec[0]]
    if vspec[0] == "fn":
        n += sum(_count_value_params(a) for a in vspec[2])
    return n


def expected_param_count(spec: Tuple) -> int:
    """Number of runtime params the kernel-side cursor consumes for
    ``spec`` — the pack-time half of the runtime protocol mirror (the
    consume-time half is ``_ParamCursor.finish()``). Walks the spec with
    the same per-op tables the static protocol lint verifies both sides
    against, so a dynamically-built spec that drifts fails loudly here
    instead of silently mis-keying results."""
    filter_spec, agg_specs, group_specs, _num_groups, _cap = spec

    def walk_filter(node: Tuple) -> int:
        op = node[0]
        if op in ("and", "or", "not"):
            return sum(walk_filter(c) for c in node[1])
        return _FILTER_PARAMS[op]

    n = walk_filter(filter_spec)
    if group_specs:
        n += 2  # the strides + bases arrays, in that order
        for gspec in group_specs:
            if gspec[0] == "gexpr":
                n += _count_value_params(gspec[1])
    for aspec in agg_specs:
        if aspec[0] == "distinctcounthll":
            n += 2  # per-dictId (bucket, rank) register LUTs
        elif aspec[0] != "distinctcount":
            n += _count_value_params(aspec[2])
    return n


def narrow_plan_groups(plan: SegmentPlan,
                       ranges: List[Tuple[int, int]]) -> SegmentPlan:
    """Rebuild a group-by plan with each group column's key range narrowed
    to the OBSERVED dictId bounds ``ranges`` (inclusive, raw dictIds — the
    pallas group-range probe's output). Exact: the bounds are min/max over
    the very rows the filter matches, so no live doc composes a key outside
    the narrowed space. The narrowed plan keeps the spec shape (and the
    params list length/order — only the strides/bases arrays are replaced
    in place), so kernels, pack/unpack, and the group decode apply
    unchanged; ``_narrowed_from`` carries the original spec for the
    executor's per-shape blocklists."""
    assert plan.group_cards and len(ranges) == len(plan.group_cards)
    cards: List[int] = []
    bases: List[int] = []
    for (lo, hi), card, base in zip(ranges, plan.group_cards,
                                    plan.group_bases):
        lo = max(base, int(lo))
        hi = min(base + card - 1, int(hi))
        if lo > hi:            # no matched rows touched this column
            lo = hi = base
        cards.append(hi - lo + 1)
        bases.append(lo)
    total = 1
    for c in cards:
        total *= c
    num_groups = _next_pow2(total)
    strides = np.ones(len(cards), dtype=np.int32)
    for i in range(len(cards) - 2, -1, -1):
        strides[i] = strides[i + 1] * cards[i + 1]

    filter_spec, agg_specs, group_specs, _old, capacity = plan.spec
    spec = (filter_spec, agg_specs, group_specs, num_groups, capacity)

    def walk_filter(node: Tuple) -> int:
        op = node[0]
        if op in ("and", "or", "not"):
            return sum(walk_filter(c) for c in node[1])
        return _FILTER_PARAMS[op]

    n_filter = walk_filter(filter_spec)
    params = list(plan.params)
    params[n_filter] = strides
    params[n_filter + 1] = np.asarray(bases, dtype=np.int64)
    narrowed = SegmentPlan(
        spec=spec, params=params, columns=list(plan.columns),
        group_defs=list(plan.group_defs), group_cards=cards,
        group_strides=strides, num_groups=num_groups,
        agg_defs=plan.agg_defs, group_bases=bases)
    narrowed._narrowed_from = getattr(plan, "_narrowed_from", plan.spec)
    return narrowed


def _conjunctive_dict_ranges(filter_spec: Tuple,
                             params: List[np.ndarray]
                             ) -> Dict[str, Tuple[int, int]]:
    """column -> (lo, hi) inclusive dictId bounds implied for every doc the
    filter can match, collected only along pure-AND paths from the root
    (predicates under OR/NOT prove nothing). Repeated predicates meet
    (intersect); an empty meet means the filter matches nothing."""
    ranges: Dict[str, Tuple[int, int]] = {}

    def meet(col: str, lo: int, hi: int) -> None:
        cur = ranges.get(col)
        ranges[col] = ((max(cur[0], lo), min(cur[1], hi))
                       if cur else (lo, hi))

    def walk(node: Tuple, i: int, conj: bool) -> int:
        op = node[0]
        if op == "and":
            for c in node[1]:
                i = walk(c, i, conj)
            return i
        if op in ("or", "not"):
            for c in node[1]:
                i = walk(c, i, False)
            return i
        if conj:
            if op == "eq":
                did = int(params[i])
                meet(node[1], did, did)
            elif op == "range":
                iv = np.asarray(params[i])
                meet(node[1], int(iv[0]), int(iv[1]))
            elif op == "lut":
                idx = np.nonzero(np.asarray(params[i]))[0]
                if idx.size:
                    meet(node[1], int(idx[0]), int(idx[-1]))
                else:
                    meet(node[1], 1, 0)  # matches nothing
        return i + _FILTER_PARAMS[op]

    walk(filter_spec, 0, True)
    return ranges


# --------------------------------------------------------------------------
# group-by strategies
# --------------------------------------------------------------------------

def _value_bounds(e: Expr, segment: ImmutableSegment
                  ) -> Optional[Tuple[int, int]]:
    """(lo, hi) integer bounds of a device-compilable expression via
    interval arithmetic over column stats, or None when unbounded /
    non-integral. Feeds the 'gexpr' group strategy: a bounded integral
    expression's value space is a dense key range, exactly like a raw int
    column's (ref: the value-based group key generators,
    NoDictionarySingleColumnGroupKeyGenerator)."""
    if isinstance(e, Literal):
        if isinstance(e.value, bool) or not isinstance(e.value, int):
            return None
        return (e.value, e.value)
    if isinstance(e, Identifier):
        if e.name.startswith("$"):
            return None
        cm = segment.metadata.column(e.name)
        if (not cm.single_value or not cm.data_type.is_integral
                or cm.min_value is None or cm.max_value is None):
            return None
        return (int(cm.min_value), int(cm.max_value))
    if isinstance(e, Function):
        rw = _device_transform_rewrite(e)
        if rw is not None:
            return _value_bounds(rw, segment)
        if e.name not in ("plus", "minus", "times", "mod", "floordiv") \
                or len(e.args) != 2:
            return None
        a = _value_bounds(e.args[0], segment)
        b = _value_bounds(e.args[1], segment)
        if a is None or b is None:
            return None
        (alo, ahi), (blo, bhi) = a, b
        if e.name == "plus":
            return (alo + blo, ahi + bhi)
        if e.name == "minus":
            return (alo - bhi, ahi - blo)
        if e.name == "times":
            corners = (alo * blo, alo * bhi, ahi * blo, ahi * bhi)
            return (min(corners), max(corners))
        # mod / floordiv: positive-constant divisor only (floor semantics)
        if blo != bhi or blo <= 0:
            return None
        if e.name == "mod":
            return (0, blo - 1)
        return (alo // blo, ahi // blo)
    return None


def _group_strategy(e: Expr, segment: ImmutableSegment,
                    dict_ranges: Optional[Dict[str, Tuple[int, int]]] = None
                    ) -> Tuple[str, Any, int, int]:
    """-> (strategy, payload, cardinality, base). Payload is the column
    name for gdict/graw; for 'gexpr' the EXPRESSION (compiled to a device
    value spec after strides/bases take their param slots).
    ``dict_ranges`` carries the filter-narrowed dictId bounds per column."""
    if isinstance(e, Identifier):
        if e.name.startswith("$"):
            raise PlanError("group-by on virtual column -> host path")
        cm = segment.metadata.column(e.name)
        if not cm.single_value:
            raise PlanError("group-by on MV column -> host path")
        if cm.has_dictionary:
            # key = dictId - narrowed base
            # (ref: DictionaryBasedGroupKeyGenerator.java:62)
            lo, hi = (dict_ranges or {}).get(e.name, (0, cm.cardinality - 1))
            lo = max(0, lo)
            hi = min(cm.cardinality - 1, hi)
            if lo > hi:
                # the conjunction is unsatisfiable for this column: no doc
                # survives the filter, a 1-slot key space is enough
                lo, hi = 0, 0
            return ("gdict", e.name, hi - lo + 1, lo)
        if cm.data_type.is_integral:
            lo, hi = int(cm.min_value), int(cm.max_value)
            span = hi - lo + 1
            if span > MAX_DEVICE_GROUPS:
                raise PlanError("raw int group-by span too large")
            # key = value - min (value-space; psum-able across segments
            # that share the base -- used by the sharded combine path)
            return ("graw", e.name, span, lo)
        raise PlanError("group-by on raw float column -> host path")
    # bounded integral EXPRESSION (time buckets: GROUP BY toEpochDays(ts),
    # dateTrunc('hour', ts), ...): key = expr value - lo
    bounds = _value_bounds(e, segment)
    if bounds is None:
        raise PlanError(f"group-by expression {e} -> host path")
    lo, hi = bounds
    span = hi - lo + 1
    if span <= 0 or span > MAX_DEVICE_GROUPS:
        raise PlanError("group-by expression span too large -> host path")
    return ("gexpr", e, span, lo)


# --------------------------------------------------------------------------
# filter compilation
# --------------------------------------------------------------------------

def _compile_filter(node: Optional[FilterNode], segment: ImmutableSegment,
                    params: List[np.ndarray], columns: List[str]) -> Tuple:
    if node is None:
        return ("true",)
    return _compile_node(node, segment, params, columns)


def _compile_node(node: FilterNode, segment: ImmutableSegment,
                  params: List[np.ndarray], columns: List[str]) -> Tuple:
    if node.op is FilterOp.AND:
        return ("and", tuple(_compile_node(c, segment, params, columns)
                             for c in node.children))
    if node.op is FilterOp.OR:
        return ("or", tuple(_compile_node(c, segment, params, columns)
                            for c in node.children))
    if node.op is FilterOp.NOT:
        return ("not", (_compile_node(node.children[0], segment, params, columns),))
    return _compile_predicate(node.predicate, segment, params, columns)


def _conv(ds: DataSource, v: Any) -> Any:
    try:
        return ds.metadata.data_type.convert(v)
    except (ValueError, TypeError) as e:
        raise QueryError(f"cannot convert {v!r} for column {ds.name!r}: {e}")


def _compile_predicate(pred: Predicate, segment: ImmutableSegment,
                       params: List[np.ndarray], columns: List[str]) -> Tuple:
    t = pred.type

    if t in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
        cols = pred.lhs.columns()
        if not cols:
            raise QueryError(f"predicate references no column: {pred}")
        col = cols[0]
        cm = segment.metadata.column(col)
        if not cm.has_nulls:
            return ("false",) if t is PredicateType.IS_NULL else ("true",)
        if col not in columns:
            columns.append(col)
        return ("isnull", col) if t is PredicateType.IS_NULL else ("isnotnull", col)

    if not isinstance(pred.lhs, Identifier):
        raise PlanError(f"expression predicate {pred.lhs} -> host path")

    col = pred.lhs.name
    if col.startswith("$"):
        raise PlanError("virtual column predicate -> host path")
    ds = segment.data_source(col)
    cm = ds.metadata
    if col not in columns:
        columns.append(col)
    mvp = "" if cm.single_value else "mv_"

    if cm.has_dictionary:
        d = ds.dictionary
        card = cm.cardinality
        # Exclusive predicates on MV columns require ALL values to satisfy
        # (ref: BaseDictionaryBasedPredicateEvaluator.applyMV isExclusive):
        # compile the inclusive form and negate the per-doc result.
        if not cm.single_value and t in (PredicateType.NOT_EQ,
                                         PredicateType.NOT_IN):
            from dataclasses import replace
            inner_t = (PredicateType.EQ if t is PredicateType.NOT_EQ
                       else PredicateType.IN)
            inner = _compile_predicate(replace(pred, type=inner_t), segment,
                                       params, columns)
            return ("not", (inner,))
        if t in (PredicateType.EQ, PredicateType.NOT_EQ):
            did = d.index_of(_conv(ds, pred.value))
            params.append(np.int32(did))
            return (mvp + ("eq" if t is PredicateType.EQ else "neq"), col)
        if t is PredicateType.RANGE:
            lo = _conv(ds, pred.lower) if pred.lower is not None else None
            hi = _conv(ds, pred.upper) if pred.upper is not None else None
            try:
                a, b = d.range_to_dict_id_interval(lo, hi,
                                                   pred.lower_inclusive,
                                                   pred.upper_inclusive)
            except TypeError:
                # unsorted (mutable) dictionary: ids are arrival-ordered,
                # so a contiguous interval doesn't exist — value-scan to a
                # dictId LUT instead (same kernel op as IN)
                ids = d.matching_range_ids(lo, hi, pred.lower_inclusive,
                                           pred.upper_inclusive)
                lut = np.zeros(d.cardinality, dtype=bool)
                lut[ids] = True
                params.append(lut)
                return (mvp + "lut", col, card)
            params.append(np.array([a, b], dtype=np.int32))
            return (mvp + "range", col)
        if t in (PredicateType.IN, PredicateType.NOT_IN,
                 PredicateType.REGEXP_LIKE, PredicateType.TEXT_MATCH,
                 PredicateType.JSON_MATCH):
            if t is PredicateType.JSON_MATCH and not cm.single_value:
                raise PlanError("JSON_MATCH on MV column is unsupported")
            lut = _build_lut(ds, pred)
            params.append(lut)
            return (mvp + "lut", col, card)
        raise PlanError(f"predicate {t} -> host path")

    # RAW column
    if not cm.single_value:
        raise PlanError("raw MV column predicate -> host path")
    if t in (PredicateType.EQ, PredicateType.NOT_EQ):
        v = _conv(ds, pred.value)
        dt = _raw_np_dtype(cm)
        if cm.data_type.is_integral:
            info = np.iinfo(dt)
            if not (info.min <= int(v) <= info.max):
                # literal outside the staged dtype's range can't match any
                # stored value (all values fit the narrowed dtype)
                return ("false",) if t is PredicateType.EQ else ("true",)
        params.append(np.asarray(v, dtype=dt))
        return ("veq" if t is PredicateType.EQ else "vneq", col)
    if t is PredicateType.RANGE:
        bounds = _raw_bounds(cm, ds, pred)
        if bounds is None:  # range provably empty for the staged dtype
            return ("false",)
        lo, hi, lo_inc, hi_inc = bounds
        params.append(lo)
        params.append(hi)
        return ("vrange", col, lo_inc, hi_inc)
    if t in (PredicateType.IN, PredicateType.NOT_IN):
        dt = _raw_np_dtype(cm)
        conv = [_conv(ds, v) for v in pred.values]
        if cm.data_type.is_integral:
            info = np.iinfo(dt)
            conv = [v for v in conv if info.min <= int(v) <= info.max]
        vals = np.array(conv, dtype=dt)
        if vals.size == 0:
            return ("false",) if t is PredicateType.IN else ("true",)
        params.append(vals)
        return ("vin" if t is PredicateType.IN else "vnotin", col, len(vals))
    raise PlanError(f"predicate {t} on raw column -> host path")


def _raw_np_dtype(cm) -> np.dtype:
    """Param dtype matching the staged raw forward array (no promotion)."""
    from pinot_tpu.engine.staging import staged_int_dtype

    return (staged_int_dtype(cm) if cm.data_type.is_integral
            else np.dtype(np.float64))


def _raw_bounds(cm, ds: DataSource, pred: Predicate):
    """(lo, hi, lo_inclusive, hi_inclusive) in the staged dtype, or None if
    the range is provably empty. A literal outside the narrowed dtype's range
    either makes the bound unrestrictive (replace with an inclusive dtype
    extreme — every stored value fits the dtype) or the range empty."""
    dt = _raw_np_dtype(cm)
    lo_inc, hi_inc = pred.lower_inclusive, pred.upper_inclusive
    if cm.data_type.is_integral:
        info = np.iinfo(dt)
        if pred.lower is None:
            lo, lo_inc = info.min, True
        else:
            lv = int(_conv(ds, pred.lower))
            if lv > info.max:
                return None          # x >/>= lv is impossible
            if lv < info.min:
                lo, lo_inc = info.min, True   # bound unrestrictive
            else:
                lo = lv
        if pred.upper is None:
            hi, hi_inc = info.max, True
        else:
            uv = int(_conv(ds, pred.upper))
            if uv < info.min:
                return None          # x </<= uv is impossible
            if uv > info.max:
                hi, hi_inc = info.max, True   # bound unrestrictive
            else:
                hi = uv
        return (np.asarray(lo, dtype=dt), np.asarray(hi, dtype=dt),
                lo_inc, hi_inc)
    lo = np.float64(_conv(ds, pred.lower)) if pred.lower is not None \
        else np.float64(float("-inf"))
    hi = np.float64(_conv(ds, pred.upper)) if pred.upper is not None \
        else np.float64(float("inf"))
    return lo, hi, lo_inc, hi_inc


def _build_lut(ds: DataSource, pred: Predicate) -> np.ndarray:
    """Boolean dictId lookup table (the vectorized dictId-set evaluator)."""
    d = ds.dictionary
    card = d.cardinality
    t = pred.type
    lut = np.zeros(card, dtype=bool)
    if t in (PredicateType.IN, PredicateType.NOT_IN):
        for v in pred.values:
            i = d.index_of(_conv(ds, v))
            if i >= 0:
                lut[i] = True
        if t is PredicateType.NOT_IN:
            lut = ~lut
        return lut
    if t is PredicateType.REGEXP_LIKE:
        try:
            rx = re.compile(str(pred.value))
        except re.error as e:
            raise QueryError(f"bad regex {pred.value!r}: {e}")
        reader = getattr(ds, "fst_index", None)
        if reader is not None:
            # FST prefix narrowing: verify the regexp only inside the
            # trie-resolved dictId interval (ref: FSTBasedRegexpPredicateEvaluator)
            lut[reader.matching_ids(str(pred.value))] = True
            return lut
        for i in range(card):
            if rx.search(str(d.get_value(i))):
                lut[i] = True
        return lut
    if t is PredicateType.JSON_MATCH:
        # parse each DISTINCT value once; the doc mask is then a dictId
        # gather on device (JSON_MATCH rides the TPU scan like IN/REGEXP)
        from pinot_tpu.segment.jsonindex import (
            match_json_value,
            parse_match_filter,
        )

        try:
            ast = parse_match_filter(str(pred.value))
        except ValueError as e:
            raise QueryError(f"bad JSON_MATCH filter: {e}")
        for i in range(card):
            if match_json_value(d.get_value(i), ast):
                lut[i] = True
        return lut
    # TEXT_MATCH: tokenized index when present (dictId postings -> LUT,
    # so the query rides the device scan); the index-less decay evaluates
    # the SAME dialect per distinct value
    from pinot_tpu.segment.textindex import match_text_value, parse_text_query

    try:
        reader = getattr(ds, "text_index", None)
        if reader is not None:
            lut[reader.matching_ids(str(pred.value))] = True
            return lut
        ast = parse_text_query(str(pred.value))
    except ValueError as e:
        raise QueryError(f"bad TEXT_MATCH query: {e}")
    for i in range(card):
        if match_text_value(d.get_value(i), ast):
            lut[i] = True
    return lut


# --------------------------------------------------------------------------
# value-expression compilation
# --------------------------------------------------------------------------

def _compile_value(e: Expr, segment: ImmutableSegment,
                   params: List[np.ndarray], columns: List[str]) -> Tuple:
    if isinstance(e, Literal):
        if not isinstance(e.value, (int, float, bool)) or e.value is None:
            raise PlanError(f"non-numeric literal {e} in value expression")
        params.append(np.float64(e.value))
        return ("lit",)
    if isinstance(e, Identifier):
        if e.name.startswith("$"):
            raise PlanError("virtual column in value expression -> host")
        cm = segment.metadata.column(e.name)
        if not cm.single_value:
            raise PlanError(f"MV column {e.name} in value expression")
        if not cm.data_type.is_numeric:
            raise PlanError(f"non-numeric column {e.name} in value expression")
        if e.name not in columns:
            columns.append(e.name)
        return ("col", e.name, cm.has_dictionary)
    if isinstance(e, Function):
        if e.name not in _ARITH_OPS:
            rewritten = _device_transform_rewrite(e)
            if rewritten is None:
                raise PlanError(f"transform {e.name} -> host path")
            return _compile_value(rewritten, segment, params, columns)
        args = tuple(_compile_value(a, segment, params, columns) for a in e.args)
        return ("fn", e.name, args)
    raise PlanError(f"cannot compile value expression {e}")
