"""Fused Pallas scan kernel: bit-unpack -> predicate -> aggregate on MXU.

TPU-native re-design of the reference's hottest loop — the per-segment
``Filter -> Projection -> GroupBy/Aggregate`` chain
(``SVScanDocIdIterator.java:36`` predicate scan, ``PinotDataBitSet.java:25``
bit extraction, ``AggregationGroupByOrderByOperator.java:61-128`` execution,
``DefaultGroupByExecutor`` scatter into group slots) — as ONE Pallas kernel
over a ``(segments, tiles)`` grid:

- forward indexes arrive as **planar bit-packed words** (engine/staging.py
  PackedColumn): a tile's value ``j`` lives in word ``j % W`` at bit slot
  ``(j // W) * B``, so the in-VMEM unpack is ``K = 32/B`` static shift+mask
  ops over contiguous words — vector ops only, no gathers;
- the filter tree is compiled to an AND/OR/NOT expression over dictId
  interval tests (sorted dictionaries turn EQ/NEQ/RANGE into intervals, the
  vectorized form of dictionary-based predicate evaluators);
- aggregation values may be **elementwise expressions** of staged columns
  (``sum(lo_extendedprice * lo_discount)``): integer expressions evaluate
  exactly in i32 (plan-time bound check), float expressions in f32;
- sums/counts/avg are a **one-hot matmul on the MXU**: rows
  ``[value rows..., mask] @ one_hot(keys)`` accumulate ``[aggs, groups]``
  partials — the fixed-shape scatter-add replacement for
  ``GroupByResultHolder``. Exactness scheme:
  - **integer sums** split each value into 12-bit limbs (``L`` limbs for a
    plan-time ``max_abs`` bound): every per-tile limb partial is at most
    ``4095 * PALLAS_TILE < 2^24`` — exactly representable in the f32 matmul.
    Limb partials land in per-limb **i32 accumulators with a carry chain**
    (base-2^12 positional rows, normalized every grid step), so provider-
    wide sums are exact up to ~2^62 with no i64 math inside the kernel;
  - **float sums** accumulate with Neumaier-compensated f32 pairs
    (sum row + compensation row), recovering near-f64 accuracy over
    hundreds of millions of rows;
- min/max/minmaxrange reduce on the VPU per 128-group chunk;
- scalar (non-group-by) aggregations are the same kernel with a single
  group (all keys 0);
- per-segment matched-doc counts accumulate into a segment-indexed i32
  output (QueryStats parity with the jnp path).

The same kernel body serves the per-segment executor (grid [1, T]) and the
sharded combine (grid [S_local, T_local] per device under shard_map, partials
merged with psum/pmin/pmax over ICI — see parallel/combine.py).

Eligibility is decided per plan (``extract_plan``); anything else falls back
to the jnp masked-vector kernels (engine/kernels.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.common.bounds import I64_FOLD_BOUND
from pinot_tpu.engine.staging import LIMB_BITS, PALLAS_TILE, StagedSegment

# one-hot chunk width along the group dimension (lane count)
_G_CHUNK = 128
# max padded group count the pallas path handles (VMEM + unroll bound);
# 8192 covers every SSB flight except the Q3.2+/Q4.3 city/brand key spaces
# (those ride the jnp sparse-group ladder, engine/kernels.py)
MAX_PALLAS_GROUPS = 8192
# int values are split into limbs of this many bits so every per-tile limb
# matmul partial is f32-exact: (2^12 - 1) * PALLAS_TILE < 2^24
# (staging.LIMB_BITS is the same constant — the host-side limb-plane split
# for i64 columns must mirror the in-kernel split bit-for-bit)
_LIMB_BITS = LIMB_BITS
_LIMB_MASK = (1 << _LIMB_BITS) - 1
# f32 can represent integers exactly below 2^24 (min/max value bound)
_F32_EXACT = 1 << 24
_I32_MAX = (1 << 31) - 1

_POS = np.float32(np.inf)
_NEG = np.float32(-np.inf)

assert _LIMB_MASK * PALLAS_TILE < _F32_EXACT, "limb partials must be f32-exact"


@dataclass(frozen=True)
class PallasSpec:
    """Hashable kernel-cache key (all static shapes/strides/tree)."""

    num_segs: int                         # grid segment dim
    tiles_per_seg: int                    # grid tile dim
    packed_bits: Tuple[int, ...]          # per packed input column
    # nested tuples: ("true",) | ("and"|"or", (children...)) | ("not", (c,))
    # | ("iv", packed_input_idx, param_slot)
    filter_tree: Tuple
    n_slots: int                          # interval param slots
    group_idx: Tuple[int, ...]            # packed input idx per group col
    group_strides: Tuple[int, ...]
    # sum(base_i * stride_i): subtracted from the composed key — nonzero
    # when plan.py filter-narrowed a group column's dictId range (masked
    # docs may then compose negative keys; the one-hot match drops them)
    group_key_offset: int
    num_groups_padded: int                # multiple of 128
    # per agg: (base, vexpr, limbs); base in count/sum/avg/min/max/minmaxrange;
    # vexpr is a nested value expression: ("v", input_idx) |
    # ("times"|"plus"|"minus", lhs, rhs); limbs = L for exact int sums,
    # None for float sums and non-sum aggregations
    aggs: Tuple[Tuple[str, Optional[Tuple], Optional[int]], ...]
    value_is_int: Tuple[bool, ...]        # per value input
    # per value input: 0 = one staged f32/i32 array ref; L > 0 = the input
    # is an i64-staged column shipped as L pre-split 12-bit limb PLANES
    # (i32 refs, host-split with the kernel's exact shift/mask scheme) —
    # its sums accumulate limb-by-limb with no i64 math in-kernel
    value_limbs: Tuple[int, ...] = ()
    interpret: bool = False


class _Ineligible(Exception):
    pass


# max interval runs a boolean dictId LUT decomposes into as STATIC spec
# leaves (each run is one compare pair baked into the filter tree); more
# runs fall back to the padded interval-set node below
_MAX_LUT_RUNS = 8
# default runtime cap on interval runs the padded "ivs" (interval-bitmap)
# fallback accepts: each run is one SMEM compare pair per tile, so the cap
# bounds in-kernel work. Configurable via
# pinot.server.query.pallas.lut.max.runs (callers thread it through).
DEFAULT_LUT_RUN_CAP = 64


def _lut_runs(lut: np.ndarray,
              cap: int = DEFAULT_LUT_RUN_CAP) -> Optional[List[Tuple[int, int]]]:
    """Boolean LUT -> [(lo, hi)] inclusive dictId runs, or None if more
    than ``cap`` (fall back to the jnp LUT-gather kernel)."""
    idx = np.nonzero(np.asarray(lut, dtype=bool))[0]
    if idx.size == 0:
        return []
    breaks = np.nonzero(np.diff(idx) > 1)[0]
    if breaks.size + 1 > cap:
        return None
    runs = []
    start = 0
    for b in list(breaks) + [idx.size - 1]:
        runs.append((int(idx[start]), int(idx[b])))
        start = b + 1
    return runs


# --------------------------------------------------------------------------
# plan -> (core spec fields, static params, column names)
# --------------------------------------------------------------------------

@dataclass
class PallasPlan:
    """Staging-independent extraction of a SegmentPlan: what to pack, what
    to stage as values, the static interval params, and the spec core."""

    packed_names: List[str]
    value_names: List[str]
    value_is_int: Tuple[bool, ...]
    filter_tree: Tuple
    n_slots: int
    group_idx: Tuple[int, ...]
    group_strides: Tuple[int, ...]
    group_key_offset: int
    num_groups_padded: int
    aggs: Tuple[Tuple[str, Optional[Tuple], Optional[int]], ...]
    static_params: np.ndarray             # [2 * n_slots] i32 interval bounds
    # per value input: limb-plane count (0 = plain f32/i32 array)
    value_limbs: Tuple[int, ...] = ()

    def spec(self, num_segs: int, tiles_per_seg: int,
             interpret: bool) -> PallasSpec:
        return PallasSpec(
            num_segs=num_segs, tiles_per_seg=tiles_per_seg,
            packed_bits=(), filter_tree=self.filter_tree,
            n_slots=self.n_slots, group_idx=self.group_idx,
            group_strides=self.group_strides,
            group_key_offset=self.group_key_offset,
            num_groups_padded=self.num_groups_padded,
            aggs=self.aggs, value_is_int=self.value_is_int,
            value_limbs=self.value_limbs,
            interpret=interpret)


def _limbs_for(max_abs: int) -> int:
    """Number of 12-bit value limbs covering |v| <= max_abs (top limb holds
    the sign; intermediate limbs are the non-negative two's-complement
    slices, so ``L * 12`` bits must cover ``max_abs`` itself)."""
    return max(1, -(-max(max_abs.bit_length(), 1) // _LIMB_BITS))


def extract_plan(plan, provider, on_decline=None,
                 lut_run_cap: int = DEFAULT_LUT_RUN_CAP,
                 unchecked_groups: bool = False) -> Optional[PallasPlan]:
    """SegmentPlan -> PallasPlan, or None when the query shape isn't covered
    by the fused kernel. ``provider`` supplies column metadata (an
    ImmutableSegment or a SegmentBatch with unified stats). ``on_decline``
    (if given) receives the machine-readable reason code whenever None is
    returned — the path-decision ledger's hook; every ineligibility is
    classified, never ``unknown``. ``lut_run_cap`` bounds the interval-set
    fallback for many-run LUT predicates. ``unchecked_groups`` skips the
    MAX_PALLAS_GROUPS bound — the group-range probe path extracts the full
    plan first, derives a probe kernel from it, and re-extracts against the
    probe-narrowed plan (never build a grouped kernel from an unchecked
    extraction directly)."""
    from pinot_tpu.engine.kernels import _ParamCursor
    from pinot_tpu.engine.staging import staged_int_dtype

    def decline(reason: str) -> None:
        if on_decline is not None:
            on_decline(reason)

    filter_spec, agg_specs, group_specs, num_groups, _ = plan.spec
    if group_specs and num_groups > MAX_PALLAS_GROUPS \
            and not unchecked_groups:
        decline("pallas_too_many_groups")
        return None
    if any(a[0] in ("distinctcount", "distinctcounthll")
           for a in agg_specs):
        decline("pallas_distinct_agg")
        return None  # 3-tuple specs (col, card/log2m) — jnp path serves
    if provider.metadata.num_docs > _I32_MAX:
        decline("pallas_docs_over_i32")
        return None  # count/carry-chain bounds assume i32 doc counts

    try:
        packed_names: List[str] = []

        def packed_idx(col: str) -> int:
            cm = provider.metadata.column(col)
            if not (cm.has_dictionary and cm.single_value):
                raise _Ineligible("unpackable column")
            if col not in packed_names:
                packed_names.append(col)
            return packed_names.index(col)

        # -- filter tree -> interval expression (mirrors the jnp kernel's
        # param consumption order exactly)
        pc = _ParamCursor(plan.params)
        intervals: List[Tuple[int, int]] = []

        def iv_leaf(col: str, lo: int, hi: int) -> Tuple:
            slot = len(intervals)
            intervals.append((lo, hi))
            return ("iv", packed_idx(col), slot)

        def walk(node) -> Tuple:
            op = node[0]
            if op == "true":
                return ("true",)
            if op in ("and", "or"):
                return (op, tuple(walk(c) for c in node[1]))
            if op == "not":
                return ("not", (walk(node[1][0]),))
            if op in ("eq", "neq"):
                did = int(pc.take())
                leaf = iv_leaf(node[1], did, did)
                return ("not", (leaf,)) if op == "neq" else leaf
            if op == "range":
                iv = np.asarray(pc.take())
                return iv_leaf(node[1], int(iv[0]), int(iv[1]))
            if op == "lut":
                # boolean LUT over a SORTED dictionary = union of dictId
                # runs; small run counts become OR-of-intervals (covers
                # IN / merged-EQ / many REGEXP predicates); past
                # _MAX_LUT_RUNS and up to ``lut_run_cap`` the runs ride ONE
                # padded interval-set node ("ivs") — the interval-bitmap
                # fallback: a pow2-padded block of runtime interval slots
                # (empty pads encoded (1, 0)) OR-reduced in-kernel, so the
                # spec stays stable across literal sets with similar run
                # counts instead of baking each run into the tree shape
                lut = np.asarray(pc.take())
                runs = _lut_runs(lut, max(_MAX_LUT_RUNS, lut_run_cap))
                if runs is None:
                    raise _Ineligible("lut with too many runs")
                if not runs:
                    return ("not", (("true",),))
                if len(runs) <= _MAX_LUT_RUNS:
                    leaves = tuple(iv_leaf(node[1], lo, hi)
                                   for lo, hi in runs)
                    return leaves[0] if len(leaves) == 1 else ("or", leaves)
                pi = packed_idx(node[1])
                n_pad = 1 << (len(runs) - 1).bit_length()
                slot0 = len(intervals)
                for lo, hi in runs:
                    intervals.append((lo, hi))
                for _ in range(n_pad - len(runs)):
                    intervals.append((1, 0))   # empty interval pad
                return ("ivs", pi, slot0, n_pad)
            raise _Ineligible(op)

        tree = walk(filter_spec)

        # -- group columns (params: strides + bases arrays)
        group_idx: List[int] = []
        strides: List[int] = []
        key_offset = 0
        if group_specs:
            for strat, col in group_specs:
                if strat != "gdict":
                    raise _Ineligible("raw group key")
                group_idx.append(packed_idx(col))
            strides = [int(s) for s in np.asarray(pc.take())]
            # gdict bases are nonzero when the planner filter-narrowed the
            # column's dictId range; fold them into one static key offset
            bases = [int(b) for b in np.asarray(pc.take())]
            key_offset = sum(b * s for b, s in zip(bases, strides))
            G = -(-num_groups // _G_CHUNK) * _G_CHUNK
        else:
            G = _G_CHUNK  # single group at key 0

        # -- aggregation value expressions (ref: the reference evaluates
        # transform expressions inside the aggregation operator,
        # AggregationFunctionUtils + TransformOperator; here int exprs run
        # exactly in i32, float exprs in f32, inside the fused kernel).
        # i64-staged columns (stats beyond i32) ship as pre-split 12-bit
        # limb PLANES (staging.value_limb_planes) and ride the existing
        # multi-limb i32 accumulation at the value-load layer: the limb
        # rows come straight from the planes, no i64 math in-kernel.
        value_names: List[str] = []
        value_is_int: List[bool] = []
        value_limbs: List[int] = []

        def leaf_idx(name: str) -> Tuple[Tuple, bool, Optional[int]]:
            cm = provider.metadata.column(name)
            if not (cm.single_value and cm.data_type.is_numeric):
                raise _Ineligible("non-numeric/MV agg value column")
            is_int = cm.data_type.is_integral
            max_abs: Optional[int] = None
            limbs = 0
            if is_int:
                if cm.min_value is None or cm.max_value is None:
                    raise _Ineligible("no stats for int value bound")
                max_abs = max(abs(int(cm.min_value)), abs(int(cm.max_value)))
                if staged_int_dtype(cm) != np.dtype(np.int32):
                    # exact reassembly needs the provider-wide sum inside
                    # i64 (the carry-chain rows shift by up to 62 bits)
                    if max_abs * max(1, provider.metadata.num_docs) \
                            >= I64_FOLD_BOUND:
                        raise _Ineligible("i64 sum bound over i64")
                    limbs = _limbs_for(max_abs)
            if name not in value_names:
                value_names.append(name)
                value_is_int.append(is_int)
                value_limbs.append(limbs)
            vi = value_names.index(name)
            leaf = ("v64", vi) if limbs else ("v", vi)
            return leaf, is_int, max_abs

        def compile_vexpr(vspec) -> Tuple[Tuple, bool, Optional[int]]:
            if vspec is None:
                raise _Ineligible("missing agg value")
            if vspec[0] == "col":
                return leaf_idx(vspec[1])
            if vspec[0] == "lit":
                # literal params become SPEC constants: units/factors are
                # low-cardinality, so keying the kernel cache on them is
                # cheap and keeps the kernel free of an extra params lane
                # (the cursor position mirrors the jnp kernel's consumption
                # order exactly)
                v = float(np.asarray(pc.take()))
                if v.is_integer() and abs(v) <= _I32_MAX:
                    return ("litc", int(v)), True, abs(int(v))
                return ("litf", v), False, None
            if (vspec[0] == "fn" and vspec[1] in ("times", "plus", "minus")
                    and len(vspec[2]) == 2):
                le, li, lm = compile_vexpr(vspec[2][0])
                re_, ri, rm = compile_vexpr(vspec[2][1])
                if li and ri:
                    max_abs = lm * rm if vspec[1] == "times" else lm + rm
                    if max_abs > _I32_MAX:
                        # in-kernel i32 arithmetic would wrap (an i64
                        # operand always lands here: its bound alone
                        # exceeds i32, so limb planes stay sum-only)
                        raise _Ineligible("int expr bound exceeds i32")
                    return (vspec[1], le, re_), True, max_abs
                if _has_v64(le) or _has_v64(re_):
                    # limb planes carry no per-doc value to convert to f32
                    raise _Ineligible("i64 column in float expression")
                return (vspec[1], le, re_), False, None
            # mod/floordiv deliberately stay jnp-served: Mosaic integer
            # division support is not guaranteed, and one lowering failure
            # at run time would disable pallas for the whole process
            raise _Ineligible(f"agg value {vspec[0]!r}")

        aggs: List[Tuple[str, Optional[Tuple], Optional[int]]] = []
        for aspec in agg_specs:
            base, mv, vspec = aspec[0], aspec[1], aspec[2]
            if mv:
                raise _Ineligible("mv aggregation")
            if base == "count":
                aggs.append(("count", None, None))
                continue
            if base not in ("sum", "avg", "min", "max", "minmaxrange"):
                raise _Ineligible(base)
            vexpr, is_int, max_abs = compile_vexpr(vspec)
            if base in ("sum", "avg"):
                aggs.append((base, vexpr, _limbs_for(max_abs) if is_int
                             else None))
            else:
                # min/max rows reduce in f32: int values >= 2^24 would round
                # (the jnp kernel keeps them exact in i32) -> ineligible;
                # i64 limb planes are sum-only (covered by this bound too)
                if is_int and max_abs >= _F32_EXACT:
                    raise _Ineligible("int min/max not f32-exact")
                aggs.append((base, vexpr, None))
        # runtime protocol mirror: every eligible plan must have walked
        # the cursor to the end (an unconsumed tail is pack/unpack drift,
        # not ineligibility — let the AssertionError propagate)
        pc.finish()
    except _Ineligible as e:
        from pinot_tpu.common.tracing import classify_decline

        reason = classify_decline(str(e))
        if not reason.startswith("pallas_"):
            # messages raised with bare op names (filter/agg ops outside
            # the covered set) classify through the generic fallback;
            # namespace them so the histogram reads per decision point
            reason = f"pallas_{reason}"
        decline(reason)
        return None

    params = np.asarray([v for lo, hi in intervals for v in (lo, hi)],
                        dtype=np.int32).reshape(-1)
    return PallasPlan(
        packed_names=packed_names, value_names=value_names,
        value_is_int=tuple(value_is_int), filter_tree=tree,
        n_slots=len(intervals), group_idx=tuple(group_idx),
        group_strides=tuple(strides), group_key_offset=key_offset,
        num_groups_padded=G,
        aggs=tuple(aggs), static_params=params,
        value_limbs=tuple(value_limbs))


def _has_v64(vexpr: Tuple) -> bool:
    if vexpr[0] == "v64":
        return True
    if vexpr[0] in ("v", "litc", "litf", "id"):
        return False
    return _has_v64(vexpr[1]) or _has_v64(vexpr[2])


# --------------------------------------------------------------------------
# group-range probe: the narrowing pass that puts LARGE-but-sparse composed
# key spaces (SSB Q3.2/Q4.3: city x city x year, brand x city x year) on the
# dense one-hot rung. The filter makes those spaces sparse (only one
# nation's cities, one category's brands survive), but plan-time narrowing
# can only use predicates ON the group columns themselves. The probe runs
# the SAME fused scan (unpack + filter) with per-group-column masked
# min/max-of-dictId aggregations — a tiny min/max-row kernel, no matmul —
# and the host narrows each column's key range to the observed [lo, hi]
# before building the real kernel (plan.narrow_plan_groups rewrites
# strides/bases, so decode/merge machinery applies unchanged). Sorted
# dictionaries make the correlated value sets contiguous, so the narrowed
# product collapses to the live group count's scale.
# --------------------------------------------------------------------------

def probe_plan_of(pp: PallasPlan) -> PallasPlan:
    """Derive the group-range probe plan from an (unchecked-groups) full
    extraction: same packed columns / filter tree / interval params, no
    value inputs, and one (min, max) masked-dictId aggregation pair per
    group column via the ``("id", packed_idx)`` value node."""
    aggs: List[Tuple[str, Optional[Tuple], Optional[int]]] = []
    for gi in pp.group_idx:
        aggs.append(("min", ("id", gi), None))
        aggs.append(("max", ("id", gi), None))
    return PallasPlan(
        packed_names=list(pp.packed_names), value_names=[],
        value_is_int=(), filter_tree=pp.filter_tree, n_slots=pp.n_slots,
        group_idx=(), group_strides=(), group_key_offset=0,
        num_groups_padded=_G_CHUNK, aggs=tuple(aggs),
        static_params=pp.static_params, value_limbs=())


def decode_probe_ranges(spec: PallasSpec, out_mm,
                        n_cols: int) -> List[Tuple[int, int]]:
    """Probe kernel output -> per-group-column inclusive (lo, hi) observed
    dictId ranges. A column no matched row touched (min row still +inf)
    collapses to (0, 0) — a 1-slot key space is enough for an empty
    result."""
    _, _, mm_row, _, _, _ = _row_layout(spec)
    mm = np.asarray(out_mm)
    ranges: List[Tuple[int, int]] = []
    for i in range(n_cols):
        vexpr = spec.aggs[2 * i][1]
        lo = float(mm[mm_row[(vexpr, "min")], 0])
        hi = float(mm[mm_row[(vexpr, "max")], 0])
        if not (np.isfinite(lo) and np.isfinite(hi)) or lo > hi:
            ranges.append((0, 0))
        else:
            ranges.append((int(lo), int(hi)))
    return ranges


def probe_narrowed_plan(plan, provider, run_probe, lut_run_cap, decline
                        ) -> Optional[Tuple]:
    """Group-range narrowing orchestration shared by the per-segment and
    sharded callers: full unchecked extraction -> probe kernel (executed
    by ``run_probe(probe_pp, probe_spec_fn)``, which stages the packed
    inputs its own way and returns the out_mm rows) -> narrowed effective
    SegmentPlan -> re-extraction. Returns (PallasPlan, effective plan) or
    None (with the reason on ``decline``)."""
    from pinot_tpu.engine.plan import narrow_plan_groups

    pp_full = extract_plan(plan, provider, on_decline=decline,
                           lut_run_cap=lut_run_cap, unchecked_groups=True)
    if pp_full is None:
        return None
    # min/max rows reduce in f32: dictIds past 2^24 would round
    for card in plan.group_cards:
        if card >= _F32_EXACT:
            decline("pallas_too_many_groups")
            return None
    probe_pp = probe_plan_of(pp_full)
    out_mm = run_probe(probe_pp)
    if out_mm is None:
        return None   # run_probe recorded its own reason
    ranges = decode_probe_ranges(
        probe_pp.spec(num_segs=1, tiles_per_seg=1, interpret=True),
        out_mm, len(plan.group_cards))
    eff = narrow_plan_groups(plan, ranges)
    if eff.num_groups > MAX_PALLAS_GROUPS:
        decline("pallas_too_many_groups")
        return None
    pp = extract_plan(eff, provider, on_decline=decline,
                      lut_run_cap=lut_run_cap)
    if pp is None:
        return None
    return pp, eff


class _DeferredDecline:
    """Capture extract declines so the probe path can retry on
    ``pallas_too_many_groups`` without double-recording; ``flush`` forwards
    the captured reason when no retry succeeded."""

    def __init__(self, on_decline):
        self.on_decline = on_decline
        self.reasons: List[str] = []

    def __call__(self, reason: str) -> None:
        self.reasons.append(reason)

    @property
    def only_group_bound(self) -> bool:
        return self.reasons == ["pallas_too_many_groups"]

    def flush(self) -> None:
        if self.on_decline is not None:
            for r in self.reasons:
                self.on_decline(r)


# --------------------------------------------------------------------------
# kernel builder
# --------------------------------------------------------------------------

def _row_layout(spec: PallasSpec):
    """Single source of truth for the accumulator layout:
    - out_f [Mf, G] f32: per float sum a (sum, compensation) Neumaier ROW
      PAIR at (r, r+1) (>=1 row, dummy if none)
    - out_i [Mi, G] i32: row 0 = count; per int sum a base-2^12 carry-chain
      of ``L + 2`` accumulator rows starting at ``start`` (limb ``k``'s
      partials add at ``start + k``; the two extra rows absorb carries)
    - out_mm [Mm, G] f32: (vexpr, kind) min/max rows (>=1 row, dummy if none)
    Returns (fsum_row, isum_row, mm_row, Mf, Mi, Mm) where fsum_row maps
    vexpr -> sum-row index, isum_row maps vexpr -> (start_row, L), mm_row
    maps (vexpr, 'min'|'max') -> row index."""
    fsum_row: Dict[Tuple, int] = {}
    isum_row: Dict[Tuple, Tuple[int, int]] = {}
    mm_row: Dict[Tuple[Tuple, str], int] = {}
    next_i = 1
    for base, vexpr, limbs in spec.aggs:
        if base in ("sum", "avg"):
            if limbs is not None:
                if vexpr not in isum_row:
                    isum_row[vexpr] = (next_i, limbs)
                    next_i += limbs + 2
            else:
                fsum_row.setdefault(vexpr, 2 * len(fsum_row))
        elif base == "min":
            mm_row.setdefault((vexpr, "min"), len(mm_row))
        elif base == "max":
            mm_row.setdefault((vexpr, "max"), len(mm_row))
        elif base == "minmaxrange":
            mm_row.setdefault((vexpr, "min"), len(mm_row))
            mm_row.setdefault((vexpr, "max"), len(mm_row))
    Mf = max(2 * len(fsum_row), 1)
    Mi = next_i
    Mm = max(len(mm_row), 1)
    return fsum_row, isum_row, mm_row, Mf, Mi, Mm


def _expr_is_int(vexpr: Tuple, value_is_int: Tuple[bool, ...]) -> bool:
    if vexpr[0] == "v":
        return value_is_int[vexpr[1]]
    if vexpr[0] == "litc":
        return True
    if vexpr[0] == "litf":
        return False
    return (_expr_is_int(vexpr[1], value_is_int)
            and _expr_is_int(vexpr[2], value_is_int))


def build_kernel(spec: PallasSpec):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T = PALLAS_TILE
    RT = T // 128
    G = spec.num_groups_padded
    n_chunks = G // _G_CHUNK
    n_packed = len(spec.packed_bits)
    n_values = len(spec.value_is_int)
    # per value input: how many refs it occupies (1 plain array, or L
    # pre-split 12-bit limb planes for i64-staged columns) and where its
    # ref block starts
    vlimbs = spec.value_limbs or (0,) * n_values
    v_start: List[int] = []
    n_value_refs = 0
    for l in vlimbs:
        v_start.append(n_value_refs)
        n_value_refs += l if l else 1
    S = spec.num_segs
    TPS = spec.tiles_per_seg

    fsum_row, isum_row, mm_row, Mf, Mi, Mm = _row_layout(spec)
    nf = len(fsum_row)
    # matmul row plan: [nf float rows][1 count row][per int sum: L limb rows]
    int_sums = sorted(isum_row.items(), key=lambda kv: kv[1][0])
    # params: [2*n_slots intervals][S num_docs][1 doc_base]
    nd_off = 2 * spec.n_slots

    def kernel(params_ref, *refs):
        packed = refs[:n_packed]
        values = refs[n_packed:n_packed + n_value_refs]
        out_f, out_i, out_mm, out_seg = refs[n_packed + n_value_refs:]
        s = pl.program_id(0)
        t = pl.program_id(1)

        @pl.when((s == 0) & (t == 0))
        def _init_global():
            out_f[...] = jnp.zeros_like(out_f)
            out_i[...] = jnp.zeros_like(out_i)
            for (vexpr, kind), r in mm_row.items():
                out_mm[r, :] = jnp.full((G,), _POS if kind == "min" else _NEG,
                                        dtype=jnp.float32)
            if not mm_row:
                out_mm[...] = jnp.zeros_like(out_mm)

        @pl.when(t == 0)
        def _init_seg():
            out_seg[...] = jnp.zeros_like(out_seg)

        # -- unpack planar words -> dictIds [RT, 128] i32 per column
        ids = []
        for ci, bits in enumerate(spec.packed_bits):
            K = 32 // bits
            vmask = jnp.uint32((1 << bits) - 1)
            w = packed[ci][0, 0]                   # [W/128, 128] u32
            planes = [((w >> jnp.uint32(k * bits)) & vmask).astype(jnp.int32)
                      for k in range(K)]
            ids.append(planes[0] if K == 1 else
                       jnp.concatenate(planes, axis=0))  # [RT, 128]

        # -- validity + filter expression
        num_docs = params_ref[nd_off + s]
        doc_base = params_ref[nd_off + S]
        row = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0)
        lane = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 1)
        doc = doc_base + t * T + row * 128 + lane
        valid = doc < num_docs

        def emit(node):
            op = node[0]
            if op == "true":
                return jnp.ones((RT, 128), dtype=bool)
            if op == "and":
                m = emit(node[1][0])
                for c in node[1][1:]:
                    m = m & emit(c)
                return m
            if op == "or":
                m = emit(node[1][0])
                for c in node[1][1:]:
                    m = m | emit(c)
                return m
            if op == "not":
                return ~emit(node[1][0])
            if op == "ivs":
                # interval-set fallback for many-run LUTs: OR over a
                # pow2-padded block of runtime interval slots (pads are
                # empty (1, 0) intervals matching nothing)
                _, pi, slot0, n_runs = node
                m = jnp.zeros((RT, 128), dtype=bool)
                for j in range(n_runs):
                    lo = params_ref[2 * (slot0 + j)]
                    hi = params_ref[2 * (slot0 + j) + 1]
                    m = m | ((ids[pi] >= lo) & (ids[pi] <= hi))
                return m
            _, pi, slot = node                     # "iv"
            lo = params_ref[2 * slot]
            hi = params_ref[2 * slot + 1]
            return (ids[pi] >= lo) & (ids[pi] <= hi)

        mask = emit(spec.filter_tree) & valid
        mask_f = mask.astype(jnp.float32)

        # -- value expressions [RT, 128]: int exprs evaluate exactly in i32
        # (plan-time bound check), float exprs in f32 (the vectorized form
        # of the reference's transform-then-aggregate chain)
        vexpr_cache: Dict[Tuple, Any] = {}

        def emit_vexpr(vexpr):
            v = vexpr_cache.get(vexpr)
            if v is not None:
                return v
            if vexpr[0] == "v64":
                # limb planes carry no single per-doc value; extract_plan
                # keeps them sum-only (their limb rows read planes directly)
                raise AssertionError("v64 leaves never emit as values")
            if vexpr[0] == "id":
                # unpacked dictIds as a value row (the group-range probe's
                # masked min/max-of-id aggregations)
                v = ids[vexpr[1]]
            elif vexpr[0] == "v":
                v = values[v_start[vexpr[1]]][0, 0]
            elif vexpr[0] == "litc":
                v = jnp.int32(vexpr[1])
            elif vexpr[0] == "litf":
                v = jnp.float32(vexpr[1])
            else:
                a = emit_vexpr(vexpr[1])
                b = emit_vexpr(vexpr[2])
                if not (_expr_is_int(vexpr[1], spec.value_is_int)
                        and _expr_is_int(vexpr[2], spec.value_is_int)):
                    a = a.astype(jnp.float32)
                    b = b.astype(jnp.float32)
                if vexpr[0] == "times":
                    v = a * b
                elif vexpr[0] == "plus":
                    v = a + b
                else:
                    v = a - b
            vexpr_cache[vexpr] = v
            return v

        # -- composed group keys (all zero for scalar aggregation); masked
        # docs outside a narrowed key range go negative and simply match no
        # one-hot column (their rows are mask-zeroed anyway)
        keys = jnp.zeros((RT, 128), dtype=jnp.int32)
        for gi, stride in zip(spec.group_idx, spec.group_strides):
            keys = keys + ids[gi] * jnp.int32(stride)
        if spec.group_key_offset:
            keys = keys - jnp.int32(spec.group_key_offset)

        # -- per-segment matched docs (QueryStats parity), exact i32
        # (dtype pinned: under jax x64 an int32 sum promotes to int64 and
        # the ref swap rejects the mismatch)
        out_seg[0, :] += mask.astype(jnp.int32).sum(axis=0, dtype=jnp.int32)

        # -- matmul row stack [nf + 1 + sum(L), RT, 128] f32
        rows = []
        for vexpr, _r in sorted(fsum_row.items(), key=lambda kv: kv[1]):
            rows.append(emit_vexpr(vexpr).astype(jnp.float32) * mask_f)
        rows.append(mask_f)                        # count row (out_i row 0)
        for vexpr, (start, L) in int_sums:
            if vexpr[0] == "v64":
                # i64-staged column: the limb rows ARE the staged planes
                # (host-split with the identical shift/mask scheme), so the
                # accumulation below is bit-for-bit the in-kernel split
                base_ref = v_start[vexpr[1]]
                for k in range(L):
                    plane = values[base_ref + k][0, 0]
                    rows.append(jnp.where(mask, plane, 0)
                                .astype(jnp.float32))
                continue
            v = jnp.where(mask, emit_vexpr(vexpr), 0)
            for k in range(L):
                if k < L - 1:
                    limb = (v >> (k * _LIMB_BITS)) & _LIMB_MASK
                else:
                    limb = v >> (k * _LIMB_BITS)   # top limb keeps the sign
                rows.append(limb.astype(jnp.float32))
        R = jnp.stack(rows)                        # [M_mat, RT, 128]

        for c in range(n_chunks):
            g0 = c * _G_CHUNK
            g_iota = g0 + jax.lax.broadcasted_iota(
                jnp.int32, (RT, 128, _G_CHUNK), 2)
            oh = (keys[:, :, None] == g_iota).astype(jnp.float32)
            part = jax.lax.dot_general(
                R, oh, (((1, 2), (0, 1)), ((), ())),
                preferred_element_type=jnp.float32)   # [M_mat, 128]

            # float sums: Neumaier-compensated accumulation (sum, comp pair)
            for j, (vexpr, r) in enumerate(
                    sorted(fsum_row.items(), key=lambda kv: kv[1])):
                x = part[j]
                a = out_f[r, g0:g0 + _G_CHUNK]
                t_ = a + x
                err = jnp.where(jnp.abs(a) >= jnp.abs(x),
                                (a - t_) + x, (x - t_) + a)
                out_f[r, g0:g0 + _G_CHUNK] = t_
                out_f[r + 1, g0:g0 + _G_CHUNK] += err

            # count + int limb partials: f32 -> exact i32 (every partial is
            # an integer < 2^24 by the limb-width bound)
            out_i[0, g0:g0 + _G_CHUNK] += part[nf].astype(jnp.int32)
            m = nf + 1
            for vexpr, (start, L) in int_sums:
                for k in range(L):
                    out_i[start + k, g0:g0 + _G_CHUNK] += \
                        part[m].astype(jnp.int32)
                    m += 1

            # -- min/max rows reduce on the VPU per chunk
            for (vexpr, kind), r in mm_row.items():
                neutral = _POS if kind == "min" else _NEG
                v = emit_vexpr(vexpr).astype(jnp.float32)
                vm = jnp.where(mask, v, neutral)
                eq = keys[:, :, None] == g_iota
                v3 = jnp.where(eq, vm[:, :, None], neutral)
                red = (v3.min(axis=(0, 1)) if kind == "min"
                       else v3.max(axis=(0, 1)))
                cur = out_mm[r, g0:g0 + _G_CHUNK]
                out_mm[r, g0:g0 + _G_CHUNK] = (
                    jnp.minimum(cur, red) if kind == "min"
                    else jnp.maximum(cur, red))

        # -- carry-chain normalization: every limb accumulator returns to
        # [0, 2^12) (arithmetic shift floors, so signed top limbs carry
        # correctly); the chain's top row absorbs the running magnitude,
        # keeping every row i32-bounded regardless of provider size
        for vexpr, (start, L) in int_sums:
            for k in range(L + 1):                 # rows start .. start+L
                acc = out_i[start + k, :]
                carry = acc >> _LIMB_BITS
                out_i[start + k, :] = acc - (carry << _LIMB_BITS)
                out_i[start + k + 1, :] += carry

    def block(shape0):
        nd = len(shape0)
        return pl.BlockSpec((1, 1) + shape0,
                            lambda s, t: (s, t) + (0,) * nd,
                            memory_space=pltpu.VMEM)

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    for bits in spec.packed_bits:
        W = T // (32 // bits)
        in_specs.append(block((W // 128, 128)))
    for _ in range(n_value_refs):
        in_specs.append(block((RT, 128)))

    out_specs = (
        pl.BlockSpec((Mf, G), lambda s, t: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((Mi, G), lambda s, t: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((Mm, G), lambda s, t: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 128), lambda s, t: (s, 0), memory_space=pltpu.VMEM),
    )
    out_shape = (
        jax.ShapeDtypeStruct((Mf, G), jnp.float32),
        jax.ShapeDtypeStruct((Mi, G), jnp.int32),
        jax.ShapeDtypeStruct((Mm, G), jnp.float32),
        jax.ShapeDtypeStruct((S, 128), jnp.int32),
    )

    return pl.pallas_call(
        kernel,
        grid=(S, TPS),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=spec.interpret,
    )


class PallasKernelCache:
    def __init__(self):
        self._cache: Dict[PallasSpec, Any] = {}

    def get(self, spec: PallasSpec):
        k = self._cache.get(spec)
        if k is None:
            k = jax.jit(build_kernel(spec))
            self._cache[spec] = k
        return k

    def pop(self, spec: PallasSpec) -> None:
        """Evict a kernel whose compile/run failed (the caller blocklists
        the plan shape; keeping the entry would only leak the closure)."""
        self._cache.pop(spec, None)

    def __len__(self):
        return len(self._cache)


# --------------------------------------------------------------------------
# output assembly: pallas accumulators -> jnp-kernel-shaped output tree
# --------------------------------------------------------------------------

def assemble_outputs(plan_spec: Tuple, spec: PallasSpec, out_f, out_i, out_mm,
                     seg_matched) -> Dict[str, Any]:
    """Map the pallas accumulators onto the jnp kernel's output tree so
    pack_outputs/unpack_outputs/decode apply unchanged. ``seg_matched`` is
    the [S] per-segment matched-doc count (summed over lanes, and over mesh
    axes by the sharded caller). Int sums re-combine their carry-chain rows
    as ``sum_k row_k * 2^(12k)`` in i64 (exact; the packed f64 output then
    carries them exactly to 2^53, the reference's own double-SUM contract)."""
    _, agg_specs, group_specs, num_groups, _ = plan_spec
    fsum_row, isum_row, mm_row, _, _, _ = _row_layout(spec)
    grouped = bool(group_specs)
    n = num_groups if grouped else 1
    counts = out_i[0, :n]

    def sum_leaf(vexpr, limbs):
        if limbs is None:
            r = fsum_row[vexpr]
            return (out_f[r, :n].astype(jnp.float64)
                    + out_f[r + 1, :n].astype(jnp.float64))
        start, L = isum_row[vexpr]
        acc = jnp.zeros((n,), dtype=jnp.int64)
        for k in range(L + 2):
            if k * _LIMB_BITS >= 63:
                # rows past the i64 range are provably zero (eligibility
                # bounds the exact sum inside i64); shifting >= 64 bits is
                # undefined, so skip them instead of lowering the shift
                continue
            acc = acc + (out_i[start + k, :n].astype(jnp.int64)
                         << (k * _LIMB_BITS))
        return acc

    out: Dict[str, Any] = {}
    if grouped:
        out["presence"] = counts
    else:
        out["num_matched"] = counts[0]
    for i, (base, vexpr, limbs) in enumerate(spec.aggs):
        if base == "count":
            leaf: Any = counts
        elif base in ("sum", "avg"):
            leaf = sum_leaf(vexpr, limbs)
            if base == "avg":
                leaf = (leaf, counts)
        elif base == "min":
            leaf = out_mm[mm_row[(vexpr, "min")], :n]
        elif base == "max":
            leaf = out_mm[mm_row[(vexpr, "max")], :n]
        else:  # minmaxrange
            leaf = (out_mm[mm_row[(vexpr, "min")], :n],
                    out_mm[mm_row[(vexpr, "max")], :n])
        if not grouped:
            leaf = (tuple(x[0] for x in leaf) if isinstance(leaf, tuple)
                    else leaf[0])
        out[f"agg{i}"] = leaf
    if seg_matched is not None:
        out["seg_matched"] = seg_matched
    return out


# --------------------------------------------------------------------------
# per-segment runner (engine/executor.py fallback path)
# --------------------------------------------------------------------------

def _stage_packed(pp: PallasPlan, staged: StagedSegment, decline):
    """(packed device blocks, bits) for the plan's packed columns, or None
    (reason recorded)."""
    packed_cols = []
    bits = []
    for nm in pp.packed_names:
        pc = staged.packed_column(nm)
        if pc is None:
            decline("pallas_column_not_packable")
            return None
        bits.append(pc.bits)
        W = PALLAS_TILE // pc.vals_per_word
        packed_cols.append(pc.words.reshape(1, -1, W // 128, 128))
    return packed_cols, bits


def _stage_values(pp: PallasPlan, staged: StagedSegment, decline):
    """Value refs in kernel order: one f32/i32 array per plain input, L
    i32 limb planes per i64-staged input (the value-load layer of the
    multi-limb accumulation). None (reason recorded) when a column can't
    serve the fused layout."""
    vlimbs = pp.value_limbs or (0,) * len(pp.value_names)
    value_cols = []
    for nm, L in zip(pp.value_names, vlimbs):
        if L:
            planes = staged.value_limb_planes(nm, L)
            if planes is None:
                decline("pallas_value_layout_unsupported")
                return None
            value_cols.extend(
                p.reshape(1, -1, PALLAS_TILE // 128, 128) for p in planes)
            continue
        v = staged.value_column(nm)
        if v is None or v.dtype not in (jnp.float32, jnp.int32):
            decline("pallas_value_layout_unsupported")
            return None
        value_cols.append(v.reshape(1, -1, PALLAS_TILE // 128, 128))
    return value_cols


def _segment_params(pp: PallasPlan, staged: StagedSegment):
    return jnp.concatenate([
        jnp.asarray(pp.static_params, dtype=jnp.int32).reshape(-1),
        jnp.asarray([staged.num_docs, 0], dtype=jnp.int32),
    ])


def _run_probe_segment(probe_pp: PallasPlan, staged: StagedSegment,
                       cache: PallasKernelCache, interpret: bool, decline):
    """Launch the group-range probe over one staged segment -> out_mm."""
    got = _stage_packed(probe_pp, staged, decline)
    if got is None:
        return None
    packed_cols, bits = got
    tiles = staged.pallas_capacity() // PALLAS_TILE
    spec = _with_bits(
        probe_pp.spec(num_segs=1, tiles_per_seg=tiles, interpret=interpret),
        tuple(bits))
    kernel = cache.get(spec)
    try:
        _f, _i, out_mm, _s = kernel(_segment_params(probe_pp, staged),
                                    *packed_cols)
    except Exception:
        cache.pop(spec)
        raise
    return out_mm


def run_segment(plan, staged: StagedSegment, cache: PallasKernelCache,
                interpret: bool, on_decline=None,
                lut_run_cap: int = DEFAULT_LUT_RUN_CAP):
    """Run the fused kernel over one staged segment; returns
    ``(packed, effective_plan)`` — the PACKED f64 output vector
    (kernels.pack_outputs layout, single D2H fetch) plus the plan whose
    spec describes it (the original plan, or the probe-narrowed plan for
    large-group shapes; the caller MUST unpack/decode against it) — or
    None when the plan/staging isn't eligible (``on_decline`` receives the
    reason code, same contract as ``extract_plan``)."""
    from pinot_tpu.engine.kernels import pack_outputs

    def decline(reason: str) -> None:
        if on_decline is not None:
            on_decline(reason)

    defer = _DeferredDecline(on_decline)
    pp = extract_plan(plan, staged.segment, on_decline=defer,
                      lut_run_cap=lut_run_cap)
    eff = plan
    if pp is None:
        if not defer.only_group_bound:
            defer.flush()
            return None

        def run_probe(probe_pp):
            return _run_probe_segment(probe_pp, staged, cache, interpret,
                                      decline)

        res = probe_narrowed_plan(plan, staged.segment, run_probe,
                                  lut_run_cap, decline)
        if res is None:
            return None
        pp, eff = res

    got = _stage_packed(pp, staged, decline)
    if got is None:
        return None
    packed_cols, bits = got
    value_cols = _stage_values(pp, staged, decline)
    if value_cols is None:
        return None

    tiles = staged.pallas_capacity() // PALLAS_TILE
    spec = pp.spec(num_segs=1, tiles_per_seg=tiles, interpret=interpret)
    spec = _with_bits(spec, tuple(bits))
    kernel = cache.get(spec)

    try:
        out_f, out_i, out_mm, out_seg = kernel(
            _segment_params(pp, staged), *packed_cols, *value_cols)
    except Exception:
        cache.pop(spec)  # symmetric with the sharded handler's eviction
        raise
    tree = assemble_outputs(eff.spec, spec, out_f, out_i, out_mm,
                            seg_matched=None)
    return pack_outputs(tree, eff.spec), eff


def _with_bits(spec: PallasSpec, bits: Tuple[int, ...]) -> PallasSpec:
    from dataclasses import replace

    return replace(spec, packed_bits=bits)
