"""Fused Pallas scan kernel: bit-unpack -> predicate -> group-by matmul.

TPU-native re-design of the reference's hottest loop — the per-segment
``Filter -> Projection -> GroupBy`` chain (``SVScanDocIdIterator.java:36``
predicate scan, ``PinotDataBitSet.java:25`` bit extraction,
``DefaultGroupByExecutor`` scatter into group slots) — as ONE Pallas kernel:

- forward indexes arrive as **planar bit-packed words** (engine/staging.py
  PackedColumn): a tile's value ``j`` lives in word ``j % W`` at bit slot
  ``(j // W) * B``, so the in-VMEM unpack is ``K = 32/B`` static shift+mask
  ops over contiguous words — vector ops only, no gathers;
- predicates are dictId-interval compares (sorted dictionaries turn EQ/RANGE
  into intervals, the vectorized form of dictionary-based predicate
  evaluators) AND-composed into one doc mask;
- group aggregation is a **one-hot matmul on the MXU**: rows
  ``[mask, masked values...] @ one_hot(keys)`` accumulate ``[aggs, groups]``
  partials — the fixed-shape scatter-add replacement for
  ``GroupByResultHolder``. Integer aggregations keep an exact i32
  accumulator (per-tile matmul results are exactly representable in f32 by
  a plan-time bound, then rounded into i32); float aggregations accumulate
  f32.

Eligibility is decided per plan (`extract_spec`); anything else falls back
to the jnp masked-vector kernels (engine/kernels.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.engine.staging import PALLAS_TILE, StagedSegment

# one-hot chunk width along the group dimension (lane count)
_G_CHUNK = 128
# max padded group count the pallas path handles (VMEM + unroll bound)
MAX_PALLAS_GROUPS = 4096
# per-tile int matmul partials must be exact in f32: max |value| * TILE < 2^24
_F32_EXACT = 1 << 24


@dataclass(frozen=True)
class PallasGroupSpec:
    """Hashable kernel-cache key (all static shapes/strides)."""

    num_tiles: int
    packed_bits: Tuple[int, ...]          # per packed input column
    filters: Tuple[Tuple[int, bool], ...]  # (packed input idx, negate)
    group_idx: Tuple[int, ...]            # packed input idx per group col
    group_strides: Tuple[int, ...]
    num_groups_padded: int                # multiple of 128
    # per agg: ("count", None) | ("sum"|"avg", value input idx)
    aggs: Tuple[Tuple[str, Optional[int]], ...]
    value_is_int: Tuple[bool, ...]        # per value input
    interpret: bool


class _Ineligible(Exception):
    pass


# --------------------------------------------------------------------------
# plan -> PallasGroupSpec (+ runtime params)
# --------------------------------------------------------------------------

def extract_spec(plan, staged: StagedSegment, interpret: bool):
    """(spec, params_i32, packed_cols, value_cols) or None if the plan shape
    isn't covered by the fused kernel."""
    from pinot_tpu.engine.kernels import _ParamCursor

    filter_spec, agg_specs, group_specs, num_groups, capacity = plan.spec
    if not group_specs or num_groups == 0:
        return None
    if num_groups > MAX_PALLAS_GROUPS:
        return None

    try:
        packed_names: List[str] = []

        def packed_idx(col: str) -> int:
            if col not in packed_names:
                packed_names.append(col)
            return packed_names.index(col)

        # -- filter tree -> interval list (mirrors kernels._emit_filter's
        # param consumption order exactly)
        pc = _ParamCursor(plan.params)
        take_param = pc.take

        filters: List[Tuple[int, bool, int, int]] = []  # (idx, neg, lo, hi)

        def walk(node):
            op = node[0]
            if op == "true":
                return
            if op == "and":
                for child in node[1]:
                    walk(child)
                return
            if op in ("eq", "neq"):
                did = int(take_param())
                filters.append((packed_idx(node[1]), op == "neq", did, did))
                return
            if op == "range":
                iv = np.asarray(take_param())
                filters.append((packed_idx(node[1]), False,
                                int(iv[0]), int(iv[1])))
                return
            raise _Ineligible(op)

        walk(filter_spec)

        # -- group columns (params: strides + bases arrays)
        group_idx = []
        for strat, col in group_specs:
            if strat != "gdict":
                raise _Ineligible("raw group key")
            group_idx.append(packed_idx(col))
        strides = [int(s) for s in np.asarray(take_param())]
        take_param()  # bases (gdict bases are 0)

        # -- aggregations
        value_names: List[str] = []
        value_is_int: List[bool] = []
        aggs: List[Tuple[str, Optional[int]]] = []
        for aspec in agg_specs:
            base = aspec[0]
            if base == "count" and not aspec[1] and aspec[2] is None:
                aggs.append(("count", None))
                continue
            if base not in ("sum", "avg") or aspec[1]:
                raise _Ineligible(base)
            vspec, acc = aspec[2], aspec[3]
            if vspec is None or vspec[0] != "col":
                raise _Ineligible("non-column agg value")
            name = vspec[1]
            cm = staged.segment.metadata.column(name)
            if acc in ("i32", "i64"):
                if acc != "i32":
                    raise _Ineligible("i64 accumulator")
                max_abs = max(abs(int(cm.min_value)), abs(int(cm.max_value)))
                if max_abs * PALLAS_TILE >= _F32_EXACT:
                    raise _Ineligible("tile sum not f32-exact")
                is_int = True
            else:
                is_int = False
            if name not in value_names:
                value_names.append(name)
                value_is_int.append(is_int)
            vi = value_names.index(name)
            if value_is_int[vi] != is_int:
                raise _Ineligible("mixed int/float use of one column")
            aggs.append((base, vi))
    except _Ineligible:
        return None

    # -- fetch device arrays
    packed_cols = []
    bits = []
    for nm in packed_names:
        pc = staged.packed_column(nm)
        if pc is None:
            return None
        bits.append(pc.bits)
        W = PALLAS_TILE // pc.vals_per_word
        packed_cols.append(pc.words.reshape(-1, W // 128, 128))
    value_cols = []
    for nm in value_names:
        v = staged.value_column(nm)
        if v is None or v.dtype not in (jnp.float32, jnp.int32):
            return None
        value_cols.append(v.reshape(-1, PALLAS_TILE // 128, 128))

    G = max(_G_CHUNK, -(-num_groups // _G_CHUNK) * _G_CHUNK)
    spec = PallasGroupSpec(
        num_tiles=staged.pallas_capacity() // PALLAS_TILE,
        packed_bits=tuple(bits),
        filters=tuple((fi, neg) for fi, neg, _, _ in filters),
        group_idx=tuple(group_idx),
        group_strides=tuple(strides),
        num_groups_padded=G,
        aggs=tuple(aggs),
        value_is_int=tuple(value_is_int),
        interpret=interpret,
    )
    params = [v for _, _, lo, hi in filters for v in (lo, hi)]
    params.append(staged.num_docs)
    return spec, np.asarray(params, dtype=np.int32), packed_cols, value_cols


# --------------------------------------------------------------------------
# kernel builder
# --------------------------------------------------------------------------

def _row_layout(spec: PallasGroupSpec):
    """The single source of truth for the matmul row stack and the two
    output accumulators: rows = [float values..., mask(count), int
    values...]; out_f holds the float rows, out_i holds [count, int rows].
    Returns (float_vals, int_vals, Mf, Mi, frow, irow)."""
    float_vals = [vi for vi, isint in enumerate(spec.value_is_int) if not isint]
    int_vals = [vi for vi, isint in enumerate(spec.value_is_int) if isint]
    Mf = max(len(float_vals), 1)
    Mi = 1 + len(int_vals)
    frow = {vi: r for r, vi in enumerate(float_vals)}
    irow = {vi: r + 1 for r, vi in enumerate(int_vals)}
    return float_vals, int_vals, Mf, Mi, frow, irow


def build_group_kernel(spec: PallasGroupSpec):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T = PALLAS_TILE
    RT = T // 128
    G = spec.num_groups_padded
    n_chunks = G // _G_CHUNK
    n_packed = len(spec.packed_bits)
    n_values = len(spec.value_is_int)

    float_vals, int_vals, Mf, Mi, _, _ = _row_layout(spec)

    def kernel(params_ref, *refs):
        packed = refs[:n_packed]
        values = refs[n_packed:n_packed + n_values]
        out_f, out_i = refs[n_packed + n_values:]
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            out_f[...] = jnp.zeros_like(out_f)
            out_i[...] = jnp.zeros_like(out_i)

        # -- unpack planar words -> dictIds [RT, 128] i32 per column
        ids = []
        for ci, bits in enumerate(spec.packed_bits):
            K = 32 // bits
            vmask = jnp.uint32((1 << bits) - 1)
            w = packed[ci][0]                      # [W/128, 128] u32
            planes = [((w >> jnp.uint32(k * bits)) & vmask).astype(jnp.int32)
                      for k in range(K)]
            ids.append(planes[0] if K == 1 else
                       jnp.concatenate(planes, axis=0))  # [RT, 128]

        # -- validity + predicate mask
        num_docs = params_ref[2 * len(spec.filters)]
        row = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0)
        lane = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 1)
        mask = (t * T + row * 128 + lane) < num_docs
        for fi, (pi, negate) in enumerate(spec.filters):
            lo = params_ref[2 * fi]
            hi = params_ref[2 * fi + 1]
            m = (ids[pi] >= lo) & (ids[pi] <= hi)
            mask = mask & (~m if negate else m)
        mask_f = mask.astype(jnp.float32)

        # -- composed group keys
        keys = jnp.zeros((RT, 128), dtype=jnp.int32)
        for gi, stride in zip(spec.group_idx, spec.group_strides):
            keys = keys + ids[gi] * jnp.int32(stride)

        # -- matmul row stack [M, RT, 128]
        rows = []
        for vi in float_vals:
            rows.append(values[vi][0].astype(jnp.float32) * mask_f)
        if not float_vals:
            rows.append(jnp.zeros((RT, 128), dtype=jnp.float32))
        rows.append(mask_f)
        for vi in int_vals:
            rows.append(values[vi][0].astype(jnp.float32) * mask_f)
        R = jnp.stack(rows)                       # [Mf+Mi, RT, 128]

        # -- one-hot matmul per 128-group chunk (MXU)
        for c in range(n_chunks):
            g0 = c * _G_CHUNK
            g_iota = g0 + jax.lax.broadcasted_iota(
                jnp.int32, (RT, 128, _G_CHUNK), 2)
            oh = (keys[:, :, None] == g_iota).astype(jnp.float32)
            part = jax.lax.dot_general(
                R, oh, (((1, 2), (0, 1)), ((), ())),
                preferred_element_type=jnp.float32)   # [M, 128]
            out_f[:, g0:g0 + _G_CHUNK] += part[:Mf]
            out_i[:, g0:g0 + _G_CHUNK] += part[Mf:].astype(jnp.int32)

    def block2(shape0):
        return pl.BlockSpec((1,) + shape0, lambda t: (t,) + (0,) * len(shape0),
                            memory_space=pltpu.VMEM)

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    for bits in spec.packed_bits:
        W = T // (32 // bits)
        in_specs.append(block2((W // 128, 128)))
    for _ in range(n_values):
        in_specs.append(block2((RT, 128)))

    out_specs = (
        pl.BlockSpec((Mf, G), lambda t: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((Mi, G), lambda t: (0, 0), memory_space=pltpu.VMEM),
    )
    out_shape = (
        jax.ShapeDtypeStruct((Mf, G), jnp.float32),
        jax.ShapeDtypeStruct((Mi, G), jnp.int32),
    )

    call = pl.pallas_call(
        kernel,
        grid=(spec.num_tiles,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=spec.interpret,
    )
    return jax.jit(call)


class PallasKernelCache:
    def __init__(self):
        self._cache: Dict[PallasGroupSpec, Any] = {}

    def get(self, spec: PallasGroupSpec):
        k = self._cache.get(spec)
        if k is None:
            k = build_group_kernel(spec)
            self._cache[spec] = k
        return k

    def __len__(self):
        return len(self._cache)


# --------------------------------------------------------------------------
# runner: plan + staged segment -> jnp-kernel-shaped output dict
# --------------------------------------------------------------------------

def run_group_by(plan, staged: StagedSegment, cache: PallasKernelCache,
                 interpret: bool) -> Optional[Dict[str, Any]]:
    """Returns the same output tree as the jnp group-by kernel
    ({"presence", "agg{i}"}) so the shared decode path applies, or None if
    the plan isn't eligible."""
    ext = extract_spec(plan, staged, interpret)
    if ext is None:
        return None
    spec, params, packed_cols, value_cols = ext
    kernel = cache.get(spec)
    out_f, out_i = kernel(params, *packed_cols, *value_cols)

    num_groups = plan.spec[3]
    _, _, _, _, frow, irow = _row_layout(spec)

    counts = out_i[0, :num_groups].astype(jnp.int64)
    out: Dict[str, Any] = {"presence": counts}
    for i, (base, vi) in enumerate(spec.aggs):
        if base == "count":
            out[f"agg{i}"] = counts
        else:
            if vi in frow:
                s = out_f[frow[vi], :num_groups].astype(jnp.float64)
            else:
                s = out_i[irow[vi], :num_groups].astype(jnp.int64)
            out[f"agg{i}"] = (s, counts) if base == "avg" else s
    return out
