"""Fused Pallas scan kernel: bit-unpack -> predicate -> aggregate on MXU.

TPU-native re-design of the reference's hottest loop — the per-segment
``Filter -> Projection -> GroupBy/Aggregate`` chain
(``SVScanDocIdIterator.java:36`` predicate scan, ``PinotDataBitSet.java:25``
bit extraction, ``DefaultGroupByExecutor`` scatter into group slots) — as ONE
Pallas kernel over a ``(segments, tiles)`` grid:

- forward indexes arrive as **planar bit-packed words** (engine/staging.py
  PackedColumn): a tile's value ``j`` lives in word ``j % W`` at bit slot
  ``(j // W) * B``, so the in-VMEM unpack is ``K = 32/B`` static shift+mask
  ops over contiguous words — vector ops only, no gathers;
- the filter tree is compiled to an AND/OR/NOT expression over dictId
  interval tests (sorted dictionaries turn EQ/NEQ/RANGE into intervals, the
  vectorized form of dictionary-based predicate evaluators);
- sums/counts/avg are a **one-hot matmul on the MXU**: rows
  ``[masked values..., mask] @ one_hot(keys)`` accumulate ``[aggs, groups]``
  partials — the fixed-shape scatter-add replacement for
  ``GroupByResultHolder``. Integer sums keep an exact i32 accumulator
  (per-tile matmul results are exactly representable in f32 by a plan-time
  bound, then rounded into i32); float sums accumulate f32;
- min/max/minmaxrange reduce on the VPU per 128-group chunk;
- scalar (non-group-by) aggregations are the same kernel with a single
  group (all keys 0);
- per-segment matched-doc counts accumulate into a segment-indexed output
  (QueryStats parity with the jnp path).

The same kernel body serves the per-segment executor (grid [1, T]) and the
sharded combine (grid [S_local, T_local] per device under shard_map, partials
merged with psum/pmin/pmax over ICI — see parallel/combine.py).

Eligibility is decided per plan (``extract_plan``); anything else falls back
to the jnp masked-vector kernels (engine/kernels.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.engine.staging import PALLAS_TILE, StagedSegment

# one-hot chunk width along the group dimension (lane count)
_G_CHUNK = 128
# max padded group count the pallas path handles (VMEM + unroll bound)
MAX_PALLAS_GROUPS = 4096
# per-tile int matmul partials must be exact in f32: max |value| * TILE < 2^24
_F32_EXACT = 1 << 24
_I32_MAX = (1 << 31) - 1

_POS = np.float32(np.inf)
_NEG = np.float32(-np.inf)


@dataclass(frozen=True)
class PallasSpec:
    """Hashable kernel-cache key (all static shapes/strides/tree)."""

    num_segs: int                         # grid segment dim
    tiles_per_seg: int                    # grid tile dim
    packed_bits: Tuple[int, ...]          # per packed input column
    # nested tuples: ("true",) | ("and"|"or", (children...)) | ("not", (c,))
    # | ("iv", packed_input_idx, param_slot)
    filter_tree: Tuple
    n_slots: int                          # interval param slots
    group_idx: Tuple[int, ...]            # packed input idx per group col
    group_strides: Tuple[int, ...]
    num_groups_padded: int                # multiple of 128
    # per agg: (base, value input idx | None); base in
    # count/sum/avg/min/max/minmaxrange
    aggs: Tuple[Tuple[str, Optional[int]], ...]
    value_is_int: Tuple[bool, ...]        # per value input
    interpret: bool


class _Ineligible(Exception):
    pass


# max interval runs a boolean dictId LUT may decompose into before the
# pallas path declines it (each run is one compare pair in-kernel)
_MAX_LUT_RUNS = 8


def _lut_runs(lut: np.ndarray) -> Optional[List[Tuple[int, int]]]:
    """Boolean LUT -> [(lo, hi)] inclusive dictId runs, or None if more
    than _MAX_LUT_RUNS (fall back to the jnp LUT-gather kernel)."""
    idx = np.nonzero(np.asarray(lut, dtype=bool))[0]
    if idx.size == 0:
        return []
    breaks = np.nonzero(np.diff(idx) > 1)[0]
    if breaks.size + 1 > _MAX_LUT_RUNS:
        return None
    runs = []
    start = 0
    for b in list(breaks) + [idx.size - 1]:
        runs.append((int(idx[start]), int(idx[b])))
        start = b + 1
    return runs


# --------------------------------------------------------------------------
# plan -> (core spec fields, static params, column names)
# --------------------------------------------------------------------------

@dataclass
class PallasPlan:
    """Staging-independent extraction of a SegmentPlan: what to pack, what
    to stage as values, the static interval params, and the spec core."""

    packed_names: List[str]
    value_names: List[str]
    value_is_int: Tuple[bool, ...]
    filter_tree: Tuple
    n_slots: int
    group_idx: Tuple[int, ...]
    group_strides: Tuple[int, ...]
    num_groups_padded: int
    aggs: Tuple[Tuple[str, Optional[int]], ...]
    static_params: np.ndarray             # [2 * n_slots] i32 interval bounds

    def spec(self, num_segs: int, tiles_per_seg: int,
             interpret: bool) -> PallasSpec:
        return PallasSpec(
            num_segs=num_segs, tiles_per_seg=tiles_per_seg,
            packed_bits=(), filter_tree=self.filter_tree,
            n_slots=self.n_slots, group_idx=self.group_idx,
            group_strides=self.group_strides,
            num_groups_padded=self.num_groups_padded,
            aggs=self.aggs, value_is_int=self.value_is_int,
            interpret=interpret)


def extract_plan(plan, provider) -> Optional[PallasPlan]:
    """SegmentPlan -> PallasPlan, or None when the query shape isn't covered
    by the fused kernel. ``provider`` supplies column metadata (an
    ImmutableSegment or a SegmentBatch with unified stats)."""
    from pinot_tpu.engine.kernels import _ParamCursor

    filter_spec, agg_specs, group_specs, num_groups, _ = plan.spec
    if group_specs and num_groups > MAX_PALLAS_GROUPS:
        return None
    if any(a[0] in ("distinctcount", "distinctcounthll")
           for a in agg_specs):
        return None  # 3-tuple specs (col, card/log2m) — jnp path serves

    try:
        packed_names: List[str] = []

        def packed_idx(col: str) -> int:
            cm = provider.metadata.column(col)
            if not (cm.has_dictionary and cm.single_value):
                raise _Ineligible("unpackable column")
            if col not in packed_names:
                packed_names.append(col)
            return packed_names.index(col)

        # -- filter tree -> interval expression (mirrors the jnp kernel's
        # param consumption order exactly)
        pc = _ParamCursor(plan.params)
        intervals: List[Tuple[int, int]] = []

        def iv_leaf(col: str, lo: int, hi: int) -> Tuple:
            slot = len(intervals)
            intervals.append((lo, hi))
            return ("iv", packed_idx(col), slot)

        def walk(node) -> Tuple:
            op = node[0]
            if op == "true":
                return ("true",)
            if op in ("and", "or"):
                return (op, tuple(walk(c) for c in node[1]))
            if op == "not":
                return ("not", (walk(node[1][0]),))
            if op in ("eq", "neq"):
                did = int(pc.take())
                leaf = iv_leaf(node[1], did, did)
                return ("not", (leaf,)) if op == "neq" else leaf
            if op == "range":
                iv = np.asarray(pc.take())
                return iv_leaf(node[1], int(iv[0]), int(iv[1]))
            if op == "lut":
                # boolean LUT over a SORTED dictionary = union of dictId
                # runs; small run counts become OR-of-intervals (covers
                # IN / merged-EQ / many REGEXP predicates)
                lut = np.asarray(pc.take())
                runs = _lut_runs(lut)
                if runs is None:
                    raise _Ineligible("lut with too many runs")
                if not runs:
                    return ("not", (("true",),))
                leaves = tuple(iv_leaf(node[1], lo, hi) for lo, hi in runs)
                return leaves[0] if len(leaves) == 1 else ("or", leaves)
            raise _Ineligible(op)

        tree = walk(filter_spec)

        # -- group columns (params: strides + bases arrays)
        group_idx: List[int] = []
        strides: List[int] = []
        if group_specs:
            for strat, col in group_specs:
                if strat != "gdict":
                    raise _Ineligible("raw group key")
                group_idx.append(packed_idx(col))
            strides = [int(s) for s in np.asarray(pc.take())]
            pc.take()  # bases (gdict bases are 0)
            G = -(-num_groups // _G_CHUNK) * _G_CHUNK
        else:
            G = _G_CHUNK  # single group at key 0

        # -- aggregations
        value_names: List[str] = []
        value_is_int: List[bool] = []

        def value_idx(vspec, acc: str) -> int:
            if vspec is None or vspec[0] != "col":
                raise _Ineligible("non-column agg value")
            name = vspec[1]
            cm = provider.metadata.column(name)
            if acc == "i32":
                is_int = True
            elif acc == "f32":
                is_int = False
            else:
                raise _Ineligible(f"{acc} accumulator")
            if name not in value_names:
                value_names.append(name)
                value_is_int.append(is_int)
            vi = value_names.index(name)
            if value_is_int[vi] != is_int:
                raise _Ineligible("mixed int/float use of one column")
            return vi

        def int_max_abs(vspec) -> int:
            cm = provider.metadata.column(vspec[1])
            if cm.min_value is None or cm.max_value is None:
                raise _Ineligible("no stats for exactness bound")
            return max(abs(int(cm.min_value)), abs(int(cm.max_value)))

        def check_sum_exact(vspec) -> None:
            max_abs = int_max_abs(vspec)
            if max_abs * PALLAS_TILE >= _F32_EXACT:
                raise _Ineligible("tile sum not f32-exact")
            # the i32 accumulator spans ALL segments in the kernel grid
            # (init at s==0 only), so the bound is the whole provider —
            # a batch's num_docs covers every stacked segment
            if max_abs * max(provider.metadata.num_docs, 1) > _I32_MAX:
                raise _Ineligible("provider-wide sum exceeds i32")

        def check_minmax_exact(vspec) -> None:
            # min/max rows reduce in f32: int values >= 2^24 would round
            # (the jnp kernel keeps them exact in i32) -> ineligible
            if int_max_abs(vspec) >= _F32_EXACT:
                raise _Ineligible("int min/max not f32-exact")

        aggs: List[Tuple[str, Optional[int]]] = []
        for aspec in agg_specs:
            base, mv, vspec, acc = aspec[0], aspec[1], aspec[2], aspec[3]
            if mv:
                raise _Ineligible("mv aggregation")
            if base == "count" and vspec is None:
                aggs.append(("count", None))
                continue
            if base not in ("count", "sum", "avg", "min", "max",
                            "minmaxrange"):
                raise _Ineligible(base)
            if base == "count":
                aggs.append(("count", None))
                continue
            vi = value_idx(vspec, acc)
            if acc == "i32":
                if base in ("sum", "avg"):
                    check_sum_exact(vspec)
                else:  # min/max/minmaxrange on int values
                    check_minmax_exact(vspec)
            aggs.append((base, vi))
    except _Ineligible:
        return None

    params = np.asarray([v for lo, hi in intervals for v in (lo, hi)],
                        dtype=np.int32).reshape(-1)
    return PallasPlan(
        packed_names=packed_names, value_names=value_names,
        value_is_int=tuple(value_is_int), filter_tree=tree,
        n_slots=len(intervals), group_idx=tuple(group_idx),
        group_strides=tuple(strides), num_groups_padded=G,
        aggs=tuple(aggs), static_params=params)


# --------------------------------------------------------------------------
# kernel builder
# --------------------------------------------------------------------------

def _row_layout(spec: PallasSpec):
    """Single source of truth for the accumulator layout:
    - out_f [Mf, G] f32: float-value sum rows (>=1 row, dummy if none)
    - out_i [Mi, G] i32: [count, int-value sum rows...]
    - out_mm [Mm, G] f32: (value, kind) min/max rows (>=1 row, dummy if none)
    Returns (fsum_row, isum_row, mm_row, Mf, Mi, Mm) where *_row map value
    input idx (or (vi, kind)) -> row index."""
    fsum_row: Dict[int, int] = {}
    isum_row: Dict[int, int] = {}
    mm_row: Dict[Tuple[int, str], int] = {}
    for base, vi in spec.aggs:
        if base in ("sum", "avg"):
            if spec.value_is_int[vi]:
                isum_row.setdefault(vi, 1 + len(isum_row))
            else:
                fsum_row.setdefault(vi, len(fsum_row))
        elif base == "min":
            mm_row.setdefault((vi, "min"), len(mm_row))
        elif base == "max":
            mm_row.setdefault((vi, "max"), len(mm_row))
        elif base == "minmaxrange":
            mm_row.setdefault((vi, "min"), len(mm_row))
            mm_row.setdefault((vi, "max"), len(mm_row))
    Mf = max(len(fsum_row), 1)
    Mi = 1 + len(isum_row)
    Mm = max(len(mm_row), 1)
    return fsum_row, isum_row, mm_row, Mf, Mi, Mm


def build_kernel(spec: PallasSpec):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T = PALLAS_TILE
    RT = T // 128
    G = spec.num_groups_padded
    n_chunks = G // _G_CHUNK
    n_packed = len(spec.packed_bits)
    n_values = len(spec.value_is_int)
    S = spec.num_segs
    TPS = spec.tiles_per_seg

    fsum_row, isum_row, mm_row, Mf, Mi, Mm = _row_layout(spec)
    # params: [2*n_slots intervals][S num_docs][1 doc_base]
    nd_off = 2 * spec.n_slots

    def kernel(params_ref, *refs):
        packed = refs[:n_packed]
        values = refs[n_packed:n_packed + n_values]
        out_f, out_i, out_mm, out_seg = refs[n_packed + n_values:]
        s = pl.program_id(0)
        t = pl.program_id(1)

        @pl.when((s == 0) & (t == 0))
        def _init_global():
            out_f[...] = jnp.zeros_like(out_f)
            out_i[...] = jnp.zeros_like(out_i)
            for (vi, kind), r in mm_row.items():
                out_mm[r, :] = jnp.full((G,), _POS if kind == "min" else _NEG,
                                        dtype=jnp.float32)
            if not mm_row:
                out_mm[...] = jnp.zeros_like(out_mm)

        @pl.when(t == 0)
        def _init_seg():
            out_seg[...] = jnp.zeros_like(out_seg)

        # -- unpack planar words -> dictIds [RT, 128] i32 per column
        ids = []
        for ci, bits in enumerate(spec.packed_bits):
            K = 32 // bits
            vmask = jnp.uint32((1 << bits) - 1)
            w = packed[ci][0, 0]                   # [W/128, 128] u32
            planes = [((w >> jnp.uint32(k * bits)) & vmask).astype(jnp.int32)
                      for k in range(K)]
            ids.append(planes[0] if K == 1 else
                       jnp.concatenate(planes, axis=0))  # [RT, 128]

        # -- validity + filter expression
        num_docs = params_ref[nd_off + s]
        doc_base = params_ref[nd_off + S]
        row = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0)
        lane = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 1)
        doc = doc_base + t * T + row * 128 + lane
        valid = doc < num_docs

        def emit(node):
            op = node[0]
            if op == "true":
                return jnp.ones((RT, 128), dtype=bool)
            if op == "and":
                m = emit(node[1][0])
                for c in node[1][1:]:
                    m = m & emit(c)
                return m
            if op == "or":
                m = emit(node[1][0])
                for c in node[1][1:]:
                    m = m | emit(c)
                return m
            if op == "not":
                return ~emit(node[1][0])
            _, pi, slot = node                     # "iv"
            lo = params_ref[2 * slot]
            hi = params_ref[2 * slot + 1]
            return (ids[pi] >= lo) & (ids[pi] <= hi)

        mask = emit(spec.filter_tree) & valid
        mask_f = mask.astype(jnp.float32)

        # -- composed group keys (all zero for scalar aggregation)
        keys = jnp.zeros((RT, 128), dtype=jnp.int32)
        for gi, stride in zip(spec.group_idx, spec.group_strides):
            keys = keys + ids[gi] * jnp.int32(stride)

        # -- per-segment matched docs (QueryStats parity)
        out_seg[0, :] += mask_f.sum(axis=0)

        # -- sum/count rows -> one-hot matmul per 128-group chunk (MXU)
        rows = [jnp.zeros((RT, 128), dtype=jnp.float32)] * Mf
        for vi, r in fsum_row.items():
            rows[r] = values[vi][0, 0].astype(jnp.float32) * mask_f
        rows.append(mask_f)                        # count row (out_i row 0)
        irows = [None] * (Mi - 1)
        for vi, r in isum_row.items():
            irows[r - 1] = values[vi][0, 0].astype(jnp.float32) * mask_f
        R = jnp.stack(rows + irows)                # [Mf + Mi, RT, 128]

        for c in range(n_chunks):
            g0 = c * _G_CHUNK
            g_iota = g0 + jax.lax.broadcasted_iota(
                jnp.int32, (RT, 128, _G_CHUNK), 2)
            oh = (keys[:, :, None] == g_iota).astype(jnp.float32)
            part = jax.lax.dot_general(
                R, oh, (((1, 2), (0, 1)), ((), ())),
                preferred_element_type=jnp.float32)   # [Mf + Mi, 128]
            out_f[:, g0:g0 + _G_CHUNK] += part[:Mf]
            out_i[:, g0:g0 + _G_CHUNK] += part[Mf:].astype(jnp.int32)

            # -- min/max rows reduce on the VPU per chunk
            for (vi, kind), r in mm_row.items():
                neutral = _POS if kind == "min" else _NEG
                v = values[vi][0, 0].astype(jnp.float32)
                vm = jnp.where(mask, v, neutral)
                eq = keys[:, :, None] == g_iota
                v3 = jnp.where(eq, vm[:, :, None], neutral)
                red = (v3.min(axis=(0, 1)) if kind == "min"
                       else v3.max(axis=(0, 1)))
                cur = out_mm[r, g0:g0 + _G_CHUNK]
                out_mm[r, g0:g0 + _G_CHUNK] = (
                    jnp.minimum(cur, red) if kind == "min"
                    else jnp.maximum(cur, red))

    def block(shape0):
        nd = len(shape0)
        return pl.BlockSpec((1, 1) + shape0,
                            lambda s, t: (s, t) + (0,) * nd,
                            memory_space=pltpu.VMEM)

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    for bits in spec.packed_bits:
        W = T // (32 // bits)
        in_specs.append(block((W // 128, 128)))
    for _ in range(n_values):
        in_specs.append(block((RT, 128)))

    out_specs = (
        pl.BlockSpec((Mf, G), lambda s, t: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((Mi, G), lambda s, t: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((Mm, G), lambda s, t: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 128), lambda s, t: (s, 0), memory_space=pltpu.VMEM),
    )
    out_shape = (
        jax.ShapeDtypeStruct((Mf, G), jnp.float32),
        jax.ShapeDtypeStruct((Mi, G), jnp.int32),
        jax.ShapeDtypeStruct((Mm, G), jnp.float32),
        jax.ShapeDtypeStruct((S, 128), jnp.float32),
    )

    return pl.pallas_call(
        kernel,
        grid=(S, TPS),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=spec.interpret,
    )


class PallasKernelCache:
    def __init__(self):
        self._cache: Dict[PallasSpec, Any] = {}

    def get(self, spec: PallasSpec):
        k = self._cache.get(spec)
        if k is None:
            k = jax.jit(build_kernel(spec))
            self._cache[spec] = k
        return k

    def __len__(self):
        return len(self._cache)


# --------------------------------------------------------------------------
# output assembly: pallas accumulators -> jnp-kernel-shaped output tree
# --------------------------------------------------------------------------

def assemble_outputs(plan_spec: Tuple, spec: PallasSpec, out_f, out_i, out_mm,
                     seg_matched) -> Dict[str, Any]:
    """Map the pallas accumulators onto the jnp kernel's output tree so
    pack_outputs/unpack_outputs/decode apply unchanged. ``seg_matched`` is
    the [S] per-segment matched-doc count (summed over lanes, and over mesh
    axes by the sharded caller)."""
    _, agg_specs, group_specs, num_groups, _ = plan_spec
    fsum_row, isum_row, mm_row, _, _, _ = _row_layout(spec)
    grouped = bool(group_specs)
    n = num_groups if grouped else 1
    counts = out_i[0, :n]

    def sum_leaf(vi):
        if spec.value_is_int[vi]:
            return out_i[isum_row[vi], :n]
        return out_f[fsum_row[vi], :n]

    out: Dict[str, Any] = {}
    if grouped:
        out["presence"] = counts
    else:
        out["num_matched"] = counts[0]
    for i, ((base, vi), aspec) in enumerate(zip(spec.aggs, agg_specs)):
        if base == "count":
            leaf: Any = counts
        elif base in ("sum", "avg"):
            leaf = sum_leaf(vi)
            if base == "avg":
                leaf = (leaf, counts)
        elif base == "min":
            leaf = out_mm[mm_row[(vi, "min")], :n]
        elif base == "max":
            leaf = out_mm[mm_row[(vi, "max")], :n]
        else:  # minmaxrange
            leaf = (out_mm[mm_row[(vi, "min")], :n],
                    out_mm[mm_row[(vi, "max")], :n])
        if not grouped:
            leaf = (tuple(x[0] for x in leaf) if isinstance(leaf, tuple)
                    else leaf[0])
        out[f"agg{i}"] = leaf
    if seg_matched is not None:
        out["seg_matched"] = seg_matched
    return out


# --------------------------------------------------------------------------
# per-segment runner (engine/executor.py fallback path)
# --------------------------------------------------------------------------

def run_segment(plan, staged: StagedSegment, cache: PallasKernelCache,
                interpret: bool):
    """Run the fused kernel over one staged segment; returns the PACKED f64
    output vector (kernels.pack_outputs layout, single D2H fetch) or None
    when the plan/staging isn't eligible."""
    from pinot_tpu.engine.kernels import pack_outputs

    pp = extract_plan(plan, staged.segment)
    if pp is None:
        return None

    packed_cols = []
    bits = []
    for nm in pp.packed_names:
        pc = staged.packed_column(nm)
        if pc is None:
            return None
        bits.append(pc.bits)
        W = PALLAS_TILE // pc.vals_per_word
        packed_cols.append(pc.words.reshape(1, -1, W // 128, 128))
    value_cols = []
    for nm in pp.value_names:
        v = staged.value_column(nm)
        if v is None or v.dtype not in (jnp.float32, jnp.int32):
            return None
        value_cols.append(v.reshape(1, -1, PALLAS_TILE // 128, 128))

    tiles = staged.pallas_capacity() // PALLAS_TILE
    spec = pp.spec(num_segs=1, tiles_per_seg=tiles, interpret=interpret)
    spec = _with_bits(spec, tuple(bits))
    kernel = cache.get(spec)

    params = jnp.concatenate([
        jnp.asarray(pp.static_params, dtype=jnp.int32).reshape(-1),
        jnp.asarray([staged.num_docs, 0], dtype=jnp.int32),
    ])
    out_f, out_i, out_mm, out_seg = kernel(params, *packed_cols, *value_cols)
    tree = assemble_outputs(plan.spec, spec, out_f, out_i, out_mm,
                            seg_matched=None)
    return pack_outputs(tree, plan.spec)


def _with_bits(spec: PallasSpec, bits: Tuple[int, ...]) -> PallasSpec:
    from dataclasses import replace

    return replace(spec, packed_bits=bits)
