"""HBM residency manager: budgeted, pinned, tiered, cost-aware staging.

The subsystem the tiered-storage / multi-table-scale work stands on: a
production table set cannot fit in HBM, so device staging must degrade
gracefully instead of OOMing. This module subsumes the old unbounded
``StagingCache`` and the sharded executor's ad-hoc device-column caches
behind one byte-accounted, lock-correct manager:

- **Accounting**: every resident (a per-segment :class:`StagedSegment` or a
  sharded-batch device-column set) reports ``nbytes()``; the manager rolls
  bytes up per resident and tracks the fleet total + peak.
- **Budget**: ``pinot.server.query.hbm.budget.bytes`` (spi/config.py layered
  keys; <= 0 means uncapped). When unset, the budget auto-derives from the
  backend's reported device memory (``bytes_limit`` fraction) — on hosts
  whose backend reports nothing (CPU), staging is uncapped.
- **Host-RAM spill tier**: eviction DEMOTES a resident's device arrays to
  host numpy copies instead of dropping them (per the ISCA'23 HBM/ICI cost
  model a D2H demote + H2D restage is ~10x cheaper than rebuilding device
  columns from the segment — the TPU analogue of Pinot's PinotDataBuffer
  mmap/heap tiering). ``stage()`` promotes from the host tier with a plain
  H2D transfer, skipping dictionary build/encode/pack entirely. Host-tier
  entries are byte-accounted against their own budget
  (``pinot.server.query.hostram.budget.bytes``, auto from psutil) and
  LRU-dropped under pressure.
- **Restage-cost-aware eviction**: candidates are ranked by
  ``bytes * staleness / rebuild_cost`` — big, cold, cheap-to-restage
  residents (host-tier-backed, batch-borrowable) evict first, so the
  budget preferentially keeps what is slow to get back (star-tree node
  arrays, full column builds). With equal costs this degrades to exact
  LRU.
- **Eviction touches UNPINNED residents only**: queries pin the residents
  they touch for their duration via a :class:`QueryLease` (the same
  acquire/release hazard discipline as ``TableDataManager.acquire_segments``
  — ref ``BaseTableDataManager.java:71`` refcounting), so an in-flight query
  never loses its arrays mid-kernel (the SURVEY §5 race note).
- **Admission control**: a query whose estimated working set cannot fit is
  granted a SLICED lease when its largest single segment fits (the sharded
  executor then runs the combine in budget-sized slices — stage k, launch,
  demote, repeat — and the per-segment path runs serially releasing pins
  per segment); only a query whose single-segment footprint is itself over
  budget still spills to the host engine. Admission estimates are
  validated against measured ``nbytes()`` after staging and a clamped EWMA
  correction factor feeds back so slicing picks k from real bytes.
- **Prefetch**: segment add/reload enqueues background staging so the first
  query pays no H2D (ref: the FetchContext prefetch path,
  ``InstancePlanMakerImplV2.java:155-170``).
- **Observability**: global counters + per-query ``QueryStats.staging``
  deltas (now incl. promotions/demotions/hostBytes/slices), ``ServerMeter``
  meters / gauges when bound to a registry, and a bytes-accurate two-tier
  snapshot for ``/debug/memory``.
"""

from __future__ import annotations

import logging
import queue
import threading

from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from pinot_tpu.engine.staging import StagedSegment, staged_int_dtype
from pinot_tpu.spi.config import CommonConstants

log = logging.getLogger(__name__)

# budget sentinel: resolve from config, then backend device memory / psutil
AUTO = object()

_STOP = object()

# Rebuild-cost weights for the eviction ranking (relative units — only the
# ratios matter). Calibrated to the staging pipeline stages a re-stage
# skips: a host-tier restage is one H2D; a batch re-adoption re-puts
# already-stacked host arrays; a borrowable column is a device-side slice;
# a cold column build pays decode+dict+H2D; star-tree node arrays pay the
# tree walk on top.
COST_HOST_RESTAGE = 1.0
COST_BATCH_RESTAGE = 1.5
COST_BORROWED_BUILD = 2.0
COST_COLUMN_BUILD = 4.0
COST_STARTREE_BUILD = 8.0

# Admission-estimate drift correction: EWMA of measured/estimated staged
# bytes, clamped so one pathological segment cannot swing admission.
_EST_ALPHA = 0.2
_EST_SCALE_MIN = 0.25
_EST_SCALE_MAX = 4.0

# Greedy slice packing fills at most this fraction of the free budget per
# slice: estimates are approximate and a slice that lands exactly on the
# budget line would thrash the evictor mid-launch.
_SLICE_FILL = 0.85


# --------------------------------------------------------------------------
# working-set estimation (admission control)
# --------------------------------------------------------------------------

def estimate_segment_bytes(segment, columns: Iterable[str]) -> int:
    """Metadata-only estimate of the device bytes staging ``columns`` of
    ``segment`` costs (fwd + dict values + null bitmap; the same layout
    contract as ``StagedSegment._stage``). Used for admission BEFORE any
    H2D, so it must not touch column data. Validated post-stage against
    measured ``nbytes()`` — see ``ResidencyManager.observe_estimate``."""
    cap = int(getattr(segment, "padded_capacity", 0) or 0)
    md = getattr(segment, "metadata", None)
    cols = getattr(md, "columns", {}) if md is not None else {}
    total = 0
    for name in columns:
        cm = cols.get(name) if hasattr(cols, "get") else None
        if cm is None:
            continue
        if cm.single_value:
            if cm.has_dictionary:
                total += cap * 4  # fwd dictIds upcast to int32
            elif cm.data_type.is_integral:
                total += cap * staged_int_dtype(cm).itemsize
            else:
                total += cap * 8  # raw floats stay f64 (staging module note)
        else:
            total += cap * 4 * max(cm.max_num_multi_values, 1) + cap * 4
        if cm.has_dictionary and cm.data_type.is_numeric:
            total += cm.cardinality * (
                staged_int_dtype(cm).itemsize if cm.data_type.is_integral
                else 4)
        if cm.has_nulls:
            total += cap
    return total


def resolve_budget_bytes(budget_bytes: Any = AUTO,
                         config=None) -> Optional[int]:
    """Budget resolution: explicit arg > layered config key > backend device
    memory. Returns None for uncapped (explicit <= 0, or nothing known)."""
    if budget_bytes is not AUTO:
        if budget_bytes is None:
            return None
        b = int(budget_bytes)
        return b if b > 0 else None
    from pinot_tpu.spi.config import PinotConfiguration

    cfg = config if config is not None else PinotConfiguration()
    v = cfg.get(CommonConstants.HBM_BUDGET_BYTES_KEY)
    if v is not None:
        b = int(v)
        return b if b > 0 else None
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        if limit:
            return int(limit * CommonConstants.DEFAULT_HBM_BUDGET_FRACTION)
    except Exception:  # backend without memory stats / not initialized
        pass
    return None


def resolve_host_budget_bytes(budget_bytes: Any = AUTO,
                              config=None) -> Optional[int]:
    """Host-RAM tier budget: explicit arg > layered config key > psutil
    available memory times the default fraction. None = uncapped (explicit
    <= 0, or psutil unavailable)."""
    if budget_bytes is not AUTO:
        if budget_bytes is None:
            return None
        b = int(budget_bytes)
        return b if b > 0 else None
    from pinot_tpu.spi.config import PinotConfiguration

    cfg = config if config is not None else PinotConfiguration()
    v = cfg.get(CommonConstants.HOSTRAM_BUDGET_BYTES_KEY)
    if v is not None:
        b = int(v)
        return b if b > 0 else None
    try:
        import psutil

        avail = psutil.virtual_memory().available
        return int(avail * CommonConstants.DEFAULT_HOSTRAM_BUDGET_FRACTION)
    except Exception:  # psutil missing / unsupported platform
        return None


# --------------------------------------------------------------------------
# leases
# --------------------------------------------------------------------------

class QueryLease:
    """One query's pin set + staging counters. Created by ``begin_query``,
    closed by ``end_query``; residents pinned through a lease survive
    eviction pressure until the lease closes (acquire/release discipline).
    A ``sliced`` lease keeps the device path but releases its pins at
    slice boundaries (``release_slice``) so an over-budget working set
    streams through the budget instead of spilling to the host engine."""

    __slots__ = ("device_allowed", "sliced", "spilled", "hits", "misses",
                 "evictions", "pin_blocked", "promotions", "demotions",
                 "slices", "admit_reason", "_pinned", "_est")

    def __init__(self, device_allowed: bool = True):
        self.device_allowed = device_allowed
        self.sliced = False
        self.spilled = not device_allowed
        # machine-readable admission outcome for the path-decision ledger
        # ("fits" | "working_set_over_budget_sliceable" |
        #  "single_segment_over_budget" |
        #  "working_set_over_budget_not_sliceable")
        self.admit_reason = "fits"
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.pin_blocked = 0
        self.promotions = 0
        self.demotions = 0
        self.slices = 0
        self._pinned: set = set()
        # raw (unscaled) admission estimates per missing segment, for the
        # post-stage drift observation in end_query
        self._est: Dict[str, int] = {}

    def staging_dict(self, staged_bytes: int,
                     host_bytes: int = 0) -> Dict[str, int]:
        """The ``QueryStats.staging`` payload (merge: counters sum, *Bytes
        keys max — see QueryStats.merge)."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "pinBlockedEvictions": self.pin_blocked,
            "spills": 1 if self.spilled else 0,
            "promotions": self.promotions,
            "demotions": self.demotions,
            "slices": self.slices,
            "stagedBytes": int(staged_bytes),
            "hostBytes": int(host_bytes),
        }


class _Entry:
    __slots__ = ("resident", "pins", "nbytes", "touch")

    def __init__(self, resident):
        self.resident = resident
        self.pins = 0
        self.nbytes = 0
        self.touch = 0


class ResidencyManager:
    """(name -> resident) two-tier cache with byte budgets, pins,
    cost-aware eviction, sliced/spill admission and background prefetch.
    A *resident* is anything with ``nbytes()`` and ``release()`` —
    :class:`StagedSegment` for the per-segment path, the sharded
    executor's batch wrapper for the combine path. A resident that also
    defines ``demote()`` (returning a host image with ``nbytes()``/
    ``release()``/``matches()``) moves to the host-RAM tier on eviction
    instead of dropping."""

    def __init__(self, budget_bytes: Any = AUTO, config=None,
                 host_budget_bytes: Any = AUTO):
        self._budget_arg = budget_bytes
        self._host_budget_arg = host_budget_bytes
        self._config = config
        self._budget_resolved = False
        self._budget: Optional[int] = None
        self._host_budget_resolved = False  # guarded-by-writes: _lock
        self._host_budget: Optional[int] = None  # guarded-by-writes: _lock
        # RLock: evicting a batch resident re-enters through the executor's
        # release callback (discard()), and that must not deadlock
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()  # guarded-by: _lock
        # host-RAM spill tier: name -> _Entry whose resident is a host
        # image (numpy copies); LRU-dropped under the host budget
        self._host_entries: "OrderedDict[str, _Entry]" = OrderedDict()  # guarded-by: _lock
        self._staged_bytes = 0  # guarded-by: _lock
        self._peak_bytes = 0  # guarded-by: _lock
        self._host_bytes = 0  # guarded-by: _lock
        self._host_peak_bytes = 0  # guarded-by: _lock
        # monotonically increasing touch sequence for the eviction ranking
        self._touch_seq = 0  # guarded-by: _lock
        # admission-estimate drift: EWMA of measured/estimated bytes
        self._est_scale = 1.0  # guarded-by: _lock
        self.est_observations = 0  # guarded-by: _lock
        # per-name eviction generation: a queued prefetch carries the seq it
        # was enqueued under and must not resurrect a segment removed while
        # it waited (the prefetch-vs-removeSegment race)
        self._retired: Dict[str, int] = {}  # guarded-by: _lock
        # global counters (process lifetime; per-query deltas ride leases)
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock
        self.pin_blocked = 0  # guarded-by: _lock
        self.spills = 0  # guarded-by: _lock
        self.prefetched = 0  # guarded-by: _lock
        self.borrows = 0  # guarded-by: _lock
        self.demotions = 0  # guarded-by: _lock
        self.promotions = 0  # guarded-by: _lock
        self.host_drops = 0  # guarded-by: _lock
        self.sliced_queries = 0  # guarded-by: _lock
        self.demoted_bytes = 0  # guarded-by: _lock
        self.promoted_bytes = 0  # guarded-by: _lock
        self.host_dropped_bytes = 0  # guarded-by: _lock
        # tier feature flags (config only — no jax/psutil touch at init)
        from pinot_tpu.spi.config import PinotConfiguration

        cfg = config if config is not None else PinotConfiguration()
        self._host_on = cfg.get_bool(CommonConstants.HOSTRAM_ENABLED_KEY,
                                     True)
        self._slicing_on = cfg.get_bool(
            CommonConstants.HBM_SLICING_ENABLED_KEY, True)
        # cross-query column dedup: ``column_borrower(segment, name)``
        # (set by the sharded executor) lets a StagedSegment serve a column
        # from a resident batch's device copy instead of staging its own
        self.column_borrower = None
        self._metrics = None  # race-ok: publish_once
        self._prefetch_q: Optional["queue.Queue"] = None
        self._prefetch_thread: Optional[threading.Thread] = None
        self._closed = False

    # -- budget --------------------------------------------------------------
    @property
    def budget_bytes(self) -> Optional[int]:
        """Lazy: resolving the auto default may initialize the jax backend,
        which must not happen at executor construction."""
        if not self._budget_resolved:
            with self._lock:
                if not self._budget_resolved:
                    self._budget = resolve_budget_bytes(self._budget_arg,
                                                        self._config)
                    self._budget_resolved = True
        return self._budget

    def set_budget_bytes(self, budget_bytes: Optional[int]) -> None:
        with self._lock:
            self._budget = (int(budget_bytes)
                            if budget_bytes and int(budget_bytes) > 0
                            else None)
            self._budget_resolved = True
            doomed = self._enforce_locked()
        self._demote_or_release_all(doomed)

    @property
    def host_budget_bytes(self) -> Optional[int]:
        """Host-RAM tier budget (lazy psutil probe); None = uncapped."""
        if not self._host_budget_resolved:
            with self._lock:
                if not self._host_budget_resolved:
                    self._host_budget = resolve_host_budget_bytes(
                        self._host_budget_arg, self._config)
                    self._host_budget_resolved = True
        return self._host_budget

    def set_host_budget_bytes(self, budget_bytes: Optional[int]) -> None:
        with self._lock:
            self._host_budget = (int(budget_bytes)
                                 if budget_bytes and int(budget_bytes) > 0
                                 else None)
            self._host_budget_resolved = True
            dropped = self._enforce_host_locked()
        for img in dropped:
            img.release()

    def set_host_tier_enabled(self, enabled: bool) -> None:
        """Runtime kill switch (bench spill baseline / ops). Disabling
        drops nothing retroactively — existing host entries keep serving;
        new evictions drop instead of demoting."""
        with self._lock:
            self._host_on = bool(enabled)

    def host_tier_enabled(self) -> bool:
        with self._lock:
            return self._host_on

    def slicing_enabled(self) -> bool:
        with self._lock:
            return self._slicing_on

    # -- staging (the StagingCache surface, now lock-correct) ---------------
    def stage(self, segment, lease: Optional[QueryLease] = None
              ) -> StagedSegment:
        """Resident StagedSegment for ``segment``, created on miss. Atomic
        get-or-create under the manager lock: concurrent stagers of the same
        segment share ONE StagedSegment (the old get-then-set built
        duplicate device arrays and leaked one set until GC). A reloaded
        segment (same name, new object) invalidates the stale resident —
        identity check, same guard as before. A miss with a matching
        host-tier image PROMOTES: the new resident restores columns with a
        plain H2D instead of rebuilding them."""
        with self._lock:
            resident, doomed = self._stage_locked(segment, lease)
        self._demote_or_release_all(doomed, lease)
        return resident

    def _stage_locked(self, segment, lease: Optional[QueryLease]):
        """Get-or-create under ``_lock`` (caller holds it). Returns
        ``(resident, doomed)``; the caller demotes/releases ``doomed``
        after dropping the lock."""
        name = segment.segment_name
        doomed: List[Any] = []
        e = self._entries.get(name)
        if e is not None and isinstance(e.resident, StagedSegment) \
                and e.resident.segment is segment:
            self._entries.move_to_end(name)
            e.touch = self._next_touch_locked()
            self.hits += 1
            if lease is not None:
                lease.hits += 1
            self._mark("STAGING_HITS")
        else:
            if e is not None:  # identity change: drop stale arrays outright
                del self._entries[name]
                doomed.append((None, e.resident))
            image = self._take_host_locked(name, segment, lease)
            e = _Entry(StagedSegment(segment,
                                     borrower=self.column_borrower,
                                     host_image=image))
            e.touch = self._next_touch_locked()
            self._entries[name] = e
            self.misses += 1
            if lease is not None:
                lease.misses += 1
            self._mark("STAGING_MISSES")
        self._pin_locked(name, e, lease)
        doomed += self._enforce_locked(lease)
        return e.resident, doomed

    def register(self, name: str, make_resident, same=None,
                 lease: Optional[QueryLease] = None):
        """Generic get-or-create for non-segment residents (sharded batch
        device-column sets). ``make_resident()`` builds on miss; ``same(r)``
        says whether the cached resident is still current."""
        doomed: List[Any] = []
        with self._lock:
            e = self._entries.get(name)
            if e is not None and (same is None or same(e.resident)):
                self._entries.move_to_end(name)
                e.touch = self._next_touch_locked()
                self.hits += 1
                if lease is not None:
                    lease.hits += 1
                self._mark("STAGING_HITS")
            else:
                if e is not None:
                    del self._entries[name]
                    doomed.append((None, e.resident))
                e = _Entry(make_resident())
                e.touch = self._next_touch_locked()
                self._entries[name] = e
                self.misses += 1
                if lease is not None:
                    lease.misses += 1
                self._mark("STAGING_MISSES")
            self._pin_locked(name, e, lease)
            # re-measure + budget-enforce on EVERY outcome, like stage():
            # without this a miss inserts an unaccounted batch resident and
            # stagedBytes drifts until the next unrelated refresh
            doomed += self._enforce_locked(lease)
            resident = e.resident
        self._demote_or_release_all(doomed, lease)
        return resident

    def _pin_locked(self, name: str, e: _Entry,
                    lease: Optional[QueryLease]) -> None:
        if lease is not None and name not in lease._pinned:
            e.pins += 1
            lease._pinned.add(name)

    def _next_touch_locked(self) -> int:
        self._touch_seq += 1
        return self._touch_seq

    def account(self, name: str,
                lease: Optional[QueryLease] = None) -> None:
        """Re-measure one resident (its arrays were staged after admission)
        and enforce the budget."""
        with self._lock:
            doomed = self._enforce_locked(lease)
        self._demote_or_release_all(doomed, lease)

    def evict(self, name: str) -> None:
        """Explicit eviction (segment unassigned / reloaded) — BOTH tiers,
        including host-tier batch images containing the segment. In-flight
        queries keep their arrays alive through python refs; XLA frees the
        HBM when the last ref drops. Bumps the retire generation so queued
        prefetches of the removed segment become no-ops."""
        with self._lock:
            self._retired[name] = self._retired.get(name, 0) + 1
            e = self._entries.pop(name, None)
            if e is not None:
                self.evictions += 1
                self._mark("STAGING_EVICTIONS")
                self._refresh_locked()
            dropped = self._drop_host_locked(name)
        if e is not None:
            # outside the lock: a resident's release may take its own lock
            # (StagedSegment serializing against in-flight column builds) or
            # re-enter the manager (batch residents clearing executor
            # caches) — lock order is always manager -> resident, held
            # never-both on the release path
            e.resident.release()
        for img in dropped:
            img.release()

    def _drop_host_locked(self, segment_name: str) -> List[Any]:
        """Remove host-tier entries backed by ``segment_name``: the exact
        per-segment image plus every batch image whose ``segment_names``
        contains the segment — a removed/reloaded segment must never be
        served from a stale host copy. Returns the images; the caller
        releases them after dropping ``_lock``."""
        dropped: List[Any] = []
        for name in list(self._host_entries):
            he = self._host_entries[name]
            names = getattr(he.resident, "segment_names", (name,))
            if name == segment_name or segment_name in names:
                del self._host_entries[name]
                self._release_host_locked(he)
                self.host_drops += 1
                self.host_dropped_bytes += he.nbytes
                self._mark("STAGING_HOST_DROPS")
                dropped.append(he.resident)
        return dropped

    def demote(self, name: str) -> bool:
        """Explicit demotion of one UNPINNED resident to the host tier
        (ops hook: ``POST /debug/memory/demote/<name>``). Returns False
        when the resident is absent or pinned by an in-flight query."""
        with self._lock:
            e = self._entries.get(name)
            if e is None or e.pins > 0:
                return False
            del self._entries[name]
            doomed = [(name, e.resident)]
            self.evictions += 1
            self._mark("STAGING_EVICTIONS")
            self._refresh_locked()
        self._demote_or_release_all(doomed)
        return True

    def note_borrow(self, batch_name: str) -> None:
        """A per-segment staging built a column FROM a resident batch's
        device copy (cross-query dedup): count it and touch the batch in
        the LRU — borrowers keep their source warm, the reference-count of
        the share."""
        with self._lock:
            self.borrows += 1
            e = self._entries.get(batch_name)
            if e is not None:
                self._entries.move_to_end(batch_name)
                e.touch = self._next_touch_locked()
            self._mark("STAGING_BORROWS")

    def discard(self, name: str) -> None:
        """Drop a DEVICE-tier entry WITHOUT calling release (the owner
        already freed the arrays). Idempotent — also the re-entry point
        for batch residents whose release callback clears executor caches.
        Host-tier images survive: they are owned copies, still valid for
        promotion."""
        with self._lock:
            self._entries.pop(name, None)  # lint: ignore[conservation] — owner already released the arrays (discard contract)
            self._refresh_locked()

    def clear(self) -> None:
        with self._lock:
            doomed = [e.resident for e in self._entries.values()]
            host_doomed = [e.resident for e in self._host_entries.values()]
            self._entries.clear()
            self._host_entries.clear()
            self._staged_bytes = 0
            self._host_bytes = 0
        self._release_all(doomed + host_doomed)

    def _release_all(self, doomed: List[Any]) -> None:
        """Release evicted residents AFTER the manager lock is dropped:
        ``release()`` may acquire the resident's own lock, whose holders
        re-enter the manager (column borrower -> ``note_borrow``) — calling
        it under ``_lock`` is the A->B/B->A inversion the lint gate exists
        to catch."""
        for r in doomed:
            try:
                r.release()
            except Exception:
                log.exception("resident release failed")

    def _demote_or_release_all(self, doomed: List[Tuple[Optional[str], Any]],
                               lease: Optional[QueryLease] = None) -> None:
        """Budget-evicted residents demote to the host-RAM tier instead of
        dropping; residents that cannot demote (no ``demote()`` hook,
        identity-invalidated — name None, tier disabled, or image larger
        than the whole host budget) release as before. Runs AFTER the
        manager lock is dropped: demotion D2H-syncs device buffers, which
        must never happen under ``_lock``."""
        for name, r in doomed:
            image = None
            if name is not None and self.host_tier_enabled():
                demote_fn = getattr(r, "demote", None)
                if demote_fn is not None:
                    hb = self.host_budget_bytes
                    size = 0
                    if hb is not None:
                        try:
                            size = int(r.nbytes())
                        except Exception:
                            size = 0
                    if hb is None or size <= hb:
                        try:
                            image = demote_fn()
                        except Exception:
                            log.exception("demotion of %r failed; "
                                          "dropping resident", name)
                            image = None
            if image is None:
                try:
                    r.release()
                except Exception:
                    log.exception("resident release failed")
                continue
            with self._lock:
                self._admit_host_locked(name, image)
                if lease is not None:
                    lease.demotions += 1

    # -- host tier -----------------------------------------------------------
    def _admit_host_locked(self, name: str, image) -> None:
        """Insert a demoted image into the host tier: replace any stale
        image under the same name, account the bytes, and LRU-drop over
        the host budget."""
        prev = self._host_entries.pop(name, None)
        if prev is not None:
            self._release_host_locked(prev)
            prev.resident.release()
        e = _Entry(image)
        try:
            e.nbytes = int(image.nbytes())
        except Exception:
            e.nbytes = 0
        self._host_entries[name] = e
        self._host_bytes += e.nbytes
        if self._host_bytes > self._host_peak_bytes:
            self._host_peak_bytes = self._host_bytes
        self.demotions += 1
        self.demoted_bytes += e.nbytes
        self._mark("STAGING_DEMOTIONS")
        dropped = self._enforce_host_locked()
        for img in dropped:
            # host images release lock-free (plain numpy container clears;
            # no resident lock, no manager re-entry)
            img.release()

    def _release_host_locked(self, e: _Entry) -> None:
        """Host-tier byte-accounting release: every entry leaving the host
        dict subtracts its bytes exactly once (the host half of the
        conservation contract the lint gate enforces)."""
        self._host_bytes -= e.nbytes
        if self._host_bytes < 0:
            self._host_bytes = 0

    def _enforce_host_locked(self) -> List[Any]:
        """LRU-drop host-tier entries until the host budget fits. Returns
        the dropped images (callers may release them under or after the
        lock — host images are lock-free)."""
        budget = self._host_budget if self._host_budget_resolved \
            else self.host_budget_bytes
        dropped: List[Any] = []
        if budget is None:
            return dropped
        while self._host_bytes > budget and self._host_entries:
            _name, e = self._host_entries.popitem(last=False)
            self._release_host_locked(e)
            self.host_drops += 1
            self.host_dropped_bytes += e.nbytes
            self._mark("STAGING_HOST_DROPS")
            dropped.append(e.resident)
        return dropped

    def _take_host_locked(self, name: str, target,
                          lease: Optional[QueryLease] = None):
        """Pop + account the host-tier entry for ``name`` when its image
        matches ``target`` identity (a segment, or the sharded batch's
        segment list). Returns the image — the caller adopts its arrays
        (promotion) — or None. A stale image is dropped on the spot."""
        he = self._host_entries.pop(name, None)
        if he is None:
            return None
        self._release_host_locked(he)
        image = he.resident
        ok = False
        try:
            ok = image.matches(target)
        except Exception:
            ok = False
        if not ok:
            self.host_drops += 1
            self.host_dropped_bytes += he.nbytes
            self._mark("STAGING_HOST_DROPS")
            image.release()
            return None
        self.promotions += 1
        self.promoted_bytes += he.nbytes
        if lease is not None:
            lease.promotions += 1
        self._mark("STAGING_PROMOTIONS")
        return image

    def promote_host(self, name: str, target=None,
                     lease: Optional[QueryLease] = None):
        """Host-tier lookup for non-segment residents (sharded batches):
        pops + accounts the entry when its identity matches ``target``;
        the caller adopts the image's host arrays (promotion is then one
        ``device_put`` per column)."""
        with self._lock:
            return self._take_host_locked(name, target, lease)

    # -- query protocol ------------------------------------------------------
    def begin_query(self, segments: List[Any], columns: Iterable[str],
                    sliceable: bool = False) -> QueryLease:
        """Admission: fit the query's estimated working set against what
        COULD be freed (budget minus other queries' pinned bytes).

        Three outcomes instead of the old fit-or-fail two:
        - fits -> normal device lease;
        - over budget but every single segment fits (and the caller can
          slice — aggregations/group-bys) -> SLICED device lease: the
          executors stream the working set through the budget in slices,
          demoting between slices;
        - a single segment alone cannot fit -> host-engine spill
          (graceful degradation, never a device OOM).

        Estimates are scaled by the measured-vs-estimated drift EWMA."""
        budget = self.budget_bytes
        if budget is None:
            return QueryLease(device_allowed=True)
        cols = list(columns)
        with self._lock:
            self._refresh_locked()
            scale = min(max(self._est_scale, _EST_SCALE_MIN),
                        _EST_SCALE_MAX)
            names = {getattr(s, "segment_name", None) for s in segments}
            reusable = 0
            missing_est = 0
            max_single = 0
            ests: Dict[str, int] = {}
            for s in segments:
                e = self._entries.get(s.segment_name)
                if e is not None and isinstance(e.resident, StagedSegment) \
                        and e.resident.segment is s:
                    reusable += e.nbytes
                    max_single = max(max_single, e.nbytes)
                else:
                    raw = estimate_segment_bytes(s, cols)
                    ests[s.segment_name] = raw
                    est = int(raw * scale)
                    missing_est += est
                    max_single = max(max_single, est)
            other_pinned = sum(e.nbytes for n, e in self._entries.items()
                               if e.pins > 0 and n not in names)
            if missing_est + reusable + other_pinned <= budget:
                lease = QueryLease(device_allowed=True)
                lease._est = ests
                return lease
            if sliceable and self._slicing_on \
                    and max_single + other_pinned <= budget:
                self.sliced_queries += 1
                self._mark("STAGING_SLICED")
                log.info(
                    "HBM admission: working set ~%d B over budget %d B "
                    "(%d B pinned elsewhere) — serving in budget-sized "
                    "slices on the device path", missing_est + reusable,
                    budget, other_pinned)
                lease = QueryLease(device_allowed=True)
                lease.sliced = True
                lease.admit_reason = "working_set_over_budget_sliceable"
                lease._est = ests
                return lease
            self.spills += 1
            self._mark("STAGING_SPILLS")
            log.info(
                "HBM admission: working set ~%d B (+%d B reusable) over "
                "budget %d B (%d B pinned elsewhere) and not sliceable; "
                "spilling query to host engine", missing_est, reusable,
                budget, other_pinned)
            lease = QueryLease(device_allowed=False)
            lease.admit_reason = (
                "single_segment_over_budget"
                if max_single + other_pinned > budget
                else "working_set_over_budget_not_sliceable")
            return lease

    def plan_slices(self, segments: List[Any], columns: Iterable[str],
                    lease: Optional[QueryLease] = None,
                    pad_to: int = 1) -> Optional[List[List[Any]]]:
        """Partition ``segments`` into budget-sized slices for the sliced
        sharded combine (stage k, launch, demote, repeat). ``pad_to`` is
        the mesh's segment-axis width: a k-segment batch stacks arrays for
        ceil(k / pad_to) * pad_to segments, so the pad overhead is part of
        each slice's cost. Estimates ride the drift-corrected scale, so
        repeat queries pick k from (approximately) real bytes. Returns
        None when even one padded segment exceeds the free budget — the
        caller degrades to the per-segment sliced path, whose footprint
        truly scales one segment at a time."""
        budget = self.budget_bytes
        if budget is None:
            return [list(segments)]
        if not segments:
            return [list(segments)]
        cols = list(columns)
        known = lease._est if lease is not None else {}
        with self._lock:
            self._refresh_locked()
            scale = min(max(self._est_scale, _EST_SCALE_MIN),
                        _EST_SCALE_MAX)
            names = {getattr(s, "segment_name", None) for s in segments}
            other_pinned = sum(e.nbytes for n, e in self._entries.items()
                               if e.pins > 0 and n not in names)
            ests = []
            for s in segments:
                raw = known.get(s.segment_name)
                if raw is None:
                    raw = estimate_segment_bytes(s, cols)
                ests.append(max(1, int(raw * scale)))
        avail = (budget - other_pinned) * _SLICE_FILL
        mean = sum(ests) / len(ests)
        if mean * pad_to > avail:
            # the mesh pad alone blows the budget: no multi-segment batch
            # can fit, so sharded slicing is pointless here
            return None
        slices: List[List[Any]] = []
        cur: List[Any] = []
        cur_cost = 0.0
        for s, est in zip(segments, ests):
            k = len(cur) + 1
            padded = -(-k // pad_to) * pad_to
            cost = cur_cost + est + (padded - k) * mean
            if cur and cost > avail:
                slices.append(cur)
                cur = [s]
                cur_cost = est
            else:
                cur.append(s)
                cur_cost += est
        if cur:
            slices.append(cur)
        return slices

    def release_slice(self, lease: Optional[QueryLease]) -> None:
        """Slice boundary for a sliced lease: unpin everything the slice
        staged and enforce the budget NOW — the evicted residents demote
        to the host tier, so the next pass over the same data promotes
        instead of rebuilding."""
        if lease is None:
            return
        with self._lock:
            for name in lease._pinned:
                e = self._entries.get(name)
                if e is not None and e.pins > 0:
                    e.pins -= 1
            lease._pinned.clear()
            lease.slices += 1
            doomed = self._enforce_locked(lease)
        self._demote_or_release_all(doomed, lease)

    def end_query(self, lease: Optional[QueryLease], stats=None) -> None:
        """Unpin everything the lease held, feed the measured-vs-estimated
        drift observation back into admission, re-enforce the budget, and
        surface the per-query staging counters on ``stats.staging``."""
        if lease is None:
            return
        with self._lock:
            self._refresh_locked()
            for name in lease._pinned:
                e = self._entries.get(name)
                if e is not None and e.pins > 0:
                    e.pins -= 1
                est = lease._est.get(name, 0)
                if est > 0 and e is not None \
                        and isinstance(e.resident, StagedSegment):
                    self._observe_estimate_locked(est, e.nbytes)
            lease._pinned.clear()
            doomed = self._enforce_locked(lease)
            staged = self._staged_bytes
        self._demote_or_release_all(doomed, lease)
        if stats is not None:
            # host bytes AFTER the demotions this close triggered — the
            # per-query tier story must include its own evictees
            with self._lock:
                host = self._host_bytes
            stats.staging = lease.staging_dict(staged, host)

    # -- admission-estimate drift --------------------------------------------
    def _observe_estimate_locked(self, est: int, measured: int) -> None:
        if est <= 0 or measured <= 0:
            return
        ratio = measured / est
        ratio = min(max(ratio, _EST_SCALE_MIN), _EST_SCALE_MAX)
        self._est_scale = ((1.0 - _EST_ALPHA) * self._est_scale
                           + _EST_ALPHA * ratio)
        self.est_observations += 1

    def observe_estimate(self, est: int, measured: int) -> None:
        """Feed one measured-vs-estimated observation into the admission
        correction EWMA (the post-stage validation path; also the unit
        test hook for deliberately mis-estimated segments)."""
        with self._lock:
            self._observe_estimate_locked(est, measured)

    def estimate_scale(self) -> float:
        """Current admission correction factor (measured/estimated EWMA,
        clamped to [0.25, 4])."""
        with self._lock:
            return min(max(self._est_scale, _EST_SCALE_MIN),
                       _EST_SCALE_MAX)

    # -- eviction engine -----------------------------------------------------
    def _refresh_locked(self) -> None:
        total = 0
        for e in self._entries.values():
            try:
                e.nbytes = int(e.resident.nbytes())
            except Exception:
                e.nbytes = 0
            total += e.nbytes
        self._staged_bytes = total
        if total > self._peak_bytes:
            self._peak_bytes = total

    def _rebuild_cost_locked(self, name: str, e: _Entry) -> float:
        """How expensive is getting this resident back after eviction —
        the cost axis of the eviction ranking. Host-tier-backed residents
        restage with one H2D; batch residents re-adopt their host stacked
        arrays; a segment riding inside a resident batch can borrow its
        columns; a cold StagedSegment pays the full build, star-trees the
        tree staging on top."""
        if name in self._host_entries:
            return COST_HOST_RESTAGE
        r = e.resident
        if not isinstance(r, StagedSegment):
            return COST_BATCH_RESTAGE
        img = getattr(r, "_host_image", None)
        if img is not None and not img.empty():
            # promoted resident with unconsumed host copies: a demotion
            # recaptures them for free, so restage stays cheap
            return COST_HOST_RESTAGE
        if r._startree:
            return COST_STARTREE_BUILD
        for other in self._entries:
            if other != name and other.startswith("batch(") \
                    and name in other[6:-1].split(","):
                return COST_BORROWED_BUILD
        return COST_COLUMN_BUILD

    def _enforce_locked(self, lease: Optional[QueryLease] = None
                        ) -> List[Tuple[Optional[str], Any]]:
        """Evict unpinned residents until the budget fits, ranked by
        ``bytes * staleness / rebuild_cost`` (descending): big, cold,
        cheap-to-restage residents go first, so the budget preferentially
        keeps what is slow to get back. With equal bytes and equal costs
        this is exact LRU. Returns ``(name, resident)`` pairs — the CALLER
        demotes/releases them after dropping ``_lock`` (see
        ``_demote_or_release_all``); their bytes are already out of the
        accounting here."""
        self._refresh_locked()
        budget = self.budget_bytes
        if budget is None:
            return []
        doomed: List[Tuple[Optional[str], Any]] = []
        total = self._staged_bytes
        if total <= budget:
            return doomed
        seq = self._touch_seq + 1
        scores: Dict[str, float] = {}
        for name, e in self._entries.items():
            scores[name] = (e.nbytes * (seq - e.touch)
                            / self._rebuild_cost_locked(name, e))
        for name in sorted(scores, key=scores.get, reverse=True):
            if total <= budget:
                break
            e = self._entries[name]
            if e.pins > 0:
                # an in-flight query owns these arrays: eviction is blocked
                # (counted — a high rate means the budget is too small for
                # the concurrent working set)
                self.pin_blocked += 1
                if lease is not None:
                    lease.pin_blocked += 1
                self._mark("STAGING_PIN_BLOCKED")
                continue
            del self._entries[name]
            total -= e.nbytes
            doomed.append((name, e.resident))
            self.evictions += 1
            if lease is not None:
                lease.evictions += 1
            self._mark("STAGING_EVICTIONS")
        self._staged_bytes = total
        return doomed

    def enforce(self) -> None:
        with self._lock:
            doomed = self._enforce_locked()
        self._demote_or_release_all(doomed)

    def release_startree(self, segment_name: str, tree_index: int) -> bool:
        """Evict ONE star-tree's node arrays from a resident segment,
        leaving sibling trees and staged columns untouched — finer grain
        than whole-resident eviction when only tree bytes must go (a
        memory-pressure actuator; /debug/memory shows the per-tree bytes
        this frees). Accounting refreshes immediately."""
        with self._lock:
            e = self._entries.get(segment_name)
            if e is None or not isinstance(e.resident, StagedSegment):
                return False
            freed = e.resident.release_startree(tree_index)
            if freed:
                self._refresh_locked()
        return freed > 0

    # -- prefetch ------------------------------------------------------------
    def prefetch(self, segment, columns: Optional[List[str]] = None) -> None:
        """Enqueue background staging (segment add/reload hot path). Mutable
        (consuming) segments never stage — their arrays grow under the
        cache's feet. Best-effort: a full budget stops the prefetch instead
        of evicting serving residents."""
        if self._closed or getattr(segment, "is_mutable", False):
            return
        with self._lock:
            # snapshot the retire generation under the same lock evict()
            # bumps it: the queued item is valid only for this generation
            gen = self._retired.get(segment.segment_name, 0)
            if self._prefetch_thread is None:
                self._prefetch_q = queue.Queue()
                self._prefetch_thread = threading.Thread(
                    target=self._prefetch_loop, daemon=True,
                    name="hbm-prefetch")
                self._prefetch_thread.start()
        self._prefetch_q.put((segment, columns, gen))

    def _prefetch_loop(self) -> None:
        while True:
            item = self._prefetch_q.get()
            try:
                if item is _STOP:
                    return
                segment, columns, gen = item
                self._prefetch_one(segment, columns, gen)
            except Exception:
                log.exception("prefetch failed")
            finally:
                self._prefetch_q.task_done()

    def _prefetch_one(self, segment, columns: Optional[List[str]],
                      gen: int) -> None:
        budget = self.budget_bytes
        name = segment.segment_name
        if columns is None:
            columns = list(segment.metadata.columns.keys())
        with self._lock:
            # a removeSegment that landed while this item sat in the queue
            # must win: staging now would resurrect the evicted segment.
            # Check + stage are one atomic step against evict(); the doomed
            # list still gets released only after the lock drops.
            if self._retired.get(name, 0) != gen:
                return
            staged, doomed = self._stage_locked(segment, None)
        self._demote_or_release_all(doomed)
        for cname in columns:
            if budget is not None:
                with self._lock:
                    self._refresh_locked()
                    if self._staged_bytes >= budget:
                        return  # best-effort: never evict for a prefetch
            try:
                staged.column(cname)
            except Exception:
                log.debug("prefetch of column %r skipped", cname,
                          exc_info=True)
        # star-tree node arrays ride the same warm-up: the first star-tree
        # rung query then pays no H2D for the tree either
        md = getattr(segment, "metadata", None)
        for ti in range(int(getattr(md, "star_tree_count", 0) or 0)):
            if budget is not None:
                with self._lock:
                    self._refresh_locked()
                    if self._staged_bytes >= budget:
                        return
            try:
                staged.startree_nodes(ti)
            except Exception:
                log.debug("prefetch of star-tree %d skipped", ti,
                          exc_info=True)
        orphaned = None
        with self._lock:
            if self._retired.get(name, 0) != gen:
                # evicted while columns were staging: the entry is already
                # gone from _entries (no orphaned resident, no stale bytes
                # in accounting) — drop our device arrays eagerly instead
                # of waiting for GC. A re-added segment owns a NEW resident
                # (stage() identity check), never this one.
                e = self._entries.get(name)
                if e is None or e.resident is not staged:
                    orphaned = staged
            else:
                self.prefetched += 1
                self._refresh_locked()
        if orphaned is not None:
            orphaned.release()

    def drain_prefetch(self) -> None:
        """Block until queued prefetches finish (tests / warm-up hooks)."""
        q = self._prefetch_q
        if q is not None:
            q.join()

    def close(self) -> None:
        self._closed = True
        if self._prefetch_q is not None:
            self._prefetch_q.put(_STOP)

    # -- observability -------------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Attach a MetricsRegistry: staged/budget byte gauges for both
        tiers + event meters (spi/metrics.py ServerMeter.STAGING_*)."""
        self._metrics = registry
        # gauge lambdas run on scrape threads: only locked accessors here
        registry.gauge("staging_staged_bytes",
                       lambda: float(self.staged_bytes()))
        registry.gauge("staging_peak_bytes",
                       lambda: float(self.peak_bytes))
        registry.gauge("staging_budget_bytes",
                       lambda: float(self.budget_bytes or 0))
        registry.gauge("staging_resident_segments",
                       lambda: float(self.resident_count()))
        registry.gauge("staging_host_bytes",
                       lambda: float(self.host_bytes()))
        registry.gauge("staging_host_peak_bytes",
                       lambda: float(self.host_peak_bytes))
        registry.gauge("staging_host_budget_bytes",
                       lambda: float(self.host_budget_bytes or 0))
        registry.gauge("staging_host_entries",
                       lambda: float(self.host_entry_count()))
        # gauge-history rings: staged/host-tier bytes at few-second
        # resolution (the history dashboards need behind /debug/memory's
        # instants). The accessors take the manager lock and read running
        # counters — never a device sync.
        from pinot_tpu.common.telemetry import TELEMETRY

        TELEMETRY.track_gauge("staging.staged_bytes",
                              lambda: float(self.staged_bytes()))
        TELEMETRY.track_gauge("staging.host_bytes",
                              lambda: float(self.host_bytes()))

    def _mark(self, name: Optional[str]) -> None:
        self._mark_n(name, 1)

    def _mark_n(self, name: Optional[str], n: int) -> None:
        if name is None or n <= 0:
            return
        # flight-recorder anomaly feed (always on, metrics bound or not):
        # an eviction/demotion STORM is a freeze trigger. note_storm_event
        # never freezes synchronously, so marking under the manager lock
        # is safe.
        from pinot_tpu.common.telemetry import note_storm_event

        note_storm_event(name, n)
        if self._metrics is None:
            return
        from pinot_tpu.spi.metrics import ServerMeter

        metric = getattr(ServerMeter, name, None)
        if metric is not None:
            self._metrics.meter(metric).mark(n)

    def staged_bytes(self) -> int:
        with self._lock:
            self._refresh_locked()
            return self._staged_bytes

    @property
    def peak_bytes(self) -> int:
        with self._lock:
            return self._peak_bytes

    def host_bytes(self) -> int:
        with self._lock:
            return self._host_bytes

    @property
    def host_peak_bytes(self) -> int:
        with self._lock:
            return self._host_peak_bytes

    def resident_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def resident_nbytes(self, name: str) -> int:
        """Measured device bytes of one resident (0 when absent) — the
        post-stage truth the admission estimates are validated against."""
        with self._lock:
            self._refresh_locked()
            e = self._entries.get(name)
            return 0 if e is None else e.nbytes

    def resident_names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def host_entry_count(self) -> int:
        with self._lock:
            return len(self._host_entries)

    def host_entry_names(self) -> List[str]:
        with self._lock:
            return list(self._host_entries)

    def stats_snapshot(self) -> Dict[str, Any]:
        """Cumulative counters (bench per-suite deltas diff two of these)."""
        with self._lock:
            self._refresh_locked()
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "pinBlockedEvictions": self.pin_blocked,
                "spills": self.spills,
                "prefetched": self.prefetched,
                "borrows": self.borrows,
                "demotions": self.demotions,
                "promotions": self.promotions,
                "hostDrops": self.host_drops,
                "slicedQueries": self.sliced_queries,
                "stagedBytes": self._staged_bytes,
                "peakBytes": self._peak_bytes,
                "hostBytes": self._host_bytes,
                "hostPeakBytes": self._host_peak_bytes,
                "demotedBytes": self.demoted_bytes,
                "promotedBytes": self.promoted_bytes,
                "hostDroppedBytes": self.host_dropped_bytes,
                "estimateScale": round(self._est_scale, 4),
                "estimateObservations": self.est_observations,
            }

    def snapshot(self) -> Dict[str, Any]:
        """Bytes-accurate two-tier residency state for ``/debug/memory``."""
        with self._lock:
            self._refresh_locked()
            residents = {}
            for name, e in self._entries.items():
                d: Dict[str, Any] = {"bytes": e.nbytes, "pins": e.pins}
                r = e.resident
                if isinstance(r, StagedSegment):
                    d.update(columns=len(r._columns), packed=len(r._packed),
                             values=len(r._values),
                             startrees=len(r._startree),
                             # each tree accounted independently: evicting
                             # one must not hide (or drop) its sibling
                             startreeBytes={str(ti): b for ti, b in
                                            r.startree_nbytes().items()})
                else:
                    d["kind"] = type(r).__name__
                residents[name] = d
            host = {name: {"bytes": e.nbytes,
                           "kind": type(e.resident).__name__}
                    for name, e in self._host_entries.items()}
            return {
                "budgetBytes": self.budget_bytes,
                "stagedBytes": self._staged_bytes,
                "peakBytes": self._peak_bytes,
                "counters": {
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "pinBlockedEvictions": self.pin_blocked,
                    "spills": self.spills, "prefetched": self.prefetched,
                    "borrows": self.borrows,
                    "demotions": self.demotions,
                    "promotions": self.promotions,
                    "hostDrops": self.host_drops,
                    "slicedQueries": self.sliced_queries,
                },
                "stagedSegments": residents,
                "hostTier": {
                    "enabled": self._host_on,
                    "budgetBytes": self.host_budget_bytes,
                    "hostBytes": self._host_bytes,
                    "peakBytes": self._host_peak_bytes,
                    "demotedBytes": self.demoted_bytes,
                    "promotedBytes": self.promoted_bytes,
                    "droppedBytes": self.host_dropped_bytes,
                    "entries": host,
                },
                "estimateScale": round(self._est_scale, 4),
            }


class StagingCache(ResidencyManager):
    """Deprecated alias: the pre-residency name, kept for callers that
    constructed the cache directly (uncapped unless configured)."""
