"""HBM residency manager: budgeted, pinned, LRU-evicting segment staging.

The subsystem the tiered-storage / multi-table-scale work stands on: a
production table set cannot fit in HBM, so device staging must degrade
gracefully instead of OOMing. This module subsumes the old unbounded
``StagingCache`` and the sharded executor's ad-hoc device-column caches
behind one byte-accounted, lock-correct manager:

- **Accounting**: every resident (a per-segment :class:`StagedSegment` or a
  sharded-batch device-column set) reports ``nbytes()``; the manager rolls
  bytes up per resident and tracks the fleet total + peak.
- **Budget**: ``pinot.server.query.hbm.budget.bytes`` (spi/config.py layered
  keys; <= 0 means uncapped). When unset, the budget auto-derives from the
  backend's reported device memory (``bytes_limit`` fraction) — on hosts
  whose backend reports nothing (CPU), staging is uncapped.
- **LRU eviction of UNPINNED residents only**: queries pin the residents
  they touch for their duration via a :class:`QueryLease` (the same
  acquire/release hazard discipline as ``TableDataManager.acquire_segments``
  — ref ``BaseTableDataManager.java:71`` refcounting), so an in-flight query
  never loses its arrays mid-kernel (the SURVEY §5 race note).
- **Admission control**: a query whose estimated working set cannot fit even
  after evicting everything unpinned is routed to the host engine (a
  *spill*, counted and surfaced) instead of device-OOMing.
- **Prefetch**: segment add/reload enqueues background staging so the first
  query pays no H2D (ref: the FetchContext prefetch path,
  ``InstancePlanMakerImplV2.java:155-170``).
- **Observability**: global counters + per-query ``QueryStats.staging``
  deltas, ``ServerMeter`` meters / gauges when bound to a registry, and a
  bytes-accurate snapshot for ``/debug/memory``.
"""

from __future__ import annotations

import logging
import queue
import threading

from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional

from pinot_tpu.engine.staging import StagedSegment, staged_int_dtype
from pinot_tpu.spi.config import CommonConstants

log = logging.getLogger(__name__)

# budget sentinel: resolve from config, then backend device memory
AUTO = object()

_STOP = object()


# --------------------------------------------------------------------------
# working-set estimation (admission control)
# --------------------------------------------------------------------------

def estimate_segment_bytes(segment, columns: Iterable[str]) -> int:
    """Metadata-only estimate of the device bytes staging ``columns`` of
    ``segment`` costs (fwd + dict values + null bitmap; the same layout
    contract as ``StagedSegment._stage``). Used for admission BEFORE any
    H2D, so it must not touch column data."""
    cap = int(getattr(segment, "padded_capacity", 0) or 0)
    md = getattr(segment, "metadata", None)
    cols = getattr(md, "columns", {}) if md is not None else {}
    total = 0
    for name in columns:
        cm = cols.get(name) if hasattr(cols, "get") else None
        if cm is None:
            continue
        if cm.single_value:
            if cm.has_dictionary:
                total += cap * 4  # fwd dictIds upcast to int32
            elif cm.data_type.is_integral:
                total += cap * staged_int_dtype(cm).itemsize
            else:
                total += cap * 8  # raw floats stay f64 (staging module note)
        else:
            total += cap * 4 * max(cm.max_num_multi_values, 1) + cap * 4
        if cm.has_dictionary and cm.data_type.is_numeric:
            total += cm.cardinality * (
                staged_int_dtype(cm).itemsize if cm.data_type.is_integral
                else 4)
        if cm.has_nulls:
            total += cap
    return total


def resolve_budget_bytes(budget_bytes: Any = AUTO,
                         config=None) -> Optional[int]:
    """Budget resolution: explicit arg > layered config key > backend device
    memory. Returns None for uncapped (explicit <= 0, or nothing known)."""
    if budget_bytes is not AUTO:
        if budget_bytes is None:
            return None
        b = int(budget_bytes)
        return b if b > 0 else None
    from pinot_tpu.spi.config import PinotConfiguration

    cfg = config if config is not None else PinotConfiguration()
    v = cfg.get(CommonConstants.HBM_BUDGET_BYTES_KEY)
    if v is not None:
        b = int(v)
        return b if b > 0 else None
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        if limit:
            return int(limit * CommonConstants.DEFAULT_HBM_BUDGET_FRACTION)
    except Exception:  # backend without memory stats / not initialized
        pass
    return None


# --------------------------------------------------------------------------
# leases
# --------------------------------------------------------------------------

class QueryLease:
    """One query's pin set + staging counters. Created by ``begin_query``,
    closed by ``end_query``; residents pinned through a lease survive
    eviction pressure until the lease closes (acquire/release discipline)."""

    __slots__ = ("device_allowed", "spilled", "hits", "misses",
                 "evictions", "pin_blocked", "_pinned")

    def __init__(self, device_allowed: bool = True):
        self.device_allowed = device_allowed
        self.spilled = not device_allowed
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.pin_blocked = 0
        self._pinned: set = set()

    def staging_dict(self, staged_bytes: int) -> Dict[str, int]:
        """The ``QueryStats.staging`` payload (merge: counters sum, *Bytes
        keys max — see QueryStats.merge)."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "pinBlockedEvictions": self.pin_blocked,
            "spills": 1 if self.spilled else 0,
            "stagedBytes": int(staged_bytes),
        }


class _Entry:
    __slots__ = ("resident", "pins", "nbytes")

    def __init__(self, resident):
        self.resident = resident
        self.pins = 0
        self.nbytes = 0


class ResidencyManager:
    """(name -> resident) LRU with byte budget, pins, spill admission and
    background prefetch. A *resident* is anything with ``nbytes()`` and
    ``release()`` — :class:`StagedSegment` for the per-segment path, the
    sharded executor's batch wrapper for the combine path."""

    def __init__(self, budget_bytes: Any = AUTO, config=None):
        self._budget_arg = budget_bytes
        self._config = config
        self._budget_resolved = False
        self._budget: Optional[int] = None
        # RLock: evicting a batch resident re-enters through the executor's
        # release callback (discard()), and that must not deadlock
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()  # guarded-by: _lock
        self._staged_bytes = 0  # guarded-by: _lock
        self._peak_bytes = 0  # guarded-by: _lock
        # per-name eviction generation: a queued prefetch carries the seq it
        # was enqueued under and must not resurrect a segment removed while
        # it waited (the prefetch-vs-removeSegment race)
        self._retired: Dict[str, int] = {}  # guarded-by: _lock
        # global counters (process lifetime; per-query deltas ride leases)
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock
        self.pin_blocked = 0  # guarded-by: _lock
        self.spills = 0  # guarded-by: _lock
        self.prefetched = 0  # guarded-by: _lock
        self.borrows = 0  # guarded-by: _lock
        # cross-query column dedup: ``column_borrower(segment, name)``
        # (set by the sharded executor) lets a StagedSegment serve a column
        # from a resident batch's device copy instead of staging its own
        self.column_borrower = None
        self._metrics = None
        self._prefetch_q: Optional["queue.Queue"] = None
        self._prefetch_thread: Optional[threading.Thread] = None
        self._closed = False

    # -- budget --------------------------------------------------------------
    @property
    def budget_bytes(self) -> Optional[int]:
        """Lazy: resolving the auto default may initialize the jax backend,
        which must not happen at executor construction."""
        if not self._budget_resolved:
            with self._lock:
                if not self._budget_resolved:
                    self._budget = resolve_budget_bytes(self._budget_arg,
                                                        self._config)
                    self._budget_resolved = True
        return self._budget

    def set_budget_bytes(self, budget_bytes: Optional[int]) -> None:
        with self._lock:
            self._budget = (int(budget_bytes)
                            if budget_bytes and int(budget_bytes) > 0
                            else None)
            self._budget_resolved = True
            doomed = self._enforce_locked()
        self._release_all(doomed)

    # -- staging (the StagingCache surface, now lock-correct) ---------------
    def stage(self, segment, lease: Optional[QueryLease] = None
              ) -> StagedSegment:
        """Resident StagedSegment for ``segment``, created on miss. Atomic
        get-or-create under the manager lock: concurrent stagers of the same
        segment share ONE StagedSegment (the old get-then-set built
        duplicate device arrays and leaked one set until GC). A reloaded
        segment (same name, new object) invalidates the stale resident —
        identity check, same guard as before."""
        with self._lock:
            resident, doomed = self._stage_locked(segment, lease)
        self._release_all(doomed)
        return resident

    def _stage_locked(self, segment, lease: Optional[QueryLease]):
        """Get-or-create under ``_lock`` (caller holds it). Returns
        ``(resident, doomed)``; the caller releases ``doomed`` after
        dropping the lock."""
        name = segment.segment_name
        doomed: List[Any] = []
        e = self._entries.get(name)
        if e is not None and isinstance(e.resident, StagedSegment) \
                and e.resident.segment is segment:
            self._entries.move_to_end(name)
            self.hits += 1
            if lease is not None:
                lease.hits += 1
            self._mark("STAGING_HITS")
        else:
            if e is not None:  # identity change: drop stale arrays
                del self._entries[name]
                doomed.append(e.resident)
            e = _Entry(StagedSegment(segment,
                                     borrower=self.column_borrower))
            self._entries[name] = e
            self.misses += 1
            if lease is not None:
                lease.misses += 1
            self._mark("STAGING_MISSES")
        self._pin_locked(name, e, lease)
        doomed += self._enforce_locked(lease)
        return e.resident, doomed

    def register(self, name: str, make_resident, same=None,
                 lease: Optional[QueryLease] = None):
        """Generic get-or-create for non-segment residents (sharded batch
        device-column sets). ``make_resident()`` builds on miss; ``same(r)``
        says whether the cached resident is still current."""
        doomed: List[Any] = []
        with self._lock:
            e = self._entries.get(name)
            if e is not None and (same is None or same(e.resident)):
                self._entries.move_to_end(name)
                self.hits += 1
                if lease is not None:
                    lease.hits += 1
                self._mark("STAGING_HITS")
            else:
                if e is not None:
                    del self._entries[name]
                    doomed.append(e.resident)
                e = _Entry(make_resident())
                self._entries[name] = e
                self.misses += 1
                if lease is not None:
                    lease.misses += 1
                self._mark("STAGING_MISSES")
            self._pin_locked(name, e, lease)
            # re-measure + budget-enforce on EVERY outcome, like stage():
            # without this a miss inserts an unaccounted batch resident and
            # stagedBytes drifts until the next unrelated refresh
            doomed += self._enforce_locked(lease)
            resident = e.resident
        self._release_all(doomed)
        return resident

    def _pin_locked(self, name: str, e: _Entry,
                    lease: Optional[QueryLease]) -> None:
        if lease is not None and name not in lease._pinned:
            e.pins += 1
            lease._pinned.add(name)

    def account(self, name: str,
                lease: Optional[QueryLease] = None) -> None:
        """Re-measure one resident (its arrays were staged after admission)
        and enforce the budget."""
        with self._lock:
            doomed = self._enforce_locked(lease)
        self._release_all(doomed)

    def evict(self, name: str) -> None:
        """Explicit eviction (segment unassigned / reloaded). In-flight
        queries keep their arrays alive through python refs; XLA frees the
        HBM when the last ref drops. Bumps the retire generation so queued
        prefetches of the removed segment become no-ops."""
        with self._lock:
            self._retired[name] = self._retired.get(name, 0) + 1
            e = self._entries.pop(name, None)
            if e is not None:
                self.evictions += 1
                self._mark("STAGING_EVICTIONS")
                self._refresh_locked()
        if e is not None:
            # outside the lock: a resident's release may take its own lock
            # (StagedSegment serializing against in-flight column builds) or
            # re-enter the manager (batch residents clearing executor
            # caches) — lock order is always manager -> resident, held
            # never-both on the release path
            e.resident.release()

    def note_borrow(self, batch_name: str) -> None:
        """A per-segment staging built a column FROM a resident batch's
        device copy (cross-query dedup): count it and touch the batch in
        the LRU — borrowers keep their source warm, the reference-count of
        the share."""
        with self._lock:
            self.borrows += 1
            if batch_name in self._entries:
                self._entries.move_to_end(batch_name)
            self._mark("STAGING_BORROWS")

    def discard(self, name: str) -> None:
        """Drop an entry WITHOUT calling release (the owner already freed
        the arrays). Idempotent — also the re-entry point for batch
        residents whose release callback clears executor caches."""
        with self._lock:
            self._entries.pop(name, None)  # lint: ignore[conservation] — owner already released the arrays (discard contract)
            self._refresh_locked()

    def clear(self) -> None:
        with self._lock:
            doomed = [e.resident for e in self._entries.values()]
            self._entries.clear()
            self._staged_bytes = 0
        self._release_all(doomed)

    def _release_all(self, doomed: List[Any]) -> None:
        """Release evicted residents AFTER the manager lock is dropped:
        ``release()`` may acquire the resident's own lock, whose holders
        re-enter the manager (column borrower -> ``note_borrow``) — calling
        it under ``_lock`` is the A->B/B->A inversion the lint gate exists
        to catch."""
        for r in doomed:
            try:
                r.release()
            except Exception:
                log.exception("resident release failed")

    # -- query protocol ------------------------------------------------------
    def begin_query(self, segments: List[Any],
                    columns: Iterable[str]) -> QueryLease:
        """Admission: fit the query's estimated working set against what
        COULD be freed (budget minus other queries' pinned bytes). A query
        that cannot fit is spilled to the host engine — graceful
        degradation, never a device OOM."""
        budget = self.budget_bytes
        if budget is None:
            return QueryLease(device_allowed=True)
        cols = list(columns)
        with self._lock:
            self._refresh_locked()
            names = {getattr(s, "segment_name", None) for s in segments}
            reusable = 0
            missing_est = 0
            for s in segments:
                e = self._entries.get(s.segment_name)
                if e is not None and isinstance(e.resident, StagedSegment) \
                        and e.resident.segment is s:
                    reusable += e.nbytes
                else:
                    missing_est += estimate_segment_bytes(s, cols)
            other_pinned = sum(e.nbytes for n, e in self._entries.items()
                               if e.pins > 0 and n not in names)
            if missing_est + reusable + other_pinned > budget:
                self.spills += 1
                self._mark("STAGING_SPILLS")
                log.info(
                    "HBM admission: working set ~%d B (+%d B reusable) over "
                    "budget %d B (%d B pinned elsewhere); spilling query to "
                    "host engine", missing_est, reusable, budget,
                    other_pinned)
                return QueryLease(device_allowed=False)
        return QueryLease(device_allowed=True)

    def end_query(self, lease: Optional[QueryLease], stats=None) -> None:
        """Unpin everything the lease held, re-enforce the budget, and
        surface the per-query staging counters on ``stats.staging``."""
        if lease is None:
            return
        with self._lock:
            for name in lease._pinned:
                e = self._entries.get(name)
                if e is not None and e.pins > 0:
                    e.pins -= 1
            lease._pinned.clear()
            doomed = self._enforce_locked(lease)
            staged = self._staged_bytes
        self._release_all(doomed)
        if stats is not None:
            stats.staging = lease.staging_dict(staged)

    # -- eviction engine -----------------------------------------------------
    def _refresh_locked(self) -> None:
        total = 0
        for e in self._entries.values():
            try:
                e.nbytes = int(e.resident.nbytes())
            except Exception:
                e.nbytes = 0
            total += e.nbytes
        self._staged_bytes = total
        if total > self._peak_bytes:
            self._peak_bytes = total

    def _enforce_locked(self, lease: Optional[QueryLease] = None
                        ) -> List[Any]:
        """LRU-evict unpinned residents until the budget fits. Returns the
        evicted residents — the CALLER releases them after dropping
        ``_lock`` (see ``_release_all``); their bytes are already out of
        the accounting here."""
        self._refresh_locked()
        budget = self.budget_bytes
        if budget is None:
            return []
        doomed: List[Any] = []
        total = self._staged_bytes
        for name in list(self._entries):
            if total <= budget:
                break
            e = self._entries[name]
            if e.pins > 0:
                # an in-flight query owns these arrays: eviction is blocked
                # (counted — a high rate means the budget is too small for
                # the concurrent working set)
                self.pin_blocked += 1
                if lease is not None:
                    lease.pin_blocked += 1
                self._mark("STAGING_PIN_BLOCKED")
                continue
            del self._entries[name]
            total -= e.nbytes
            doomed.append(e.resident)
            self.evictions += 1
            if lease is not None:
                lease.evictions += 1
            self._mark("STAGING_EVICTIONS")
        self._staged_bytes = total
        return doomed

    def enforce(self) -> None:
        with self._lock:
            doomed = self._enforce_locked()
        self._release_all(doomed)

    # -- prefetch ------------------------------------------------------------
    def prefetch(self, segment, columns: Optional[List[str]] = None) -> None:
        """Enqueue background staging (segment add/reload hot path). Mutable
        (consuming) segments never stage — their arrays grow under the
        cache's feet. Best-effort: a full budget stops the prefetch instead
        of evicting serving residents."""
        if self._closed or getattr(segment, "is_mutable", False):
            return
        with self._lock:
            # snapshot the retire generation under the same lock evict()
            # bumps it: the queued item is valid only for this generation
            gen = self._retired.get(segment.segment_name, 0)
            if self._prefetch_thread is None:
                self._prefetch_q = queue.Queue()
                self._prefetch_thread = threading.Thread(
                    target=self._prefetch_loop, daemon=True,
                    name="hbm-prefetch")
                self._prefetch_thread.start()
        self._prefetch_q.put((segment, columns, gen))

    def _prefetch_loop(self) -> None:
        while True:
            item = self._prefetch_q.get()
            try:
                if item is _STOP:
                    return
                segment, columns, gen = item
                self._prefetch_one(segment, columns, gen)
            except Exception:
                log.exception("prefetch failed")
            finally:
                self._prefetch_q.task_done()

    def _prefetch_one(self, segment, columns: Optional[List[str]],
                      gen: int) -> None:
        budget = self.budget_bytes
        name = segment.segment_name
        if columns is None:
            columns = list(segment.metadata.columns.keys())
        with self._lock:
            # a removeSegment that landed while this item sat in the queue
            # must win: staging now would resurrect the evicted segment.
            # Check + stage are one atomic step against evict(); the doomed
            # list still gets released only after the lock drops.
            if self._retired.get(name, 0) != gen:
                return
            staged, doomed = self._stage_locked(segment, None)
        self._release_all(doomed)
        for cname in columns:
            if budget is not None:
                with self._lock:
                    self._refresh_locked()
                    if self._staged_bytes >= budget:
                        return  # best-effort: never evict for a prefetch
            try:
                staged.column(cname)
            except Exception:
                log.debug("prefetch of column %r skipped", cname,
                          exc_info=True)
        # star-tree node arrays ride the same warm-up: the first star-tree
        # rung query then pays no H2D for the tree either
        md = getattr(segment, "metadata", None)
        for ti in range(int(getattr(md, "star_tree_count", 0) or 0)):
            if budget is not None:
                with self._lock:
                    self._refresh_locked()
                    if self._staged_bytes >= budget:
                        return
            try:
                staged.startree_nodes(ti)
            except Exception:
                log.debug("prefetch of star-tree %d skipped", ti,
                          exc_info=True)
        orphaned = None
        with self._lock:
            if self._retired.get(name, 0) != gen:
                # evicted while columns were staging: the entry is already
                # gone from _entries (no orphaned resident, no stale bytes
                # in accounting) — drop our device arrays eagerly instead
                # of waiting for GC. A re-added segment owns a NEW resident
                # (stage() identity check), never this one.
                e = self._entries.get(name)
                if e is None or e.resident is not staged:
                    orphaned = staged
            else:
                self.prefetched += 1
                self._refresh_locked()
        if orphaned is not None:
            orphaned.release()

    def drain_prefetch(self) -> None:
        """Block until queued prefetches finish (tests / warm-up hooks)."""
        q = self._prefetch_q
        if q is not None:
            q.join()

    def close(self) -> None:
        self._closed = True
        if self._prefetch_q is not None:
            self._prefetch_q.put(_STOP)

    # -- observability -------------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Attach a MetricsRegistry: staged/budget byte gauges + event
        meters (spi/metrics.py ServerMeter.STAGING_*)."""
        self._metrics = registry
        # gauge lambdas run on scrape threads: only locked accessors here
        registry.gauge("staging_staged_bytes",
                       lambda: float(self.staged_bytes()))
        registry.gauge("staging_peak_bytes",
                       lambda: float(self.peak_bytes))
        registry.gauge("staging_budget_bytes",
                       lambda: float(self.budget_bytes or 0))
        registry.gauge("staging_resident_segments",
                       lambda: float(self.resident_count()))

    def _mark(self, name: Optional[str]) -> None:
        self._mark_n(name, 1)

    def _mark_n(self, name: Optional[str], n: int) -> None:
        if self._metrics is None or name is None or n <= 0:
            return
        from pinot_tpu.spi.metrics import ServerMeter

        metric = getattr(ServerMeter, name, None)
        if metric is not None:
            self._metrics.meter(metric).mark(n)

    def staged_bytes(self) -> int:
        with self._lock:
            self._refresh_locked()
            return self._staged_bytes

    @property
    def peak_bytes(self) -> int:
        with self._lock:
            return self._peak_bytes

    def resident_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def resident_names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def stats_snapshot(self) -> Dict[str, int]:
        """Cumulative counters (bench per-suite deltas diff two of these)."""
        with self._lock:
            self._refresh_locked()
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "pinBlockedEvictions": self.pin_blocked,
                "spills": self.spills,
                "prefetched": self.prefetched,
                "borrows": self.borrows,
                "stagedBytes": self._staged_bytes,
                "peakBytes": self._peak_bytes,
            }

    def snapshot(self) -> Dict[str, Any]:
        """Bytes-accurate residency state for ``/debug/memory``."""
        with self._lock:
            self._refresh_locked()
            residents = {}
            for name, e in self._entries.items():
                d: Dict[str, Any] = {"bytes": e.nbytes, "pins": e.pins}
                r = e.resident
                if isinstance(r, StagedSegment):
                    d.update(columns=len(r._columns), packed=len(r._packed),
                             values=len(r._values),
                             startrees=len(r._startree))
                else:
                    d["kind"] = type(r).__name__
                residents[name] = d
            return {
                "budgetBytes": self.budget_bytes,
                "stagedBytes": self._staged_bytes,
                "peakBytes": self._peak_bytes,
                "counters": {
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "pinBlockedEvictions": self.pin_blocked,
                    "spills": self.spills, "prefetched": self.prefetched,
                    "borrows": self.borrows,
                },
                "stagedSegments": residents,
            }


class StagingCache(ResidencyManager):
    """Deprecated alias: the pre-residency name, kept for callers that
    constructed the cache directly (uncapped unless configured)."""
