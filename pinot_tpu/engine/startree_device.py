"""Star-tree device rung: pre-aggregated node slices through the kernels.

The device promotion of ``engine/startree_exec.py``'s host walker
(re-design of ``StarTreeFilterOperator.java:87`` +
``StarTreeGroupByExecutor.java:43``): the *tree walk* stays host-side — it
is a pointer chase over R pre-aggregated records (R << num_docs) — but the
aggregation runs on device through the SAME group-by kernel ladder the
forward-index scan uses:

1. ``resolve_matches`` + ``StarTree.select_records`` pick the answering
   record indices (a few hundred to a few thousand for the SSB Q2.x
   shape — vs a 3M-doc scan).
2. The indices pad to a power-of-two capacity and ride to the device as
   ONE small int32 array; the jitted kernel gathers the staged node
   columns (``StagedSegment.startree_nodes`` — byte-accounted, pinned,
   evictable residents like any column) down to the selected slice and
   runs ``build_kernel_body`` over it — dense scatter for narrowed key
   spaces, the hash/sort rungs past the sparse threshold, identical
   packed-output framing, one D2H fetch.
3. Decode reassembles the ORIGINAL aggregation states from the rewritten
   pre-agg leaves (``StarTreePlan.agg_map``: count = sum of the count
   column, avg = sum+count pair), so ``GroupByResult``/``AggResult``
   merging — the CombineOperator analogue — applies unchanged.

Queries the node plan can't serve (key space past MAX_DEVICE_GROUPS)
raise PlanError and the host walker serves; queries the TREE can't serve
never reach here (``pick_star_tree`` gates both paths).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.engine.aggregates import AggDef
from pinot_tpu.engine.plan import PlanError, StarTreePlan, plan_star_tree
from pinot_tpu.engine.results import AggResult, GroupByResult, QueryStats
from pinot_tpu.query.context import QueryContext

POS_INF = float("inf")
NEG_INF = float("-inf")


def build_startree_kernel(spec: Tuple):
    """Jitted ``fn(cols, idx, params, num_docs) -> packed f64 vector``:
    gathers each staged node column down to the ``idx`` slice (padding
    gathers row 0; the kernel's ``doc < num_docs`` mask drops it) and runs
    the standard kernel body — the node table IS a segment to the kernel."""
    import jax
    import jax.numpy as jnp

    from pinot_tpu.engine.kernels import (
        build_kernel_body,
        pack_outputs,
        sparse_mode,
    )

    body = build_kernel_body(spec, sparse_k=sparse_mode(spec))

    def kernel(cols, idx, params, num_docs):
        gathered = {name: {k: v[idx] for k, v in tree.items()}
                    for name, tree in cols.items()}
        return pack_outputs(body(gathered, params, num_docs, jnp.int32(0)),
                            spec)

    return jax.jit(kernel)


def _empty_states(aggs: List[AggDef]) -> List[Any]:
    """Zero-match scalar states, matching the scan path's conventions."""
    out: List[Any] = []
    for agg in aggs:
        out.append({"count": 0, "sum": 0.0, "min": POS_INF,
                    "max": NEG_INF, "avg": (0.0, 0)}[agg.base])
    return out


def _leaf_states(base: str, leaves: List[np.ndarray], gidx) -> List[Any]:
    """One original aggregation's per-group states from its rewritten
    pre-agg leaves (``gidx`` = live group indexes into dense leaves)."""
    if base == "count":
        arr = np.asarray(leaves[0])[gidx]
        return [int(v) for v in arr]
    if base in ("sum", "min", "max"):
        arr = np.asarray(leaves[0])[gidx]
        return [float(v) for v in arr]
    if base == "avg":
        s = np.asarray(leaves[0])[gidx]
        c = np.asarray(leaves[1])[gidx]
        return [(float(a), int(b)) for a, b in zip(s, c)]
    raise AssertionError(base)


def _decode_grouped(plan: StarTreePlan, segment,
                    out: Dict[str, Any]) -> GroupByResult:
    """Kernel output -> GroupByResult keyed on dictionary VALUES, using the
    plan's own strides/bases (the narrowed-gdict decode contract shared
    with ``executor.decode_grouped_result``)."""
    presence = np.asarray(out["presence"])
    gidx = np.nonzero(presence)[0]
    result = GroupByResult()
    if gidx.size == 0:
        return result
    strides = plan.group_strides.astype(np.int64)
    key_cols: List[List[Any]] = []
    for i, col in enumerate(plan.group_cols):
        dids = (gidx // strides[i]) % plan.group_cards[i]
        d = segment.data_source(col).dictionary
        key_cols.append(d.get_values(dids + plan.group_bases[i]))
    keys = list(zip(*key_cols))

    states_per_agg = [
        _leaf_states(base, [out[f"agg{j}"] for j in leaf_idx], gidx)
        for base, leaf_idx in plan.agg_map]
    for gi, key in enumerate(keys):
        result.groups[key] = [states_per_agg[ai][gi]
                              for ai in range(len(plan.agg_map))]
    return result


def _decode_scalar(plan: StarTreePlan, out: Dict[str, Any]) -> AggResult:
    states: List[Any] = []
    for base, leaf_idx in plan.agg_map:
        leaves = [out[f"agg{j}"] for j in leaf_idx]
        if base == "count":
            states.append(int(leaves[0]))
        elif base in ("sum", "min", "max"):
            states.append(float(leaves[0]))
        else:  # avg
            states.append((float(leaves[0]), int(leaves[1])))
    return AggResult(states)


def execute_star_tree_device(executor, ctx: QueryContext,
                             aggs: List[AggDef], segment, tree,
                             matches: Dict[str, Any],
                             stats: QueryStats,
                             tree_index: Optional[int] = None
                             ) -> Optional[Any]:
    """-> AggResult / GroupByResult served from device-resident node
    arrays, or raises PlanError (host walker serves). ``executor`` provides
    the residency manager (staging + lease pinning) and the star-tree
    kernel cache. ``tree_index`` is the pick's index into
    ``segment.star_trees`` (derived by identity when omitted)."""
    import jax.numpy as jnp

    from pinot_tpu.engine.kernels import unpack_outputs

    if tree_index is None:
        tree_index = segment.star_trees.index(tree)
    group_cols = [e.name for e in ctx.group_by]
    idx = tree.select_records(matches, group_cols)
    n = int(idx.shape[0])

    plan = plan_star_tree(ctx, segment, tree, matches, n)

    if n == 0:
        # nothing selected: skip the launch, emit the scan path's empty
        # shapes (stats still count the segment as processed, zero scanned)
        stats.num_segments_processed += 1
        stats.total_docs += segment.num_docs
        if ctx.is_group_by:
            return GroupByResult()
        return AggResult(_empty_states(aggs))

    # stage the node arrays through the residency manager: the segment
    # resident is pinned by this query's lease, so the arrays cannot be
    # evicted out from under the launch
    staged = executor.residency.stage(segment,
                                      lease=executor._lease_of(stats))

    def launch():
        nodes = staged.startree_nodes(tree_index)
        cols = {key: {"fwd": nodes[key]} for key in plan.columns}
        capacity = plan.spec[-1]
        padded = np.zeros(capacity, dtype=np.int32)
        padded[:n] = idx.astype(np.int32)
        kernel = executor._startree_kernel(plan.spec)
        packed = kernel(cols, jnp.asarray(padded), tuple(plan.params),
                        np.int32(n))
        return unpack_outputs(packed, plan.spec)  # may raise PlanError

    # per-segment coalescing contract (engine/executor._kernel_flight):
    # concurrent identical dashboard queries — the SAME compiled ctx object
    # over the same staged tree — share one node-slice launch + D2H. The
    # walk/plan above stays per-caller (host work, query-private stats).
    from pinot_tpu.common.tracing import maybe_span

    with maybe_span(stats, "Kernel", kernel="startree_device",
                    segment=segment.segment_name, records=n):
        out, _ = executor._kernel_flight.do(
            ("startree", id(ctx), segment.segment_name, tree_index,
             id(staged)),
            launch)

    stats.num_segments_processed += 1
    stats.total_docs += segment.num_docs
    stats.num_docs_scanned += n
    stats.num_segments_matched += 1
    if not ctx.is_group_by:
        return _decode_scalar(plan, out)
    return _decode_grouped(plan, segment, out)
