"""Device path for ordered selection: filter + top-k on the accelerator.

The reference's hot realtime shape — ``SELECT cols FROM t WHERE ...
ORDER BY ts DESC LIMIT 10`` (``SelectionOrderByOperator.java``) — runs the
filter scan AND the order-by selection on device: the boolean mask and a
lexicographic ``lax.sort`` over the order keys (+ docId as the final key,
which reproduces the host's stable-sort tie semantics exactly) produce the
per-segment top-k doc ids; only k ids cross the wire, and the k rows
materialize from the host-side column files (row materialization is
O(k · columns), never O(capacity)).

Eligibility (everything else falls back to the numpy host path):
- every ORDER BY expression is a non-null numeric/dict SV column
  (dictionary columns sort by dictId — the dictionary is sorted, so
  dictId order IS value order);
- the filter compiles for the device (plan._compile_filter);
- offset+limit bounded (top-k stays a small D2H);
- immutable, non-upsert segments.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pinot_tpu.engine import host_engine
from pinot_tpu.engine.kernels import _ParamCursor, _emit_filter
from pinot_tpu.engine.plan import PlanError, _compile_filter
from pinot_tpu.engine.results import DataSchema, QueryStats, ResultTable
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Identifier
from pinot_tpu.segment.immutable import ImmutableSegment

# top-k cap: past this the dense sort + D2H stops beating the host path
MAX_DEVICE_SELECTION_K = 8192
# LRU bound on compiled top-k kernels (k rides in the cache key)
_KERNEL_CACHE_CAP = 256


def _order_columns(ctx: QueryContext,
                   segment: ImmutableSegment) -> Optional[List[str]]:
    import math

    cols = []
    for ob in ctx.order_by:
        e = ob.expr
        if not isinstance(e, Identifier) or e.name.startswith("$"):
            return None
        cm = segment.metadata.column(e.name)
        if not cm.single_value or cm.has_nulls:
            return None
        if not (cm.has_dictionary or cm.data_type.is_numeric):
            return None
        if not cm.has_dictionary:
            from pinot_tpu.engine.staging import staged_int_dtype

            if (cm.data_type.is_integral
                    and staged_int_dtype(cm) != np.dtype(np.int32)):
                return None  # i64 keys would round through the f64 sort
            if not cm.data_type.is_integral:
                # the kernel parks filtered-out rows at +inf: a raw float
                # column containing ±inf/NaN would collide with (or sort
                # past) the sentinel — stats must PROVE finiteness
                try:
                    if (cm.min_value is None or cm.max_value is None
                            or not math.isfinite(float(cm.min_value))
                            or not math.isfinite(float(cm.max_value))):
                        return None
                except (TypeError, ValueError):
                    return None
        cols.append(e.name)
    return cols


def _build_kernel(filter_spec, directions: Tuple[bool, ...], capacity: int,
                  k: int):
    """jitted fn(cols, params, num_docs, keys) -> (docids[k], n_matched).
    Keys sort lexicographically with docId as the FINAL key — a unique
    total order identical to the host's stable lexsort."""

    def kernel(cols, params, num_docs, keys):
        pc = _ParamCursor(params)
        mask = _emit_filter(filter_spec, cols, pc, capacity)
        pc.finish()  # selection params are exactly the filter params
        mask = mask & (jnp.arange(capacity, dtype=jnp.int32) < num_docs)
        operands = []
        for key, asc in zip(keys, directions):
            v = key.astype(jnp.float64)
            if not asc:
                v = -v
            operands.append(jnp.where(mask, v, jnp.inf))
        iota = jnp.arange(capacity, dtype=jnp.int32)
        sorted_ops = jax.lax.sort(
            tuple(operands) + (iota,), num_keys=len(operands) + 1)
        return sorted_ops[-1][:k], mask.sum(dtype=jnp.int32)

    return jax.jit(kernel)


def device_selection(ctx: QueryContext, segments: List[ImmutableSegment],
                     staging, kernel_cache: Dict,
                     stats: Optional[QueryStats]) -> Optional[ResultTable]:
    """The ordered-selection branch of host_engine.execute_selection with
    the per-segment scan+sort on device; returns None when ineligible."""
    need = ctx.offset + ctx.limit
    if not ctx.order_by or need <= 0 or need > MAX_DEVICE_SELECTION_K:
        return None

    schema = segments[0].metadata.schema
    select = host_engine._expand_select(ctx, schema)
    names = host_engine._select_names(ctx, select)
    types = [host_engine._column_type(segments[0], e) for e in select]

    # phase 1: verify EVERY segment is eligible before any kernel runs or
    # stats mutate — a mid-loop fallback would otherwise double-count the
    # already-processed segments when the host path re-tracks them all
    plans: List[Tuple[ImmutableSegment, List[str], Tuple, List[Any],
                      List[str]]] = []
    for seg in segments:
        if getattr(seg, "is_mutable", False) \
                or getattr(seg, "valid_doc_ids", None) is not None:
            return None
        order_cols = _order_columns(ctx, seg)
        if order_cols is None:
            return None
        try:
            params: List[Any] = []
            columns: List[str] = []
            filter_spec = _compile_filter(ctx.filter, seg, params, columns)
        except PlanError:
            return None
        plans.append((seg, order_cols, filter_spec, params, columns))

    picked: List[Tuple[ImmutableSegment, np.ndarray]] = []
    lease = getattr(stats, "_staging_lease", None)
    for seg, order_cols, filter_spec, params, columns in plans:
        staged = staging.stage(seg, lease=lease)
        cols = {name: staged.column(name).tree() for name in columns}
        keys = [staged.column(c).tree()["fwd"] for c in order_cols]
        k = min(need, seg.padded_capacity)
        ckey = (filter_spec, tuple(ob.ascending for ob in ctx.order_by),
                seg.padded_capacity, k,
                tuple(sorted((n, tuple(sorted(t))) for n, t in
                             ((nm, cols[nm].keys()) for nm in cols))))
        kern = kernel_cache.get(ckey)
        if kern is None:
            kern = _build_kernel(
                filter_spec, tuple(ob.ascending for ob in ctx.order_by),
                seg.padded_capacity, k)
            kernel_cache[ckey] = kern
            while len(kernel_cache) > _KERNEL_CACHE_CAP:
                kernel_cache.popitem(last=False)
        elif hasattr(kernel_cache, "move_to_end"):
            kernel_cache.move_to_end(ckey)
        docids_dev, n = kern(cols, tuple(params), jnp.int32(seg.num_docs),
                             keys)
        n = int(n)
        if stats is not None:
            stats.num_segments_processed += 1
            stats.total_docs += seg.num_docs
            stats.num_docs_scanned += n
            stats.num_segments_matched += 1 if n else 0
        if n == 0:
            continue
        picked.append((seg, np.asarray(docids_dev)[:min(n, k)]))

    if not picked:
        return ResultTable(DataSchema(names, types), [])

    # merge the per-segment top-k candidates exactly like the host path:
    # stable lexsort over (keys...) in segment order == global ordering
    key_cols: List[np.ndarray] = []
    for ki, ob in enumerate(ctx.order_by):
        key_cols.append(np.concatenate(
            [host_engine._order_key_array(seg, ob.expr, d)
             for seg, d in picked]))
    order = host_engine._lexsort(key_cols,
                                 [ob.ascending for ob in ctx.order_by])
    order = order[ctx.offset: ctx.offset + ctx.limit]

    bounds = np.cumsum([0] + [len(d) for _, d in picked])
    rows: List[List[Any]] = [None] * len(order)  # type: ignore[list-item]
    for si, (seg, docids) in enumerate(picked):
        local = [(oi, int(gi - bounds[si])) for oi, gi in enumerate(order)
                 if bounds[si] <= gi < bounds[si + 1]]
        if not local:
            continue
        ids = np.asarray([docids[li] for _, li in local])
        cols_v = [host_engine._select_values(seg, e, ids) for e in select]
        for row_i, (oi, _li) in enumerate(local):
            rows[oi] = [c[row_i] for c in cols_v]
    return ResultTable(DataSchema(names, types), rows)
