"""Star-tree query execution: fit check + pre-aggregated record aggregation.

Re-design of ``pinot-core/.../startree/StarTreeUtils.java:47``
(``isFitForStarTree`` + predicate-map extraction), the node walk
(``StarTreeFilterOperator.java:87``) and the pre-agg aggregation
(``StarTreeGroupByExecutor.java:43``); selection logic mirrors
``AggregationGroupByOrderByPlanNode.java:66-87``.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Set, Tuple

import numpy as np

from pinot_tpu.engine.aggregates import AggDef, agg_value_expr
from pinot_tpu.engine.results import AggResult, GroupByResult, QueryStats
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    FilterNode,
    FilterOp,
    Function,
    Identifier,
    Predicate,
    PredicateType,
    canonical_arith_key,
)
from pinot_tpu.segment.startree import STAR, DictIdRange, StarTree

# cap on MATERIALIZED dictId sets: a predicate matching more ids than this
# never builds a python set. Contiguous runs (every RANGE over a sorted
# dictionary) decline to a DictIdRange slice check instead; only
# non-contiguous overflows (NOT_IN over a huge dictionary) bail to the scan
_MAX_RANGE_IDS = 100_000


def _flatten_and(node: Optional[FilterNode]) -> Optional[List[Predicate]]:
    """Filter -> flat AND-ed predicate list, or None when the shape doesn't
    fit (OR/NOT — the reference also bails to the normal path there)."""
    if node is None:
        return []
    if node.op is FilterOp.PREDICATE:
        return [node.predicate]
    if node.op is not FilterOp.AND:
        return None
    out: List[Predicate] = []
    for c in node.children:
        sub = _flatten_and(c)
        if sub is None:
            return None
        out.extend(sub)
    return out


def _agg_pair(agg: AggDef, fn: Function) -> Optional[Tuple[str, str]]:
    """AggDef -> (function, column) pair stored in tree records. The
    column half may be a canonical EXPRESSION key (``(a*b)``) — derived
    pre-agg pairs over +/-/* arithmetic, ref: the StarTreeV2 builder's
    derived-column function-column pairs."""
    if agg.mv:
        return None
    vexpr = agg_value_expr(fn)
    if agg.base == "count" and vexpr is None:
        return ("count", "*")
    if agg.base in ("sum", "min", "max") and vexpr is not None:
        key = canonical_arith_key(vexpr)
        if key is not None:
            return (agg.base, key)
    return None


def _pairs_needed(agg: AggDef, fn: Function) -> Optional[List[Tuple[str, str]]]:
    """Pairs the tree must store to answer this aggregation (AVG = SUM+COUNT,
    ref: AggregationFunctionColumnPair resolution)."""
    p = _agg_pair(agg, fn)
    if p is not None:
        return [p]
    vexpr = agg_value_expr(fn)
    if agg.base == "avg" and not agg.mv and vexpr is not None:
        key = canonical_arith_key(vexpr)
        if key is not None:
            return [("sum", key), ("count", "*")]
    return None


def _pair_column(fn: Function) -> str:
    """Aggregation argument -> stored pair column key ('*' for COUNT(*),
    a column name, or the canonical expression key)."""
    vexpr = agg_value_expr(fn)
    if vexpr is None:
        return "*"
    key = canonical_arith_key(vexpr)
    return key if key is not None else "*"


class StarTreePick(NamedTuple):
    """``pick_star_tree``'s result: the chosen tree, its index in
    ``segment.star_trees`` (rides the decision ledger + QueryStats), and
    the flattened AND-ed predicate list."""

    tree: StarTree
    index: int
    preds: List[Predicate]


# Specificity rank of the per-tree decline reasons: how deep in the fit
# checks a tree got before failing. With multiple trees, the MOST-specific
# reason across trees reaches the ledger — a tree missing only a function
# pair was one config line from serving; a tree whose split order lacks the
# group columns never stood a chance, and reporting the latter when the
# former exists would misdirect the operator.
_REASON_RANK = {
    "startree_group_off_split_order": 0,
    "startree_filter_non_dimension": 1,
    "startree_predicate_type_unsupported": 2,
    "startree_agg_not_pairable": 3,
    "startree_expression_agg_no_pair": 4,
    "startree_missing_function_pair": 5,
}


def _pred_match_estimate(segment, pred: Predicate, card: int) -> int:
    """Estimated count of dictIds a predicate matches — a plan-time proxy
    (never materializes id sets; tree selection must stay cheap)."""
    t = pred.type
    if t is PredicateType.EQ:
        return 1
    if t is PredicateType.IN:
        return min(card, len(pred.values))
    if t is PredicateType.NOT_EQ:
        return max(1, card - 1)
    if t is PredicateType.NOT_IN:
        return max(1, card - len(pred.values))
    if t is PredicateType.RANGE:
        try:
            d = segment.data_source(pred.lhs.name).dictionary
            if d is not None:
                a, b = d.range_to_dict_id_interval(
                    pred.lower, pred.upper, pred.lower_inclusive,
                    pred.upper_inclusive)
                return max(0, int(b) - int(a) + 1)
        except (ValueError, TypeError, KeyError):
            pass
        return max(1, card // 3)
    return card


def _estimate_records(tree: StarTree, preds: List[Predicate],
                      group_cols: List[str], segment) -> float:
    """Records-read estimate for a FITTING tree — the selection cost
    proxy: walk the split order; a predicated dim narrows to its match
    estimate, a grouped dim fans out to its cardinality, a free dim
    descends the star child (×1) unless star creation was skipped
    (×cardinality). Capped at the tree's record count (a leaf-heavy tree
    can never read more than it stores)."""
    by_col: Dict[str, int] = {}
    for p in preds:
        col = p.lhs.name
        card = segment.metadata.column(col).cardinality
        est = _pred_match_estimate(segment, p, card)
        by_col[col] = min(by_col.get(col, card), est)
    grouped = set(group_cols)
    est = 1.0
    for d in tree.config.dimensions_split_order:
        if d in by_col:
            est *= max(1, by_col[d])
        elif d in grouped or d in tree.config.skip_star_creation:
            est *= max(1, segment.metadata.column(d).cardinality)
    return min(est, float(tree.num_records))


def pick_star_tree(ctx: QueryContext, aggs: List[AggDef],
                   segment, on_decline=None) -> Optional[StarTreePick]:
    """Ref: StarTreeUtils.isFitForStarTree + StarTreeIndexConfig
    multi-tree resolution — the CHEAPEST tree satisfying the query (every
    fitting tree scored by :func:`_estimate_records`; the lower index
    breaks ties), or None. ``on_decline`` (if given) receives a
    machine-readable reason code when the segment HAS trees but none
    fits — the path-decision ledger's hook (a segment without trees is
    not a decline). With multiple trees the reported reason is the
    most-specific across trees (``_REASON_RANK``)."""

    def decline(reason: str):
        if on_decline is not None:
            on_decline(reason)
        return None

    trees = getattr(segment, "star_trees", None)
    if not trees or not ctx.is_aggregation:
        return None  # no trees / non-agg shape: not a decline (docstring)
    if getattr(segment, "valid_doc_ids", None) is not None:
        # pre-agg records ignore upsert invalidation
        return decline("startree_upsert_valid_docs")
    preds = _flatten_and(ctx.filter)
    if preds is None:
        return decline("startree_filter_or_not_shape")
    group_cols: List[str] = []
    for e in ctx.group_by:
        if not isinstance(e, Identifier):
            return decline("startree_group_expression")
        group_cols.append(e.name)

    # needed pairs are a property of the QUERY, not the tree: resolve once
    needed: List[Tuple[str, str]] = []
    for agg, fn in zip(aggs, ctx.aggregations):
        ps = _pairs_needed(agg, fn)
        if ps is None:
            # not pair-able by ANY tree: non-arith expression aggs
            # (sum(a/b), transforms) vs un-mergeable/MV aggregations
            return decline("startree_expression_agg_no_pair"
                           if isinstance(agg_value_expr(fn), Function)
                           else "startree_agg_not_pairable")
        needed.extend(ps)

    reason: Optional[str] = None

    def note(r: str) -> None:
        nonlocal reason
        if reason is None or (_REASON_RANK.get(r, 0)
                              > _REASON_RANK.get(reason, 0)):
            reason = r

    fitting: List[Tuple[float, int, StarTree]] = []
    for ti, tree in enumerate(trees):
        dims = set(tree.config.dimensions_split_order)
        if any(c not in dims for c in group_cols):
            note("startree_group_off_split_order")
            continue
        ok = True
        for p in preds:
            if not isinstance(p.lhs, Identifier) or p.lhs.name not in dims:
                note("startree_filter_non_dimension")
                ok = False
                break
            if p.type not in (PredicateType.EQ, PredicateType.IN,
                              PredicateType.NOT_EQ, PredicateType.NOT_IN,
                              PredicateType.RANGE):
                note("startree_predicate_type_unsupported")
                ok = False
                break
        if not ok:
            continue
        missing = [c for f, c in needed if not tree.has_pair(f, c)]
        if missing:
            # the Q1.x ledger code when a derived pair is absent (the
            # ROADMAP coverage gap); plain column pairs keep their own
            note("startree_expression_agg_no_pair"
                 if any(c.startswith("(") for c in missing)
                 else "startree_missing_function_pair")
            continue
        fitting.append((_estimate_records(tree, preds, group_cols, segment),
                        ti, tree))
    if not fitting:
        return decline(reason or "startree_no_fitting_tree")
    _est, ti, tree = min(fitting, key=lambda t: (t[0], t[1]))
    return StarTreePick(tree, ti, preds)


def _matching_ids(segment, pred: Predicate):
    """Predicate -> dictId match over the dimension's dictionary (reuses
    the host predicate evaluators): a set when small enough to materialize,
    a :class:`DictIdRange` when the ids are contiguous but over the cap
    (the RANGE shape), a reason STRING when neither fits (scan path
    serves; the string feeds the decision ledger)."""
    from pinot_tpu.engine.host_eval import _matching_dict_ids

    ds = segment.data_source(pred.lhs.name)
    if ds.dictionary is None:
        return "startree_raw_dimension"
    ids = _matching_dict_ids(ds, pred)
    if len(ids) > _MAX_RANGE_IDS:
        if int(ids[-1]) - int(ids[0]) + 1 == len(ids):
            return DictIdRange(int(ids[0]), int(ids[-1]))
        # non-contiguous overflow (NOT_IN over a huge dictionary): the
        # RANGE shape declines to a slice check, this cannot
        return "startree_dictid_overflow_noncontiguous"
    return set(int(i) for i in ids)


def _intersect(a, b):
    """Meet of two dictId matches (set | DictIdRange)."""
    if isinstance(a, DictIdRange) and isinstance(b, DictIdRange):
        return DictIdRange(max(a.lo, b.lo), min(a.hi, b.hi))
    if isinstance(a, DictIdRange):
        return {v for v in b if v in a}
    if isinstance(b, DictIdRange):
        return {v for v in a if v in b}
    return a & b


def resolve_matches(segment, preds: List[Predicate], on_decline=None
                    ) -> Optional[Dict[str, Any]]:
    """AND-ed predicates -> per-dimension dictId match (set | DictIdRange),
    or None when a predicate cannot be translated (the caller falls back to
    the scan path; ``on_decline`` receives the reason code). Shared by the
    host walker and the device rung."""
    matches: Dict[str, Any] = {}
    for p in preds:
        ids = _matching_ids(segment, p)
        if isinstance(ids, str):
            if on_decline is not None:
                on_decline(ids)
            return None
        col = p.lhs.name
        matches[col] = ids if col not in matches \
            else _intersect(matches[col], ids)
    return matches


def execute_star_tree(ctx: QueryContext, aggs: List[AggDef], segment,
                      tree: StarTree, preds: List[Predicate],
                      stats: Optional[QueryStats] = None):
    """-> AggResult or GroupByResult built from pre-aggregated records."""
    matches = resolve_matches(segment, preds)
    if matches is None:
        return None
    return execute_with_matches(ctx, aggs, segment, tree, matches, stats)


def execute_with_matches(ctx: QueryContext, aggs: List[AggDef], segment,
                         tree: StarTree, matches: Dict[str, Any],
                         stats: Optional[QueryStats] = None):
    """Host (numpy) aggregation over the tree-walk-selected records."""
    group_cols = [e.name for e in ctx.group_by]
    idx = tree.select_records(matches, group_cols)

    if stats is not None:
        stats.num_segments_processed += 1
        stats.total_docs += segment.num_docs
        stats.num_docs_scanned += int(idx.shape[0])
        stats.num_segments_matched += 1 if idx.shape[0] else 0

    if not ctx.is_group_by:
        return AggResult([_scalar_state(tree, agg, fn, idx)
                          for agg, fn in zip(aggs, ctx.aggregations)])

    gb = GroupByResult()
    if idx.shape[0] == 0:
        return gb
    from pinot_tpu.engine.groupkeys import compose_group_keys

    dim_pos = {d: i for i, d in enumerate(tree.config.dimensions_split_order)}
    key_ids = [np.asarray(tree.dims[idx, dim_pos[c]]) for c in group_cols]
    cards = [int(k.max()) + 1 if k.size else 1 for k in key_ids]
    uniq, gid, decode_codes = compose_group_keys(key_ids, cards)

    # decode dictIds through the segment dictionaries
    keys = [tuple(segment.data_source(c).dictionary.get_value(int(i))
                  for c, i in zip(group_cols, decode_codes(int(u))))
            for u in uniq]
    n = len(uniq)
    states_per_agg = [
        _grouped_states(tree, agg, fn, idx, gid, n)
        for agg, fn in zip(aggs, ctx.aggregations)]
    for g, key in enumerate(keys):
        gb.groups[key] = [states_per_agg[a][g] for a in range(len(aggs))]
    return gb


def _metric(tree: StarTree, fn: str, col: str, idx: np.ndarray) -> np.ndarray:
    return np.asarray(tree.metrics[f"{fn}__{col}"][idx])


def _scalar_state(tree: StarTree, agg: AggDef, fn: Function,
                  idx: np.ndarray) -> Any:
    col = _pair_column(fn)
    if agg.base == "count":
        return int(_metric(tree, "count", "*", idx).sum())
    if idx.shape[0] == 0:
        return {"sum": 0.0, "min": float("inf"), "max": float("-inf"),
                "avg": (0.0, 0)}[agg.base]
    if agg.base == "sum":
        return float(_metric(tree, "sum", col, idx).sum())
    if agg.base == "min":
        return float(_metric(tree, "min", col, idx).min())
    if agg.base == "max":
        return float(_metric(tree, "max", col, idx).max())
    if agg.base == "avg":
        return (float(_metric(tree, "sum", col, idx).sum()),
                int(_metric(tree, "count", "*", idx).sum()))
    raise AssertionError(agg.base)


def _grouped_states(tree: StarTree, agg: AggDef, fn: Function,
                    idx: np.ndarray, gid: np.ndarray, n: int) -> List[Any]:
    col = _pair_column(fn)
    if agg.base == "count":
        out = np.zeros(n, dtype=np.int64)
        np.add.at(out, gid, _metric(tree, "count", "*", idx))
        return [int(v) for v in out]
    if agg.base == "sum":
        out = np.zeros(n)
        np.add.at(out, gid, _metric(tree, "sum", col, idx))
        return [float(v) for v in out]
    if agg.base == "min":
        out = np.full(n, np.inf)
        np.minimum.at(out, gid, _metric(tree, "min", col, idx))
        return [float(v) for v in out]
    if agg.base == "max":
        out = np.full(n, -np.inf)
        np.maximum.at(out, gid, _metric(tree, "max", col, idx))
        return [float(v) for v in out]
    if agg.base == "avg":
        s = np.zeros(n)
        c = np.zeros(n, dtype=np.int64)
        np.add.at(s, gid, _metric(tree, "sum", col, idx))
        np.add.at(c, gid, _metric(tree, "count", "*", idx))
        return [(float(a), int(b)) for a, b in zip(s, c)]
    raise AssertionError(agg.base)
