"""Aggregation function library.

Re-design of ``pinot-core/.../query/aggregation/function/*`` (50 files): each
function defines (a) an intermediate *state* that partials from different
segments/servers merge into (the analogue of the reference's intermediate
result + ``merge()``), (b) host (numpy) computation, and (c) whether the
per-segment partial can be computed by the device kernels (kernels.py emits
the jax ops by function name).

States are plain python values/tuples so they serialize over the wire
(ref: ObjectSerDeUtils custom serde).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.engine.errors import QueryError, UnsupportedQueryError
from pinot_tpu.query.expressions import Expr, Function, Identifier, Literal
from pinot_tpu.utils.hll import HyperLogLog
from pinot_tpu.utils.tdigest import TDigest
from pinot_tpu.utils.theta import ThetaSketch

POS_INF = float("inf")
NEG_INF = float("-inf")


@dataclass
class AggDef:
    """One aggregation function's behavior."""

    name: str               # canonical lower-case (incl. percentile suffix)
    base: str               # family: count/sum/min/.../percentile
    mv: bool                # MV variant (arg is a multi-value column)
    percentile: Optional[float] = None  # percentile family only
    precision: Optional[int] = None     # sumprecision's optional argument
    device_scalar: bool = True    # device kernel for filtered scalar agg
    device_grouped: bool = True   # device kernel for group-by agg
    result_type: str = "DOUBLE"   # DataSchema column type of the final value

    # ---- state algebra ---------------------------------------------------
    def empty_state(self) -> Any:
        return _EMPTY[self.base]() if callable(_EMPTY[self.base]) else _EMPTY[self.base]

    def merge(self, a: Any, b: Any) -> Any:
        return _MERGE[self.base](a, b)

    def finalize(self, state: Any) -> Any:
        return _FINAL[self.base](self, state)

    # ---- host computation ------------------------------------------------
    def compute_host(self, values: Optional[np.ndarray],
                     mask: np.ndarray) -> Any:
        """Scalar aggregation over filtered docs. ``values`` is per-doc for SV
        functions; for MV functions it is a list-of-arrays per doc."""
        return _HOST[self.base](self, values, mask)


# --------------------------------------------------------------------------
# state algebra per family
# --------------------------------------------------------------------------

_EMPTY: Dict[str, Any] = {
    "count": 0,
    "sum": 0.0,
    "min": POS_INF,
    "max": NEG_INF,
    "avg": (0.0, 0),
    "minmaxrange": (POS_INF, NEG_INF),
    "distinctcount": frozenset(),
    "distinctcounthll": lambda: HyperLogLog().serialize(),
    "mode": dict,
    "percentile": tuple,
    "percentiletdigest": lambda: TDigest().serialize(),
    "distinctcountthetasketch": lambda: ThetaSketch().serialize(),
    "sumprecision": "0",  # exact decimal sum as a string-encoded Decimal
    "idset": frozenset(),
    # (time, value) of the chosen row, or None when no row matched yet
    "lastwithtime": None,
    "firstwithtime": None,
    "stunion": "",  # WKT of the union-so-far ("" = nothing yet)
}

import decimal as _decimal
import math as _math


def _exact_dec_add(a: "_decimal.Decimal",
                   b: "_decimal.Decimal") -> "_decimal.Decimal":
    """EXACT decimal addition (ref: BigDecimal.add is exact): the context
    is sized to the operands' full digit span, so no rounding can occur
    at any magnitude and merges are order-independent."""
    if not a.is_finite() or not b.is_finite():
        return a + b  # NaN/Infinity propagate per IEEE decimal semantics
    if not a:
        return b
    if not b:
        return a
    hi = max(a.adjusted(), b.adjusted())
    lo = min(a.as_tuple().exponent, b.as_tuple().exponent)
    return _decimal.Context(prec=max(hi - lo + 2, 1)).add(a, b)


def _decimal_add(a: str, b: str) -> str:
    """String-encoded exact decimal merge (wire-safe state)."""
    return str(_exact_dec_add(_decimal.Decimal(a), _decimal.Decimal(b)))


_MERGE: Dict[str, Callable[[Any, Any], Any]] = {
    "count": lambda a, b: a + b,
    "sum": lambda a, b: a + b,
    "min": lambda a, b: min(a, b),
    "max": lambda a, b: max(a, b),
    "avg": lambda a, b: (a[0] + b[0], a[1] + b[1]),
    "minmaxrange": lambda a, b: (min(a[0], b[0]), max(a[1], b[1])),
    "distinctcount": lambda a, b: frozenset(a) | frozenset(b),
    "distinctcounthll": lambda a, b: HyperLogLog.deserialize(a).merge(
        HyperLogLog.deserialize(b)).serialize(),
    "mode": lambda a, b: _merge_counts(a, b),
    "percentile": lambda a, b: tuple(a) + tuple(b),
    "percentiletdigest": lambda a, b: TDigest.deserialize(a).merge(
        TDigest.deserialize(b)).serialize(),
    "distinctcountthetasketch": lambda a, b: ThetaSketch.deserialize(a).merge(
        ThetaSketch.deserialize(b)).serialize(),
    "sumprecision": _decimal_add,
    "idset": lambda a, b: frozenset(a) | frozenset(b),
    # deterministic across merge orders: lexicographic (time, value) extreme
    # (the reference keeps the row with the largest/smallest time; ties are
    # merge-order-dependent there — here the value breaks the tie)
    "lastwithtime": lambda a, b: b if a is None else a if b is None
    else max(a, b),
    "firstwithtime": lambda a, b: b if a is None else a if b is None
    else min(a, b),
    "stunion": lambda a, b: _stunion_merge(a, b),
}


def _stunion_merge(a: str, b: str) -> str:
    from pinot_tpu.utils import geo

    if not a:
        return b
    if not b:
        return a
    g = geo.union([geo.parse_ewkt(a), geo.parse_ewkt(b)])
    return (geo.GEOG_PREFIX + g.wkt()) if g.geography else g.wkt()


def _merge_counts(a: Dict, b: Dict) -> Dict:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def _final_avg(d: AggDef, s) -> float:
    # ref: AvgAggregationFunction — sum/count, NEGATIVE_INFINITY for empty
    return s[0] / s[1] if s[1] else NEG_INF


def _final_percentile(d: AggDef, s) -> float:
    vals = np.sort(np.asarray(s, dtype=np.float64))
    if vals.size == 0:
        return NEG_INF
    # ref: PercentileAggregationFunction.extractFinalResult
    idx = int(vals.size * d.percentile / 100.0)
    return float(vals[min(idx, vals.size - 1)])


def _final_sumprecision(d: AggDef, s: str):
    """Integral sums finalize as exact python ints; fractional sums as
    floats — both JSON-safe AND mutually comparable, so ORDER BY / HAVING
    over mixed groups work numerically. (Deviation from the reference's
    BigDecimal string rendering: fractional finals may round to f64 at
    DISPLAY; merge states stay exact throughout.) The optional precision
    argument quantizes at finalize only."""
    v = _decimal.Decimal(s)
    if d.precision is not None:
        v = _decimal.Context(prec=d.precision).plus(v)
    if v.is_finite() and v == v.to_integral_value():
        return int(v)
    f = float(v)
    if _math.isinf(f) and v.is_finite():
        # beyond f64 range: the exact decimal string beats silent inf
        return str(v)
    return f


def _final_idset(d: AggDef, s) -> str:
    """Serialized id set, base64 (ref: IdSetAggregationFunction -> the
    IN_ID_SET / IN_PARTITIONED_SUBQUERY filter consumes this string)."""
    import base64

    from pinot_tpu.common import serde

    return base64.b64encode(serde.dumps(
        sorted(s, key=lambda v: (str(type(v)), v)))).decode("ascii")


def _final_withtime(d: AggDef, s):
    if s is None:  # no matching rows
        return None if d.result_type == "STRING" else NEG_INF
    v = s[1]
    if d.result_type in ("INT", "LONG"):
        return int(v)
    if d.result_type in ("FLOAT", "DOUBLE"):
        return float(v)
    if d.result_type == "BOOLEAN":
        return bool(v)
    return v if isinstance(v, str) else str(v)


_FINAL: Dict[str, Callable[[AggDef, Any], Any]] = {
    "count": lambda d, s: int(s),
    "sum": lambda d, s: float(s),
    "min": lambda d, s: float(s),
    "max": lambda d, s: float(s),
    "avg": _final_avg,
    "minmaxrange": lambda d, s: float(s[1] - s[0]),
    "distinctcount": lambda d, s: len(s),
    "distinctcounthll": lambda d, s: (
        s.hex() if d.name.startswith("distinctcountrawhll")
        else HyperLogLog.deserialize(s).cardinality()),
    "mode": lambda d, s: (float(max(s, key=lambda k: (s[k], k))) if s else NEG_INF),
    "percentile": _final_percentile,
    "percentiletdigest": lambda d, s: TDigest.deserialize(s).quantile(
        d.percentile / 100.0),
    "distinctcountthetasketch": lambda d, s: (
        s.hex() if d.name.startswith("distinctcountrawthetasketch")
        else int(round(ThetaSketch.deserialize(s).estimate()))),
    "sumprecision": lambda d, s: _final_sumprecision(d, s),
    "idset": _final_idset,
    "lastwithtime": lambda d, s: _final_withtime(d, s),
    "firstwithtime": lambda d, s: _final_withtime(d, s),
    # ref: StUnionAggregationFunction returns the serialized geometry; the
    # framework's geometry wire form is (E)WKT text
    "stunion": lambda d, s: s,
}


# --------------------------------------------------------------------------
# host computation per family
# --------------------------------------------------------------------------

def _host_count(d: AggDef, values, mask) -> int:
    if d.mv:
        return int(sum(len(v) for v, m in zip(values, mask) if m))
    return int(np.count_nonzero(mask))


def _flat_filtered(d: AggDef, values, mask) -> np.ndarray:
    """Filtered values flattened (MV: all values of matching docs)."""
    if d.mv:
        parts = [np.asarray(v, dtype=np.float64)
                 for v, m in zip(values, mask) if m and len(v)]
        return (np.concatenate(parts) if parts
                else np.empty(0, dtype=np.float64))
    return np.asarray(values, dtype=np.float64)[mask]


def _host_sum(d: AggDef, values, mask) -> float:
    return float(_flat_filtered(d, values, mask).sum())


def _host_min(d: AggDef, values, mask) -> float:
    v = _flat_filtered(d, values, mask)
    return float(v.min()) if v.size else POS_INF


def _host_max(d: AggDef, values, mask) -> float:
    v = _flat_filtered(d, values, mask)
    return float(v.max()) if v.size else NEG_INF


def _host_avg(d: AggDef, values, mask):
    v = _flat_filtered(d, values, mask)
    return (float(v.sum()), int(v.size))


def _host_minmaxrange(d: AggDef, values, mask):
    v = _flat_filtered(d, values, mask)
    if not v.size:
        return (POS_INF, NEG_INF)
    return (float(v.min()), float(v.max()))


def _host_distinctcount(d: AggDef, values, mask):
    if d.mv:
        out = set()
        for v, m in zip(values, mask):
            if m:
                out.update(v)
        return frozenset(out)
    vals = np.asarray(values, dtype=object)[mask] if getattr(values, "dtype", None) == object \
        else np.asarray(values)[mask]
    return frozenset(np.unique(vals).tolist())


def _host_mode(d: AggDef, values, mask):
    v = _flat_filtered(d, values, mask)
    uniq, counts = np.unique(v, return_counts=True)
    return {float(u): int(c) for u, c in zip(uniq, counts)}


def _host_percentile(d: AggDef, values, mask):
    return tuple(_flat_filtered(d, values, mask).tolist())


def _host_hll(d: AggDef, values, mask):
    if d.mv:
        flat = []
        for v, m in zip(values, mask):
            if m:
                flat.extend(v if isinstance(v, (list, np.ndarray)) else [v])
        h = HyperLogLog()
        if flat:
            h.add_values(flat)
        return h.serialize()
    vals = np.asarray(values)[mask] if not isinstance(values, list) \
        else [v for v, m in zip(values, mask) if m]
    h = HyperLogLog()
    if len(vals):
        h.add_values(vals)
    return h.serialize()


def _host_tdigest(d: AggDef, values, mask):
    return TDigest.of(_flat_filtered(d, values, mask)).serialize()


def _raw_filtered(d: AggDef, values, mask) -> list:
    """Filtered values kept raw (strings included), MV flattened."""
    if d.mv:
        out = []
        for v, m in zip(values, mask):
            if m:
                out.extend(v.tolist() if hasattr(v, "tolist") else list(v))
        return out
    if isinstance(values, list):
        return [v for v, m in zip(values, mask) if m]
    vals = np.asarray(values)[mask]
    return vals.tolist()


def _host_sumprecision(d: AggDef, values, mask):
    total = _decimal.Decimal(0)
    for v in _raw_filtered(d, values, mask):
        total = _exact_dec_add(total, _decimal.Decimal(str(v)))
    return str(total)


def _host_theta(d: AggDef, values, mask):
    return ThetaSketch.of(_raw_filtered(d, values, mask)).serialize()


def _host_idset(d: AggDef, values, mask):
    vals = _raw_filtered(d, values, mask)
    return frozenset(v.item() if hasattr(v, "item") else v for v in vals)


def _host_withtime(d: AggDef, values, mask):
    """``values`` is (value array/list, time array): pick the row with the
    extreme time (ref: LastWithTimeAggregationFunction /
    FirstWithTimeAggregationFunction)."""
    vals, times = values
    idx = np.nonzero(np.asarray(mask))[0]
    if idx.size == 0:
        return None
    t = np.asarray(times)[idx]  # native dtype: float times must not truncate
    pos = int(np.argmax(t) if d.base == "lastwithtime" else np.argmin(t))
    chosen_time = t[pos].item() if hasattr(t[pos], "item") else t[pos]
    # deterministic tie-break on value (matches the merge algebra)
    tied = idx[t == t[pos]]
    pick = lambda i: vals[i] if isinstance(vals, list) else vals[int(i)]
    cand = [pick(i) for i in tied]
    cand = [c.item() if hasattr(c, "item") else c for c in cand]
    v = max(cand) if d.base == "lastwithtime" else min(cand)
    return (chosen_time, v)


def _host_stunion(d: AggDef, values, mask):
    from pinot_tpu.utils import geo

    vals = _raw_filtered(d, values, mask)
    if not vals:
        return ""
    g = geo.union([geo.parse_ewkt(str(v)) for v in vals])
    return (geo.GEOG_PREFIX + g.wkt()) if g.geography else g.wkt()


_HOST: Dict[str, Callable] = {
    "count": _host_count,
    "stunion": _host_stunion,
    "sum": _host_sum,
    "min": _host_min,
    "max": _host_max,
    "avg": _host_avg,
    "minmaxrange": _host_minmaxrange,
    "distinctcount": _host_distinctcount,
    "distinctcounthll": _host_hll,
    "mode": _host_mode,
    "percentile": _host_percentile,
    "percentiletdigest": _host_tdigest,
    "distinctcountthetasketch": _host_theta,
    "sumprecision": _host_sumprecision,
    "idset": _host_idset,
    "lastwithtime": _host_withtime,
    "firstwithtime": _host_withtime,
}


# --------------------------------------------------------------------------
# registry / resolution
# --------------------------------------------------------------------------

_RESULT_TYPE = {
    "count": "LONG",
    "sum": "DOUBLE",
    "min": "DOUBLE",
    "max": "DOUBLE",
    "avg": "DOUBLE",
    "minmaxrange": "DOUBLE",
    "distinctcount": "INT",
    "distinctcounthll": "LONG",
    "mode": "DOUBLE",
    "percentile": "DOUBLE",
    "percentiletdigest": "DOUBLE",
    "distinctcountthetasketch": "LONG",
    "sumprecision": "STRING",
    "idset": "STRING",
    "lastwithtime": "DOUBLE",  # overridden by the dataType argument
    "firstwithtime": "DOUBLE",
    "stunion": "STRING",
}

# families with device kernels (kernels.py); others run on the host path
_DEVICE_SCALAR = {"count", "sum", "min", "max", "avg", "minmaxrange",
                  "distinctcount", "distinctcounthll"}
_DEVICE_GROUPED = {"count", "sum", "min", "max", "avg", "minmaxrange",
                   "distinctcounthll"}


def resolve_agg(fn: Function) -> AggDef:
    """Canonical Function -> AggDef (ref: AggregationFunctionFactory)."""
    name = fn.name
    mv = name.endswith("mv")
    base_name = name[:-2] if mv else name

    percentile = None
    for prefix in ("percentiletdigest", "percentileest", "percentile"):
        if base_name.startswith(prefix):
            digits = base_name[len(prefix):]
            if digits.isdigit():
                percentile = float(digits)
                base_name = prefix
                break
            if digits == "":
                # percentile(col, N) 2-arg form
                if len(fn.args) >= 2 and isinstance(fn.args[1], Literal):
                    percentile = float(fn.args[1].value)
                    base_name = prefix
                    break
                raise QueryError(f"{name} requires a percentile argument")

    family = {
        "count": "count", "sum": "sum", "min": "min", "max": "max",
        "avg": "avg", "minmaxrange": "minmaxrange",
        "distinctcount": "distinctcount", "distinctcountbitmap": "distinctcount",
        "segmentpartitioneddistinctcount": "distinctcount",
        "distinctcounthll": "distinctcounthll",
        # RAW variants return the serialized sketch itself (hex), resolved
        # at finalize via the same family state
        "distinctcountrawhll": "distinctcounthll",
        "mode": "mode",
        # percentileest (QuantileDigest in the reference) shares the exact
        # family here; percentiletdigest is the approximate sketch
        "percentile": "percentile", "percentileest": "percentile",
        "percentiletdigest": "percentiletdigest",
        "distinctcountthetasketch": "distinctcountthetasketch",
        "sumprecision": "sumprecision",
        "distinctcountrawthetasketch": "distinctcountthetasketch",
        "idset": "idset",
        "lastwithtime": "lastwithtime",
        "firstwithtime": "firstwithtime",
        "stunion": "stunion", "st_union": "stunion",
    }.get(base_name)
    if family is None:
        raise UnsupportedQueryError(f"aggregation function {name!r} not supported")

    result_type = _RESULT_TYPE[family]
    if base_name in ("distinctcountrawhll", "distinctcountrawthetasketch"):
        result_type = "STRING"
    precision = None
    if family == "sumprecision" and len(fn.args) >= 2:
        if not (isinstance(fn.args[1], Literal)
                and type(fn.args[1].value) is int
                and fn.args[1].value >= 1):
            raise QueryError(
                "sumprecision precision must be an int literal >= 1")
        precision = int(fn.args[1].value)
    if family in ("lastwithtime", "firstwithtime"):
        # 3rd argument is the value's data type label
        # (ref: LastWithTimeAggregationFunction 3-arg form)
        if len(fn.args) != 3:
            raise QueryError(
                f"{name} requires (valueColumn, timeColumn, 'dataType')")
        dt = fn.args[2]
        if not isinstance(dt, Literal) or not isinstance(dt.value, str):
            raise QueryError(f"{name}: dataType argument must be a string")
        result_type = dt.value.upper()
        if result_type not in ("INT", "LONG", "FLOAT", "DOUBLE", "STRING",
                               "BOOLEAN"):
            raise QueryError(f"{name}: unsupported dataType {dt.value!r}")

    return AggDef(
        name=name,
        base=family,
        mv=mv,
        percentile=percentile,
        precision=precision,
        device_scalar=(family in _DEVICE_SCALAR) and not mv or (mv and family in
                      {"count", "sum", "min", "max", "avg"}),
        device_grouped=(family in _DEVICE_GROUPED) and not mv,
        result_type=result_type,
    )


def agg_value_expr(fn: Function) -> Optional[Expr]:
    """The expression aggregated over, or None for COUNT(*)."""
    if not fn.args:
        return None
    a0 = fn.args[0]
    if isinstance(a0, Identifier) and a0.name == "*":
        return None
    return a0
