"""TPU query execution engine (ref: pinot-core query engine, SURVEY.md 2.4).

The per-segment Filter -> Projection -> Transform -> Aggregation chain runs
as fused masked vector ops under jax.jit (kernels.py), planned per query
structure (plan.py), with host paths for selection/distinct/fallback
(host_engine.py) and reduce-side merging (results.py).
"""

def ensure_x64() -> None:
    """Enable 64-bit jax types for exact OLAP semantics (reference aggregates
    in double/long). Called at executor/session setup — not at import — so
    importing this package does not flip process-global jax config. On TPU
    f64/i64 are emulated (f32-pairs); metadata-driven narrowing to f32/i32 is
    the planned optimization for the hot kernels."""
    import jax

    jax.config.update("jax_enable_x64", True)


from pinot_tpu.engine.errors import QueryError, UnsupportedQueryError
from pinot_tpu.engine.executor import ServerQueryExecutor
from pinot_tpu.engine.residency import QueryLease, ResidencyManager
from pinot_tpu.engine.results import DataSchema, QueryStats, ResultTable

__all__ = [
    "QueryError",
    "UnsupportedQueryError",
    "ServerQueryExecutor",
    "ResidencyManager",
    "QueryLease",
    "DataSchema",
    "QueryStats",
    "ResultTable",
]
