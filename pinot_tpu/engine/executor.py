"""Segment query executor: per-segment execution + instance-level combine.

Re-design of ``ServerQueryExecutorV1Impl.java:75`` +
``BaseCombineOperator.java:55``: dispatches each query to the device kernels
(aggregation/group-by), the host paths (selection/distinct/fallback), or the
metadata fast paths (ref: MetadataBasedAggregationOperator /
DictionaryBasedAggregationOperator, AggregationPlanNode.java:172-181), then
merges per-segment partials and reduces to a ResultTable.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.engine import host_engine
from pinot_tpu.engine.aggregates import AggDef, agg_value_expr, resolve_agg
from pinot_tpu.engine.errors import QueryError
from pinot_tpu.engine.kernels import KernelCache
from pinot_tpu.engine.plan import PlanError, SegmentPlan, plan_segment
from pinot_tpu.engine.results import (
    AggResult,
    GroupByResult,
    QueryStats,
    ResultTable,
    reduce_aggregation,
    reduce_group_by,
)
from pinot_tpu.common.tracing import (
    QueryRegistry,
    maybe_span,
    record_decision,
    start_trace,
    stats_tracer,
)
from pinot_tpu.engine.residency import ResidencyManager
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Identifier
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.spi.config import CommonConstants


def grouped_rung(spec: Tuple, out: Dict[str, Any]) -> str:
    """Which group-by rung of the device cardinality ladder served this
    kernel output: 'dense' | 'compact' (dense scatter, compact D2H) |
    'hash' | 'sort' (the sparse rungs; 'sort' means the hash table
    overflowed and the sort fallback ran)."""
    from pinot_tpu.engine.kernels import compact_mode, sparse_mode

    if sparse_mode(spec):
        return "sort" if out.get("rung") else "hash"
    return "compact" if compact_mode(spec) else "dense"


def filter_fingerprint(ctx: QueryContext) -> str:
    """Digest of the filter tree, memoized per ctx — cache keys must
    distinguish same-SQL contexts whose filters were rewritten (hybrid
    time boundary, IN_SUBQUERY idsets)."""
    fp = getattr(ctx, "_filter_fp", None)
    if fp is None:
        import hashlib

        fp = hashlib.blake2b(str(ctx.filter).encode("utf-8"),
                             digest_size=16).hexdigest()
        ctx._filter_fp = fp
    return fp


def _segment_tracer(ctx: QueryContext, stats: QueryStats, op: str, seg):
    """``done(result, path)`` pass-through that records a per-segment SPAN
    when the query is traced (ref: TraceContext.java:46 — operator timings
    attach to the request's trace tree); the legacy flat entry is emitted
    from the span at close. Untraced queries get the zero-allocation
    pass-through."""
    rec = stats_tracer(stats)
    if rec is None:
        return lambda result, path: result
    sp = rec.span_begin(op, segment=seg.segment_name)

    def done(result, path):
        # the closure owns the span close (graftlint spanpair contract);
        # an error path that skips done() is swept closed when the parent
        # (or the root, at query teardown) ends
        rec.span_end(sp, path=path)
        return result

    return done


class ServerQueryExecutor:
    """One per server instance; owns the staging + kernel caches."""

    def __init__(self, use_device: bool = True,
                 num_groups_limit: int = CommonConstants.DEFAULT_NUM_GROUPS_LIMIT,
                 use_pallas: Optional[bool] = None,
                 hbm_budget_bytes=None, host_budget_bytes=None, config=None):
        from pinot_tpu.engine import ensure_x64
        from pinot_tpu.engine.pallas_kernels import PallasKernelCache
        from pinot_tpu.engine.residency import AUTO

        ensure_x64()
        self.config = config
        # HBM residency manager: budget/pins/cost-aware eviction with a
        # host-RAM spill tier + sliced/spill admission for every
        # device-resident array this executor stages. ``hbm_budget_bytes``
        # / ``host_budget_bytes``: None = resolve from the config keys
        # (pinot.server.query.hbm.budget.bytes /
        # pinot.server.query.hostram.budget.bytes) then the backend device
        # memory / psutil; <= 0 forces uncapped.
        self.residency = ResidencyManager(
            budget_bytes=AUTO if hbm_budget_bytes is None else hbm_budget_bytes,
            host_budget_bytes=(AUTO if host_budget_bytes is None
                               else host_budget_bytes),
            config=config)
        # legacy alias (pre-residency name); same object
        self.staging = self.residency
        self.kernels = KernelCache()
        # (sql, segment) -> (segment identity, SegmentPlan): the per-segment
        # analogue of the sharded executor's query cache — repeat queries
        # skip predicate translation / LUT builds. Safe because params no
        # longer embed mutable state: the upsert validdocs placeholder is
        # filled per run — immutable segments from staged.valid_mask(),
        # consuming segments from the watermark snapshot's device mask
        # (mutable_staging._serve). LRU-bounded; mutable segments bypass
        # the cache entirely (their plans are watermark-specific).
        import threading
        from collections import OrderedDict

        self._plan_cache: "OrderedDict" = OrderedDict()
        self._plan_cache_cap = 512
        self._plan_cache_lock = threading.Lock()
        self.pallas_kernels = PallasKernelCache()
        self.use_device = use_device
        # pallas kernels compile for real TPUs; on the CPU backend they run
        # only in (slow) interpret mode, so auto-enable on TPU and leave
        # interpret mode to tests that opt in explicitly
        self.use_pallas = use_pallas
        # plan.spec values whose pallas kernel failed to lower/run on this
        # backend — or that the kernel preflight predicted would — take
        # the jnp path; everything else keeps the fused kernel. Created
        # below once the config (persistence path) is resolved.
        # ordered-selection top-k kernels (engine/selection_device.py);
        # LRU-capped like the sibling caches (k rides in the key, so
        # unbounded LIMIT variety must not pin kernels forever)
        self._selection_kernels: "OrderedDict" = OrderedDict()
        # star-tree node-slice kernels (engine/startree_device.py): spec ->
        # jitted gather+aggregate fn. The spec's capacity is the pow2-padded
        # selected-record count, so variety is bounded; LRU-capped anyway
        self._startree_kernels: "OrderedDict" = OrderedDict()  # guarded-by: _startree_kernel_lock
        self._startree_kernel_lock = threading.Lock()
        self.num_groups_limit = num_groups_limit
        # segment fan-out width: pinot.server.query.worker.threads (the
        # reference's pqw pool size); default preserves the old hardcoded
        # min(cpu, 8). The pool itself is persistent and lazily built —
        # per-query ThreadPoolExecutor spawn/teardown was pure overhead on
        # the serving path.
        import os

        from pinot_tpu.spi.config import (
            CommonConstants as _CC,
            PinotConfiguration,
        )

        cfg = config if config is not None else PinotConfiguration()
        self.worker_threads = max(1, cfg.get_int(
            _CC.WORKER_THREADS_KEY, min(os.cpu_count() or 1, 8)))
        # pallas LUT interval-run cap (the "ivs" fallback bound)
        self._pallas_lut_runs = max(1, cfg.get_int(
            _CC.PALLAS_LUT_MAX_RUNS_KEY, _CC.DEFAULT_PALLAS_LUT_MAX_RUNS))
        # per-shape pallas blocklist (reason-carrying, optionally
        # persisted): runtime lowering failures + preflight-seeded shapes
        from pinot_tpu.engine.pallas_blocklist import PallasBlocklist

        self._pallas_blocked = PallasBlocklist(
            path=cfg.get(_CC.PALLAS_BLOCKLIST_PATH_KEY))
        # last kernel-preflight verdict table run against this executor
        # (tools/preflight.attach_verdicts); surfaced on GET /debug/pallas
        self.preflight_verdicts: Optional[dict] = None
        self._segment_pool = None
        self._segment_pool_lock = threading.Lock()
        # request-tier admission: bounded concurrency + bounded queue in
        # front of execution; past the bound queries are REJECTED with a
        # typed retriable error instead of convoying (server/admission.py).
        # Lazy import: pinot_tpu.server pulls this module back in.
        from pinot_tpu.server.admission import AdmissionGate

        self.admission = AdmissionGate.from_config(cfg)
        # query lifecycle tracing (common/tracing.py): spans are recorded
        # when the request asks (trace=true), the sample rate hits, or a
        # slow-query threshold is configured (the registry then retains
        # over-threshold trees sampling missed). The registry also backs
        # /debug/queries (running set + completed ring).
        self.trace_sample = cfg.get_float(
            _CC.TRACE_SAMPLE_KEY, _CC.DEFAULT_TRACE_SAMPLE)
        self.queries = QueryRegistry(slow_threshold_ms=cfg.get_float(
            _CC.SLOW_THRESHOLD_MS_KEY, _CC.DEFAULT_SLOW_THRESHOLD_MS))
        # continuous telemetry (common/telemetry.py): apply config
        # (sampler resolution, SLO objectives, flight-recorder knobs) to
        # the process-wide center and register this executor's state as
        # flight-recorder bundle providers — a frozen bundle carries the
        # residency + admission snapshots of the LAST executor built
        # (one per process everywhere outside multi-instance tests)
        from pinot_tpu.common.telemetry import TELEMETRY

        TELEMETRY.configure(cfg)
        TELEMETRY.recorder.register_provider("residency",
                                             self.residency.snapshot)
        TELEMETRY.recorder.register_provider("admission",
                                             self.admission.snapshot)
        # backend selection is itself a path decision: a CPU default
        # backend is why no pallas kernel can compile — record it ONCE so
        # the ledger explains the whole pallas story, not just per-plan
        # declines
        import jax as _jax

        if _jax.default_backend() == "cpu":
            record_decision(None, "backend", "cpu", "tpu",
                            "cpu_default_backend")
        # per-segment half of the launch-coalescing contract: concurrent
        # identical kernel launches (same cached plan + same staged
        # resident) share one device program + one D2H fetch
        from pinot_tpu.common.singleflight import SingleFlight

        self._kernel_flight = SingleFlight()
        # whole-query single-flight for the direct execute() surface (the
        # embedded / bench path — the broker front door has its own): a
        # concurrent identical query (same compiled ctx object, same
        # segment objects) rides the leader's full execution instead of
        # paying its own serialized device programs
        self._query_flight = SingleFlight()

    def _pallas_mode(self) -> Optional[bool]:
        """None = disabled; True/False = enabled (interpret or compiled)."""
        import jax

        backend = jax.default_backend()
        if self.use_pallas is None:
            # auto: compiled pallas only on TPU-like backends (the kernels
            # use pltpu memory spaces and cannot lower on GPU)
            return False if backend not in ("cpu", "gpu", "cuda", "rocm") \
                else None
        if not self.use_pallas:
            return None
        if backend in ("gpu", "cuda", "rocm"):
            return None  # pltpu memory spaces cannot lower on GPU
        return backend == "cpu"  # interpret on CPU

    # -- public ------------------------------------------------------------
    def execute_instance(self, ctx: QueryContext,
                         segments: List[ImmutableSegment]):
        """Instance-level execution returning a mergeable DataTable — the
        scatter/gather server half (ref: InstanceResponseOperator wrapping
        combine output into a serialized DataTable). The broker merges
        DataTables from all servers and reduces (BrokerReduceService).
        Admission-gated: past the bounded queue this raises a typed
        retriable QueryRejectedError BEFORE any lease/pin is taken."""
        ticket = self.admission.admit(ctx.table_name or "")
        try:
            return self._execute_instance_admitted(
                ctx, segments, admit_wait_ms=ticket.wait_ms)
        finally:
            self.admission.release(ticket)

    # -- tracing bookends ----------------------------------------------------
    def _open_query(self, ctx: QueryContext, segments,
                    admit_wait_ms: float = 0.0):
        """Create the query's stats + registry token and, when the query
        is traced (trace=true / sample hit / slow-log force), its span
        recorder and root span. The admission-gate queue wait — measured
        before stats existed — lands as the first child with full queue
        attribution."""
        stats = QueryStats(num_segments_queried=len(segments))
        stats._tel_table = ctx.table_name or ""  # telemetry attribution
        requested = ctx.trace_enabled
        if not requested and self.trace_sample > 0:
            import random

            requested = random.random() < self.trace_sample
        if requested or self.queries.force_trace:
            rec = start_trace(stats)
            stats._trace_requested = requested
            root = rec.span_begin("ServerQuery", table=ctx.table_name)
            stats._root_span = root  # closed by _close_query's close_all
            rec.add_completed("Admission", wall_ms=admit_wait_ms,
                              queue_ms=admit_wait_ms)
        token = self.queries.begin(ctx, stats)
        stats._registry_token = token  # phase updates from inner layers
        return stats, token

    def _close_query(self, stats: QueryStats, token, error=None) -> None:
        """Query teardown: close every open span (exception edges leave
        the tree closed, never dangling), finish the registry entry (the
        slow log snapshots over-threshold trees here), and — when the
        recording was slow-log-forced rather than requested — strip the
        spans/entries off the wire payload."""
        rec = stats_tracer(stats)
        if rec is not None:
            rec.close_all()
        self.queries.end(token, error=error)
        if rec is not None and not getattr(stats, "_trace_requested", False):
            # forced recording: the slow log copied what it needed; the
            # response must look exactly like an untraced one
            stats.spans.clear()
            stats.trace.clear()
            stats._recorder = None

    def _execute_instance_admitted(self, ctx: QueryContext,
                                   segments: List[ImmutableSegment],
                                   admit_wait_ms: float = 0.0):
        import time as _time

        from pinot_tpu.common.telemetry import observe_ms

        t0 = _time.perf_counter()
        stats, token = self._open_query(ctx, segments, admit_wait_ms)
        error = None
        try:
            return self._execute_instance_traced(ctx, segments, stats)
        except BaseException as e:
            error = e
            raise
        finally:
            self._close_query(stats, token, error=error)
            observe_ms(ctx.table_name, "server_exec",
                       (_time.perf_counter() - t0) * 1e3)

    def _execute_instance_traced(self, ctx: QueryContext,
                                 segments: List[ImmutableSegment],
                                 stats: QueryStats):
        from dataclasses import replace

        from pinot_tpu.common.datatable import DataTable

        if not segments:
            raise QueryError(f"no segments for table {ctx.table_name!r}")
        self._validate_columns(ctx, segments[0])
        segments = self._prune(ctx, segments, stats)
        lease = self._begin_lease(ctx, segments, stats)
        try:
            if ctx.distinct:
                # HAVING is broker-side (it sees the global distinct set);
                # ORDER BY stays server-side so each server ships its true
                # top rows — order-by keys are always in the distinct select
                # list, so a per-server sorted prefix of offset+limit rows
                # is sufficient
                if ctx.having is not None:
                    sub = replace(ctx, order_by=[], having=None,
                                  limit=self.num_groups_limit, offset=0)
                else:
                    sub = replace(ctx, having=None,
                                  limit=ctx.offset + ctx.limit, offset=0)
                record_decision(stats, "plan", "host_engine",
                                "device_kernel", "distinct_host_only")
                table = host_engine.execute_distinct(sub, segments, stats)
                if len(table.rows) >= self.num_groups_limit:
                    stats.num_groups_limit_reached = True
                return DataTable.for_distinct(table.schema, table.rows, stats)

            if ctx.is_selection:
                if not ctx.order_by:
                    sub = replace(ctx, limit=ctx.offset + ctx.limit, offset=0)
                    table = host_engine.execute_selection(sub, segments, stats)
                    return DataTable.for_selection(table.schema, table.rows,
                                                   stats)
                # ordered: append order-by expressions as hidden trailing
                # columns so the broker can merge-sort without re-reading
                # segments (ref: SelectionOrderByOperator rows carry
                # order-by columns)
                present = {str(e) for e in ctx.select_expressions}
                hidden = [ob.expr for ob in ctx.order_by
                          if str(ob.expr) not in present]
                sub = replace(
                    ctx,
                    select_expressions=list(ctx.select_expressions) + hidden,
                    aliases=list(ctx.aliases) + [None] * len(hidden),
                    limit=ctx.offset + ctx.limit, offset=0)
                table = self._selection(sub, segments, stats)
                # server-side ORDER-BY trim: the block ships at most
                # offset+limit rows ALREADY in query order — flagged so
                # the broker merge treats it as a pre-sorted block
                # (ref: SelectionOperatorUtils sorted-block contract)
                return DataTable.for_selection(table.schema, table.rows,
                                               stats, num_hidden=len(hidden),
                                               sorted_rows=True)

            aggs = [resolve_agg(f) for f in ctx.aggregations]
            if ctx.is_group_by:
                merged = self._execute_group_by(ctx, aggs, segments, stats)
                if merged.trim(self.num_groups_limit):
                    stats.num_groups_limit_reached = True
                return DataTable.for_group_by(merged.groups,
                                              self._schema_types(segments[0]),
                                              stats)
            merged_agg = self._execute_aggregation(ctx, aggs, segments, stats)
            return DataTable.for_aggregation(merged_agg.states, stats)
        finally:
            self.residency.end_query(lease, stats)

    def execute(self, ctx: QueryContext,
                segments: List[ImmutableSegment]) -> Tuple[ResultTable, QueryStats]:
        ticket = self.admission.admit(ctx.table_name or "")
        try:
            # whole-query single-flight: the identical-dashboard-query
            # case pays ONE execution; followers share the leader's
            # (ResultTable, QueryStats) — bit-identical by construction.
            # Admission stays per caller (a coalesced request is still a
            # request; its slot releases when the shared flight resolves).
            out, _ = self._query_flight.do(
                self._query_flight_key(ctx, segments),
                lambda: self._execute_admitted(
                    ctx, segments, admit_wait_ms=ticket.wait_ms))
            return out
        finally:
            self.admission.release(ticket)

    @staticmethod
    def _query_flight_key(ctx: QueryContext, segments) -> Optional[Tuple]:
        """None = not shareable. Keyed on OBJECT identity of the compiled
        ctx and every segment: a reloaded segment (new object) or a
        re-compiled ctx never joins a stale flight, and the leader's own
        references keep the ids stable for the flight's lifetime. Mutable
        (consuming) and upsert-managed segments are excluded — their
        contents advance between two otherwise-identical executions."""
        for s in segments:
            if getattr(s, "valid_doc_ids", None) is not None \
                    or getattr(s, "is_mutable", False):
                return None
        return (id(ctx), tuple(id(s) for s in segments))

    def _execute_admitted(self, ctx: QueryContext,
                          segments: List[ImmutableSegment],
                          admit_wait_ms: float = 0.0
                          ) -> Tuple[ResultTable, QueryStats]:
        import time as _time

        from pinot_tpu.common.telemetry import observe_ms

        t0 = _time.perf_counter()
        stats, token = self._open_query(ctx, segments, admit_wait_ms)
        error = None
        try:
            return self._execute_traced(ctx, segments, stats)
        except BaseException as e:
            error = e
            raise
        finally:
            self._close_query(stats, token, error=error)
            observe_ms(ctx.table_name, "server_exec",
                       (_time.perf_counter() - t0) * 1e3)

    def _execute_traced(self, ctx: QueryContext,
                        segments: List[ImmutableSegment],
                        stats: QueryStats
                        ) -> Tuple[ResultTable, QueryStats]:
        if not segments:
            raise QueryError(f"no segments for table {ctx.table_name!r}")
        self._validate_columns(ctx, segments[0])
        segments = self._prune(ctx, segments, stats)
        lease = self._begin_lease(ctx, segments, stats)
        try:
            if ctx.distinct:
                record_decision(stats, "plan", "host_engine",
                                "device_kernel", "distinct_host_only")
                return (host_engine.execute_distinct(ctx, segments, stats),
                        stats)
            if ctx.is_selection:
                return self._selection(ctx, segments, stats), stats

            aggs = [resolve_agg(f) for f in ctx.aggregations]
            if ctx.is_group_by:
                merged = self._execute_group_by(ctx, aggs, segments, stats)
                if merged.trim(self.num_groups_limit):
                    stats.num_groups_limit_reached = True
                schema_types = self._schema_types(segments[0])
                return reduce_group_by(ctx, aggs, merged, schema_types), stats

            merged_agg = self._execute_aggregation(ctx, aggs, segments, stats)
            return reduce_aggregation(ctx, aggs, merged_agg), stats
        finally:
            self.residency.end_query(lease, stats)

    def _begin_lease(self, ctx: QueryContext,
                     segments: List[ImmutableSegment], stats: QueryStats):
        """Open the residency lease for this query: admission decides
        device vs sliced-device vs host-spill, the lease pins every
        resident the query stages until ``end_query`` (a sliced lease
        releases pins at slice boundaries instead). Only aggregation /
        group-by shapes are sliceable — their partials merge with the
        existing combine merges; selection/distinct keep the old
        fit-or-spill admission. Host-only executors skip the protocol
        entirely (they stage nothing)."""
        token = getattr(stats, "_registry_token", None)
        if token is not None:
            token["phase"] = "staging"
        if not self.use_device:
            record_decision(stats, "backend", "host_engine", "device",
                            "device_disabled")
            return None
        sliceable = not ctx.distinct and not ctx.is_selection
        with maybe_span(stats, "Lease", segments=len(segments)) as sp:
            lease = self.residency.begin_query(segments,
                                               ctx.referenced_columns(),
                                               sliceable=sliceable)
            if sp is not None:
                sp.attrs.update(sliced=lease.sliced, spilled=lease.spilled,
                                reason=lease.admit_reason)
        if not lease.device_allowed:
            record_decision(stats, "residency", "host_engine", "device",
                            lease.admit_reason)
        elif lease.sliced:
            record_decision(stats, "residency", "sliced_device",
                            "resident_device", lease.admit_reason)
        stats._staging_lease = lease
        return lease

    @staticmethod
    def _lease_of(stats: QueryStats):
        return getattr(stats, "_staging_lease", None)

    def _device_admitted(self, stats: QueryStats) -> bool:
        """False when admission spilled this query to the host engine."""
        lease = self._lease_of(stats)
        return lease is None or lease.device_allowed

    def evict_segment(self, segment_name: str) -> None:
        """Drop a segment's device arrays (unassignment / reload hook)."""
        self.residency.evict(segment_name)

    def _prune(self, ctx: QueryContext, segments: List[ImmutableSegment],
               stats: QueryStats) -> List[ImmutableSegment]:
        """Server-side pruning before planning/staging (ref:
        SegmentPrunerService at ServerQueryExecutorV1Impl:277). At least
        one segment is kept so result-shape machinery (schema derivation,
        identity aggregation states) runs unchanged — a provably-empty
        scan of one segment is cheap and exact."""
        import time as _time

        from pinot_tpu.engine.pruner import prune_segments
        from pinot_tpu.spi.metrics import ServerQueryPhase

        t0 = _time.perf_counter()
        kept = prune_segments(ctx, segments, stats)
        stats.add_phase_ms(ServerQueryPhase.SEGMENT_PRUNING,
                           (_time.perf_counter() - t0) * 1e3)
        if not kept:
            kept = segments[:1]
            stats.num_segments_pruned -= 1
        # totalDocs covers ALL acquired segments (ref: the reference adds
        # pruned segments' docs to numTotalDocs); processed segments add
        # theirs during execution
        kept_names = {s.segment_name for s in kept}
        stats.total_docs += sum(s.num_docs for s in segments
                                if s.segment_name not in kept_names)
        return kept

    # -- aggregation (no group-by) ----------------------------------------
    def _execute_aggregation(self, ctx: QueryContext, aggs: List[AggDef],
                             segments: List[ImmutableSegment],
                             stats: QueryStats) -> AggResult:
        parts = self._map_segments(
            lambda seg, st: self._segment_aggregation(ctx, aggs, seg, st),
            segments, stats)
        merged: Optional[AggResult] = None
        for part in parts:
            if merged is None:
                merged = part
            else:
                merged.merge(part, aggs)
        return merged

    def _map_segments(self, fn, segments: List[ImmutableSegment],
                      stats: QueryStats) -> List[Any]:
        """Per-segment execution on the persistent worker pool (ref: the
        reference's combine runs segment plans on a sized executor pool,
        BaseCombineOperator.java:55 + the pqw server pool). The numpy-heavy
        host families (sketch builds, sorts, percentiles) release the GIL,
        so segments overlap on multi-core servers; each task gets a private
        QueryStats merged in-order afterwards (QueryStats mutation is not
        thread-safe). Sized by pinot.server.query.worker.threads; the pool
        is shared across concurrent queries, so the thread count is a
        server-level bound instead of multiplying per in-flight query.

        A SLICED lease serializes the fan-out instead: each segment is a
        budget slice — stage, execute, then unpin + demote-to-host before
        the next segment stages — so a working set far over the HBM budget
        still rides the device kernels one segment at a time."""
        token = getattr(stats, "_registry_token", None)
        if token is not None:
            token["phase"] = "executing"
        lease = self._lease_of(stats)
        if lease is not None and lease.sliced:
            parts = []
            for seg in segments:
                parts.append(fn(seg, stats))
                self.residency.release_slice(lease)
            return parts
        if self.worker_threads <= 1 or len(segments) <= 1:
            return [fn(seg, stats) for seg in segments]
        pool = self._worker_pool()
        traced = stats_tracer(stats) is not None
        locals_ = [QueryStats() for _ in segments]
        for st in locals_:  # the pin set must ride into worker threads
            st._staging_lease = lease
            st._tel_table = getattr(stats, "_tel_table", "")
            if traced:
                # recorders are thread-confined: each worker records into
                # its private stats; merge() below re-parents the
                # finished spans under the caller's open span
                start_trace(st)
        parts = pool.map(fn, segments, locals_)
        for st in locals_:
            rec = stats_tracer(st)
            if rec is not None:
                rec.close_all()
            stats.merge(st)
        return parts

    def _worker_pool(self):
        """Lazily-built persistent segment-fanout pool (daemon threads;
        spawn once per executor, not once per query)."""
        pool = self._segment_pool
        if pool is None:
            from pinot_tpu.server.scheduler import WorkerPool

            with self._segment_pool_lock:
                pool = self._segment_pool
                if pool is None:
                    pool = WorkerPool(self.worker_threads, name="pqw")
                    self._segment_pool = pool
        return pool

    def close(self) -> None:
        """Drain the worker pool (server shutdown hook). Safe to reuse the
        executor afterwards: the pool rebuilds lazily on the next fan-out."""
        with self._segment_pool_lock:
            pool, self._segment_pool = self._segment_pool, None
        if pool is not None:
            pool.stop()

    def _segment_aggregation(self, ctx: QueryContext, aggs: List[AggDef],
                             seg: ImmutableSegment,
                             stats: QueryStats) -> AggResult:
        done = _segment_tracer(ctx, stats, "SegmentAggregate", seg)

        fast = self._metadata_fast_path(ctx, aggs, seg, stats)
        if fast is not None:
            return done(fast, "metadata")
        st = self._try_star_tree(ctx, aggs, seg, stats)
        if st is not None:
            result, rung = st
            return done(result, rung)
        if self.use_device and self._device_admitted(stats):
            if getattr(seg, "is_mutable", False):
                from pinot_tpu.engine import mutable_staging

                res = mutable_staging.serve_aggregation(self, ctx, aggs,
                                                        seg, stats)
                if res is not None:
                    return done(res, "mutable_device")
            else:
                from pinot_tpu.engine import index_exec

                ix = index_exec.try_index_rung(self, ctx, aggs, seg, stats,
                                               grouped=False)
                if ix is not None:
                    return done(ix, "index")
                try:
                    plan = self._plan_for(ctx, seg)
                    return done(self._run_device_scalar(plan, seg, stats),
                                "device")
                except PlanError as e:
                    record_decision(stats, "plan", "host_engine",
                                    "device_kernel", e.reason_code)
        with maybe_span(stats, "HostScan", segment=seg.segment_name):
            return done(host_engine.host_aggregate_segment(ctx, aggs, seg,
                                                           stats), "host")

    def _selection(self, ctx: QueryContext,
                   segments: List[ImmutableSegment],
                   stats: QueryStats) -> ResultTable:
        """Selection with the ordered top-k scan on device when eligible
        (engine/selection_device.py); host numpy path otherwise."""
        if self.use_device and ctx.order_by and self._device_admitted(stats):
            from pinot_tpu.engine.selection_device import device_selection

            table = device_selection(ctx, segments, self.residency,
                                     self._selection_kernels, stats)
            if table is not None:
                return table
            record_decision(stats, "selection", "host_engine",
                            "device_topk", "selection_not_device_eligible")
        with maybe_span(stats, "HostSelection"):
            return host_engine.execute_selection(ctx, segments, stats)

    def _star_tree_pick(self, ctx: QueryContext, aggs: List[AggDef],
                        seg: ImmutableSegment, on_decline=None):
        """StarTreePick(tree, index, predicates) for the CHEAPEST fitting
        tree when one exists and the option allows it, else None — the
        single gate for both executors. ``on_decline`` receives the
        most-specific reason code when trees exist but none fits (the
        decision ledger's hook)."""
        from pinot_tpu.engine import startree_exec

        if ctx.options.get("useStarTree", "true").lower() == "false":
            return None  # operator opt-out, not a decline
        return startree_exec.pick_star_tree(ctx, aggs, seg,
                                            on_decline=on_decline)

    def _startree_kernel(self, spec: Tuple):
        """spec -> jitted star-tree node-slice kernel (LRU-capped)."""
        from pinot_tpu.engine.startree_device import build_startree_kernel

        with self._startree_kernel_lock:
            k = self._startree_kernels.get(spec)
            if k is not None:
                self._startree_kernels.move_to_end(spec)
                return k
        k = build_startree_kernel(spec)
        with self._startree_kernel_lock:
            cur = self._startree_kernels.setdefault(spec, k)
            self._startree_kernels.move_to_end(spec)
            if len(self._startree_kernels) > 256:
                self._startree_kernels.popitem(last=False)
            return cur

    def _index_kernel(self, spec: Tuple):
        """spec -> jitted index-rung docId-gather kernel. Shares the
        star-tree kernel LRU under a distinct key: the gather differs
        (dictvals stay un-gathered — they're dictId-shaped), so the two
        rungs never alias a cache entry."""
        from pinot_tpu.engine.index_exec import build_gather_kernel

        key = ("index", spec)
        with self._startree_kernel_lock:
            k = self._startree_kernels.get(key)
            if k is not None:
                self._startree_kernels.move_to_end(key)
                return k
        k = build_gather_kernel(spec)
        with self._startree_kernel_lock:
            cur = self._startree_kernels.setdefault(key, k)
            self._startree_kernels.move_to_end(key)
            if len(self._startree_kernels) > 256:
                self._startree_kernels.popitem(last=False)
            return cur

    def _try_star_tree(self, ctx: QueryContext, aggs: List[AggDef],
                       seg: ImmutableSegment, stats: QueryStats):
        """Pre-aggregated path when a star-tree fits the query
        (ref: AggregationGroupByOrderByPlanNode.java:66-87 selection).
        Returns ``(result, rung)`` — rung 'startree_device' when the node
        arrays served through the device kernels, 'startree' for the host
        walker — or None (no fit / untranslatable predicate -> scan)."""
        from pinot_tpu.engine import startree_device, startree_exec

        def declined(reason: str) -> None:
            record_decision(stats, "startree", "scan", "startree", reason)

        pick = self._star_tree_pick(ctx, aggs, seg, on_decline=declined)
        if pick is None:
            return None
        tree, tree_index, preds = pick
        matches = startree_exec.resolve_matches(seg, preds,
                                                on_decline=declined)
        if matches is None:
            return None  # predicate not dictId-translatable -> scan path

        def chose(rung: str) -> None:
            # the CHOSEN tree rides the ledger and QueryStats: with
            # multiple trees per segment, "which tree served" is the
            # fact the bench records per query (startree:scan->
            # startree_device:tree<i>)
            record_decision(stats, "startree", rung, "scan",
                            f"tree{tree_index}")
            stats.startree_tree_index = tree_index

        if self.use_device and self._device_admitted(stats):
            try:
                res = startree_device.execute_star_tree_device(
                    self, ctx, aggs, seg, tree, matches, stats,
                    tree_index=tree_index)
                if res is not None:
                    chose("startree_device")
                    return res, "startree_device"
            except PlanError as e:
                # node plan over device limits -> host walker
                record_decision(stats, "startree", "startree_host",
                                "startree_device", e.reason_code)
        res = startree_exec.execute_with_matches(ctx, aggs, seg, tree,
                                                 matches, stats)
        if res is None:
            # the host walker refused a tree the pick accepted (defensive:
            # the fit re-check inside execute_with_matches disagreed) —
            # the scan serves, and the ledger says why
            declined("startree_walker_declined")
            return None
        chose("startree")
        return res, "startree"

    def _metadata_fast_path(self, ctx: QueryContext, aggs: List[AggDef],
                            seg: ImmutableSegment,
                            stats: QueryStats) -> Optional[AggResult]:
        """Filter-less COUNT(*)/MIN/MAX answered from metadata
        (ref: MetadataBasedAggregationOperator, DictionaryBasedAggregationOperator)."""
        if ctx.filter is not None or ctx.is_group_by:
            return None
        if getattr(seg, "is_mutable", False):
            # consuming segment: live dictionary min/max can include an
            # in-flight (unpublished) row — answer from a real scan
            return None
        if getattr(seg, "valid_doc_ids", None) is not None:
            # upsert: metadata counts/extremes include invalidated docs
            # (ref: the fast paths require allDocsMatch + no validDocIds)
            return None
        states: List[Any] = []
        for agg, fn in zip(aggs, ctx.aggregations):
            vexpr = agg_value_expr(fn)
            if agg.base == "count" and not agg.mv and vexpr is None:
                states.append(seg.num_docs)
                continue
            if (agg.base in ("min", "max", "minmaxrange") and not agg.mv
                    and isinstance(vexpr, Identifier)):
                cm = seg.metadata.columns.get(vexpr.name)
                if (cm is not None and cm.data_type.is_numeric
                        and not cm.has_nulls and cm.min_value is not None):
                    lo, hi = float(cm.min_value), float(cm.max_value)
                    states.append(lo if agg.base == "min" else
                                  hi if agg.base == "max" else (lo, hi))
                    continue
            return None
        stats.num_segments_processed += 1
        stats.num_segments_matched += 1
        stats.total_docs += seg.num_docs
        return AggResult(states)

    def _run_device_scalar(self, plan: SegmentPlan, seg: ImmutableSegment,
                           stats: QueryStats) -> AggResult:
        served = self._try_pallas(plan, seg, stats)
        if served is not None:
            out, eff = served
            return decode_scalar_result(eff, seg, out)
        out = self._run_kernel(plan, seg, stats)
        return decode_scalar_result(plan, seg, out)

    # -- group-by ----------------------------------------------------------
    def _execute_group_by(self, ctx: QueryContext, aggs: List[AggDef],
                          segments: List[ImmutableSegment],
                          stats: QueryStats) -> GroupByResult:
        merged = GroupByResult()
        for part in self._map_segments(
                lambda seg, st: self._segment_group_by(ctx, aggs, seg, st),
                segments, stats):
            merged.merge(part, aggs)
        return merged

    def _segment_group_by(self, ctx: QueryContext, aggs: List[AggDef],
                          seg: ImmutableSegment,
                          stats: QueryStats) -> GroupByResult:
        done = _segment_tracer(ctx, stats, "SegmentGroupBy", seg)

        st = self._try_star_tree(ctx, aggs, seg, stats)
        if st is not None:
            result, rung = st
            stats.group_by_rung = rung
            return done(result, rung)
        if self.use_device and self._device_admitted(stats):
            if getattr(seg, "is_mutable", False):
                from pinot_tpu.engine import mutable_staging

                res = mutable_staging.serve_group_by(self, ctx, aggs,
                                                     seg, stats)
                if res is not None:
                    stats.group_by_rung = "mutable_device"
                    return done(res, "mutable_device")
            else:
                from pinot_tpu.engine import index_exec

                ix = index_exec.try_index_rung(self, ctx, aggs, seg, stats,
                                               grouped=True)
                if ix is not None:
                    stats.group_by_rung = "index"
                    return done(ix, "index")
                try:
                    plan = self._plan_for(ctx, seg)
                    return done(self._run_device_grouped(plan, seg, stats),
                                "device")
                except PlanError as e:
                    record_decision(stats, "plan", "host_engine",
                                    "device_kernel", e.reason_code)
        stats.group_by_rung = "host"
        with maybe_span(stats, "HostScan", segment=seg.segment_name):
            return done(host_engine.host_group_by_segment(ctx, aggs, seg,
                                                          stats), "host")

    def _plan_for(self, ctx: QueryContext, seg: ImmutableSegment):
        """plan_segment with an LRU keyed on (sql, segment); a reloaded
        segment (new object, same name) misses via the identity check."""
        if ctx.sql is None:
            return plan_segment(ctx, seg)
        import weakref

        # the key carries: a filter FINGERPRINT (the hybrid split and the
        # IN_SUBQUERY rewrite change ctx.filter under the SAME sql) and
        # bitmap presence (a valid-doc bitmap attached after caching must
        # not serve the no-validdocs plan). The fingerprint is a digest
        # memoized per ctx — str(filter) can embed large idset literals and
        # must not be rebuilt per segment. The segment rides as a weakref:
        # entries must not pin unloaded segments + their LUT params alive.
        key = (ctx.sql, filter_fingerprint(ctx), seg.segment_name,
               getattr(seg, "valid_doc_ids", None) is not None)
        with self._plan_cache_lock:
            hit = self._plan_cache.get(key)
            if hit is not None and hit[0]() is seg:
                self._plan_cache.move_to_end(key)
                return hit[1]
        plan = plan_segment(ctx, seg)
        with self._plan_cache_lock:
            self._plan_cache[key] = (weakref.ref(seg), plan)
            if len(self._plan_cache) > self._plan_cache_cap:
                self._plan_cache.popitem(last=False)
        return plan

    def _run_device_grouped(self, plan: SegmentPlan, seg: ImmutableSegment,
                            stats: QueryStats) -> GroupByResult:
        served = self._try_pallas(plan, seg, stats)
        if served is not None:
            # decode against the EFFECTIVE plan: the probe-narrowed shape
            # (large sparse key spaces) carries its own strides/bases
            out, eff = served
            result = decode_grouped_result(eff, seg, out)
            stats.group_by_rung = grouped_rung(eff.spec, out)
            return result
        out = self._run_kernel(plan, seg, stats)
        result = decode_grouped_result(plan, seg, out)
        stats.group_by_rung = grouped_rung(plan.spec, out)
        return result

    def _try_pallas(self, plan: SegmentPlan, seg: ImmutableSegment,
                    stats: QueryStats
                    ) -> Optional[Tuple[Dict[str, Any], SegmentPlan]]:
        """Fused Pallas scan when the plan is eligible; returns the
        unpacked output tree (same shape as the jnp kernel's) plus the
        EFFECTIVE plan it decodes against (the original, or the
        probe-narrowed plan for large-group shapes), or None."""
        from pinot_tpu.engine import pallas_kernels
        from pinot_tpu.engine.kernels import unpack_outputs

        interpret = self._pallas_mode()
        if interpret is None:
            # auto mode on a non-TPU backend is a BACKEND decision, not a
            # pallas-eligibility one: it records under the backend point
            # so the ledger still explains the fallback per query, while
            # the pallas histogram (and its decline-burst trigger) stays
            # reserved for real eligibility gaps. Explicit config
            # (use_pallas=False / GPU) keeps the pallas-point record.
            point = "backend" if self.use_pallas is None else "pallas"
            record_decision(stats, point, "jnp_kernel", "pallas_kernel",
                            "pallas_disabled_on_backend")
            return None
        if plan.spec in self._pallas_blocked:
            # preflight-seeded shapes decline with their predicted rule
            # (pallas_preflight_*); runtime failures keep the generic code
            record_decision(stats, "pallas", "jnp_kernel", "pallas_kernel",
                            self._pallas_blocked.reason_for(plan.spec))
            return None
        with maybe_span(stats, "Stage", segment=seg.segment_name):
            staged = self.residency.stage(seg, lease=self._lease_of(stats))

        def declined(reason: str) -> None:
            record_decision(stats, "pallas", "jnp_kernel", "pallas_kernel",
                            reason)

        def launch():
            served = pallas_kernels.run_segment(
                plan, staged, self.pallas_kernels, interpret,
                on_decline=declined, lut_run_cap=self._pallas_lut_runs)
            if served is None:
                return None
            packed, eff = served
            return unpack_outputs(packed, eff.spec), eff

        try:
            # per-segment coalescing contract: concurrent identical queries
            # (same cached plan object, same staged resident) share ONE
            # fused-kernel launch + ONE D2H; followers decode the shared
            # tree. id()-keying is sound because the leader's closure pins
            # both objects alive for the flight's lifetime.
            import time as _time

            from pinot_tpu.common.telemetry import observe_ms

            t0 = _time.perf_counter()
            with maybe_span(stats, "Kernel", kernel="pallas",
                            segment=seg.segment_name) as sp:
                served, _ = self._kernel_flight.do(
                    ("pallas", id(plan), id(staged)), launch)
                if sp is not None:
                    sp.attrs["served"] = served is not None
            observe_ms(getattr(stats, "_tel_table", ""), "kernel",
                       (_time.perf_counter() - t0) * 1e3)
        except Exception:  # lowering/compile failure -> jnp kernels
            import logging

            logging.getLogger(__name__).exception(
                "pallas kernel failed; disabling pallas for this QUERY "
                "SHAPE (other shapes keep the fused path)")
            # per-SPEC blocklist, not a process-wide kill switch: one
            # Mosaic-unlowerable shape must not cost every other query
            # its fused kernel
            self._pallas_blocked.add(plan.spec)
            declined("pallas_exec_failed")
            return None
        if served is None:
            return None  # run_segment recorded its own reason (on_decline)
        self._track_kernel_stats(served[0], seg, stats)
        return served

    # -- shared ------------------------------------------------------------
    def _run_kernel(self, plan: SegmentPlan, seg: ImmutableSegment,
                    stats: QueryStats) -> Dict[str, Any]:
        from pinot_tpu.engine.kernels import unpack_outputs

        with maybe_span(stats, "Stage", segment=seg.segment_name):
            staged = self.residency.stage(seg, lease=self._lease_of(stats))
        has_validdocs = plan.spec[0][:1] == ("and",) \
            and plan.spec[0][1][0] == ("validdocs",)

        def launch():
            cols = {name: staged.column(name).tree()
                    for name in plan.columns}
            kernel = self.kernels.get(plan.spec)
            params = tuple(plan.params)
            if has_validdocs:
                # fill the planner's placeholder (staging owns the snapshot
                # build + version-keyed device cache)
                params = (staged.valid_mask(),) + params[1:]
            packed = kernel(cols, params, np.int32(seg.num_docs))
            # one D2H fetch for the whole output tree (tunnel-latency fix)
            return unpack_outputs(packed, plan.spec)

        # per-segment coalescing: identical concurrent queries (same cached
        # plan object + same staged resident) share one launch + D2H.
        # Upsert-managed plans are excluded — their valid mask advances
        # between calls, so two launches are NOT interchangeable.
        import time as _time

        from pinot_tpu.common.telemetry import observe_ms

        key = None if has_validdocs else ("seg", id(plan), id(staged))
        t0 = _time.perf_counter()
        with maybe_span(stats, "Kernel", kernel="jnp",
                        segment=seg.segment_name):
            out, _ = self._kernel_flight.do(key, launch)
        observe_ms(getattr(stats, "_tel_table", ""), "kernel",
                   (_time.perf_counter() - t0) * 1e3)
        self._track_kernel_stats(out, seg, stats)
        return out

    def _track_kernel_stats(self, out: Dict[str, Any], seg: ImmutableSegment,
                            stats: QueryStats) -> None:
        stats.num_segments_processed += 1
        stats.total_docs += seg.num_docs
        matched = int(out.get("num_matched",
                              np.asarray(out.get("presence", [0])).sum()))
        stats.num_docs_scanned += matched
        stats.num_segments_matched += 1 if matched else 0

    def _validate_columns(self, ctx: QueryContext,
                          seg: ImmutableSegment) -> None:
        from pinot_tpu.engine.host_eval import VIRTUAL_COLUMNS

        known = set(seg.metadata.columns.keys()) | set(VIRTUAL_COLUMNS)
        for c in ctx.referenced_columns():
            if c not in known:
                raise QueryError(f"unknown column {c!r} in table "
                                 f"{ctx.table_name!r}")

    def _schema_types(self, seg: ImmutableSegment) -> Dict[str, str]:
        from pinot_tpu.engine.host_eval import VIRTUAL_COLUMNS

        out = {name: cm.data_type.label
               for name, cm in seg.metadata.columns.items()}
        out.update(VIRTUAL_COLUMNS)
        return out


# --------------------------------------------------------------------------
# kernel-output decode (shared with the sharded combine path, which merges
# partials on device and decodes against the batch's unified dictionaries)
# --------------------------------------------------------------------------

def decode_scalar_result(plan: SegmentPlan, provider: Any,
                         out: Dict[str, Any]) -> AggResult:
    """``provider`` is anything with ``data_source(col).dictionary`` —
    an ImmutableSegment or a SegmentBatch."""
    states: List[Any] = []
    for i, aspec in enumerate(plan.spec[1]):
        raw = out[f"agg{i}"]
        states.append(_decode_scalar_state(aspec, raw, provider))
    return AggResult(states)


def _decode_scalar_state(aspec: Tuple, raw: Any, provider: Any) -> Any:
    base = aspec[0]
    if base == "distinctcount":
        presence = np.asarray(raw)
        ids = np.nonzero(presence)[0]
        d = provider.data_source(aspec[1]).dictionary
        return frozenset(d.get_values(ids))
    if base == "distinctcounthll":
        from pinot_tpu.utils.hll import HyperLogLog

        regs = np.asarray(raw).astype(np.uint8)
        return HyperLogLog(aspec[2], regs).serialize()
    if base == "count":
        return int(raw)
    if base in ("sum", "min", "max"):
        return float(raw)
    if base == "avg":
        return (float(raw[0]), int(raw[1]))
    if base == "minmaxrange":
        return (float(raw[0]), float(raw[1]))
    raise AssertionError(base)


def decode_grouped_result(plan: SegmentPlan, provider: Any,
                          out: Dict[str, Any]) -> GroupByResult:
    presence = np.asarray(out["presence"])
    gidx = np.nonzero(presence)[0]
    result = GroupByResult()
    if gidx.size == 0:
        return result

    # decode composed keys -> per-column dictIds -> values, using the
    # planner's own strides and bases (single source of truth for key
    # layout; gdict bases are nonzero when the filter narrowed the column's
    # dictId range)
    cards = plan.group_cards
    strides = plan.group_strides.astype(np.int64)
    bases = plan.group_bases or [0] * len(cards)
    key_cols: List[List[Any]] = []
    for i, ((strat, payload), card) in enumerate(zip(plan.group_defs, cards)):
        dids = (gidx // strides[i]) % card
        base = int(bases[i])
        if strat == "gdict":
            d = provider.data_source(payload).dictionary
            key_cols.append(d.get_values(dids + base))
        elif strat == "graw":  # value-space (base = the column's min value)
            key_cols.append([int(x) + base for x in dids])
        else:  # gexpr: the def carries the expression's lower bound
            key_cols.append([int(x) + int(payload) for x in dids])
    keys = list(zip(*key_cols))

    agg_specs = plan.spec[1]
    states_per_agg: List[List[Any]] = []
    for i, aspec in enumerate(agg_specs):
        raw = out[f"agg{i}"]
        base = aspec[0]
        if base == "count":
            arr = np.asarray(raw)[gidx]
            states_per_agg.append([int(v) for v in arr])
        elif base in ("sum", "min", "max"):
            arr = np.asarray(raw)[gidx]
            states_per_agg.append([float(v) for v in arr])
        elif base == "avg":
            s = np.asarray(raw[0])[gidx]
            c = np.asarray(raw[1])[gidx]
            states_per_agg.append([(float(a), int(b)) for a, b in zip(s, c)])
        elif base == "minmaxrange":
            lo = np.asarray(raw[0])[gidx]
            hi = np.asarray(raw[1])[gidx]
            states_per_agg.append([(float(a), float(b)) for a, b in zip(lo, hi)])
        elif base == "distinctcounthll":
            from pinot_tpu.utils.hll import HyperLogLog

            log2m = aspec[2]
            regs = np.asarray(raw).reshape(-1, 1 << log2m)[gidx]
            states_per_agg.append(
                [HyperLogLog(log2m, r.astype(np.uint8)).serialize()
                 for r in regs])
        else:
            raise AssertionError(base)

    for gi, key in enumerate(keys):
        result.groups[key] = [states_per_agg[ai][gi]
                              for ai in range(len(plan.agg_defs))]
    return result
