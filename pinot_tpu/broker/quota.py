"""Per-table query quota: QPS admission at the broker front door.

Re-design of ``pinot-broker/.../queryquota/
HelixExternalViewBasedQueryQuotaManager.java:55`` + ``HitCounter.java``:
a sliding 1-second window of 100ms buckets per table; a query admits only
while the window's hit count stays under the table's
``quota.maxQueriesPerSecond``. The reference divides the cluster-wide
quota by the online broker count; with the embedded single-broker
deployment the divisor is 1 (documented deviation — a broker count hook
is threaded for multi-broker setups).
"""

from __future__ import annotations

import threading
import time

from typing import Dict, Optional

_BUCKETS = 10
_BUCKET_MS = 100


class HitCounter:
    """Ref: HitCounter.java — ring of per-100ms hit buckets."""

    def __init__(self):
        self._counts = [0] * _BUCKETS  # guarded-by: _lock
        self._stamps = [0] * _BUCKETS  # guarded-by: _lock
        self._lock = threading.Lock()

    def hit(self, now_ms: Optional[int] = None) -> None:
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        idx = (now_ms // _BUCKET_MS) % _BUCKETS
        stamp = now_ms // _BUCKET_MS
        with self._lock:
            if self._stamps[idx] != stamp:
                self._stamps[idx] = stamp
                self._counts[idx] = 0
            self._counts[idx] += 1

    def count(self, now_ms: Optional[int] = None) -> int:
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        stamp = now_ms // _BUCKET_MS
        with self._lock:
            return sum(c for c, s in zip(self._counts, self._stamps)
                       if stamp - s < _BUCKETS)


class QueryQuotaManager:
    """One per broker; consulted before routing. The parsed per-table
    quota is CACHED and invalidated by table-config watch — the common
    no-quota case must not re-parse TableConfig on the query front door
    (ref: the reference caches quota state and refreshes on config /
    external-view changes)."""

    def __init__(self, store, num_brokers_fn=None):
        self.store = store
        # lock-free reads are safe (atomic dict ops; a racy miss just
        # re-creates/re-parses); mutation must serialize
        self._counters: Dict[str, HitCounter] = {}  # guarded-by-writes: _lock
        self._quotas: Dict[str, Optional[float]] = {}  # guarded-by-writes: _lock
        self._lock = threading.Lock()
        self._num_brokers_fn = num_brokers_fn or (lambda: 1)
        store.watch("tables/", self._on_table_change)

    def _on_table_change(self, path: str, _value) -> None:
        table = path.split("/", 1)[-1]
        with self._lock:
            self._quotas.pop(table, None)

    def _qps(self, table: str) -> Optional[float]:
        if table in self._quotas:
            return self._quotas[table]
        cfg = self.store.get_table_config(table)
        qps = (cfg.quota_config.max_queries_per_second
               if cfg is not None else None)
        with self._lock:
            self._quotas[table] = qps
        return qps

    def _counter(self, table: str) -> HitCounter:
        c = self._counters.get(table)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(table, HitCounter())
        return c

    def acquire(self, table_with_type: str,
                now_ms: Optional[int] = None) -> bool:
        """True = admitted (and counted). False = over quota
        (ref: acquire() gating in BaseBrokerRequestHandler)."""
        qps = self._qps(table_with_type)
        if not qps:
            return True
        per_broker = qps / max(self._num_brokers_fn(), 1)
        counter = self._counter(table_with_type)
        if counter.count(now_ms) >= per_broker:
            return False
        counter.hit(now_ms)
        return True
