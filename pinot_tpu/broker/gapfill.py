"""Gapfill: fill missing time buckets in a grouped result at broker reduce.

Re-design of the reference's gapfill processor
(``pinot-core/.../query/reduce/GapfillProcessor.java``, dispatched from
``BrokerReduceService.java:44`` via ``ResultReducerFactory`` when
``GapfillUtils.isGapfill`` sees a gapfill select expression): the broker
strips the ``gapfill(...)`` wrapper before scatter (servers execute the
plain time-bucket group-by), then the reducer inserts rows for every absent
bucket of every dimension combination.

Surface (simplified from the reference's 7-argument TIMESERIESON form, which
leans on Java DateTimeFormat specs):

    SELECT gapfill(bucketExpr, start, end, step[, 'FILL_PREVIOUS_VALUE']),
           dims..., agg(...) FROM t
    GROUP BY gapfill(...), dims...

- buckets are the numeric range ``[start, end)`` stepping ``step`` (the
  caller buckets time however it likes — the reference's datetime-format
  conversions live in the transform layer here);
- FILL_DEFAULT_VALUE (default): absent buckets carry 0 for aggregation
  columns; FILL_PREVIOUS_VALUE: they carry the previous present bucket's
  values (the reference's carry-forward fill).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional, Tuple

from pinot_tpu.engine.errors import QueryError
from pinot_tpu.engine.results import ResultTable
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import Expr, Function, Literal, OrderByExpr

_MODES = ("FILL_DEFAULT_VALUE", "FILL_PREVIOUS_VALUE")


@dataclass
class GapfillSpec:
    select_pos: int          # gapfill expression's position in the select list
    start: int
    end: int
    step: int
    fill_mode: str
    limit: int               # the QUERY's limit/offset, applied AFTER filling
    offset: int


# reduce-side row cap while gapfill is active: the reducer must hand gapfill
# every live group in the window (a reduce-side ORDER BY/LIMIT trim would
# make trimmed-but-present buckets indistinguishable from absent ones and
# fabricate zero rows over real data); the broker's num_groups_limit still
# bounds memory upstream
_REDUCE_LIMIT = 10_000_000


def _parse(fn: Function) -> Tuple[Expr, int, int, int, str]:
    if len(fn.args) not in (4, 5):
        raise QueryError(
            "gapfill(bucketExpr, start, end, step[, 'FILL_...']) expected")
    nums = []
    for a in fn.args[1:4]:
        if not (isinstance(a, Literal) and isinstance(a.value, (int, float))
                and not isinstance(a.value, bool)):
            raise QueryError("gapfill start/end/step must be numeric literals")
        nums.append(int(a.value))
    start, end, step = nums
    if step <= 0 or end < start:
        raise QueryError("gapfill needs step > 0 and end >= start")
    mode = "FILL_DEFAULT_VALUE"
    if len(fn.args) == 5:
        m = fn.args[4]
        if not (isinstance(m, Literal) and isinstance(m.value, str)) \
                or m.value.upper() not in _MODES:
            raise QueryError(f"gapfill fill mode must be one of {_MODES}")
        mode = m.value.upper()
    return fn.args[0], start, end, step, mode


def extract_gapfill(ctx: QueryContext
                    ) -> Tuple[QueryContext, Optional[GapfillSpec]]:
    """Strip gapfill(...) from the context; servers run the inner bucket
    expression. Returns the rewritten context + the fill spec (or None)."""
    gf = None
    for e in ctx.group_by:
        if isinstance(e, Function) and e.name == "gapfill":
            gf = e
            break
    if gf is None:
        # gapfill outside GROUP BY is the reference's error too
        if any(isinstance(e, Function) and e.name == "gapfill"
               for e in ctx.select_expressions):
            raise QueryError("gapfill(...) must be a GROUP BY expression")
        return ctx, None

    inner, start, end, step, mode = _parse(gf)

    def rw(e: Expr) -> Expr:
        return inner if e == gf else e

    select = [rw(e) for e in ctx.select_expressions]
    try:
        select_pos = ctx.select_expressions.index(gf)
    except ValueError:
        raise QueryError("gapfill(...) must also appear in the select list")
    new_ctx = replace(
        ctx,
        select_expressions=select,
        group_by=[rw(e) for e in ctx.group_by],
        order_by=[OrderByExpr(rw(o.expr), o.ascending)
                  for o in ctx.order_by],
        # LIMIT/OFFSET move to the post-fill trim (see _REDUCE_LIMIT note)
        limit=_REDUCE_LIMIT,
        offset=0,
    )
    return new_ctx, GapfillSpec(select_pos=select_pos, start=start, end=end,
                                step=step, fill_mode=mode,
                                limit=ctx.limit, offset=ctx.offset)


def apply_gapfill(ctx: QueryContext, table: ResultTable,
                  spec: GapfillSpec) -> ResultTable:
    """Insert rows for absent buckets per dimension combination. ``ctx`` is
    the REWRITTEN context (post extract). Aggregation columns are the select
    positions that are not group expressions; fabricated rows fill them with
    0 (default mode) or the previous bucket's values (carry-forward). The
    reduce ran UNTRIMMED (extract_gapfill lifts the limit) so every present
    bucket is visible here; the query's ORDER BY re-applies over the FILLED
    rows and the original LIMIT/OFFSET trim last."""
    group_keys = {str(e) for e in ctx.group_by}
    dim_pos = [i for i, e in enumerate(ctx.select_expressions)
               if str(e) in group_keys and i != spec.select_pos]
    agg_pos = [i for i in range(len(ctx.select_expressions))
               if i not in dim_pos and i != spec.select_pos]

    series: dict = {}
    order: List[Tuple] = []
    for row in table.rows:
        key = tuple(row[i] for i in dim_pos)
        if key not in series:
            series[key] = {}
            order.append(key)
        try:
            t = int(row[spec.select_pos])
        except (TypeError, ValueError):
            raise QueryError(
                f"gapfill bucket value {row[spec.select_pos]!r} not numeric")
        if not (spec.start <= t < spec.end):
            continue  # outside the fill window: window semantics drop it
        if (t - spec.start) % spec.step:
            # a misaligned bucket would be SILENTLY shadowed by a fabricated
            # zero row — refuse loudly instead (the bucket expression must
            # produce start + k*step values)
            raise QueryError(
                f"gapfill bucket {t} is not aligned to "
                f"start={spec.start} step={spec.step}")
        series[key][t] = row

    out = []
    for key in order:
        have = series[key]
        prev = None
        for t in range(spec.start, spec.end, spec.step):
            row = have.get(t)
            if row is None:
                row = [None] * len(ctx.select_expressions)
                row[spec.select_pos] = t
                for p, v in zip(dim_pos, key):
                    row[p] = v
                for p in agg_pos:
                    if spec.fill_mode == "FILL_PREVIOUS_VALUE" \
                            and prev is not None:
                        row[p] = prev[p]
                    else:
                        row[p] = 0
            else:
                row = list(row)
            prev = row
            out.append(row)

    if ctx.order_by:
        # re-apply the query's ORDER BY over the FILLED rows (fabricated
        # rows participate; a LIMIT-ed top-N over the series stays correct)
        from pinot_tpu.engine.results import _Reversible

        pos_of = {str(e): i for i, e in enumerate(ctx.select_expressions)}
        idx_dir = [(pos_of[str(ob.expr)], ob.ascending)
                   for ob in ctx.order_by if str(ob.expr) in pos_of]

        def sort_key(row):
            return tuple(_Reversible(row[i], asc) for i, asc in idx_dir)

        out.sort(key=sort_key)
    return ResultTable(table.schema,
                       out[spec.offset:spec.offset + spec.limit])
