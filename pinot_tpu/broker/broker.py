"""Broker request handler: SQL front door + scatter/gather.

Re-design of ``pinot-broker/.../requesthandler/BaseBrokerRequestHandler.java:176``:
parse SQL -> resolve the table (offline / realtime / hybrid with the time
boundary, ``:2002``) -> routing tables -> scatter per-server instance
requests -> gather DataTables -> BrokerReduceService -> BrokerResponse
(ref: SingleConnectionBrokerRequestHandler.java:82-146).

Transport: an in-process server registry (the embedded-cluster mode, ref:
ClusterTest single-JVM). Multi-host deployments register gRPC stubs that
expose the same ``execute_query`` signature.
"""

from __future__ import annotations

import logging
import time

from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

from pinot_tpu.broker.reduce import BrokerReduceService
from pinot_tpu.broker.routing import RoutingManager
from pinot_tpu.common.datatable import DataTable
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.controller.state import ClusterStateStore
from pinot_tpu.engine.errors import QueryError, QueryRejectedError
from pinot_tpu.engine.results import QueryStats
from pinot_tpu.query import SqlParseError, compile_query
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    FilterNode,
    FilterOp,
    Identifier,
    Predicate,
    PredicateType,
)
from pinot_tpu.spi.config import CommonConstants
from pinot_tpu.spi.table import TableType, table_name_with_type

log = logging.getLogger(__name__)

# ref: QueryException codes
SQL_PARSING_ERROR = 150
TABLE_DOES_NOT_EXIST_ERROR = 190
BROKER_REQUEST_SEND_ERROR = 425
SERVER_NOT_RESPONDING_ERROR = 427
QUERY_EXECUTION_ERROR = 200
ACCESS_DENIED_ERROR = 180
TOO_MANY_REQUESTS_ERROR = 429


class AccessDeniedError(QueryError):
    """A subquery (or other nested execution) was denied by access control;
    carries the denial through QueryError-shaped handling so the outer
    response keeps errorCode 180 (-> HTTP 403)."""


class BrokerRequestHandler:
    """Ref: BaseBrokerRequestHandler.java:176."""

    def __init__(self, store: ClusterStateStore,
                 routing: Optional[RoutingManager] = None,
                 scatter_workers: int = 16,
                 query_timeout_s: float = 30.0,
                 coalesce: bool = True,
                 device_reduce: Optional[bool] = None):
        from pinot_tpu.spi.metrics import MetricsRegistry

        if device_reduce is None:
            # operator knob (pinot.broker.reduce.device.enabled): an
            # explicit constructor argument — the embedded cluster's and
            # the bench's path — wins over the environment
            from pinot_tpu.spi.config import PinotConfiguration

            device_reduce = PinotConfiguration().get_bool(
                CommonConstants.BROKER_DEVICE_REDUCE_KEY,
                CommonConstants.DEFAULT_BROKER_DEVICE_REDUCE)
        self.store = store
        self.routing = routing or RoutingManager(store)
        self.reduce_service = BrokerReduceService(
            device_reduce=device_reduce)
        self._servers: Dict[str, object] = {}
        from pinot_tpu.server.scheduler import _DaemonPool

        from pinot_tpu.broker.quota import QueryQuotaManager

        self._pool = _DaemonPool(scatter_workers, "scatter")
        self.query_timeout_s = query_timeout_s
        self.metrics = MetricsRegistry(role="broker")
        import threading as _threading

        self._subq_local = _threading.local()
        self.quota = QueryQuotaManager(
            store,
            num_brokers_fn=lambda: max(
                len(store.instances("BROKER", only_alive=True)), 1))
        # single admission gate for the front door: the per-table QPS
        # quota rides it (reason="quota" rejections), and operators can
        # bound broker concurrency through configure() — the server-side
        # executor gate bounds execution below.
        from pinot_tpu.server.admission import AdmissionGate

        self.admission = AdmissionGate(max_concurrent=-1, quota=self.quota,
                                       name="broker-admission")
        # single-flight coalescing: concurrent IDENTICAL dashboard queries
        # (same normalized SQL + principal + cluster-state generation)
        # share one compile/scatter/gather/reduce, before any fan-out
        from pinot_tpu.common.singleflight import SingleFlight

        self.coalesce = coalesce
        self._flights = SingleFlight()
        self._leading = _threading.local()
        # continuous telemetry: the broker front door records per-table
        # windowed latency/error (the SLO tracker's input) and exposes
        # the process telemetry families on this registry's /metrics
        from pinot_tpu.common.telemetry import TELEMETRY

        TELEMETRY.configure()
        self.metrics.bind_telemetry(TELEMETRY)
        TELEMETRY.recorder.register_provider(
            "brokerScheduler", self.scheduler_snapshot)

    # -- transport registry --------------------------------------------------
    def register_server(self, instance_id: str, server) -> None:
        """``server`` exposes execute_query(ctx, table, segments)->DataTable
        (a ServerInstance, or a gRPC stub with the same surface)."""
        self._servers[instance_id] = server

    # -- entry (ref: handleSQLRequest:203) -----------------------------------
    def handle_sql(self, sql: str, principal=None,
                   access_control=None) -> BrokerResponse:
        """Front door. Concurrent IDENTICAL queries — same normalized SQL,
        same principal, same cluster-state generation — single-flight: one
        leader runs the full compile/authorize/scatter/gather/reduce and
        every concurrent duplicate receives the same BrokerResponse (the
        dashboard-fanout case: N browser tabs refreshing one chart cost
        ONE execution). A store mutation (segment push, table config)
        bumps the generation, so later arrivals never join a flight whose
        answer predates the change. Coalescing is skipped for
        time-dependent SQL (``now()``)."""
        key = self._flight_key(sql, principal, access_control)
        led = getattr(self._leading, "keys", None)
        if led is None:
            led = self._leading.keys = set()
        if key is None or key in led:
            # non-coalescable, or a re-entrant subquery on the leader's own
            # thread (joining our own flight would deadlock)
            return self._handle_sql(sql, principal, access_control)

        def lead():
            led.add(key)
            try:
                return self._handle_sql(sql, principal, access_control)
            finally:
                led.discard(key)

        resp, coalesced = self._flights.do(key, lead)
        if coalesced:
            from pinot_tpu.spi.metrics import BrokerMeter

            self.metrics.meter(BrokerMeter.QUERIES).mark()
            self.metrics.meter(BrokerMeter.QUERIES_COALESCED).mark()
        return resp

    def _flight_key(self, sql: str, principal, access_control):
        """None = don't coalesce. The key carries the cluster-state
        VERSION as the table generation: any store mutation invalidates
        joinability (conservatively — a whole-store counter, not per
        table, trading a few missed coalesces for zero staleness)."""
        if not self.coalesce or not isinstance(sql, str):
            return None
        norm = " ".join(sql.split())
        if not norm or "now(" in norm.lower():
            return None  # time-dependent: two calls are NOT identical work
        pkey = getattr(principal, "name", None) if principal is not None \
            else None
        return (norm, pkey,
                id(access_control) if access_control is not None else None,
                self.store.version)

    def scheduler_snapshot(self) -> Dict[str, object]:
        """Broker half of ``/debug/scheduler``: single-flight coalescing
        counters + the front-door admission gate."""
        return {"singleFlight": self._flights.snapshot(),
                "admission": self.admission.snapshot()}

    # -- continuous telemetry (process-wide center; broker-side routes) ------
    def telemetry_snapshot(self) -> Dict[str, object]:
        """``GET /debug/telemetry``: windowed (table, phase) histograms
        with sliding AND lifetime quantiles + the gauge-history rings."""
        from pinot_tpu.common.telemetry import TELEMETRY

        return TELEMETRY.snapshot()

    def slo_snapshot(self) -> Dict[str, object]:
        """``GET /debug/slo``: per-table objectives + multi-window burn."""
        from pinot_tpu.common.telemetry import TELEMETRY

        return TELEMETRY.slo_snapshot()

    def flightrecorder_snapshot(self) -> Dict[str, object]:
        """``GET /debug/flightrecorder``: bundle index + last bundle."""
        from pinot_tpu.common.telemetry import TELEMETRY

        return TELEMETRY.recorder.snapshot()

    def freshness_snapshot(self) -> Dict[str, object]:
        """``GET /debug/freshness``: per-table ingest-to-queryable
        histograms + freshness-objective burn."""
        from pinot_tpu.common.telemetry import TELEMETRY

        return TELEMETRY.freshness_snapshot()

    def _handle_sql(self, sql: str, principal=None,
                    access_control=None) -> BrokerResponse:
        """``access_control``/``principal`` enable per-table authorization
        on the PARSED query (ref: BaseBrokerRequestHandler.handleRequest
        authorizing on the compiled request, not the raw SQL — a regex over
        the SQL text is spoofable via string literals). Subquery rewrites
        re-enter with the same principal so inner queries are checked too."""
        from pinot_tpu.spi.metrics import BrokerMeter, BrokerQueryPhase

        start = time.perf_counter()
        self.metrics.meter(BrokerMeter.QUERIES).mark()
        response = BrokerResponse()
        tel_table: List[str] = [""]  # resolved after compile, read by finish

        def phase(name: str, t0: float) -> float:
            """Record a broker phase (ref: BrokerQueryPhase timers at
            SingleConnectionBrokerRequestHandler.java:90-123)."""
            now = time.perf_counter()
            ms = (now - t0) * 1e3
            response.phase_times_ms[name] = \
                response.phase_times_ms.get(name, 0.0) + ms
            self.metrics.timer(name).update_ms(ms)
            return now

        def finish(resp: BrokerResponse) -> BrokerResponse:
            # exactly one exceptions_total tick per failed query, whatever
            # the failure mode (parse / no table / unavailable / reduce)
            if resp.has_exceptions:
                self.metrics.meter(BrokerMeter.EXCEPTIONS).mark()
            # every front-door outcome lands in the per-table windowed
            # latency histogram + the SLO error-budget counters — the
            # continuous (sliding-percentile) view of broker latency
            from pinot_tpu.common.telemetry import TELEMETRY

            TELEMETRY.note_broker_query(
                tel_table[0], (time.perf_counter() - start) * 1e3,
                resp.has_exceptions)
            return resp

        try:
            ctx = compile_query(sql)
        except SqlParseError as e:
            response.add_exception(SQL_PARSING_ERROR, str(e))
            return finish(response)
        tel_table[0] = ctx.table_name or ""
        t = phase(BrokerQueryPhase.COMPILATION, start)

        if access_control is not None:
            from pinot_tpu.spi.auth import READ

            # ctx.table_name is never None (the grammar requires FROM), so
            # the parsed table — not a spoofable raw-SQL regex — is what
            # gets authorized
            if not access_control.has_access(principal, ctx.table_name,
                                             READ):
                response.add_exception(
                    ACCESS_DENIED_ERROR,
                    f"Permission denied for table {ctx.table_name!r}")
                return finish(response)

        try:
            physical = self._resolve_tables(ctx.table_name)
        except QueryError as e:
            response.add_exception(TABLE_DOES_NOT_EXIST_ERROR, str(e))
            return finish(response)

        if ctx.explain:
            # EXPLAIN PLAN FOR: logical operator tree, no execution — but
            # AFTER table resolution, so explaining a nonexistent table
            # errors like the real query would (ref: ExplainPlanDataTableReducer)
            from pinot_tpu.engine.results import DataSchema, ResultTable
            from pinot_tpu.query.explain import EXPLAIN_COLUMNS, explain_rows

            names, types = EXPLAIN_COLUMNS
            response.result_table = ResultTable(DataSchema(names, types),
                                                explain_rows(ctx))
            response.time_used_ms = (time.perf_counter() - start) * 1e3
            return finish(response)

        try:
            # strip gapfill(...) BEFORE scatter: servers execute the plain
            # bucket group-by; the reducer fills the gaps (ref:
            # GapfillProcessor dispatched from BrokerReduceService.java:44)
            from pinot_tpu.broker.gapfill import extract_gapfill

            ctx, gapfill_spec = extract_gapfill(ctx)
        except QueryError as e:
            response.add_exception(QUERY_EXECUTION_ERROR, str(e))
            return finish(response)

        # admission FIRST — per-table QPS quota + broker concurrency bound
        # ride ONE gate: a throttled/rejected request must not get to
        # trigger subquery execution work (ref: queryquota acquire before
        # routing). Tickets release in the finally below; rejection is the
        # typed retriable error, surfaced as a 429-coded exception.
        tickets: List[object] = []
        try:
            for table in physical:
                t_adm = self.admission.admit(table)
                tickets.append(t_adm)
        except QueryRejectedError as e:
            for t_adm in tickets:
                self.admission.release(t_adm)
            self.metrics.meter(BrokerMeter.QUERIES_REJECTED).mark()
            response.add_exception(
                TOO_MANY_REQUESTS_ERROR,
                f"{e} (retriable; queueDepth={e.queue_depth})")
            return finish(response)
        admit_wait_ms = sum(getattr(t_adm, "wait_ms", 0.0)
                            for t_adm in tickets)
        try:
            return self._scatter_reduce(ctx, physical, gapfill_spec,
                                        response, phase, finish, start,
                                        principal, access_control,
                                        admit_wait_ms=admit_wait_ms)
        finally:
            for t_adm in tickets:
                self.admission.release(t_adm)

    def _scatter_reduce(self, ctx, physical, gapfill_spec, response,
                        phase, finish, start, principal, access_control,
                        admit_wait_ms: float = 0.0) -> BrokerResponse:
        """Post-admission half of the front door: subquery rewrite ->
        hybrid split -> routing -> scatter/gather -> reduce.
        ``admit_wait_ms`` is the front-door admission-gate queue wait —
        the broker-level queue span in the trace tree."""
        from pinot_tpu.spi.metrics import BrokerMeter, BrokerQueryPhase

        try:
            ctx = self._rewrite_subqueries(ctx, principal=principal,
                                           access_control=access_control)
        except AccessDeniedError as e:
            response.add_exception(ACCESS_DENIED_ERROR, str(e))
            return finish(response)
        except QueryError as e:
            response.add_exception(QUERY_EXECUTION_ERROR, str(e))
            return finish(response)

        tables: List[DataTable] = []
        servers_queried = set()
        servers_responded = set()
        # broker-side stats carrier: routing + gather decisions recorded
        # here merge into the reduced stats so the response's decision
        # ledger explains why each server was or wasn't scattered to
        broker_stats = QueryStats()
        # reduce-as-arrivals: every gathered DataTable folds into the
        # merge state the moment it lands, so the reduce work overlaps
        # the stragglers' network wait; finish() below runs only the
        # final trim/HAVING/post-agg pass
        acc = self.reduce_service.accumulator(ctx)
        for table, sub_ctx in self._split_hybrid(ctx, physical,
                                                 stats=broker_stats):
            t = time.perf_counter()
            route = self.routing.route(table, sub_ctx, stats=broker_stats)
            routing, unavailable = route.routing, route.unavailable
            t = phase(BrokerQueryPhase.ROUTING, t)
            if unavailable:
                self.metrics.meter(BrokerMeter.NO_SERVING_HOST).mark(
                    len(unavailable))
                response.add_exception(
                    SERVER_NOT_RESPONDING_ERROR,
                    f"{len(unavailable)} segments of {table} unavailable: "
                    f"{unavailable[:5]}")
            if not routing:
                continue
            if self._use_streaming(sub_ctx, routing):
                gathered, queried, responded = \
                    self._scatter_gather_streaming(table, sub_ctx, routing,
                                                   broker_stats, acc)
            else:
                gathered, queried, responded = self._scatter_gather(
                    table, sub_ctx, routing, broker_stats, acc)
            phase(BrokerQueryPhase.SCATTER_GATHER, t)
            tables.extend(gathered)
            servers_queried |= queried
            servers_responded |= responded

        response.num_servers_queried = len(servers_queried)
        response.num_servers_responded = len(servers_responded)
        broker_stats.num_servers_queried = len(servers_queried)
        broker_stats.num_servers_responded = len(servers_responded)
        if not tables:
            # an existing-but-empty table answers with an empty result
            response.stats = broker_stats
            response.time_used_ms = (time.perf_counter() - start) * 1e3
            return finish(response)

        t = time.perf_counter()
        try:
            table, stats, server_errors = acc.finish()
            if gapfill_spec is not None:
                from pinot_tpu.broker.gapfill import apply_gapfill

                table = apply_gapfill(ctx, table, gapfill_spec)
            response.result_table = table
            # fold the broker-side routing/gather ledger + scatter
            # accounting into the reduced stats: numServersQueried /
            # numServersResponded ride the stats (and thus the wire /
            # QueryStats merges) so a partial result is LOUD everywhere
            # the stats travel, not just on the top-level response
            stats.merge(broker_stats)
            response.stats = stats
            traced_stats = stats if (stats.trace or stats.spans) else None
            for msg in server_errors:
                # partial result: the table stands, but the caller sees it
                response.add_exception(SERVER_NOT_RESPONDING_ERROR, msg)
        except QueryError as e:
            traced_stats = None
            response.stats = broker_stats
            response.add_exception(QUERY_EXECUTION_ERROR, str(e))
        phase(BrokerQueryPhase.REDUCE, t)
        response.time_used_ms = (time.perf_counter() - start) * 1e3
        if traced_stats is not None:
            # ref: trace JSON attached to response metadata
            # (ServerQueryExecutorV1Impl.java:221-226). The legacy flat
            # "entries" view is preserved (emitted from the span tree at
            # each span close); "spans" is the broker root with the
            # measured broker phases as children and every server's tree
            # — instance-tagged at gather, see _tag_trace — re-parented
            # under ScatterGather. Assembled AFTER the REDUCE phase timer
            # so the root's children account the full broker wall time.
            from pinot_tpu.common.tracing import build_broker_root

            root = build_broker_root(
                response.phase_times_ms, traced_stats.spans,
                response.time_used_ms, admission_wait_ms=admit_wait_ms,
                reduce_folds=acc.fold_spans)
            response.trace_info = {"entries": traced_stats.trace,
                                   "spans": [root]}
        return finish(response)

    # -- table resolution + hybrid split -------------------------------------
    # -- IN_SUBQUERY (IdSet semijoin) ---------------------------------------
    MAX_SUBQUERY_DEPTH = 3

    def _rewrite_subqueries(self, ctx: QueryContext, principal=None,
                            access_control=None) -> QueryContext:
        """``inSubquery(col, '<sql>')`` predicates: pre-execute the inner
        query (typically ``SELECT idset(col) FROM ...``), then rewrite to
        ``inIdSet(col, <serialized set>)`` so servers evaluate a plain
        membership transform (ref: the broker-side IN_SUBQUERY rewrite +
        server IdSet resolution, ServerQueryExecutorV1Impl.java:404-441)."""
        from dataclasses import replace

        from pinot_tpu.query.expressions import (
            FilterNode,
            Function,
            Literal,
        )

        if ctx.filter is None:
            return ctx

        def walk(node: FilterNode) -> FilterNode:
            if node.predicate is not None:
                p = node.predicate
                lhs = p.lhs
                if (isinstance(lhs, Function)
                        and lhs.name in ("insubquery", "in_subquery")):
                    if len(lhs.args) != 2 \
                            or not isinstance(lhs.args[1], Literal):
                        raise QueryError(
                            "inSubquery(column, 'sql literal') expected")
                    inner_sql = str(lhs.args[1].value)
                    tl = self._subq_local
                    tl.depth = getattr(tl, "depth", 0) + 1
                    try:
                        if tl.depth > self.MAX_SUBQUERY_DEPTH:
                            raise QueryError("IN_SUBQUERY nesting too deep")
                        # inner queries carry the OUTER principal: a
                        # table-scoped caller must not semijoin/probe
                        # other tables through the rewrite
                        inner = self.handle_sql(
                            inner_sql, principal=principal,
                            access_control=access_control)
                    finally:
                        tl.depth -= 1
                    if any(e.get("errorCode") == ACCESS_DENIED_ERROR
                           for e in inner.exceptions):
                        # the denial must keep its identity end to end so
                        # the REST layer returns 403, same as a direct query
                        raise AccessDeniedError(
                            f"IN_SUBQUERY inner query denied: "
                            f"{inner.exceptions[0].get('message')}")
                    if inner.has_exceptions or inner.result_table is None \
                            or not inner.result_table.rows:
                        raise QueryError(
                            f"IN_SUBQUERY inner query failed: "
                            f"{inner.exceptions[:1] or 'empty result'}")
                    if (len(inner.result_table.rows) != 1
                            or len(inner.result_table.rows[0]) != 1):
                        raise QueryError(
                            "IN_SUBQUERY inner query must return exactly "
                            "one IDSET() value (no GROUP BY)")
                    idset = inner.result_table.rows[0][0]
                    if not isinstance(idset, str):
                        raise QueryError(
                            "IN_SUBQUERY inner query must produce IDSET()")
                    new_lhs = Function("inidset",
                                       (lhs.args[0], Literal(idset)))
                    return FilterNode.pred(replace(p, lhs=new_lhs))
                return node
            kids = tuple(walk(c) for c in node.children)
            if all(a is b for a, b in zip(kids, node.children)):
                return node  # untouched subtree: no rebuild on the hot path
            return FilterNode(node.op, children=kids, predicate=None)

        new_filter = walk(ctx.filter)
        if new_filter is ctx.filter:
            return ctx
        return replace(ctx, filter=new_filter)

    def _resolve_tables(self, raw_name: str) -> List[str]:
        """'myTable' -> its physical tables; explicit _OFFLINE/_REALTIME
        names pass through (ref: table resolution via TableCache)."""
        known = set(self.store.table_names())
        if raw_name in known:
            return [raw_name]
        out = [table_name_with_type(raw_name, t)
               for t in (TableType.OFFLINE, TableType.REALTIME)
               if table_name_with_type(raw_name, t) in known]
        if not out:
            raise QueryError(f"table {raw_name!r} does not exist")
        return out

    @staticmethod
    def _hybrid_route(stats, reason: str, chosen: str,
                      declined: str) -> None:
        """Time-boundary routing outcome onto the decision ledger (the
        'hybrid' ReasonNamespace scans the first string literal)."""
        from pinot_tpu.common.tracing import record_decision

        record_decision(stats, "hybrid", chosen, declined, reason)

    def _split_hybrid(self, ctx: QueryContext, physical: List[str],
                      stats: Optional[QueryStats] = None
                      ) -> List[Tuple[str, QueryContext]]:
        """Hybrid tables get the time-boundary split
        (ref: BaseBrokerRequestHandler attachTimeBoundary :2002); every
        outcome lands on the decision ledger."""
        if len(physical) < 2:
            self._hybrid_route(stats, "hybrid_single_table", "direct",
                               "time_split")
            return [(physical[0], ctx)]
        offline = next(t for t in physical if t.endswith("_OFFLINE"))
        realtime = next(t for t in physical if t.endswith("_REALTIME"))
        cfg = self.store.get_table_config(offline)
        tc = cfg.validation_config.time_column_name if cfg else None
        boundary = self.routing.time_boundary.get_boundary(offline)
        if tc is None:
            # no time column: the split predicate can't be expressed
            self._hybrid_route(stats, "hybrid_no_time_column",
                               "realtime_all", "time_split")
            return [(realtime, ctx)]
        if boundary is None:
            # no boundary yet: realtime serves everything
            self._hybrid_route(stats, "hybrid_no_boundary",
                               "realtime_all", "time_split")
            return [(realtime, ctx)]
        self._hybrid_route(stats, "hybrid_time_split", "time_split",
                           "realtime_all")
        off_pred = FilterNode(
            FilterOp.PREDICATE,
            predicate=Predicate(PredicateType.RANGE, Identifier(tc),
                                upper=boundary, upper_inclusive=True))
        rt_pred = FilterNode(
            FilterOp.PREDICATE,
            predicate=Predicate(PredicateType.RANGE, Identifier(tc),
                                lower=boundary, lower_inclusive=False))
        return [
            (offline, replace(ctx, filter=_and(ctx.filter, off_pred))),
            (realtime, replace(ctx, filter=_and(ctx.filter, rt_pred))),
        ]

    # -- streaming scatter/gather (ref: GrpcBrokerRequestHandler +
    # StreamingReduceService): selection-only queries pull per-segment
    # blocks from ALL servers concurrently and stop the moment
    # offset+limit rows arrived — the wire analogue of
    # SelectionOnlyCombineOperator's early exit.
    def _scatter_gather_streaming(self, table: str, ctx: QueryContext,
                                  routing: Dict[str, List[str]],
                                  broker_stats: Optional[QueryStats] = None,
                                  acc=None):
        import threading

        from pinot_tpu.common.tracing import record_decision

        need = ctx.offset + ctx.limit
        queried, responded = set(), set()
        enough = threading.Event()
        lock = threading.Lock()
        have = [0]

        def pull(server, segments) -> List[DataTable]:
            out: List[DataTable] = []
            for block in server.execute_query_streaming(ctx, table,
                                                        segments):
                out.append(block)
                if not block.exceptions:
                    with lock:
                        have[0] += block.num_rows()
                        if have[0] >= need:
                            enough.set()
                if enough.is_set():
                    break
            return out

        futures = {}
        for instance_id, segments in routing.items():
            queried.add(instance_id)
            server = self._servers.get(instance_id)
            if server is None:
                futures[instance_id] = None
                continue
            futures[instance_id] = self._pool.submit(
                lambda srv=server, segs=segments: pull(srv, segs))

        gathered: List[DataTable] = []

        def took(dt: DataTable, instance_id: str) -> None:
            gathered.append(dt)
            if acc is not None:
                acc.add(dt, instance=instance_id)

        deadline = time.monotonic() + self.query_timeout_s
        for instance_id, fut in self._as_arrivals(futures, deadline):
            if fut is None:
                took(DataTable.for_exception(
                    f"server {instance_id} is not connected"), instance_id)
                record_decision(broker_stats, "gather", "partial_result",
                                "full_result", "server_not_connected")
                continue
            try:
                if isinstance(fut, FutureTimeout):
                    raise fut
                ok = False
                for dt in fut.result(timeout=0.001):
                    _tag_trace(dt, instance_id)
                    took(dt, instance_id)
                    ok = ok or not dt.exceptions
                # responded = returned at least one USABLE block; a server
                # that only errored is down for accounting purposes
                if ok:
                    responded.add(instance_id)
                else:
                    record_decision(broker_stats, "gather", "partial_result",
                                    "full_result", "server_error")
            except FutureTimeout:
                enough.set()  # stop the straggler's pull loop
                took(DataTable.for_exception(
                    f"server {instance_id} timed out after "
                    f"{self.query_timeout_s}s"), instance_id)
                record_decision(broker_stats, "gather", "partial_result",
                                "full_result", "server_timeout")
            except Exception as e:  # noqa: BLE001
                took(DataTable.for_exception(
                    f"server {instance_id} failed: {e!r}"), instance_id)
                record_decision(broker_stats, "gather", "partial_result",
                                "full_result", "server_error")
        return gathered, queried, responded

    @staticmethod
    def _as_arrivals(futures: Dict[str, object], deadline: float):
        """Yield ``(instance_id, future)`` in COMPLETION order (the
        reduce-as-arrivals contract: a fast server's table folds while
        the stragglers are still on the wire). Not-connected entries
        (None) yield first; a future still pending at the deadline
        yields a ``FutureTimeout`` instance in its place."""
        from concurrent.futures import as_completed

        pending = {}
        for instance_id, fut in futures.items():
            if fut is None:
                yield instance_id, None
            else:
                pending[fut] = instance_id
        if not pending:
            return
        try:
            for fut in as_completed(
                    pending, timeout=max(deadline - time.monotonic(),
                                         0.001)):
                yield pending.pop(fut), fut
        except FutureTimeout as e:
            for fut, instance_id in pending.items():
                yield instance_id, (fut if fut.done() else e)

    def _use_streaming(self, ctx: QueryContext,
                       routing: Dict[str, List[str]]) -> bool:
        return (ctx.is_selection and not ctx.order_by
                and not ctx.distinct
                and all(hasattr(self._servers.get(i), "execute_query_streaming")
                        for i in routing))

    # -- scatter/gather (ref: QueryRouter.submitQuery:85) --------------------
    def _scatter_gather(self, table: str, ctx: QueryContext,
                        routing: Dict[str, List[str]],
                        broker_stats: Optional[QueryStats] = None,
                        acc=None):
        """Per-server failure handling: a down / not-connected / timed-out
        server yields a partial result — its error travels as an exception
        DataTable, it is NOT counted as responded, and the reason lands on
        the decision ledger — never a hung or silently-wrong answer.

        Tables are processed in COMPLETION order and folded into ``acc``
        (the reduce accumulator) as they land — the broker reduces the
        fast servers' answers while the stragglers are still running."""
        from pinot_tpu.common.tracing import record_decision

        queried, responded = set(), set()
        futures = {}
        for instance_id, segments in routing.items():
            server = self._servers.get(instance_id)
            queried.add(instance_id)
            if server is None:
                futures[instance_id] = None
                continue
            futures[instance_id] = self._pool.submit(
                lambda srv=server, segs=segments:
                srv.execute_query(ctx, table, segs))
        gathered: List[DataTable] = []

        def took(dt: DataTable, instance_id: str) -> None:
            gathered.append(dt)
            if acc is not None:
                acc.add(dt, instance=instance_id)

        deadline = time.monotonic() + self.query_timeout_s
        for instance_id, fut in self._as_arrivals(futures, deadline):
            if fut is None:
                took(DataTable.for_exception(
                    f"server {instance_id} is not connected"), instance_id)
                record_decision(broker_stats, "gather", "partial_result",
                                "full_result", "server_not_connected")
                continue
            try:
                if isinstance(fut, FutureTimeout):
                    raise fut
                dt = fut.result(timeout=0.001)
                _tag_trace(dt, instance_id)
                took(dt, instance_id)
                # responded = came back with a USABLE DataTable; a server
                # that answered with only an error (shut down mid-scatter,
                # table not hosted) is accounted as a gather failure
                if dt.exceptions:
                    record_decision(broker_stats, "gather", "partial_result",
                                    "full_result", "server_error")
                else:
                    responded.add(instance_id)
            except FutureTimeout:
                took(DataTable.for_exception(
                    f"server {instance_id} timed out after "
                    f"{self.query_timeout_s}s"), instance_id)
                record_decision(broker_stats, "gather", "partial_result",
                                "full_result", "server_timeout")
            except Exception as e:
                took(DataTable.for_exception(
                    f"server {instance_id} failed: {e!r}"), instance_id)
                record_decision(broker_stats, "gather", "partial_result",
                                "full_result", "server_error")
        return gathered, queried, responded

    def shutdown(self) -> None:
        self._pool.stop()


def _and(a: Optional[FilterNode], b: FilterNode) -> FilterNode:
    if a is None:
        return b
    return FilterNode(FilterOp.AND, children=(a, b))


def _tag_trace(dt: DataTable, instance_id: str) -> None:
    """Attribute trace entries AND span-tree roots to their server BEFORE
    the reduce merges/re-parents them (the reference keys traceInfo per
    instance) — after the broker root adopts every server's trees, the
    per-server origin is only recoverable from these tags."""
    for e in dt.stats.trace:
        e.setdefault("instance", instance_id)
    for root in dt.stats.spans:
        root.setdefault("instance", instance_id)
