"""Broker reduce: merge per-server DataTables into the final ResultTable.

Re-design of ``pinot-core/.../query/reduce/BrokerReduceService.java:44``
(``reduceOnDataTable:49`` dispatching by query type) +
``GroupByDataTableReducer.java:66`` (IndexedTable merge, HAVING,
post-aggregation) / ``AggregationDataTableReducer`` /
``SelectionDataTableReducer`` / ``DistinctDataTableReducer``.

Two execution paths share one accumulator surface:

- **vectorized** (the default): per-server tables fold AS THEY ARRIVE
  (``ReduceAccumulator.add`` — reduce overlaps the stragglers' network
  wait), keeping the wire's typed column buffers as numpy arrays the
  whole way. Group-by merges via ONE stable ``np.lexsort`` + boundary
  ``reduceat`` pass (engine/results.py ``lexsort_runs``/
  ``fold_grouped_runs``); selection merges the servers' pre-trimmed
  ORDER-BY blocks with a vectorized k-way lexsort and boxes ONLY the
  offset+limit output rows; distinct dedups via vectorized run detection
  over the concatenated key columns. Numeric columns never box a cell.
- **row path** (``vectorized=False`` or the ``vectorizedReduce=false``
  query option): the original per-row reducers, kept verbatim as the
  bit-parity oracle. Any shape the vectorized path cannot prove exact
  (object-typed keys, mixed column kinds across servers, NaN order keys,
  i64 sums near overflow) falls back here — recorded on the decision
  ledger under the ``reduce`` point.

On top of the vectorized path sits the **device** group-by route
(``BrokerReduceService(device_reduce=True)`` or the ``deviceReduce``
query option; off by default): when broker and servers share the
process (embedded cluster — tables never crossed a wire), the
concatenated (keys, states) block merges ON DEVICE through
``parallel/reduce_device.py`` — composite-key segment scatter + psum
over the broker mesh — and only the host finalization (insertion-order
restore, trim, ORDER BY, output boxing) runs on CPU. Shapes the device
fold cannot prove exact decline to the vectorized host path with a
``reduce:device->host:<reason>`` ledger record, giving the full ladder
device -> vectorized host -> row oracle.
"""

from __future__ import annotations

import time

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.bounds import I64_FOLD_BOUND
from pinot_tpu.common.datatable import Column, DataTable, ResponseType
from pinot_tpu.engine.aggregates import AggDef, resolve_agg
from pinot_tpu.engine.errors import QueryError
from pinot_tpu.engine.host_engine import _lexsort
from pinot_tpu.engine.results import (
    _VEC_STATE_FOLDS,
    AggResult,
    DataSchema,
    GroupByResult,
    QueryStats,
    ResultTable,
    _eval_scalar_filter,
    _result_schema,
    _Reversible,
    fold_grouped_runs,
    lexsort_runs,
    reduce_aggregation,
    reduce_group_by,
)
from pinot_tpu.query.context import QueryContext
from pinot_tpu.spi.config import CommonConstants

# vec state bases -> device segment/collective op (exactly the
# _VEC_STATE_FOLDS bases: count states fold by addition)
_DEVICE_OPS = {"count": "sum", "sum": "sum", "min": "min", "max": "max"}


class MixedResponseTypeError(QueryError):
    """Servers answered one scatter with DIFFERENT response types — a
    merge across them would be silently wrong-shaped (ref: the reference
    trusts DataTable data schemas to agree; here the mismatch is loud)."""


def _selection_key_spec(ctx: QueryContext, schema: DataSchema,
                        num_hidden: int) -> Tuple[List[int], List[bool]]:
    """Resolve ORDER BY expressions to column indices over a selection
    schema (visible by name/alias, order-by-only keys in the hidden
    tail). ONE resolver for the row-path oracle and the vectorized
    merge — the two paths cannot drift on key lookup."""
    names = schema.column_names
    visible_n = len(names) - num_hidden
    # aliased select expressions: ORDER BY references the expression,
    # the schema shows the alias — map through select_expressions
    alias_of: Dict[str, int] = {}
    if visible_n == len(ctx.select_expressions):
        for i, e in enumerate(ctx.select_expressions):
            alias_of.setdefault(str(e), i)
    key_idx: List[int] = []
    for ob in ctx.order_by:
        key = str(ob.expr)
        if key in names:
            key_idx.append(names.index(key))
        elif key in alias_of:
            key_idx.append(alias_of[key])
        else:
            hidden_names = names[visible_n:]
            if key not in hidden_names:
                raise QueryError(
                    f"ORDER BY {key} not found in selection schema")
            key_idx.append(visible_n + hidden_names.index(key))
    return key_idx, [ob.ascending for ob in ctx.order_by]


def _sortable_arrays(cols: List[np.ndarray]) -> List[np.ndarray]:
    """Rank-encode string arrays so ``lexsort_runs`` compares integers;
    numeric arrays pass through (NaN semantics preserved)."""
    out = []
    for a in cols:
        if a.dtype.kind in ("U", "S", "O"):
            _, codes = np.unique(a, return_inverse=True)
            a = codes
        out.append(a)
    return out


class ReduceAccumulator:
    """Streaming reduce state: ``add()`` one DataTable per arrival (the
    gather loop calls it the moment a server answers), ``finish()`` runs
    the final merge/trim/HAVING/post-agg pass. Fold timings land in
    ``fold_spans`` — the Reduce span's per-table split."""

    def __init__(self, service: "BrokerReduceService", ctx: QueryContext):
        self._svc = service
        self.ctx = ctx
        self.stats = QueryStats()
        self.exceptions: List[str] = []
        self.tables: List[DataTable] = []
        self.fold_spans: List[Dict[str, Any]] = []
        self.rtype: Optional[ResponseType] = None
        self._mixed: Optional[MixedResponseTypeError] = None
        self.vectorized = service.vectorized and ctx.options.get(
            "vectorizedReduce", "true").lower() != "false"
        dev_opt = ctx.options.get("deviceReduce")
        self.device_route = self.vectorized and (
            dev_opt.lower() == "true" if dev_opt is not None
            else service.device_reduce)
        self._served_device = False
        self._wire_decoded = False
        self._fallback: Optional[str] = None
        self._aggs: List[AggDef] = [resolve_agg(f)
                                    for f in ctx.aggregations]
        # aggregation
        self._agg_merged: Optional[AggResult] = None
        # group-by
        self._gb_types: Dict[str, str] = {}
        self._gb_key_kinds: Optional[List[int]] = None
        self._gb_state_vec: Optional[List[bool]] = None
        self._gb_state_kinds: Optional[List[int]] = None
        self._gb_keys: List[List[np.ndarray]] = []
        self._gb_states: List[List[Any]] = []
        self._gb_i64_bound = 0
        # selection / distinct
        self._schema: Optional[DataSchema] = None
        self._num_hidden = 0
        self._col_kinds: Optional[List[int]] = None
        self._row_cols: List[List[Column]] = []
        self._row_counts: List[int] = []
        self._all_sorted = True

    # -- arrival fold --------------------------------------------------------
    def add(self, table: DataTable, instance: Optional[str] = None) -> None:
        t0 = time.perf_counter()
        self.stats.merge(table.stats)
        self.exceptions.extend(table.exceptions)
        if table.exceptions:
            return
        if self.rtype is None:
            self.rtype = table.response_type
        elif table.response_type is not self.rtype:
            if self._mixed is None:
                self._mixed = MixedResponseTypeError(
                    f"servers disagree on response type: "
                    f"{self.rtype.value} vs {table.response_type.value} — "
                    f"refusing a wrong-shaped merge")
            return
        self.tables.append(table)
        if table.wire_decoded:
            # crossed a process boundary: the device route's premise
            # (states already resident, no D2H paid) does not hold
            self._wire_decoded = True
        if self.vectorized and self._fallback is None:
            self._fold(table)
        span = {"name": "Fold", "rows": table.num_rows(),
                "ms": round((time.perf_counter() - t0) * 1e3, 3)}
        if instance is not None:
            span["instance"] = instance
        self.fold_spans.append(span)

    def _decline(self, reason: str) -> None:
        from pinot_tpu.common.tracing import record_decision

        self._fallback = reason
        record_decision(self.stats, "reduce", "row_path", "vectorized",
                        reason)

    def _decline_device(self, reason: str) -> None:
        """Device merge cannot serve this shape: fall back ONE rung (to
        the vectorized host path, not the oracle) and say why."""
        from pinot_tpu.common.tracing import record_decision

        self.device_route = False
        record_decision(self.stats, "reduce", "host", "device", reason)

    def _fold(self, table: DataTable) -> None:
        rtype = table.response_type
        if rtype is ResponseType.AGGREGATION:
            part = AggResult(table.agg_states())
            if self._agg_merged is None:
                self._agg_merged = part
            else:
                self._agg_merged.merge(part, self._aggs)
            return
        if rtype is ResponseType.GROUP_BY:
            self._fold_group_by(table)
            return
        self._fold_rows(table)

    def _fold_group_by(self, table: DataTable) -> None:
        self._gb_types.update(table.schema_types())
        if table.num_rows() == 0:
            return  # nothing to merge (empty wire columns carry no
            #         kind): not a decline
        key_cols, agg_cols = table.group_columns()
        kinds = [c.kind for c in key_cols]
        if any(not (c.is_numeric or c.is_string) for c in key_cols):
            return self._decline("reduce_group_key_not_sortable")
        if self._gb_key_kinds is None:
            self._gb_key_kinds = kinds
            self._gb_state_vec = [
                a.base in _VEC_STATE_FOLDS and c.is_numeric
                for a, c in zip(self._aggs, agg_cols)]
            self._gb_state_kinds = [c.kind for c in agg_cols]
        elif kinds != self._gb_key_kinds:
            return self._decline("reduce_column_kind_mismatch")
        states: List[Any] = []
        for vec, agg, col, want in zip(self._gb_state_vec, self._aggs,
                                       agg_cols, self._gb_state_kinds):
            if vec:
                if col.kind != want:
                    # i64 on one server, f64 on another: the oracle's
                    # exact-int-then-float arithmetic is the contract
                    return self._decline("reduce_column_kind_mismatch")
                arr = col.array()
                if arr.dtype.kind == "i" and agg.base in ("count", "sum"):
                    self._gb_i64_bound += max(
                        abs(int(arr.max())), abs(int(arr.min())))
                elif arr.dtype.kind == "f" \
                        and agg.base in ("min", "max") \
                        and bool(np.isnan(arr).any()):
                    # np.minimum propagates NaN; python min() does not —
                    # only the oracle's semantics are the contract
                    return self._decline("reduce_nan_numeric_state")
                states.append(("vec", arr))
            else:
                states.append(("obj", col.tolist()))
        self._gb_keys.append([c.array() for c in key_cols])
        self._gb_states.append(states)

    def _fold_rows(self, table: DataTable) -> None:
        """SELECTION / DISTINCT arrival: keep the typed columns, box
        nothing. Kind consistency across servers is the exactness guard
        (the oracle would coerce, e.g. int and float keys comparing
        equal — a mix falls back to it)."""
        if self._schema is None:
            self._schema = table.data_schema()
        self._num_hidden = max(self._num_hidden, table.num_hidden)
        self._all_sorted = self._all_sorted and table.selection_sorted
        if table.num_rows() == 0:
            return  # empty arrival: not a decline
        cols = table.columns()
        kinds = [c.kind for c in cols]
        if self._col_kinds is None:
            self._col_kinds = kinds
        elif kinds != self._col_kinds:
            return self._decline("reduce_column_kind_mismatch")
        if self.rtype is ResponseType.DISTINCT \
                and any(not (c.is_numeric or c.is_string) for c in cols):
            return self._decline("reduce_distinct_key_not_sortable")
        self._row_cols.append(cols)
        self._row_counts.append(table.num_rows())

    # -- final pass ----------------------------------------------------------
    def finish(self) -> Tuple[ResultTable, QueryStats, List[str]]:
        if not self.tables:
            raise QueryError("; ".join(self.exceptions)
                             or "no server responses")
        if self._mixed is not None:
            raise self._mixed
        svc, ctx = self._svc, self.ctx
        if not self.vectorized or self._fallback is not None:
            table = svc._reduce_rows(ctx, self.rtype, self.tables,
                                     self.stats)
            self.stats.reduce_path = "oracle"
            return table, self.stats, self.exceptions
        if self.rtype is ResponseType.AGGREGATION:
            table = reduce_aggregation(ctx, self._aggs, self._agg_merged)
        elif self.rtype is ResponseType.GROUP_BY:
            table = self._finish_group_by()
        elif self.rtype is ResponseType.SELECTION:
            table = self._finish_selection()
        else:
            table = self._finish_distinct()
        if self._fallback is not None:
            # a finish-time guard tripped (NaN order key, i64 bound):
            # rerun the retained tables through the oracle
            table = svc._reduce_rows(ctx, self.rtype, self.tables,
                                     self.stats)
            self.stats.reduce_path = "oracle"
        else:
            self.stats.reduce_path = ("device" if self._served_device
                                      else "vectorized")
        return table, self.stats, self.exceptions

    def _finish_group_by(self) -> Optional[ResultTable]:
        ctx, aggs = self.ctx, self._aggs
        if self._gb_i64_bound >= I64_FOLD_BOUND:
            if self.device_route:
                self._decline_device("reduce_device_i64_sum_bound")
            self._decline("reduce_i64_sum_bound")
            return None
        if not self._gb_keys:
            merged = GroupByResult()
            if merged.trim(self._svc.num_groups_limit):
                self.stats.num_groups_limit_reached = True
            return reduce_group_by(ctx, aggs, merged, self._gb_types)
        arity = len(self._gb_keys[0])
        key_concat = [
            np.concatenate([t[k] for t in self._gb_keys])
            for k in range(arity)]
        n = int(key_concat[0].shape[0])
        entries = []
        for a in range(len(aggs)):
            parts = [t[a] for t in self._gb_states]
            if self._gb_state_vec[a]:
                entries.append(
                    ("vec", np.concatenate([p[1] for p in parts])))
            else:
                flat: List[Any] = []
                for p in parts:
                    flat.extend(p[1])
                entries.append(("obj", flat))
        merged = self._device_group_by(key_concat, entries, n) \
            if self.device_route else None
        if merged is not None:
            # device contract == host contract: per group (any fixed
            # enumeration), earliest input index + exactly-folded state;
            # the stable argsort below restores insertion order either way
            first_idx, folded = merged
            self._served_device = True
        else:
            order, starts = lexsort_runs(_sortable_arrays(key_concat))
            folded = fold_grouped_runs(order, starts, n, entries, aggs)
            first_idx = order[starts]
        # restore the oracle's dict-insertion order: groups appear in
        # first-occurrence order of the concatenated input (stable
        # lexsort -> each run's first sorted element IS its earliest)
        perm = np.argsort(first_idx, kind="stable")
        if len(perm) > self._svc.num_groups_limit:
            # the oracle trims the merged dict to its first
            # num_groups_limit INSERTION-ordered entries — same cut
            perm = perm[: self._svc.num_groups_limit]
            self.stats.num_groups_limit_reached = True

        table = self._finalize_group_by_vectorized(
            key_concat, first_idx, perm, folded)
        if table is not None:
            return table

        # shape outside the vectorized finalization (HAVING, post-agg
        # arithmetic, unsortable finals): build the merged GroupByResult
        # and run the UNCHANGED trim/HAVING/post-agg pass — the merge
        # itself stayed array-native
        boxed_keys = [_box_indexed(key_concat[k], first_idx)
                      for k in range(arity)]
        groups: Dict[Tuple, List[Any]] = {}
        for j in perm:
            j = int(j)
            key = tuple(bk[j] for bk in boxed_keys)
            groups[key] = [_box_state(folded[a][j],
                                      self._gb_state_vec[a])
                           for a in range(len(aggs))]
        return reduce_group_by(ctx, aggs, GroupByResult(groups),
                               self._gb_types)

    def _device_group_by(self, key_concat, entries, n
                         ) -> Optional[Tuple[np.ndarray, List[np.ndarray]]]:
        """Try the on-device merge -> ``(first_idx, folded)``, or None
        after a ``reduce:device->host:<reason>`` ledger record. Every
        guard here is an EXACTNESS proof obligation: only folds whose
        result is order-independent bit-for-bit may leave the host."""
        from pinot_tpu.parallel import reduce_device as rdev

        if self._wire_decoded:
            # decoded wire tables already paid D2H + serialization —
            # the host lexsort is the natural frame for them
            self._decline_device("reduce_device_cross_process")
            return None
        if any(kind != "vec" for kind, _ in entries):
            self._decline_device("reduce_device_obj_state")
            return None
        mesh = rdev.broker_mesh()
        if mesh is None:
            self._decline_device("reduce_device_mesh_unavailable")
            return None
        if n > rdev.MAX_MERGE_ROWS:
            self._decline_device("reduce_device_rows_over_capacity")
            return None
        for a in key_concat:
            if a.dtype.kind == "f" and bool(np.isnan(a).any()):
                # NaN != NaN breaks the composite-key group identity
                self._decline_device("reduce_device_nan_key")
                return None
        comp, space = rdev.encode_composite_keys(key_concat)
        if comp is None:
            self._decline_device("reduce_device_key_space_overflow")
            return None
        ops: List[str] = []
        vals: List[np.ndarray] = []
        for agg, (_, arr) in zip(self._aggs, entries):
            if agg.base == "sum" and arr.dtype.kind == "f" \
                    and not rdev.f64_sum_exact(arr):
                # f64 addition is order-dependent; the psum order is not
                # the reduceat order, so only provably-exact sums go
                self._decline_device("reduce_device_f64_sum_order")
                return None
            ops.append(_DEVICE_OPS[agg.base])
            vals.append(arr)
        try:
            return rdev.device_group_merge(mesh, comp, space, vals, ops)
        except Exception:
            self._decline_device("reduce_device_kernel_error")
            return None

    def _finalize_group_by_vectorized(self, key_concat, first_idx, perm,
                                      folded) -> Optional[ResultTable]:
        """Array-native HAVING-free finalization: when every SELECT
        expression is a group key or an aggregation (no post-agg
        arithmetic) the final columns build straight from the folded
        arrays, ORDER BY runs as one more stable lexsort, and only the
        offset..offset+limit OUTPUT rows ever box. Returns None when the
        shape needs the row-path ``reduce_group_by`` (semantics there are
        the contract — this is purely the fast lane)."""
        ctx, aggs = self.ctx, self._aggs
        if ctx.having is not None:
            return None
        key_of = {str(g): k for k, g in enumerate(ctx.group_by)}
        agg_of = {str(fn): a for a, fn in enumerate(ctx.aggregations)}

        final_cache: Dict[str, Any] = {}

        def final_column(name: str):
            """Final values for a key/agg column over ``perm`` order —
            an ndarray for vectorized finals, a boxed list otherwise."""
            if name in final_cache:
                return final_cache[name]
            if name in key_of:
                out = key_concat[key_of[name]][first_idx[perm]]
            else:
                a = agg_of[name]
                agg = aggs[a]
                if self._gb_state_vec[a]:
                    arr = folded[a][perm]
                    # mirror _FINAL: count -> int, sum/min/max -> float
                    out = (arr.astype(np.int64) if agg.base == "count"
                           else arr.astype(np.float64))
                else:
                    states = folded[a]
                    out = [agg.finalize(states[int(j)]) for j in perm]
            final_cache[name] = out
            return out

        for e in ctx.select_expressions:
            if str(e) not in key_of and str(e) not in agg_of:
                return None  # post-aggregation arithmetic -> row path
        for ob in ctx.order_by:
            if str(ob.expr) not in key_of and str(ob.expr) not in agg_of:
                return None

        ngroups = len(perm)
        if ctx.order_by and ngroups:
            sort_cols = []
            for ob in ctx.order_by:
                col = final_column(str(ob.expr))
                arr = np.asarray(col) if not isinstance(col, np.ndarray) \
                    else col
                if arr.dtype == object:
                    return None  # non-uniform finals: oracle comparisons
                if arr.dtype.kind == "f" and bool(np.isnan(arr).any()):
                    return None
                sort_cols.append(arr)
            window = _lexsort(sort_cols,
                              [ob.ascending for ob in ctx.order_by])
            window = window[ctx.offset: ctx.offset + ctx.limit]
        else:
            lo = min(ctx.offset, ngroups)
            hi = min(ctx.offset + ctx.limit, ngroups)
            window = np.arange(lo, hi, dtype=np.int64)

        out_cols = []
        for e in ctx.select_expressions:
            col = final_column(str(e))
            if isinstance(col, np.ndarray):
                taken = col[window]
                if taken.dtype.kind in ("U", "S", "O"):
                    out_cols.append([str(v) for v in taken])
                else:
                    out_cols.append(taken.tolist())
            else:
                out_cols.append([col[int(j)] for j in window])
        rows = [[c[i] for c in out_cols] for i in range(len(window))]
        names, types = _result_schema(ctx, aggs, self._gb_types)
        return ResultTable(DataSchema(names, types), rows)

    def _selected_rows(self, sel: np.ndarray, visible: int
                       ) -> List[List[Any]]:
        """Box ONLY the chosen global row indices (output order = sel
        order), gathering per table through ``Column.take_boxed``."""
        bounds = np.concatenate(
            (np.zeros(1, np.int64),
             np.cumsum(self._row_counts))).astype(np.int64)
        rows: List[Optional[List[Any]]] = [None] * len(sel)
        tno = np.searchsorted(bounds, sel, side="right") - 1
        for ti, cols in enumerate(self._row_cols):
            pos = np.flatnonzero(tno == ti)
            if pos.size == 0:
                continue
            local = sel[pos] - bounds[ti]
            cells = [c.take_boxed(local) for c in cols[:visible]]
            for j, p in enumerate(pos):
                rows[int(p)] = [c[j] for c in cells]
        return rows  # type: ignore[return-value]

    def _finish_selection(self) -> Optional[ResultTable]:
        ctx = self.ctx
        schema = self._schema
        if schema is None:  # every ok table was empty AND schema-less
            schema = self.tables[0].data_schema()
        num_hidden = self._num_hidden
        total = int(sum(self._row_counts))
        visible = len(schema.column_names) - num_hidden
        out_schema = schema if not num_hidden else DataSchema(
            schema.column_names[:visible], schema.column_types[:visible])

        if not ctx.order_by or total == 0:
            lo = min(ctx.offset, total)
            hi = min(ctx.offset + ctx.limit, total)
            sel = np.arange(lo, hi, dtype=np.int64)
            return ResultTable(out_schema,
                               self._selected_rows(sel, visible))

        # resolve ORDER BY -> column indices (shared with the oracle)
        key_idx, directions = _selection_key_spec(ctx, schema, num_hidden)
        if any(not (self._row_cols[0][i].is_numeric
                    or self._row_cols[0][i].is_string)
               for i in key_idx):
            self._decline("reduce_order_key_not_sortable")
            return None
        if len(self._row_cols) == 1 and self._all_sorted:
            # single pre-sorted block (ref: SelectionOperatorUtils — the
            # one-server case): the trim window IS the answer
            lo = min(ctx.offset, total)
            hi = min(ctx.offset + ctx.limit, total)
            sel = np.arange(lo, hi, dtype=np.int64)
            return ResultTable(out_schema,
                               self._selected_rows(sel, visible))
        key_cols = [
            np.concatenate([cols[i].array() for cols in self._row_cols])
            for i in key_idx]
        for a in key_cols:
            if a.dtype.kind == "f" and bool(np.isnan(a).any()):
                # python-sort NaN comparisons are order-dependent; only
                # the oracle's (ill-defined but historical) order counts
                self._decline("reduce_nan_order_key")
                return None
        order = _lexsort(key_cols, directions)
        sel = order[ctx.offset: ctx.offset + ctx.limit].astype(np.int64)
        return ResultTable(out_schema, self._selected_rows(sel, visible))

    def _finish_distinct(self) -> Optional[ResultTable]:
        ctx = self.ctx
        schema = self._schema
        if schema is None:
            schema = self.tables[0].data_schema()
        names = schema.column_names
        rows: List[List[Any]] = []
        if self._row_cols:
            cols_concat = [
                np.concatenate([cols[i].array()
                                for cols in self._row_cols])
                for i in range(len(names))]
            order, starts = lexsort_runs(_sortable_arrays(cols_concat))
            first_idx = order[starts]
            first_idx.sort()  # first-occurrence (insertion) order
            rows = self._selected_rows(first_idx.astype(np.int64),
                                       len(names))
        if ctx.having is not None:
            rows = [r for r in rows
                    if _eval_scalar_filter(ctx.having,
                                           dict(zip(names, r)))]
        if ctx.order_by:
            idx_of = {n: i for i, n in enumerate(names)}

            def sort_key(row):
                parts = []
                for ob in ctx.order_by:
                    i = idx_of.get(str(ob.expr))
                    if i is None:
                        raise QueryError(
                            f"ORDER BY {ob.expr} not in DISTINCT list")
                    parts.append(_Reversible(row[i], ob.ascending))
                return tuple(parts)

            rows.sort(key=sort_key)
        return ResultTable(schema,
                           rows[ctx.offset: ctx.offset + ctx.limit])


def _box_indexed(arr: np.ndarray, idx: np.ndarray) -> list:
    """Box the selected key cells (one per OUTPUT group, never per row)."""
    taken = arr[idx]
    if taken.dtype.kind in ("U", "S", "O"):
        return [str(v) for v in taken]
    return taken.tolist()


def _box_state(v: Any, vec: bool) -> Any:
    return v.item() if vec else v


class BrokerReduceService:
    """Ref: BrokerReduceService.java:44."""

    def __init__(self, num_groups_limit: int =
                 CommonConstants.DEFAULT_NUM_GROUPS_LIMIT,
                 vectorized: bool = True,
                 device_reduce: bool =
                 CommonConstants.DEFAULT_BROKER_DEVICE_REDUCE):
        self.num_groups_limit = num_groups_limit
        self.vectorized = vectorized
        self.device_reduce = device_reduce

    def accumulator(self, ctx: QueryContext) -> ReduceAccumulator:
        """Streaming entry: the gather loop folds tables as they arrive
        (reduce-as-arrivals), then calls ``finish()``."""
        return ReduceAccumulator(self, ctx)

    def reduce(self, ctx: QueryContext, tables: List[DataTable]
               ) -> Tuple[ResultTable, QueryStats, List[str]]:
        """-> (result, merged stats, per-server error messages). A partial
        failure still reduces the successful servers' tables, but the errors
        MUST reach the response so the caller can tell a partial result from
        a complete one (ref: partial-results + exceptions behavior,
        SingleConnectionBrokerRequestHandler.java:134-141)."""
        acc = self.accumulator(ctx)
        for t in tables:
            acc.add(t)
        return acc.finish()

    # -- row-path reducers (the bit-parity oracle) ---------------------------
    def _reduce_rows(self, ctx: QueryContext, rtype: ResponseType,
                     ok: List[DataTable], stats: QueryStats) -> ResultTable:
        if rtype is ResponseType.AGGREGATION:
            return self._reduce_aggregation(ctx, ok)
        if rtype is ResponseType.GROUP_BY:
            return self._reduce_group_by(ctx, ok, stats)
        if rtype is ResponseType.SELECTION:
            return self._reduce_selection(ctx, ok)
        return self._reduce_distinct(ctx, ok)

    def _reduce_aggregation(self, ctx: QueryContext,
                            tables: List[DataTable]) -> ResultTable:
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        merged: AggResult = None
        for t in tables:
            part = AggResult(t.agg_states())
            if merged is None:
                merged = part
            else:
                merged.merge(part, aggs)
        return reduce_aggregation(ctx, aggs, merged)

    def _reduce_group_by(self, ctx: QueryContext, tables: List[DataTable],
                         stats: QueryStats) -> ResultTable:
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        merged = GroupByResult()
        schema_types: Dict[str, str] = {}
        for t in tables:
            schema_types.update(t.schema_types())
            merged.merge(GroupByResult(t.group_by_groups()), aggs)
        if merged.trim(self.num_groups_limit):
            stats.num_groups_limit_reached = True
        return reduce_group_by(ctx, aggs, merged, schema_types)

    def _reduce_selection(self, ctx: QueryContext,
                          tables: List[DataTable]) -> ResultTable:
        schema = tables[0].data_schema()
        num_hidden = max(t.num_hidden for t in tables)
        rows: List[List[Any]] = []
        for t in tables:
            rows.extend(t.rows())

        if ctx.order_by and rows:
            # hidden trailing columns hold the order-by expression values;
            # visible order-by columns are found by name
            key_idx, directions = _selection_key_spec(ctx, schema,
                                                      num_hidden)

            def sort_key(row):
                return tuple(_Reversible(row[i], asc)
                             for i, asc in zip(key_idx, directions))

            rows.sort(key=sort_key)

        rows = rows[ctx.offset: ctx.offset + ctx.limit]
        if num_hidden:
            visible = len(schema.column_names) - num_hidden
            schema = DataSchema(schema.column_names[:visible],
                                schema.column_types[:visible])
            rows = [r[:visible] for r in rows]
        return ResultTable(schema, rows)

    def _reduce_distinct(self, ctx: QueryContext,
                         tables: List[DataTable]) -> ResultTable:
        schema = tables[0].data_schema()
        seen: Dict[Tuple, List[Any]] = {}
        for t in tables:
            for r in t.rows():
                key = tuple(tuple(v) if isinstance(v, list) else v for v in r)
                if key not in seen:
                    seen[key] = r
        rows = list(seen.values())
        names = schema.column_names
        if ctx.having is not None:
            rows = [r for r in rows
                    if _eval_scalar_filter(ctx.having, dict(zip(names, r)))]
        if ctx.order_by:
            idx_of = {n: i for i, n in enumerate(names)}

            def sort_key(row):
                parts = []
                for ob in ctx.order_by:
                    i = idx_of.get(str(ob.expr))
                    if i is None:
                        raise QueryError(
                            f"ORDER BY {ob.expr} not in DISTINCT list")
                    parts.append(_Reversible(row[i], ob.ascending))
                return tuple(parts)

            rows.sort(key=sort_key)
        return ResultTable(schema, rows[ctx.offset: ctx.offset + ctx.limit])
