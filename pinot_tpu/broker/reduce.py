"""Broker reduce: merge per-server DataTables into the final ResultTable.

Re-design of ``pinot-core/.../query/reduce/BrokerReduceService.java:44``
(``reduceOnDataTable:49`` dispatching by query type) +
``GroupByDataTableReducer.java:66`` (IndexedTable merge, HAVING,
post-aggregation) / ``AggregationDataTableReducer`` /
``SelectionDataTableReducer`` / ``DistinctDataTableReducer``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from pinot_tpu.common.datatable import DataTable, ResponseType
from pinot_tpu.engine.aggregates import resolve_agg
from pinot_tpu.engine.errors import QueryError
from pinot_tpu.engine.results import (
    AggResult,
    DataSchema,
    GroupByResult,
    QueryStats,
    ResultTable,
    _eval_scalar_filter,
    _Reversible,
    reduce_aggregation,
    reduce_group_by,
)
from pinot_tpu.query.context import QueryContext
from pinot_tpu.spi.config import CommonConstants


class BrokerReduceService:
    """Ref: BrokerReduceService.java:44."""

    def __init__(self, num_groups_limit: int =
                 CommonConstants.DEFAULT_NUM_GROUPS_LIMIT):
        self.num_groups_limit = num_groups_limit

    def reduce(self, ctx: QueryContext, tables: List[DataTable]
               ) -> Tuple[ResultTable, QueryStats, List[str]]:
        """-> (result, merged stats, per-server error messages). A partial
        failure still reduces the successful servers' tables, but the errors
        MUST reach the response so the caller can tell a partial result from
        a complete one (ref: partial-results + exceptions behavior,
        SingleConnectionBrokerRequestHandler.java:134-141)."""
        stats = QueryStats()
        exceptions: List[str] = []
        ok: List[DataTable] = []
        for t in tables:
            stats.merge(t.stats)
            exceptions.extend(t.exceptions)
            if not t.exceptions:
                ok.append(t)
        if not ok:
            raise QueryError("; ".join(exceptions) or "no server responses")

        rtype = ok[0].response_type
        if rtype is ResponseType.AGGREGATION:
            table = self._reduce_aggregation(ctx, ok)
        elif rtype is ResponseType.GROUP_BY:
            table = self._reduce_group_by(ctx, ok, stats)
        elif rtype is ResponseType.SELECTION:
            table = self._reduce_selection(ctx, ok)
        else:
            table = self._reduce_distinct(ctx, ok)
        return table, stats, exceptions

    # -- per-type reducers ---------------------------------------------------
    def _reduce_aggregation(self, ctx: QueryContext,
                            tables: List[DataTable]) -> ResultTable:
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        merged: AggResult = None
        for t in tables:
            part = AggResult(t.agg_states())
            if merged is None:
                merged = part
            else:
                merged.merge(part, aggs)
        return reduce_aggregation(ctx, aggs, merged)

    def _reduce_group_by(self, ctx: QueryContext, tables: List[DataTable],
                         stats: QueryStats) -> ResultTable:
        aggs = [resolve_agg(f) for f in ctx.aggregations]
        merged = GroupByResult()
        schema_types: Dict[str, str] = {}
        for t in tables:
            schema_types.update(t.schema_types())
            merged.merge(GroupByResult(t.group_by_groups()), aggs)
        if merged.trim(self.num_groups_limit):
            stats.num_groups_limit_reached = True
        return reduce_group_by(ctx, aggs, merged, schema_types)

    def _reduce_selection(self, ctx: QueryContext,
                          tables: List[DataTable]) -> ResultTable:
        schema = tables[0].data_schema()
        num_hidden = max(t.num_hidden for t in tables)
        rows: List[List[Any]] = []
        for t in tables:
            rows.extend(t.rows())

        if ctx.order_by and rows:
            # hidden trailing columns hold the order-by expression values;
            # visible order-by columns are found by name
            names = schema.column_names
            visible_n = len(names) - num_hidden
            # aliased select expressions: ORDER BY references the expression,
            # the schema shows the alias — map through select_expressions
            alias_of: Dict[str, int] = {}
            if visible_n == len(ctx.select_expressions):
                for i, e in enumerate(ctx.select_expressions):
                    alias_of.setdefault(str(e), i)
            key_idx: List[int] = []
            for ob in ctx.order_by:
                key = str(ob.expr)
                if key in names:
                    key_idx.append(names.index(key))
                elif key in alias_of:
                    key_idx.append(alias_of[key])
                else:
                    hidden_names = names[visible_n:]
                    if key not in hidden_names:
                        raise QueryError(
                            f"ORDER BY {key} not found in selection schema")
                    key_idx.append(visible_n + hidden_names.index(key))
            directions = [ob.ascending for ob in ctx.order_by]

            def sort_key(row):
                return tuple(_Reversible(row[i], asc)
                             for i, asc in zip(key_idx, directions))

            rows.sort(key=sort_key)

        rows = rows[ctx.offset: ctx.offset + ctx.limit]
        if num_hidden:
            visible = len(schema.column_names) - num_hidden
            schema = DataSchema(schema.column_names[:visible],
                                schema.column_types[:visible])
            rows = [r[:visible] for r in rows]
        return ResultTable(schema, rows)

    def _reduce_distinct(self, ctx: QueryContext,
                         tables: List[DataTable]) -> ResultTable:
        schema = tables[0].data_schema()
        seen: Dict[Tuple, List[Any]] = {}
        for t in tables:
            for r in t.rows():
                key = tuple(tuple(v) if isinstance(v, list) else v for v in r)
                if key not in seen:
                    seen[key] = r
        rows = list(seen.values())
        names = schema.column_names
        if ctx.having is not None:
            rows = [r for r in rows
                    if _eval_scalar_filter(ctx.having, dict(zip(names, r)))]
        if ctx.order_by:
            idx_of = {n: i for i, n in enumerate(names)}

            def sort_key(row):
                parts = []
                for ob in ctx.order_by:
                    i = idx_of.get(str(ob.expr))
                    if i is None:
                        raise QueryError(
                            f"ORDER BY {ob.expr} not in DISTINCT list")
                    parts.append(_Reversible(row[i], ob.ascending))
                return tuple(parts)

            rows.sort(key=sort_key)
        return ResultTable(schema, rows[ctx.offset: ctx.offset + ctx.limit])
