"""Broker routing: segment->server routing tables with pruning + replica
selection.

Re-design of ``pinot-broker/.../routing/RoutingManager.java:85``
(``buildRouting:300``, ``getRoutingTable:459``, ``onAssignmentChange:562``)
+ instance selectors (``routing/instanceselector/BaseInstanceSelector.java``)
+ broker-side segment pruners (``routing/segmentpruner/TimeSegmentPruner``,
``PartitionSegmentPruner``) + the hybrid time boundary
(``routing/timeboundary/TimeBoundaryManager.java:52``).

The per-query hot path reads a per-table :class:`RoutingTable` SNAPSHOT —
replicas, resolved partition functions, and time ranges per segment —
built once from the state store and invalidated by store watches (the
reference pushes ExternalView/IdealState/ZK-metadata changes into each
``RoutingEntry`` the same way: ``buildRouting`` on change, never a ZK
round-trip per query). Routing follows the ExternalView: only segments a
live server actually serves are routable.

Every routing outcome lands on the path-decision ledger: a prune records
``routing:all_servers->pruned:partition_prune`` / ``:time_prune``; a
configured pruner that could NOT prune records why
(``no_filter`` / ``no_partition_predicate`` / ``no_partition_metadata`` /
``partition_all_match`` / ``no_time_bound`` / ``time_all_match``), so
post-mortem bundles explain why a server was or wasn't scattered to.
"""

from __future__ import annotations

import threading

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from pinot_tpu.common.tracing import record_decision
from pinot_tpu.controller.state import CONSUMING, ONLINE, ClusterStateStore
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    FilterNode,
    FilterOp,
    Identifier,
    Predicate,
    PredicateType,
)

class BalancedInstanceSelector:
    """Round-robin replica pick by requestId with unavailable-instance
    exclusion (ref: BalancedInstanceSelector)."""

    def select(self, segment: str, replicas: List[str], request_id: int,
               excluded: frozenset) -> Optional[str]:
        candidates = sorted(r for r in replicas if r not in excluded)
        if not candidates:
            return None
        return candidates[request_id % len(candidates)]


class ReplicaGroupInstanceSelector:
    """One replica GROUP serves the whole query (ref:
    ReplicaGroupInstanceSelector): requestId picks the group, so each
    query fans out to 1/N of the servers — the reference's QPS-scaling
    story. A segment unavailable in the picked group falls back to any
    live replica (non-strict semantics)."""

    def __init__(self, groups: List[List[str]]):
        self.groups = [set(g) for g in groups if g]

    def select(self, segment: str, replicas: List[str], request_id: int,
               excluded: frozenset) -> Optional[str]:
        live = sorted(r for r in replicas if r not in excluded)
        if not live:
            return None
        if self.groups:
            n = len(self.groups)
            for off in range(n):
                group = self.groups[(request_id + off) % n]
                in_group = [r for r in live if r in group]
                if in_group:
                    return in_group[0]
        return live[request_id % len(live)]


class StrictReplicaGroupInstanceSelector(ReplicaGroupInstanceSelector):
    """Strict variant (ref: StrictReplicaGroupInstanceSelector): NO
    cross-group fallback per segment — if the picked group cannot serve a
    segment, the segment is unavailable for this query. Selection is
    deterministic per requestId, so every segment of the query lands on
    the same group (the upsert-consistency requirement)."""

    def select(self, segment: str, replicas: List[str], request_id: int,
               excluded: frozenset) -> Optional[str]:
        live = {r for r in replicas if r not in excluded}
        if not live or not self.groups:
            return None
        group = self.groups[request_id % len(self.groups)]
        in_group = sorted(live & group)
        return in_group[0] if in_group else None


# how wide a closed integer RANGE on the partition column may be before
# enumerating its values stops being cheaper than scattering everywhere
_MAX_PARTITION_RANGE_ENUM = 1024


def _int_literal(v) -> Optional[int]:
    """The literal as an int ONLY when it already is one — a string
    column's lexicographic range ('1'..'3' matches '25') must never be
    enumerated numerically."""
    return v if isinstance(v, int) and not isinstance(v, bool) else None


def _partition_filter_values(node: Optional[FilterNode]) -> Dict[str, List]:
    """column -> candidate literal values from top-level AND-ed EQ/IN
    predicates, plus closed integer RANGEs narrow enough to enumerate —
    the only shapes partition pruning can use soundly (a matched row's
    value is guaranteed to be in the returned list)."""
    out: Dict[str, List] = {}
    if node is None:
        return out

    def visit(n: FilterNode):
        if n.op is FilterOp.AND:
            for c in n.children:
                visit(c)
            return
        if n.op is not FilterOp.PREDICATE:
            return
        p = n.predicate
        if not isinstance(p.lhs, Identifier):
            return
        if p.type is PredicateType.EQ:
            out.setdefault(p.lhs.name, []).append(p.value)
        elif p.type is PredicateType.IN:
            out.setdefault(p.lhs.name, []).extend(p.values)
        elif p.type is PredicateType.RANGE:
            lo = _int_literal(p.lower)
            hi = _int_literal(p.upper)
            if lo is None or hi is None:
                return
            lo += 0 if p.lower_inclusive else 1
            hi -= 0 if p.upper_inclusive else 1
            if lo > hi or hi - lo + 1 > _MAX_PARTITION_RANGE_ENUM:
                return
            out.setdefault(p.lhs.name, []).extend(range(lo, hi + 1))

    visit(node)
    return out


# kept under its historical name: callers/tests predating the RANGE
# enumeration use it for the EQ/IN shapes
def _top_level_eq_values(node: FilterNode) -> Dict[str, List]:
    return _partition_filter_values(node)


def extract_time_interval(node: Optional[FilterNode], time_column: str
                          ) -> Tuple[Optional[int], Optional[int]]:
    """[lo, hi] bound on the time column implied by the filter (only
    top-level AND-ed predicates are used — ref: TimeSegmentPruner interval
    extraction)."""
    lo: Optional[int] = None
    hi: Optional[int] = None
    if node is None:
        return lo, hi

    def visit(n: FilterNode):
        nonlocal lo, hi
        if n.op is FilterOp.AND:
            for c in n.children:
                visit(c)
            return
        if n.op is not FilterOp.PREDICATE:
            return
        p = n.predicate
        if not isinstance(p.lhs, Identifier) or p.lhs.name != time_column:
            return
        if p.type is PredicateType.EQ:
            v = int(p.value)
            lo = v if lo is None else max(lo, v)
            hi = v if hi is None else min(hi, v)
        elif p.type is PredicateType.RANGE:
            if p.lower is not None:
                v = int(p.lower) + (0 if p.lower_inclusive else 1)
                lo = v if lo is None else max(lo, v)
            if p.upper is not None:
                v = int(p.upper) - (0 if p.upper_inclusive else 1)
                hi = v if hi is None else min(hi, v)

    visit(node)
    return lo, hi


class TimeBoundaryManager:
    """Hybrid-table split point (ref: TimeBoundaryManager.java:52): offline
    side serves ``time <= boundary``, realtime serves ``time > boundary``;
    boundary = max offline end-time minus one raw time-column unit (the
    reference subtracts a full period only for daily/hourly push
    frequencies — segment-push granularity is not modeled here)."""

    def __init__(self, store: ClusterStateStore):
        self.store = store

    def get_boundary(self, offline_table: str) -> Optional[int]:
        end_times = [md.end_time for md
                     in self.store.segment_metadata_list(offline_table)
                     if md.end_time is not None]
        if not end_times:
            return None
        return max(end_times) - 1


@dataclass(frozen=True)
class SegmentRouteInfo:
    """Everything routing needs about one segment, resolved at table-build
    time (the 'metadata pushed into the routing table' half of the ref's
    SegmentZKMetadata handling in buildRouting)."""

    replicas: Tuple[str, ...]                 # instances serving it (EV)
    # (start, end) time range; None = never time-prunable (missing
    # metadata, or a CONSUMING segment whose range is still growing)
    time_range: Optional[Tuple[int, int]]
    # per partitioned column: (column, partition function, partition set)
    partitions: Tuple[Tuple[str, object, frozenset], ...] = ()


@dataclass
class RoutingTable:
    """Per-table routing snapshot. Immutable once built; replaced (never
    mutated) when a watch invalidates it."""

    table: str
    version: int                              # store version at build
    segments: Dict[str, SegmentRouteInfo]
    time_column: Optional[str]
    partition_pruning: bool                   # pruner configured on table
    has_partition_metadata: bool              # any segment carries it
    selector: object


@dataclass
class RouteResult:
    """One query's routing outcome with the prune accounting the bench's
    scatter fan-out / prune-ratio gates read."""

    routing: Dict[str, List[str]]
    unavailable: List[str]
    segments_total: int = 0
    segments_routed: int = 0
    time_pruned: int = 0
    partition_pruned: int = 0
    # scatter fan-out had no pruning happened vs what was actually used
    servers_unpruned: int = 0
    servers_routed: int = 0


class RoutingManager:
    """Ref: RoutingManager.java:85. Watches ExternalView + instance
    liveness and serves per-query routing tables from per-table cached
    snapshots (``onAssignmentChange``-style invalidation, zero state-store
    reads on the warmed hot path)."""

    def __init__(self, store: ClusterStateStore):
        self.store = store
        self.selector = BalancedInstanceSelector()
        self.time_boundary = TimeBoundaryManager(store)
        self._request_id = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        # table -> RoutingTable snapshot (guarded-by: _lock); invalidated
        # by the prefix watches below — the Helix-spectator push model
        self._tables: Dict[str, RoutingTable] = {}
        # (store version, dead-instance frozenset) (guarded-by: _lock)
        self._dead: Optional[Tuple[int, frozenset]] = None
        # table -> (store version at compute time, hidden segment set); the
        # version stamp closes the TOCTOU where a watch-driven clear lands
        # between computing the set and caching it (the stale insert would
        # otherwise persist until the next lineage mutation)
        self._lineage_cache: Dict[str, Tuple[int, frozenset]] = {}
        store.watch("lineage/",
                    lambda path, value: self._lineage_cache.clear())
        # routing follows every input that fed the snapshot: segment ZK
        # metadata, ExternalView, table config, instance partitions
        for prefix in ("segments/", "externalview/", "tables/",
                       "instancepartitions/"):
            store.watch(prefix, self._on_table_change)
        store.watch("instances/", self._on_instance_change)

    # -- watch callbacks (ref: onAssignmentChange:562 / onInstancesChange) --
    def _on_table_change(self, path: str, value) -> None:
        parts = path.split("/")
        if len(parts) < 2:
            return
        with self._lock:
            self._tables.pop(parts[1], None)

    def _on_instance_change(self, path: str, value) -> None:
        with self._lock:
            self._dead = None

    def _next_request_id(self) -> int:
        with self._lock:
            self._request_id += 1
            return self._request_id

    def routable_tables(self) -> List[str]:
        return self.store.table_names()

    def table_exists(self, table_with_type: str) -> bool:
        return self.store.get_table_config(table_with_type) is not None

    # -- snapshot build (ref: buildRouting:300) ------------------------------
    def _routing_entry(self, table: str) -> RoutingTable:
        with self._lock:
            entry = self._tables.get(table)
        if entry is not None:
            return entry
        entry = self._build_entry(table)
        with self._lock:
            self._tables[table] = entry
        # a mutation racing this build may have fired the invalidating
        # watch BEFORE the insert above; self-evict so the stale snapshot
        # can't outlive the race (any post-mutation clear removes it too)
        if self.store.version != entry.version:
            with self._lock:
                if self._tables.get(table) is entry:
                    del self._tables[table]
        return entry

    def _build_entry(self, table: str) -> RoutingTable:
        from pinot_tpu.utils.partition import get_partition_function

        ver = self.store.version
        ev = self.store.get_external_view(table)
        cfg = self.store.get_table_config(table)
        time_column = (cfg.validation_config.time_column_name
                       if cfg else None)
        pruners = (cfg.routing_config.segment_pruner_types if cfg else [])
        partition_pruning = any(p.lower() == "partition" for p in pruners)
        mds = {md.segment_name: md
               for md in self.store.segment_metadata_list(table)}

        segments: Dict[str, SegmentRouteInfo] = {}
        any_partition_md = False
        for seg, imap in ev.items():
            md = mds.get(seg)
            time_range = None
            parts: Tuple = ()
            if md is not None:
                # consuming segments are never time-pruned: their range is
                # still growing (ref: TimeSegmentPruner consuming skip)
                if (md.status != CONSUMING and md.start_time is not None
                        and md.end_time is not None):
                    time_range = (md.start_time, md.end_time)
                if partition_pruning and md.partition_metadata:
                    built = []
                    for col, pm in md.partition_metadata.items():
                        if pm and pm.get("partitions"):
                            fn = get_partition_function(
                                pm["functionName"], pm["numPartitions"])
                            built.append((col, fn,
                                          frozenset(pm["partitions"])))
                    parts = tuple(built)
                    any_partition_md = any_partition_md or bool(parts)
            segments[seg] = SegmentRouteInfo(
                replicas=tuple(sorted(
                    inst for inst, st in imap.items()
                    if st in (ONLINE, CONSUMING))),
                time_range=time_range, partitions=parts)
        return RoutingTable(
            table=table, version=ver, segments=segments,
            time_column=time_column, partition_pruning=partition_pruning,
            has_partition_metadata=any_partition_md,
            selector=self._build_selector(cfg, table))

    def _build_selector(self, cfg, table: str):
        """Per-table instance selector from the routing config
        (ref: InstanceSelectorFactory); part of the snapshot, so a config
        or instance-partitions change rebuilds it with the table entry."""
        kind = (cfg.routing_config.instance_selector_type
                if cfg else "balanced")
        if kind == "balanced":
            return self.selector
        groups = self.store.get_instance_partitions(table) or []
        return (StrictReplicaGroupInstanceSelector(groups)
                if kind == "strictReplicaGroup"
                else ReplicaGroupInstanceSelector(groups))

    def _dead_instances(self) -> frozenset:
        with self._lock:
            cached = self._dead
        if cached is not None:
            return cached[1]
        ver = self.store.version
        dead = frozenset(i.instance_id
                         for i in self.store.instances("SERVER")
                         if not i.alive)
        with self._lock:
            self._dead = (ver, dead)
        if self.store.version != ver:
            with self._lock:
                if self._dead is not None and self._dead[0] == ver:
                    self._dead = None
        return dead

    # -- the routing table ---------------------------------------------------
    def get_routing_table(self, table: str, ctx: Optional[QueryContext] = None,
                          request_id: Optional[int] = None
                          ) -> Tuple[Dict[str, List[str]], List[str]]:
        """-> ({server: [segments]}, unavailable_segments). Thin wrapper
        over :meth:`route` for callers without stats plumbing."""
        res = self.route(table, ctx, request_id=request_id)
        return res.routing, res.unavailable

    def route(self, table: str, ctx: Optional[QueryContext] = None,
              request_id: Optional[int] = None,
              stats=None) -> RouteResult:
        """Routes from the cached snapshot (segments actually being
        served), prunes by partition + time metadata, picks one replica
        per segment. ``stats`` (a QueryStats, usually the broker-side
        one) receives the routing decision records."""
        if request_id is None:
            request_id = self._next_request_id()
        entry = self._routing_entry(table)
        dead = self._dead_instances()

        segments = list(entry.segments.keys())
        # lineage visibility: replaced inputs / in-flight outputs are hidden
        # (ref: SegmentLineageUtils.filterSegmentsBasedOnLineageInPlace)
        hidden = self._lineage_hidden(table)
        if hidden:
            segments = [s for s in segments if s not in hidden]
        total = len(segments)

        after_time = self._time_prune(entry, ctx, segments, stats)
        pruned = self._partition_prune(entry, ctx, after_time, stats)
        res = RouteResult(
            routing={}, unavailable=[], segments_total=total,
            segments_routed=len(pruned),
            time_pruned=total - len(after_time),
            partition_pruned=len(after_time) - len(pruned))

        def select(seg_list):
            routing: Dict[str, List[str]] = {}
            unavailable: List[str] = []
            for segment in seg_list:
                replicas = list(entry.segments[segment].replicas)
                chosen = entry.selector.select(segment, replicas,
                                               request_id, dead)
                if chosen is None:
                    unavailable.append(segment)
                else:
                    routing.setdefault(chosen, []).append(segment)
            return routing, unavailable

        res.routing, res.unavailable = select(pruned)
        res.servers_routed = len(res.routing)
        if len(pruned) != total:
            # the counterfactual fan-out: same selector, same requestId,
            # over the UNPRUNED list — what the prune-ratio gates compare
            res.servers_unpruned = len(select(segments)[0])
        else:
            res.servers_unpruned = res.servers_routed
        return res

    def _lineage_hidden(self, table: str) -> frozenset:
        cached = self._lineage_cache.get(table)
        if cached is not None:
            return cached[1]
        from pinot_tpu.controller.lineage import SegmentLineageManager

        ver = self.store.version
        hidden = frozenset(
            SegmentLineageManager(self.store).hidden_segments(table))
        self._lineage_cache[table] = (ver, hidden)
        # a mutation racing this compute may have fired the invalidating
        # watch BEFORE the insert above; self-evict so the stale set can't
        # outlive the race (any post-mutation clear removes it anyway)
        if self.store.version != ver:
            self._lineage_cache.pop(table, None)
        return hidden

    def _partition_prune(self, entry: RoutingTable,
                         ctx: Optional[QueryContext],
                         segments: List[str], stats) -> List[str]:
        """Ref: PartitionSegmentPruner — top-level AND-ed EQ/IN predicates
        (+ narrow closed int ranges) on a partitioned column keep only
        segments whose recorded partition set contains a literal's
        partition. Every outcome is a ledger record."""
        if not entry.partition_pruning:
            return segments  # pruner not configured: not a decline (ref:
            #                  PartitionSegmentPruner runs only when set
            #                  in routing.segmentPrunerTypes)

        def declined(reason: str) -> None:
            if ctx is not None:
                record_decision(stats, "routing", "all_servers", "pruned",
                                reason)

        if ctx is None or ctx.filter is None:
            declined("no_filter")
            return segments
        if not entry.has_partition_metadata:
            declined("no_partition_metadata")
            return segments
        values = _partition_filter_values(ctx.filter)
        if not values:
            declined("no_partition_predicate")
            return segments
        out = []
        for seg in segments:
            info = entry.segments[seg]
            keep = True
            for col, fn, parts in info.partitions:
                lits = values.get(col)
                if not lits:
                    continue
                if not any(fn.partition(v) in parts for v in lits):
                    keep = False
                    break
            if keep:
                out.append(seg)
        if len(out) < len(segments):
            record_decision(stats, "routing", "pruned", "all_servers",
                            "partition_prune")
        else:
            declined("partition_all_match")
        return out

    def _time_prune(self, entry: RoutingTable, ctx: Optional[QueryContext],
                    segments: List[str], stats) -> List[str]:
        """Ref: TimeSegmentPruner — drop segments whose [start,end] time
        range cannot intersect the query's time interval."""
        if ctx is None or entry.time_column is None:
            return segments  # no time column / bare routing probe:
            #                  pruner cannot apply — not a decline

        def declined(reason: str) -> None:
            record_decision(stats, "routing", "all_servers", "pruned",
                            reason)

        lo, hi = extract_time_interval(ctx.filter, entry.time_column)
        if lo is None and hi is None:
            declined("no_time_bound")
            return segments
        out = []
        for seg in segments:
            tr = entry.segments[seg].time_range
            if tr is None:
                out.append(seg)  # consuming / missing range: never pruned
                continue
            if hi is not None and tr[0] > hi:
                continue
            if lo is not None and tr[1] < lo:
                continue
            out.append(seg)
        if len(out) < len(segments):
            record_decision(stats, "routing", "pruned", "all_servers",
                            "time_prune")
        else:
            declined("time_all_match")
        return out
