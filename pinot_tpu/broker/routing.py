"""Broker routing: segment->server routing tables with pruning + replica
selection.

Re-design of ``pinot-broker/.../routing/RoutingManager.java:85``
(``buildRouting:300``, ``getRoutingTable:459``) + instance selectors
(``routing/instanceselector/BaseInstanceSelector.java``) + broker-side
segment pruners (``routing/segmentpruner/TimeSegmentPruner``) + the hybrid
time boundary (``routing/timeboundary/TimeBoundaryManager.java:52``).
Routing follows the ExternalView: only segments a live server actually
serves are routable.
"""

from __future__ import annotations

import threading

from typing import Dict, List, Optional, Tuple

from pinot_tpu.controller.state import CONSUMING, ONLINE, ClusterStateStore
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    FilterNode,
    FilterOp,
    Identifier,
    Predicate,
    PredicateType,
)

class BalancedInstanceSelector:
    """Round-robin replica pick by requestId with unavailable-instance
    exclusion (ref: BalancedInstanceSelector)."""

    def select(self, segment: str, replicas: List[str], request_id: int,
               excluded: frozenset) -> Optional[str]:
        candidates = sorted(r for r in replicas if r not in excluded)
        if not candidates:
            return None
        return candidates[request_id % len(candidates)]


def extract_time_interval(node: Optional[FilterNode], time_column: str
                          ) -> Tuple[Optional[int], Optional[int]]:
    """[lo, hi] bound on the time column implied by the filter (only
    top-level AND-ed predicates are used — ref: TimeSegmentPruner interval
    extraction)."""
    lo: Optional[int] = None
    hi: Optional[int] = None
    if node is None:
        return lo, hi

    def visit(n: FilterNode):
        nonlocal lo, hi
        if n.op is FilterOp.AND:
            for c in n.children:
                visit(c)
            return
        if n.op is not FilterOp.PREDICATE:
            return
        p = n.predicate
        if not isinstance(p.lhs, Identifier) or p.lhs.name != time_column:
            return
        if p.type is PredicateType.EQ:
            v = int(p.value)
            lo = v if lo is None else max(lo, v)
            hi = v if hi is None else min(hi, v)
        elif p.type is PredicateType.RANGE:
            if p.lower is not None:
                v = int(p.lower) + (0 if p.lower_inclusive else 1)
                lo = v if lo is None else max(lo, v)
            if p.upper is not None:
                v = int(p.upper) - (0 if p.upper_inclusive else 1)
                hi = v if hi is None else min(hi, v)

    visit(node)
    return lo, hi


class TimeBoundaryManager:
    """Hybrid-table split point (ref: TimeBoundaryManager.java:52): offline
    side serves ``time <= boundary``, realtime serves ``time > boundary``;
    boundary = max offline end-time minus one raw time-column unit (the
    reference subtracts a full period only for daily/hourly push
    frequencies — segment-push granularity is not modeled here)."""

    def __init__(self, store: ClusterStateStore):
        self.store = store

    def get_boundary(self, offline_table: str) -> Optional[int]:
        end_times = [md.end_time for md
                     in self.store.segment_metadata_list(offline_table)
                     if md.end_time is not None]
        if not end_times:
            return None
        return max(end_times) - 1


class RoutingManager:
    """Ref: RoutingManager.java:85. Watches ExternalView + instance liveness
    and serves per-query routing tables."""

    def __init__(self, store: ClusterStateStore):
        self.store = store
        self.selector = BalancedInstanceSelector()
        self.time_boundary = TimeBoundaryManager(store)
        self._request_id = 0
        self._lock = threading.Lock()

    def _next_request_id(self) -> int:
        with self._lock:
            self._request_id += 1
            return self._request_id

    def routable_tables(self) -> List[str]:
        return self.store.table_names()

    def table_exists(self, table_with_type: str) -> bool:
        return self.store.get_table_config(table_with_type) is not None

    # -- the routing table ---------------------------------------------------
    def get_routing_table(self, table: str, ctx: Optional[QueryContext] = None,
                          request_id: Optional[int] = None
                          ) -> Tuple[Dict[str, List[str]], List[str]]:
        """-> ({server: [segments]}, unavailable_segments). Routes from the
        ExternalView (segments actually being served), prunes by time range,
        picks one replica per segment."""
        if request_id is None:
            request_id = self._next_request_id()
        ev = self.store.get_external_view(table)
        dead = frozenset(i.instance_id for i in self.store.instances("SERVER")
                         if not i.alive)

        pruned = self._time_prune(table, ctx, list(ev.keys()))

        routing: Dict[str, List[str]] = {}
        unavailable: List[str] = []
        for segment in pruned:
            replicas = [inst for inst, st in ev.get(segment, {}).items()
                        if st in (ONLINE, CONSUMING)]
            chosen = self.selector.select(segment, replicas, request_id, dead)
            if chosen is None:
                unavailable.append(segment)
            else:
                routing.setdefault(chosen, []).append(segment)
        return routing, unavailable

    def _time_prune(self, table: str, ctx: Optional[QueryContext],
                    segments: List[str]) -> List[str]:
        """Ref: TimeSegmentPruner — drop segments whose [start,end] time
        range cannot intersect the query's time interval."""
        if ctx is None:
            return segments
        cfg = self.store.get_table_config(table)
        tc = cfg.validation_config.time_column_name if cfg else None
        if not tc:
            return segments
        lo, hi = extract_time_interval(ctx.filter, tc)
        if lo is None and hi is None:
            return segments
        out = []
        for seg in segments:
            md = self.store.get_segment_metadata(table, seg)
            if md is None or md.status == CONSUMING:
                out.append(seg)  # consuming segments are never time-pruned
                continue
            if md.start_time is None or md.end_time is None:
                out.append(seg)
                continue
            if hi is not None and md.start_time > hi:
                continue
            if lo is not None and md.end_time < lo:
                continue
            out.append(seg)
        return out
