"""Broker routing: segment->server routing tables with pruning + replica
selection.

Re-design of ``pinot-broker/.../routing/RoutingManager.java:85``
(``buildRouting:300``, ``getRoutingTable:459``) + instance selectors
(``routing/instanceselector/BaseInstanceSelector.java``) + broker-side
segment pruners (``routing/segmentpruner/TimeSegmentPruner``) + the hybrid
time boundary (``routing/timeboundary/TimeBoundaryManager.java:52``).
Routing follows the ExternalView: only segments a live server actually
serves are routable.
"""

from __future__ import annotations

import threading

from typing import Dict, List, Optional, Tuple

from pinot_tpu.controller.state import CONSUMING, ONLINE, ClusterStateStore
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    FilterNode,
    FilterOp,
    Identifier,
    Predicate,
    PredicateType,
)

class BalancedInstanceSelector:
    """Round-robin replica pick by requestId with unavailable-instance
    exclusion (ref: BalancedInstanceSelector)."""

    def select(self, segment: str, replicas: List[str], request_id: int,
               excluded: frozenset) -> Optional[str]:
        candidates = sorted(r for r in replicas if r not in excluded)
        if not candidates:
            return None
        return candidates[request_id % len(candidates)]


class ReplicaGroupInstanceSelector:
    """One replica GROUP serves the whole query (ref:
    ReplicaGroupInstanceSelector): requestId picks the group, so each
    query fans out to 1/N of the servers — the reference's QPS-scaling
    story. A segment unavailable in the picked group falls back to any
    live replica (non-strict semantics)."""

    def __init__(self, groups: List[List[str]]):
        self.groups = [set(g) for g in groups if g]

    def select(self, segment: str, replicas: List[str], request_id: int,
               excluded: frozenset) -> Optional[str]:
        live = sorted(r for r in replicas if r not in excluded)
        if not live:
            return None
        if self.groups:
            n = len(self.groups)
            for off in range(n):
                group = self.groups[(request_id + off) % n]
                in_group = [r for r in live if r in group]
                if in_group:
                    return in_group[0]
        return live[request_id % len(live)]


class StrictReplicaGroupInstanceSelector(ReplicaGroupInstanceSelector):
    """Strict variant (ref: StrictReplicaGroupInstanceSelector): NO
    cross-group fallback per segment — if the picked group cannot serve a
    segment, the segment is unavailable for this query. Selection is
    deterministic per requestId, so every segment of the query lands on
    the same group (the upsert-consistency requirement)."""

    def select(self, segment: str, replicas: List[str], request_id: int,
               excluded: frozenset) -> Optional[str]:
        live = {r for r in replicas if r not in excluded}
        if not live or not self.groups:
            return None
        group = self.groups[request_id % len(self.groups)]
        in_group = sorted(live & group)
        return in_group[0] if in_group else None


def _top_level_eq_values(node: FilterNode) -> Dict[str, List]:
    """column -> literal values from top-level AND-ed EQ/IN predicates
    (the only shapes partition pruning can use soundly)."""
    out: Dict[str, List] = {}

    def visit(n: FilterNode):
        if n.op is FilterOp.AND:
            for c in n.children:
                visit(c)
            return
        if n.op is not FilterOp.PREDICATE:
            return
        p = n.predicate
        if not isinstance(p.lhs, Identifier):
            return
        if p.type is PredicateType.EQ:
            out.setdefault(p.lhs.name, []).append(p.value)
        elif p.type is PredicateType.IN:
            out.setdefault(p.lhs.name, []).extend(p.values)

    visit(node)
    return out


def extract_time_interval(node: Optional[FilterNode], time_column: str
                          ) -> Tuple[Optional[int], Optional[int]]:
    """[lo, hi] bound on the time column implied by the filter (only
    top-level AND-ed predicates are used — ref: TimeSegmentPruner interval
    extraction)."""
    lo: Optional[int] = None
    hi: Optional[int] = None
    if node is None:
        return lo, hi

    def visit(n: FilterNode):
        nonlocal lo, hi
        if n.op is FilterOp.AND:
            for c in n.children:
                visit(c)
            return
        if n.op is not FilterOp.PREDICATE:
            return
        p = n.predicate
        if not isinstance(p.lhs, Identifier) or p.lhs.name != time_column:
            return
        if p.type is PredicateType.EQ:
            v = int(p.value)
            lo = v if lo is None else max(lo, v)
            hi = v if hi is None else min(hi, v)
        elif p.type is PredicateType.RANGE:
            if p.lower is not None:
                v = int(p.lower) + (0 if p.lower_inclusive else 1)
                lo = v if lo is None else max(lo, v)
            if p.upper is not None:
                v = int(p.upper) - (0 if p.upper_inclusive else 1)
                hi = v if hi is None else min(hi, v)

    visit(node)
    return lo, hi


class TimeBoundaryManager:
    """Hybrid-table split point (ref: TimeBoundaryManager.java:52): offline
    side serves ``time <= boundary``, realtime serves ``time > boundary``;
    boundary = max offline end-time minus one raw time-column unit (the
    reference subtracts a full period only for daily/hourly push
    frequencies — segment-push granularity is not modeled here)."""

    def __init__(self, store: ClusterStateStore):
        self.store = store

    def get_boundary(self, offline_table: str) -> Optional[int]:
        end_times = [md.end_time for md
                     in self.store.segment_metadata_list(offline_table)
                     if md.end_time is not None]
        if not end_times:
            return None
        return max(end_times) - 1


class RoutingManager:
    """Ref: RoutingManager.java:85. Watches ExternalView + instance liveness
    and serves per-query routing tables."""

    def __init__(self, store: ClusterStateStore):
        self.store = store
        self.selector = BalancedInstanceSelector()
        self.time_boundary = TimeBoundaryManager(store)
        self._request_id = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        # table -> (selector kind, groups key, selector): rebuilt only when
        # the routing config / instance partitions change (ref:
        # InstanceSelectorFactory caching per RoutingEntry)
        self._selector_cache: Dict[str, Tuple] = {}
        # table -> (store version at compute time, hidden segment set); the
        # version stamp closes the TOCTOU where a watch-driven clear lands
        # between computing the set and caching it (the stale insert would
        # otherwise persist until the next lineage mutation)
        self._lineage_cache: Dict[str, Tuple[int, frozenset]] = {}
        store.watch("lineage/",
                    lambda path, value: self._lineage_cache.clear())

    def _next_request_id(self) -> int:
        with self._lock:
            self._request_id += 1
            return self._request_id

    def routable_tables(self) -> List[str]:
        return self.store.table_names()

    def table_exists(self, table_with_type: str) -> bool:
        return self.store.get_table_config(table_with_type) is not None

    # -- the routing table ---------------------------------------------------
    def get_routing_table(self, table: str, ctx: Optional[QueryContext] = None,
                          request_id: Optional[int] = None
                          ) -> Tuple[Dict[str, List[str]], List[str]]:
        """-> ({server: [segments]}, unavailable_segments). Routes from the
        ExternalView (segments actually being served), prunes by time range,
        picks one replica per segment."""
        if request_id is None:
            request_id = self._next_request_id()
        ev = self.store.get_external_view(table)
        dead = frozenset(i.instance_id for i in self.store.instances("SERVER")
                         if not i.alive)

        segments = list(ev.keys())
        # lineage visibility: replaced inputs / in-flight outputs are hidden
        # (ref: SegmentLineageUtils.filterSegmentsBasedOnLineageInPlace)
        hidden = self._lineage_hidden(table)
        if hidden:
            segments = [s for s in segments if s not in hidden]

        pruned = self._time_prune(table, ctx, segments)
        pruned = self._partition_prune(table, ctx, pruned)
        selector = self._selector_for(table)

        routing: Dict[str, List[str]] = {}
        unavailable: List[str] = []
        for segment in pruned:
            replicas = [inst for inst, st in ev.get(segment, {}).items()
                        if st in (ONLINE, CONSUMING)]
            chosen = selector.select(segment, replicas, request_id, dead)
            if chosen is None:
                unavailable.append(segment)
            else:
                routing.setdefault(chosen, []).append(segment)
        return routing, unavailable

    def _lineage_hidden(self, table: str) -> frozenset:
        cached = self._lineage_cache.get(table)
        if cached is not None:
            return cached[1]
        from pinot_tpu.controller.lineage import SegmentLineageManager

        ver = self.store.version
        hidden = frozenset(
            SegmentLineageManager(self.store).hidden_segments(table))
        self._lineage_cache[table] = (ver, hidden)
        # a mutation racing this compute may have fired the invalidating
        # watch BEFORE the insert above; self-evict so the stale set can't
        # outlive the race (any post-mutation clear removes it anyway)
        if self.store.version != ver:
            self._lineage_cache.pop(table, None)
        return hidden

    def _selector_for(self, table: str):
        """Per-table instance selector from the routing config
        (ref: InstanceSelectorFactory), cached against its inputs."""
        cfg = self.store.get_table_config(table)
        kind = (cfg.routing_config.instance_selector_type
                if cfg else "balanced")
        if kind == "balanced":
            return self.selector
        groups = self.store.get_instance_partitions(table) or []
        key = (kind, tuple(tuple(g) for g in groups))
        cached = self._selector_cache.get(table)
        if cached is not None and cached[0] == key:
            return cached[1]
        sel = (StrictReplicaGroupInstanceSelector(groups)
               if kind == "strictReplicaGroup"
               else ReplicaGroupInstanceSelector(groups))
        self._selector_cache[table] = (key, sel)
        return sel

    def _partition_prune(self, table: str, ctx: Optional[QueryContext],
                         segments: List[str]) -> List[str]:
        """Ref: PartitionSegmentPruner — top-level AND-ed EQ/IN predicates
        on a partitioned column keep only segments whose recorded partition
        set contains the literal's partition."""
        if ctx is None or ctx.filter is None:
            return segments
        cfg = self.store.get_table_config(table)
        pruners = (cfg.routing_config.segment_pruner_types if cfg else [])
        if not any(p.lower() == "partition" for p in pruners):
            return segments  # ref: PartitionSegmentPruner runs only when
            #                  configured in routing.segmentPrunerTypes
        from pinot_tpu.utils.partition import get_partition_function

        eq_values = _top_level_eq_values(ctx.filter)
        if not eq_values:
            return segments
        out = []
        for seg in segments:
            md = self.store.get_segment_metadata(table, seg)
            if md is None or not md.partition_metadata:
                out.append(seg)
                continue
            keep = True
            for col, values in eq_values.items():
                pm = md.partition_metadata.get(col)
                if not pm or not pm.get("partitions"):
                    continue
                fn = get_partition_function(pm["functionName"],
                                            pm["numPartitions"])
                if not any(fn.partition(v) in pm["partitions"]
                           for v in values):
                    keep = False
                    break
            if keep:
                out.append(seg)
        return out

    def _time_prune(self, table: str, ctx: Optional[QueryContext],
                    segments: List[str]) -> List[str]:
        """Ref: TimeSegmentPruner — drop segments whose [start,end] time
        range cannot intersect the query's time interval."""
        if ctx is None:
            return segments
        cfg = self.store.get_table_config(table)
        tc = cfg.validation_config.time_column_name if cfg else None
        if not tc:
            return segments
        lo, hi = extract_time_interval(ctx.filter, tc)
        if lo is None and hi is None:
            return segments
        out = []
        for seg in segments:
            md = self.store.get_segment_metadata(table, seg)
            if md is None or md.status == CONSUMING:
                out.append(seg)  # consuming segments are never time-pruned
                continue
            if md.start_time is None or md.end_time is None:
                out.append(seg)
                continue
            if hi is not None and md.start_time > hi:
                continue
            if lo is not None and md.end_time < lo:
                continue
            out.append(seg)
        return out
