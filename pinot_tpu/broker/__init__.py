"""Broker: routing, time boundary, scatter/gather, reduce
(ref: pinot-broker + pinot-core query/reduce)."""

from pinot_tpu.broker.broker import BrokerRequestHandler
from pinot_tpu.broker.reduce import BrokerReduceService
from pinot_tpu.broker.routing import (
    BalancedInstanceSelector,
    RoutingManager,
    TimeBoundaryManager,
    extract_time_interval,
)

__all__ = [
    "BrokerRequestHandler", "BrokerReduceService",
    "BalancedInstanceSelector", "RoutingManager", "TimeBoundaryManager",
    "extract_time_interval",
]
