"""Server: segment hosting, refcounted data managers, query scheduler,
instance query execution (ref: pinot-server + pinot-core data managers)."""

from pinot_tpu.server.data_manager import (
    InstanceDataManager,
    RealtimeTableDataManager,
    SegmentDataManager,
    TableDataManager,
)
from pinot_tpu.server.scheduler import (
    FcfsScheduler,
    QueryScheduler,
    TokenBucketScheduler,
    make_scheduler,
)
from pinot_tpu.server.server import ServerInstance

__all__ = [
    "InstanceDataManager", "RealtimeTableDataManager", "SegmentDataManager",
    "TableDataManager",
    "FcfsScheduler", "QueryScheduler", "TokenBucketScheduler",
    "make_scheduler",
    "ServerInstance",
]
