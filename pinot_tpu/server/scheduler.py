"""Query scheduler: admission control for server query execution.

Re-design of ``pinot-core/.../query/scheduler/QueryScheduler.java:56``
(``processQueryAndSerialize:147``) with the reference's pluggable policies:
FCFS (``fcfs/``), token-bucket resource accounting per table
(``tokenbucket/``), the multi-level priority queue (``priority/``), and —
the default under concurrency — shortest-expected-work-first
(:class:`SewfScheduler`): per-query-shape latency EWMAs order the queue so
cheap dashboard queries stop convoying behind expensive scans, with an
age-based boost bounding how long an expensive shape can be deferred.
"""

from __future__ import annotations

import queue
import threading
import time

from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional


class _DaemonPool:
    """Fixed pool of daemon worker threads. Daemon matters: a query stuck in
    a long device compile must never block process exit (the
    ThreadPoolExecutor default of non-daemon threads does)."""

    def __init__(self, num_workers: int, name: str):
        self._q: "queue.Queue" = queue.Queue()
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"{name}-{i}")
            for i in range(num_workers)]
        for t in self._threads:
            t.start()

    def _work(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, on_skip = item
            if not fut.set_running_or_notify_cancel():
                # cancelled while queued: bookkeeping (inflight counters)
                # must still run or shutdown blocks on a phantom query
                if on_skip is not None:
                    on_skip()
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)

    def submit(self, fn: Callable[[], Any],
               on_skip: Optional[Callable[[], None]] = None) -> Future:
        fut: Future = Future()
        self._q.put((fut, fn, on_skip))
        return fut

    def qsize(self) -> int:
        return self._q.qsize()

    def stop(self) -> None:
        for _ in self._threads:
            self._q.put(None)


class WorkerPool:
    """Persistent segment-fanout pool (the reference's pqw worker threads,
    ``pinot.server.query.worker.threads``): one per executor, shared by
    every in-flight query, so segment fan-out stops paying thread
    spawn/teardown per query AND the thread count is a server-level bound
    instead of multiplying per concurrent query."""

    def __init__(self, num_workers: int, name: str = "pqw"):
        self.num_workers = max(1, int(num_workers))
        self._pool = _DaemonPool(self.num_workers, name)

    def map(self, fn, *iterables) -> list:
        """Ordered results; the first task exception propagates (matching
        the old per-query ``ThreadPoolExecutor.map`` semantics)."""
        import functools

        futs = [self._pool.submit(functools.partial(fn, *args))
                for args in zip(*iterables)]
        return [f.result() for f in futs]

    def submit(self, fn, *args) -> Future:
        import functools

        return self._pool.submit(functools.partial(fn, *args))

    def stop(self) -> None:
        self._pool.stop()


class QueryScheduler:
    """Base: bounded worker pool, graceful drain on shutdown. ``shape`` on
    ``submit`` is an optional query-shape key (table + normalized SQL);
    FCFS/token-bucket policies ignore it, the SEWF policy orders by it."""

    def __init__(self, num_workers: int = 8, name: str = "query"):
        self.num_workers = max(1, int(num_workers))
        self._pool = _DaemonPool(self.num_workers, name)
        self._accepting = True  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)

    def submit(self, fn: Callable[[], Any], table: str = "",
               shape: Any = None) -> Future:
        with self._lock:
            if not self._accepting:
                raise RuntimeError("scheduler is shut down")
            self._inflight += 1

        def done():
            with self._lock:
                self._inflight -= 1
                self._drained.notify_all()

        t_submit = time.perf_counter()

        def run():
            self._note_wait((time.perf_counter() - t_submit) * 1e3,
                            table=table)
            try:
                return fn()
            finally:
                done()

        return self._pool.submit(run, on_skip=done)

    def _note_wait(self, wait_ms: float, table: str = "") -> None:
        """Scheduler-queue wait accounting — the queue half of the
        queue-vs-work attribution at the scheduler level (the span tree's
        SchedulerQueue spans carry the per-query value; these totals feed
        ``/debug/scheduler``; the windowed (table, scheduler_wait)
        histogram gives the sliding-percentile view). Lazily-initialized
        so subclasses that own their queues (priority/SEWF) share it
        without base ``__init__``."""
        with self._lock:
            self.queue_waits = getattr(self, "queue_waits", 0) + 1
            self.queue_wait_ms_total = \
                getattr(self, "queue_wait_ms_total", 0.0) + wait_ms
            if wait_ms > getattr(self, "queue_wait_ms_max", 0.0):
                self.queue_wait_ms_max = wait_ms
        from pinot_tpu.common.telemetry import observe_ms

        observe_ms(table, "scheduler_wait", wait_ms)

    def queue_depth(self) -> int:
        return self._pool.qsize()

    def stats_snapshot(self) -> Dict[str, Any]:
        """``/debug/scheduler`` body: live policy/queue/in-flight state."""
        with self._lock:
            inflight = self._inflight
            waits = getattr(self, "queue_waits", 0)
            wait_total = getattr(self, "queue_wait_ms_total", 0.0)
            wait_max = getattr(self, "queue_wait_ms_max", 0.0)
        return {"policy": type(self).__name__,
                "workers": self.num_workers,
                "inflight": inflight,
                "queued": self.queue_depth(),
                "queueWaits": waits,
                "queueWaitMsTotal": round(wait_total, 3),
                "queueWaitMsMax": round(wait_max, 3)}

    def shutdown(self, timeout_s: float = 30.0) -> None:
        """Disable new queries, drain in-flight ones
        (ref: server shutdown = disable queries, drain, unregister)."""
        with self._lock:
            self._accepting = False
            deadline = time.monotonic() + timeout_s
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._drained.wait(remaining)
        self._pool.stop()


class FcfsScheduler(QueryScheduler):
    """Ref: fcfs/FCFSQueryScheduler — plain pool order."""


class TokenBucketScheduler(QueryScheduler):
    """Per-table token buckets (ref: tokenbucket/ — tables consume tokens
    per query; an exhausted table's queries wait for refill, so one hot
    table cannot starve the rest)."""

    def __init__(self, num_workers: int = 8, tokens_per_second: float = 100.0,
                 burst: float = 200.0):
        super().__init__(num_workers, name="tb-query")
        self._rate = tokens_per_second
        self._burst = burst
        # table -> (tokens, last_ts)
        self._buckets: Dict[str, tuple] = {}  # guarded-by: _bucket_lock
        self._bucket_lock = threading.Lock()

    def _take_token(self, table: str) -> float:
        """Returns seconds to wait (0 = admitted now)."""
        now = time.monotonic()
        with self._bucket_lock:
            tokens, last = self._buckets.get(table, (self._burst, now))
            tokens = min(self._burst, tokens + (now - last) * self._rate)
            if tokens >= 1.0:
                self._buckets[table] = (tokens - 1.0, now)
                return 0.0
            wait = (1.0 - tokens) / self._rate
            self._buckets[table] = (0.0, now + wait)
            return wait

    def submit(self, fn: Callable[[], Any], table: str = "",
               shape: Any = None) -> Future:
        wait = self._take_token(table) if table else 0.0
        if wait <= 0:
            return super().submit(fn, table, shape=shape)

        def delayed():
            time.sleep(wait)
            return fn()

        return super().submit(delayed, table, shape=shape)


class PriorityScheduler(QueryScheduler):
    """Multi-level priority queue with per-table fairness (ref:
    ``priority/MultiLevelPriorityQueue.java`` + ``PriorityScheduler``):
    a fixed worker pool pops from per-table queues; the next queue is the
    one with the LOWEST in-progress+pending cost share, scaled by the
    table's priority weight, so a flood from one table cannot starve
    others and high-priority tables drain first under contention."""

    def __init__(self, num_workers: int = 8,
                 table_priorities: Optional[Dict[str, float]] = None):
        # intentionally does NOT call super().__init__: this scheduler owns
        # its queues instead of a shared _DaemonPool queue
        self.num_workers = max(1, int(num_workers))
        self._accepting = True  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._priorities = dict(table_priorities or {})
        self._queues: Dict[str, "queue.Queue"] = {}  # guarded-by: _lock
        self._costs: Dict[str, float] = {}  # guarded-by: _lock
        self._available = threading.Semaphore(0)
        self._stop = False  # guarded-by: _lock
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"prio-query-{i}")
            for i in range(num_workers)]
        for t in self._threads:
            t.start()

    def _pick_table_locked(self) -> Optional[str]:
        """Lowest weighted cost wins (the multi-level 'wakeup' choice).
        Caller holds ``_lock`` (the ``_locked`` suffix is the lint
        convention for that contract)."""
        best, best_score = None, None
        for table, q in self._queues.items():
            if q.empty():
                continue
            weight = max(self._priorities.get(table, 1.0), 1e-6)
            score = self._costs.get(table, 0.0) / weight
            if best_score is None or score < best_score:
                best, best_score = table, score
        return best

    def _work(self) -> None:
        while True:
            self._available.acquire()
            with self._lock:
                if self._stop and all(q.empty()
                                      for q in self._queues.values()):
                    return
                table = self._pick_table_locked()
                if table is None:
                    continue
                fut, fn = self._queues[table].get_nowait()
            done = self._finish(table)
            if not fut.set_running_or_notify_cancel():
                done()  # cancelled while queued: release its cost+inflight
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)
            finally:
                done()

    def _finish(self, table: str) -> Callable[[], None]:
        """One-shot completion: releases the table's cost share (cost =
        pending + in-progress, so it DECAYS — a long-lived table must not
        be starved by newly-seen tables) and the drain counter."""
        fired = [False]

        def done():
            if fired[0]:
                return
            fired[0] = True
            with self._lock:
                self._costs[table] = max(
                    self._costs.get(table, 1.0) - 1.0, 0.0)
                self._inflight -= 1
                self._drained.notify_all()

        return done

    def submit(self, fn: Callable[[], Any], table: str = "",
               shape: Any = None) -> Future:
        fut: Future = Future()
        with self._lock:
            if not self._accepting:
                raise RuntimeError("scheduler is shut down")
            self._inflight += 1
            self._costs[table] = self._costs.get(table, 0.0) + 1.0
            self._queues.setdefault(table, queue.Queue()).put((fut, fn))
        self._available.release()
        return fut

    def queue_depth(self) -> int:
        with self._lock:
            return sum(q.qsize() for q in self._queues.values())

    def shutdown(self, timeout_s: float = 30.0) -> None:
        with self._lock:
            self._accepting = False
            deadline = time.monotonic() + timeout_s
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._drained.wait(remaining)
            self._stop = True
        for _ in self._threads:
            self._available.release()


class SewfScheduler(QueryScheduler):
    """Shortest-expected-work-first with an age-based anti-starvation
    boost — the two-level dispatch policy for mixed dashboard traffic.

    Each query shape (table + normalized SQL passed as ``submit(...,
    shape=)``) keeps a latency EWMA from its own completions. Workers pop
    the pending entry with the lowest ``expected_ms - age_ms *
    aging_boost``: cheap shapes overtake expensive scans (a 10 ms Q2.1
    stops waiting behind a 400 ms Q4.x convoy), while the age term
    guarantees an expensive query deferred ``expected_diff / aging_boost``
    milliseconds runs next regardless of what keeps arriving. Unknown
    shapes score as zero expected work — run soon, then their own EWMA
    places them."""

    EWMA_ALPHA = 0.25

    def __init__(self, num_workers: int = 8, aging_boost: float = 2.0):
        # owns its own ordered queue instead of the base _DaemonPool FIFO
        self.num_workers = max(1, int(num_workers))
        self.aging_boost = float(aging_boost)
        self._accepting = True  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        # pending entries: (enqueue_ts, shape, fut, fn)
        self._pending: list = []  # guarded-by: _lock
        self._ewma_ms: Dict[Any, float] = {}  # guarded-by: _lock
        self.starvation_boosts = 0  # guarded-by: _lock
        self._available = threading.Semaphore(0)
        self._stop = False  # guarded-by: _lock
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"sewf-query-{i}")
            for i in range(self.num_workers)]
        for t in self._threads:
            t.start()

    def _score_locked(self, entry, now: float) -> float:
        t_enq, shape, _fut, _fn = entry
        expected = self._ewma_ms.get(shape, 0.0)
        return expected - (now - t_enq) * 1e3 * self.aging_boost

    def _pick_locked(self):
        """Pop the lowest-scoring pending entry (caller holds ``_lock``).
        O(pending) scan — queue depths here are bounded by the admission
        gate, so a heap's reordering complexity buys nothing."""
        if not self._pending:
            return None
        now = time.monotonic()
        best_i = 0
        best_s = None
        for i, entry in enumerate(self._pending):
            s = self._score_locked(entry, now)
            if best_s is None or s < best_s:
                best_i, best_s = i, s
        entry = self._pending.pop(best_i)
        # an entry that won on age rather than expected work is a
        # starvation-boost event (the anti-starvation half working)
        if best_i != 0 and self._ewma_ms.get(entry[1], 0.0) \
                >= max(self._ewma_ms.get(e[1], 0.0)
                       for e in self._pending + [entry]):
            self.starvation_boosts += 1
        return entry

    def _work(self) -> None:
        while True:
            self._available.acquire()
            with self._lock:
                if self._stop and not self._pending:
                    return
                entry = self._pick_locked()
            if entry is None:
                continue
            _t_enq, shape, fut, fn = entry
            table = shape[0] if isinstance(shape, tuple) and shape \
                and isinstance(shape[0], str) else \
                (shape if isinstance(shape, str) else "")
            self._note_wait((time.monotonic() - _t_enq) * 1e3, table=table)
            if not fut.set_running_or_notify_cancel():
                self._done(shape, None)  # cancelled while queued
                continue
            t0 = time.perf_counter()
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)
            finally:
                self._done(shape, (time.perf_counter() - t0) * 1e3)

    def _done(self, shape: Any, ms: Optional[float]) -> None:
        with self._lock:
            if ms is not None and shape is not None:
                e = self._ewma_ms.get(shape)
                self._ewma_ms[shape] = ms if e is None else \
                    self.EWMA_ALPHA * ms + (1 - self.EWMA_ALPHA) * e
                if len(self._ewma_ms) > 4096:
                    # shape churn bound: drop ~half, newest keep their EWMA
                    for k in list(self._ewma_ms)[:2048]:
                        del self._ewma_ms[k]
            self._inflight -= 1
            self._drained.notify_all()

    def submit(self, fn: Callable[[], Any], table: str = "",
               shape: Any = None) -> Future:
        fut: Future = Future()
        with self._lock:
            if not self._accepting:
                raise RuntimeError("scheduler is shut down")
            self._inflight += 1
            self._pending.append((time.monotonic(),
                                  shape if shape is not None else table,
                                  fut, fn))
        self._available.release()
        return fut

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def expected_ms(self, shape: Any) -> Optional[float]:
        with self._lock:
            return self._ewma_ms.get(shape)

    def stats_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"policy": type(self).__name__,
                    "workers": self.num_workers,
                    "inflight": self._inflight,
                    "queued": len(self._pending),
                    "shapesTracked": len(self._ewma_ms),
                    "starvationBoosts": self.starvation_boosts,
                    "agingBoost": self.aging_boost,
                    "queueWaits": getattr(self, "queue_waits", 0),
                    "queueWaitMsTotal": round(
                        getattr(self, "queue_wait_ms_total", 0.0), 3),
                    "queueWaitMsMax": round(
                        getattr(self, "queue_wait_ms_max", 0.0), 3)}

    def shutdown(self, timeout_s: float = 30.0) -> None:
        with self._lock:
            self._accepting = False
            deadline = time.monotonic() + timeout_s
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._drained.wait(remaining)
            self._stop = True
        for _ in self._threads:
            self._available.release()


def make_scheduler(policy: str = "fcfs", config=None, **kw) -> QueryScheduler:
    """Ref: QuerySchedulerFactory. ``config`` sizes the runner pool from
    ``pinot.server.query.runner.threads`` (the reference's pqr threads)
    unless the caller passed ``num_workers`` explicitly."""
    if config is not None and "num_workers" not in kw:
        from pinot_tpu.spi.config import CommonConstants

        kw["num_workers"] = max(1, config.get_int(
            CommonConstants.RUNNER_THREADS_KEY,
            CommonConstants.DEFAULT_RUNNER_THREADS))
    policy = policy.lower()
    if policy == "fcfs":
        return FcfsScheduler(**kw)
    if policy in ("tokenbucket", "token_bucket"):
        return TokenBucketScheduler(**kw)
    if policy == "priority":
        return PriorityScheduler(**kw)
    if policy in ("sewf", "shortest", "sjf"):
        return SewfScheduler(**kw)
    raise ValueError(f"unknown scheduler policy {policy!r}")
