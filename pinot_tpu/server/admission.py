"""Admission gate: bounded concurrency + bounded queue above execution.

Re-design of the reference's scheduler-tier admission
(``QueryScheduler.java`` returning 503-shaped results once its resource
manager is saturated, plus the broker-side
``HelixExternalViewBasedQueryQuotaManager`` 429s): concurrent load above
the bound must degrade to *bounded-latency rejection*, never to a convoy
where every query's latency is the sum of everyone else's.

One gate instance fronts one executor (or one broker). ``admit`` either:

- passes immediately (a concurrency slot is free),
- waits — bounded by the queue depth bound AND the wait-time bound — for
  a slot, or
- raises a typed, retriable :class:`QueryRejectedError` carrying the
  queue depth it observed, so clients can back off proportionally.

An optional :class:`~pinot_tpu.broker.quota.QueryQuotaManager` folds the
per-table QPS quota into the same gate (the broker front door): a quota
trip is the same typed rejection with ``reason="quota"``. Residency
leases (``ResidencyManager.begin_query``) open strictly AFTER admission
and close in the caller's ``finally`` — a rejected query therefore never
holds pins, and the graftlint pairing family gates the admit/release and
begin/end pairs on every path.
"""

from __future__ import annotations

import os
import threading
import time

from typing import Any, Dict, Optional

from pinot_tpu.engine.errors import QueryRejectedError


def _auto_concurrent() -> int:
    return max(8, 2 * (os.cpu_count() or 1))


class _Ticket:
    """One admission; ``release`` through the gate is idempotent.
    ``wait_ms`` is the queue wait this admission paid — the tracing
    layer's queue-vs-work attribution at the admission level."""

    __slots__ = ("released", "gated", "wait_ms")

    def __init__(self, gated: bool, wait_ms: float = 0.0):
        self.released = False
        self.gated = gated
        self.wait_ms = wait_ms


class AdmissionGate:
    """Bounded-slot, bounded-queue admission with typed rejection.

    ``max_concurrent``: executing-query slots (0 = auto from cpu count,
    < 0 = gate disabled — admits always pass, quota still applies).
    ``max_queue``: waiters allowed behind the slots (0 = auto, 8x slots;
    < 0 = no queue, a full gate rejects immediately).
    ``max_wait_ms``: a waiter past this bound is rejected (the
    bounded-latency guarantee for the queued path)."""

    def __init__(self, max_concurrent: int = 0, max_queue: int = 0,
                 max_wait_ms: float = 10_000.0, quota=None,
                 name: str = "query-admission"):
        self._name = name
        self._quota = quota
        self._cond = threading.Condition()
        self._slots = 0  # guarded-by-writes: _cond
        self._max_queue = 0  # guarded-by-writes: _cond
        self._max_wait_s = 0.0  # guarded-by-writes: _cond
        self._inflight = 0  # guarded-by-writes: _cond
        self._waiting = 0  # guarded-by-writes: _cond
        # cumulative counters (process lifetime; bench suites diff
        # stats_snapshot() marks). Writes-only guards: gauge lambdas read
        # single ints lock-free; snapshots take the condition for a
        # consistent cut.
        self.admitted = 0  # guarded-by-writes: _cond
        self.rejected_queue_full = 0  # guarded-by-writes: _cond
        self.rejected_wait_expired = 0  # guarded-by-writes: _cond
        self.rejected_quota = 0  # guarded-by-writes: _cond
        self.max_queue_depth_seen = 0  # guarded-by-writes: _cond
        self.queue_wait_ms_total = 0.0  # guarded-by-writes: _cond
        self.queue_wait_ms_max = 0.0  # guarded-by-writes: _cond
        self._metrics = None
        self.configure(max_concurrent=max_concurrent, max_queue=max_queue,
                       max_wait_ms=max_wait_ms)

    @classmethod
    def from_config(cls, config=None, quota=None,
                    name: str = "query-admission") -> "AdmissionGate":
        from pinot_tpu.spi.config import CommonConstants, PinotConfiguration

        cfg = config if config is not None else PinotConfiguration()
        return cls(
            max_concurrent=cfg.get_int(
                CommonConstants.ADMISSION_MAX_CONCURRENT_KEY,
                CommonConstants.DEFAULT_ADMISSION_MAX_CONCURRENT),
            max_queue=cfg.get_int(
                CommonConstants.ADMISSION_MAX_QUEUE_KEY,
                CommonConstants.DEFAULT_ADMISSION_MAX_QUEUE),
            max_wait_ms=cfg.get_float(
                CommonConstants.ADMISSION_MAX_WAIT_MS_KEY,
                CommonConstants.DEFAULT_ADMISSION_MAX_WAIT_MS),
            quota=quota, name=name)

    def configure(self, max_concurrent: Optional[int] = None,
                  max_queue: Optional[int] = None,
                  max_wait_ms: Optional[float] = None) -> None:
        """Re-bound the gate at runtime (bench saturation levels, ops
        tuning). Waiters re-evaluate against the new bounds."""
        with self._cond:
            if max_concurrent is not None:
                mc = int(max_concurrent)
                self._slots = mc if mc != 0 else _auto_concurrent()
            if max_queue is not None:
                mq = int(max_queue)
                if mq == 0:
                    self._max_queue = 8 * max(self._slots, 1)
                else:
                    self._max_queue = max(mq, 0)
            if max_wait_ms is not None:
                self._max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
            self._cond.notify_all()

    @property
    def enabled(self) -> bool:
        return self._slots > 0

    # -- admission -----------------------------------------------------------
    def admit(self, table: str = "") -> _Ticket:
        """Admit one query (blocking, bounded) or raise
        :class:`QueryRejectedError`. The returned ticket MUST be released
        in a ``finally`` — the graftlint pairing family enforces it."""
        from pinot_tpu.common.telemetry import TELEMETRY

        if self._quota is not None and table \
                and not self._quota.acquire(table):
            with self._cond:
                self.rejected_quota += 1
                depth = self._waiting
            self._mark("ADMISSION_REJECTED")
            TELEMETRY.note_rejection(table)
            raise QueryRejectedError(
                f"query quota exceeded for table {table}",
                queue_depth=depth, reason="quota")
        if self._slots <= 0:  # disabled: count, never queue
            with self._cond:
                self.admitted += 1
            self._mark("ADMISSION_ADMITTED")
            return _Ticket(gated=False)
        t0 = time.monotonic()
        reject: Optional[Any] = None
        wait_ms = 0.0
        with self._cond:
            if self._inflight >= self._slots \
                    and self._waiting >= self._max_queue:
                self.rejected_queue_full += 1
                reject = ("queue_full",
                          f"admission queue full ({self._waiting} waiting, "
                          f"{self._slots} slots) for {self._name}",
                          self._waiting)
            else:
                deadline = t0 + self._max_wait_s
                self._waiting += 1
                if self._waiting > self.max_queue_depth_seen:
                    self.max_queue_depth_seen = self._waiting
                try:
                    while self._inflight >= self._slots:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            self.rejected_wait_expired += 1
                            reject = (
                                "wait_expired",
                                f"admission wait bound "
                                f"{self._max_wait_s * 1e3:.0f} ms expired "
                                f"({self._waiting} waiting) for "
                                f"{self._name}", self._waiting)
                            # a release() notify may have landed on THIS
                            # dying waiter — pass it along or another
                            # waiter sleeps out its full bound on a slot
                            # that is actually free
                            self._cond.notify()
                            break
                        self._cond.wait(remaining)
                finally:
                    self._waiting -= 1
                if reject is None:
                    self._inflight += 1
                    self.admitted += 1
                    wait_ms = (time.monotonic() - t0) * 1e3
                    self.queue_wait_ms_total += wait_ms
                    if wait_ms > self.queue_wait_ms_max:
                        self.queue_wait_ms_max = wait_ms
        if reject is not None:
            reason, msg, depth = reject
            self._mark("ADMISSION_REJECTED")
            # flight-recorder anomaly feed: a rejection BURST (not one
            # rejection — that's load shedding working) freezes the box
            TELEMETRY.note_rejection(table)
            raise QueryRejectedError(msg, queue_depth=depth, reason=reason)
        self._mark("ADMISSION_ADMITTED")
        # windowed gate-wait histogram per (table, phase): the queue half
        # of the admission tier's queue-vs-work attribution, continuously
        TELEMETRY.observe(table or "", "admission_wait", wait_ms)
        return _Ticket(gated=True, wait_ms=wait_ms)

    def release(self, ticket: Optional[_Ticket]) -> None:
        """Free the ticket's slot (idempotent; None is a no-op so error
        paths can release unconditionally)."""
        if ticket is None or ticket.released:
            return
        ticket.released = True
        if not ticket.gated:
            return
        with self._cond:
            if self._inflight > 0:
                self._inflight -= 1
            self._cond.notify()

    # -- observability -------------------------------------------------------
    def bind_metrics(self, registry) -> None:
        from pinot_tpu.common.telemetry import TELEMETRY

        self._metrics = registry
        # gauge lambdas run on scrape threads: single-int reads are
        # GIL-atomic under the writes-only guards above
        registry.gauge("admission_inflight", lambda: float(self._inflight))
        registry.gauge("admission_queue_depth",
                       lambda: float(self._waiting))
        # gauge-history rings: queue depth + cumulative rejections at
        # few-second resolution (rejection RATE is the ring's derivative)
        TELEMETRY.track_gauge(f"{self._name}.queue_depth",
                              lambda: float(self._waiting))
        TELEMETRY.track_gauge(
            f"{self._name}.rejected",
            lambda: float(self.rejected_queue_full
                          + self.rejected_wait_expired
                          + self.rejected_quota))

    def _mark(self, name: str) -> None:
        if self._metrics is None:
            return
        from pinot_tpu.spi.metrics import ServerMeter

        metric = getattr(ServerMeter, name, None)
        if metric is not None:
            self._metrics.meter(metric).mark()

    def stats_snapshot(self) -> Dict[str, float]:
        """Cumulative counters (bench per-level deltas diff two of these)."""
        with self._cond:
            return {
                "admitted": self.admitted,
                "rejectedQueueFull": self.rejected_queue_full,
                "rejectedWaitExpired": self.rejected_wait_expired,
                "rejectedQuota": self.rejected_quota,
                "rejected": (self.rejected_queue_full
                             + self.rejected_wait_expired
                             + self.rejected_quota),
                "maxQueueDepth": self.max_queue_depth_seen,
                "queueWaitMsTotal": round(self.queue_wait_ms_total, 3),
                "queueWaitMsMax": round(self.queue_wait_ms_max, 3),
            }

    def snapshot(self) -> Dict[str, Any]:
        """``/debug/scheduler`` body: bounds + live depth + counters."""
        out: Dict[str, Any] = self.stats_snapshot()
        with self._cond:
            out.update(enabled=self._slots > 0, maxConcurrent=self._slots,
                       maxQueue=self._max_queue,
                       maxWaitMs=round(self._max_wait_s * 1e3, 3),
                       inflight=self._inflight, queued=self._waiting)
        return out
