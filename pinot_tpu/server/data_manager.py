"""Server-side segment lifecycle: table data managers with refcounting.

Re-design of ``pinot-core/.../data/manager/BaseTableDataManager.java:71``
(``addSegment:161``, ``addOrReplaceSegment:343``, refcounted
acquire/release) + ``RealtimeTableDataManager.java:80``: queries acquire
segments (refcount++) before executing and release after, so a segment
swapped out mid-query is destroyed only when the last reader finishes —
the same hazard protocol the TPU path needs before evicting HBM-staged
blocks (SURVEY.md §5 race-detection note).
"""

from __future__ import annotations

import logging
import threading

from typing import Any, Dict, List, Optional

from pinot_tpu.ingestion.realtime import RealtimeSegmentDataManager
from pinot_tpu.segment.immutable import ImmutableSegment, load_segment

log = logging.getLogger(__name__)


class SegmentDataManager:
    """One segment + its refcount (ref: SegmentDataManager in the reference;
    starts at 1 for the registration reference)."""

    def __init__(self, segment: Any):
        self.segment = segment
        self._refcount = 1
        self._lock = threading.Lock()

    @property
    def segment_name(self) -> str:
        return self.segment.segment_name

    def acquire(self) -> bool:
        with self._lock:
            if self._refcount <= 0:
                return False
            self._refcount += 1
            return True

    def release(self) -> int:
        with self._lock:
            self._refcount -= 1
            rc = self._refcount
        if rc == 0:
            self._destroy()
        return rc

    def _destroy(self) -> None:
        # mmap views close with GC; consuming segments stop their consumer
        stop = getattr(self.segment, "stop", None)
        if callable(stop):
            try:
                stop()
            except Exception:
                log.exception("destroy of %s failed", self.segment_name)


class TableDataManager:
    """Ref: BaseTableDataManager.java:71 (offline tables)."""

    def __init__(self, table_name_with_type: str):
        self.table_name = table_name_with_type
        self._segments: Dict[str, SegmentDataManager] = {}
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def add_segment(self, segment: Any) -> None:
        """Add or replace (ref: addOrReplaceSegment:343): the old manager's
        registration reference is released; in-flight queries holding an
        acquire keep the old segment alive until they release."""
        sdm = SegmentDataManager(segment)
        with self._lock:
            old = self._segments.get(segment.segment_name)
            self._segments[segment.segment_name] = sdm
        if old is not None:
            old.release()

    def add_segment_from_dir(self, segment_dir: str) -> ImmutableSegment:
        seg = load_segment(segment_dir)
        self.add_segment(seg)
        return seg

    def remove_segment(self, segment_name: str) -> None:
        with self._lock:
            sdm = self._segments.pop(segment_name, None)
        if sdm is not None:
            sdm.release()

    def segment_names(self) -> List[str]:
        with self._lock:
            return sorted(self._segments)

    def has_segment(self, segment_name: str) -> bool:
        with self._lock:
            return segment_name in self._segments

    # -- query-time acquire/release (ref: acquireSegments) -------------------
    def acquire_segments(self, segment_names: Optional[List[str]] = None
                         ) -> List[SegmentDataManager]:
        """Acquire the named segments (all when None). Missing or
        concurrently-destroyed segments are skipped — the reference reports
        them in the response metadata as missing segments."""
        with self._lock:
            wanted = (list(self._segments.values()) if segment_names is None
                      else [self._segments[n] for n in segment_names
                            if n in self._segments])
        out = []
        for sdm in wanted:
            if sdm.acquire():
                out.append(sdm)
        return out

    def release_segments(self, sdms: List[SegmentDataManager]) -> None:
        for sdm in sdms:
            sdm.release()

    def shutdown(self) -> None:
        with self._lock:
            sdms = list(self._segments.values())
            self._segments.clear()
        for sdm in sdms:
            sdm.release()


class RealtimeTableDataManager(TableDataManager):
    """Ref: RealtimeTableDataManager.java:80 — additionally owns the
    consuming-segment managers; their mutable segments serve queries until
    sealed, then the immutable build replaces them in-place."""

    def __init__(self, table_name_with_type: str):
        super().__init__(table_name_with_type)
        self._consumers: Dict[str, RealtimeSegmentDataManager] = {}

    def add_consuming(self, mgr: RealtimeSegmentDataManager) -> None:
        with self._lock:
            self._consumers[mgr.segment_name] = mgr
        self.add_segment(mgr.segment)  # the mutable segment serves queries

    def consuming_manager(self, segment_name: str
                          ) -> Optional[RealtimeSegmentDataManager]:
        with self._lock:
            return self._consumers.get(segment_name)

    def remove_segment(self, segment_name: str) -> None:
        """Unassignment must also stop a live consumer, or it keeps
        consuming and re-adds itself from its terminal callback."""
        with self._lock:
            mgr = self._consumers.pop(segment_name, None)
        if mgr is not None:
            mgr.stop(reason="unassigned")
        super().remove_segment(segment_name)

    def drop_consumer(self, segment_name: str) -> None:
        with self._lock:
            self._consumers.pop(segment_name, None)

    def on_sealed(self, segment_name: str, segment_dir: str) -> None:
        """CONSUMING -> ONLINE flip: swap the mutable segment for the
        immutable build (ref: CONSUMING->ONLINE state transition)."""
        with self._lock:
            self._consumers.pop(segment_name, None)
        self.add_segment_from_dir(segment_dir)

    def shutdown(self) -> None:
        with self._lock:
            consumers = list(self._consumers.values())
            self._consumers.clear()
        for c in consumers:
            c.stop()
        super().shutdown()


class InstanceDataManager:
    """table -> TableDataManager registry
    (ref: HelixInstanceDataManager.java:74)."""

    def __init__(self):
        self._tables: Dict[str, TableDataManager] = {}
        self._lock = threading.Lock()

    def get_or_create(self, table: str, realtime: bool = False) -> TableDataManager:
        with self._lock:
            tdm = self._tables.get(table)
            if tdm is None:
                tdm = (RealtimeTableDataManager(table) if realtime
                       else TableDataManager(table))
                self._tables[table] = tdm
            return tdm

    def get(self, table: str) -> Optional[TableDataManager]:
        with self._lock:
            return self._tables.get(table)

    def table_names(self) -> List[str]:
        with self._lock:
            return sorted(self._tables)

    def shutdown(self) -> None:
        with self._lock:
            tdms = list(self._tables.values())
            self._tables.clear()
        for tdm in tdms:
            tdm.shutdown()
