"""Server-side segment lifecycle: table data managers with refcounting.

Re-design of ``pinot-core/.../data/manager/BaseTableDataManager.java:71``
(``addSegment:161``, ``addOrReplaceSegment:343``, refcounted
acquire/release) + ``RealtimeTableDataManager.java:80``: queries acquire
segments (refcount++) before executing and release after, so a segment
swapped out mid-query is destroyed only when the last reader finishes —
the same hazard protocol the TPU path needs before evicting HBM-staged
blocks (SURVEY.md §5 race-detection note).
"""

from __future__ import annotations

import logging
import threading

from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.ingestion.realtime import RealtimeSegmentDataManager
from pinot_tpu.segment.immutable import ImmutableSegment, load_segment


class _LiveValidDocs:
    """Array-like view over the upsert manager's live bitmap: slicing reads
    the current state (docs invalidated after attach stay invisible)."""

    def __init__(self, pm, segment_name: str):
        self._pm = pm
        self._segment_name = segment_name

    @property
    def version(self) -> int:
        """Bitmap mutation counter (device-mask cache key)."""
        return self._pm.valid_docs_version(self._segment_name)

    def __getitem__(self, item):
        v = self._pm.valid_docs(self._segment_name)
        if isinstance(item, slice):
            stop = item.stop if item.stop is not None else \
                (0 if v is None else v.shape[0])
            if v is None:
                return np.ones(stop, dtype=bool)[item]
            if v.shape[0] < stop:
                # bitmap lags the doc count briefly: unseen docs are valid
                grown = np.ones(stop, dtype=bool)
                grown[:v.shape[0]] = v
                v = grown
            return v[item]
        return True if v is None or item >= v.shape[0] else bool(v[item])

log = logging.getLogger(__name__)


def _segment_partition(segment, segment_name: str) -> int:
    """Stream partition of a sealed realtime segment: committed metadata
    first (segment.realtime.partition), LLC name second."""
    p = segment.metadata.custom.get("segment.realtime.partition")
    if p is not None:
        return int(p)
    parts = segment_name.split("__")
    if len(parts) >= 3:
        try:
            return int(parts[1])
        except ValueError:
            pass
    return 0


class SegmentDataManager:
    """One segment + its refcount (ref: SegmentDataManager in the reference;
    starts at 1 for the registration reference)."""

    def __init__(self, segment: Any):
        self.segment = segment
        self._refcount = 1  # guarded-by: _lock
        self._lock = threading.Lock()

    @property
    def segment_name(self) -> str:
        return self.segment.segment_name

    def acquire(self) -> bool:
        with self._lock:
            if self._refcount <= 0:
                return False
            self._refcount += 1
            return True

    def release(self) -> int:
        with self._lock:
            self._refcount -= 1
            rc = self._refcount
        if rc == 0:
            self._destroy()
        return rc

    def _destroy(self) -> None:
        # mmap views close with GC; consuming segments stop their consumer
        stop = getattr(self.segment, "stop", None)
        if callable(stop):
            try:
                stop()
            except Exception:
                log.exception("destroy of %s failed", self.segment_name)


class TableDataManager:
    """Ref: BaseTableDataManager.java:71 (offline tables).

    ``listener`` (optional) observes the segment lifecycle:
    ``segment_added(table, segment)`` after registration (the HBM prefetch
    hook) and ``segment_removed(table, segment_name)`` after unregistration
    (the HBM eviction hook). Listener failures never break lifecycle."""

    def __init__(self, table_name_with_type: str, listener: Any = None):
        self.table_name = table_name_with_type
        self.listener = listener
        self._segments: Dict[str, SegmentDataManager] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def _notify(self, method: str, *args) -> None:
        fn = getattr(self.listener, method, None)
        if fn is None:
            return
        try:
            fn(self.table_name, *args)
        except Exception:
            log.exception("segment lifecycle listener %s failed", method)

    # -- lifecycle -----------------------------------------------------------
    def add_segment(self, segment: Any) -> None:
        """Add or replace (ref: addOrReplaceSegment:343): the old manager's
        registration reference is released; in-flight queries holding an
        acquire keep the old segment alive until they release."""
        sdm = SegmentDataManager(segment)
        with self._lock:
            old = self._segments.get(segment.segment_name)
            self._segments[segment.segment_name] = sdm
        if old is not None:
            old.release()
        self._notify("segment_added", segment)

    def add_segment_from_dir(self, segment_dir: str) -> ImmutableSegment:
        seg = load_segment(segment_dir)
        self.add_segment(seg)
        return seg

    def remove_segment(self, segment_name: str) -> None:
        with self._lock:
            sdm = self._segments.pop(segment_name, None)
        if sdm is not None:
            sdm.release()
            self._notify("segment_removed", segment_name)

    def segment_names(self) -> List[str]:
        with self._lock:
            return sorted(self._segments)

    def has_segment(self, segment_name: str) -> bool:
        with self._lock:
            return segment_name in self._segments

    # -- query-time acquire/release (ref: acquireSegments) -------------------
    def acquire_segments(self, segment_names: Optional[List[str]] = None
                         ) -> List[SegmentDataManager]:
        """Acquire the named segments (all when None). Missing or
        concurrently-destroyed segments are skipped — the reference reports
        them in the response metadata as missing segments."""
        with self._lock:
            wanted = (list(self._segments.values()) if segment_names is None
                      else [self._segments[n] for n in segment_names
                            if n in self._segments])
        out = []
        for sdm in wanted:
            if sdm.acquire():
                out.append(sdm)
        return out

    def release_segments(self, sdms: List[SegmentDataManager]) -> None:
        for sdm in sdms:
            sdm.release()

    def shutdown(self) -> None:
        with self._lock:
            sdms = list(self._segments.values())
            self._segments.clear()
        for sdm in sdms:
            sdm.release()


class RealtimeTableDataManager(TableDataManager):
    """Ref: RealtimeTableDataManager.java:80 — additionally owns the
    consuming-segment managers; their mutable segments serve queries until
    sealed, then the immutable build replaces them in-place. With upsert
    enabled, every hosted segment registers with the table's upsert manager
    and carries a valid-doc bitmap (ref: upsert wiring in
    RealtimeTableDataManager)."""

    def __init__(self, table_name_with_type: str, upsert_manager=None,
                 listener: Any = None):
        super().__init__(table_name_with_type, listener=listener)
        # the base class __init__ created _lock; guarded-by resolves
        # through the inheritance chain
        self._consumers: Dict[str, RealtimeSegmentDataManager] = {}  # guarded-by: _lock
        self.upsert_manager = upsert_manager  # TableUpsertMetadataManager

    def add_consuming(self, mgr: RealtimeSegmentDataManager) -> None:
        with self._lock:
            self._consumers[mgr.segment_name] = mgr
        if self.upsert_manager is not None:
            from pinot_tpu.segment.upsert import attach_valid_docs

            pm = self.upsert_manager.partition(mgr.partition)
            seg_name = mgr.segment_name

            def hook(row, doc_id, pm=pm, seg_name=seg_name):
                pm.add_record(seg_name, doc_id, pm.key_of_row(row),
                              row.get(pm.comparison_column))

            mgr.upsert_hook = hook
            # live view over the growing bitmap
            attach_valid_docs(mgr.segment, _LiveValidDocs(pm, seg_name))
        self.add_segment(mgr.segment)  # the mutable segment serves queries

    def consuming_manager(self, segment_name: str
                          ) -> Optional[RealtimeSegmentDataManager]:
        with self._lock:
            return self._consumers.get(segment_name)

    def remove_segment(self, segment_name: str) -> None:
        """Unassignment must also stop a live consumer, or it keeps
        consuming and re-adds itself from its terminal callback — and ghost
        upsert locations must go with it, or a stale location outranks
        future records of the same key."""
        with self._lock:
            mgr = self._consumers.pop(segment_name, None)
        if mgr is not None:
            mgr.stop(reason="unassigned")
        if self.upsert_manager is not None:
            for pm in self.upsert_manager.partition_managers():
                pm.remove_segment(segment_name)
        super().remove_segment(segment_name)

    def drop_consumer(self, segment_name: str) -> None:
        with self._lock:
            self._consumers.pop(segment_name, None)

    def on_sealed(self, segment_name: str, segment_dir: str,
                  partition: Optional[int] = None) -> None:
        """CONSUMING -> ONLINE flip: swap the mutable segment for the
        immutable build (ref: CONSUMING->ONLINE state transition). Also the
        entry point for replica downloads of upsert tables (keys must
        register, ref: PartitionUpsertMetadataManager.addSegment).

        No partial-result window: ``add_segment`` is add-or-replace under
        the registry lock — a query that acquired the consuming segment
        before the swap finishes against it (refcount keeps it alive), a
        query routing after sees only the immutable build."""
        from pinot_tpu.common.tracing import record_decision

        with self._lock:
            mgr = self._consumers.pop(segment_name, None)
        record_decision(None, "seal", "immutable_swap",
                        "consuming_segment",
                        "seal_swap" if mgr is not None else "seal_download")
        if mgr is not None:
            # final freshness flush: rows ingested after the last serving
            # snapshot still count once, against the seal watermark
            from pinot_tpu.engine.mutable_staging import observe_freshness
            from pinot_tpu.spi.table import raw_table_name

            observe_freshness(mgr.segment, int(mgr.segment.num_docs),
                              raw_table_name(self.table_name))
        seg = load_segment(segment_dir)
        if self.upsert_manager is not None:
            from pinot_tpu.segment.upsert import attach_valid_docs

            if mgr is not None:
                partition = mgr.partition
            elif partition is None:
                partition = _segment_partition(seg, segment_name)
            pm = self.upsert_manager.partition(partition)
            if mgr is not None:
                # same rows/order as the consuming segment: carry the bitmap
                pm.replace_segment(seg)
            else:
                # replica download: rebuild key locations from the segment
                pm.add_segment(seg)
            attach_valid_docs(seg, _LiveValidDocs(pm, segment_name))
        self.add_segment(seg)

    def shutdown(self) -> None:
        with self._lock:
            consumers = list(self._consumers.values())
            self._consumers.clear()
        for c in consumers:
            c.stop()
        super().shutdown()


class InstanceDataManager:
    """table -> TableDataManager registry
    (ref: HelixInstanceDataManager.java:74)."""

    def __init__(self, listener: Any = None):
        self._tables: Dict[str, TableDataManager] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.listener = listener  # forwarded to created TableDataManagers

    def get_or_create(self, table: str, realtime: bool = False,
                      upsert_manager=None) -> TableDataManager:
        with self._lock:
            tdm = self._tables.get(table)
            if tdm is None:
                tdm = (RealtimeTableDataManager(table, upsert_manager,
                                                listener=self.listener)
                       if realtime
                       else TableDataManager(table, listener=self.listener))
                self._tables[table] = tdm
            return tdm

    def get(self, table: str) -> Optional[TableDataManager]:
        with self._lock:
            return self._tables.get(table)

    def table_names(self) -> List[str]:
        with self._lock:
            return sorted(self._tables)

    def shutdown(self) -> None:
        with self._lock:
            tdms = list(self._tables.values())
            self._tables.clear()
        for tdm in tdms:
            tdm.shutdown()
