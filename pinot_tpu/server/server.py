"""Server instance: segment hosting + instance-level query execution.

Re-design of ``pinot-server/.../starter/helix/BaseServerStarter.java:117`` +
``ServerInstance.java:53`` + the state-model transitions
(``SegmentOnlineOfflineStateModelFactory.java:53,76``): the server watches
the cluster store's IdealState, reconciles its assigned segments
(OFFLINE->ONLINE = load; OFFLINE->CONSUMING = start stream consumer;
CONSUMING->ONLINE = seal/swap), reports ExternalView states, and answers
instance query requests through the scheduler -> executor pipeline
(ref: InstanceRequestHandler.channelRead0:90 ->
QueryScheduler.processQueryAndSerialize:147 ->
ServerQueryExecutorV1Impl.processQuery:119).
"""

from __future__ import annotations

import logging
import os
import threading
import time

from typing import Any, Dict, List, Optional

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.controller.state import (
    CONSUMING,
    ONLINE,
    ClusterStateStore,
    InstanceInfo,
)
from pinot_tpu.engine.executor import ServerQueryExecutor
from pinot_tpu.ingestion.realtime import (
    ConsumerState,
    RealtimeSegmentDataManager,
    SegmentCompletionProtocol,
)
from pinot_tpu.ingestion.stream import StreamOffset
from pinot_tpu.query.context import QueryContext
from pinot_tpu.server.data_manager import (
    InstanceDataManager,
    RealtimeTableDataManager,
)
from pinot_tpu.server.scheduler import QueryScheduler, make_scheduler
from pinot_tpu.spi.table import TableType, table_type_from_name

log = logging.getLogger(__name__)


class ServerInstance:
    """One query server (ref: ServerInstance.java:53). In-process transport:
    the broker calls ``execute_query`` directly (the embedded-cluster mode,
    ref: ClusterTest single-JVM multi-instance); the gRPC service wraps the
    same entry point for multi-process deployments."""

    def __init__(self, instance_id: str, store: ClusterStateStore,
                 completion_protocol: Optional[SegmentCompletionProtocol] = None,
                 executor: Optional[ServerQueryExecutor] = None,
                 scheduler: Optional[QueryScheduler] = None,
                 segment_dir: str = "/tmp/pinot_tpu_server",
                 consumer_tick_s: float = 0.02,
                 config=None):
        from pinot_tpu.spi.metrics import MetricsRegistry

        self.instance_id = instance_id
        self.store = store
        self.completion_protocol = completion_protocol
        self.executor = executor or ServerQueryExecutor(config=config)
        # runner pool sized by pinot.server.query.runner.threads (pqr);
        # policy from pinot.server.query.scheduler.policy — default SEWF
        # (shortest-expected-work-first with anti-starvation aging)
        from pinot_tpu.spi.config import CommonConstants

        policy = (config.get_str(CommonConstants.SCHEDULER_POLICY_KEY,
                                 CommonConstants.DEFAULT_SCHEDULER_POLICY)
                  if config is not None
                  else CommonConstants.DEFAULT_SCHEDULER_POLICY)
        self.scheduler = scheduler or make_scheduler(policy, config=config)
        self.metrics = MetricsRegistry(role="server")
        # segment lifecycle -> HBM residency: adds prefetch, removals evict
        self.data_manager = InstanceDataManager(listener=self)
        residency = getattr(self.executor, "residency", None)
        if residency is not None:
            residency.bind_metrics(self.metrics)
        # launch-coalescing meters/gauges (sharded executors only)
        launcher = getattr(self.executor, "launcher", None)
        if launcher is not None:
            launcher.bind_metrics(self.metrics)
        # admission-gate meters/gauges (server/admission.py)
        admission = getattr(self.executor, "admission", None)
        if admission is not None:
            admission.bind_metrics(self.metrics)
        # path-decision ledger -> /metrics: every decline of a faster
        # rung becomes a cell of the labeled decision_declined_total family
        from pinot_tpu.common.tracing import LEDGER

        LEDGER.bind_metrics(self.metrics)
        # continuous telemetry: export the histogram/SLO families on this
        # server's /metrics, give the flight recorder this instance's
        # scheduler/memory state, and ring-track the scheduler queue depth
        from pinot_tpu.common.telemetry import TELEMETRY

        TELEMETRY.configure(config)
        self.metrics.bind_telemetry(TELEMETRY)
        TELEMETRY.recorder.register_provider("scheduler",
                                             self.scheduler_debug)
        TELEMETRY.track_gauge(
            f"scheduler.queue_depth.{instance_id}",
            lambda: float(self.scheduler.queue_depth()))
        self.segment_dir = segment_dir
        self.consumer_tick_s = consumer_tick_s
        self._started = False
        self._queries_enabled = False
        self._reconcile_lock = threading.RLock()
        self._upsert_managers: Dict[str, object] = {}  # guarded-by: _reconcile_lock

    # -- lifecycle (ref: BaseServerStarter.start) ---------------------------
    def start(self, heartbeat_interval_s: float = 0.0) -> None:
        from pinot_tpu.spi.environment import get_environment_provider

        # a RESTART must not wipe operator-set tenant tags (PUT updateTags):
        # re-registration carries the stored tags forward
        prior = self.store.get_instance(self.instance_id)
        self.store.register_instance(
            InstanceInfo(self.instance_id, "SERVER", port=0,
                         tags=(prior.tags if prior is not None
                               else ["DefaultTenant"]),
                         failure_domain=get_environment_provider()
                         .failure_domain()))
        # replay current assignments, then watch for changes (the Helix
        # participant registration + state-transition replay)
        self.store.watch("idealstate/", self._on_ideal_state_change)
        self.store.watch("reloadrequests/", self._on_reload_request)
        for path in self.store.children("idealstate"):
            table = path.split("/", 1)[1]
            self._reconcile_table(table)
        self._started = True
        self._queries_enabled = True
        if heartbeat_interval_s > 0:
            # the ephemeral-znode keepalive: the controller's liveness
            # check marks us dead when these stop
            self._hb_stop = threading.Event()

            def beat():
                while not self._hb_stop.wait(heartbeat_interval_s):
                    try:
                        self.store.touch_instance(self.instance_id)
                    except Exception:
                        log.exception("[%s] heartbeat failed",
                                      self.instance_id)

            self.store.touch_instance(self.instance_id)
            self._hb_thread = threading.Thread(
                target=beat, daemon=True,
                name=f"heartbeat-{self.instance_id}")
            self._hb_thread.start()

    def shutdown(self) -> None:
        """Ref: shutdown = disable queries, drain, unregister."""
        self._queries_enabled = False
        hb = getattr(self, "_hb_stop", None)
        if hb is not None:
            hb.set()
            # join BEFORE marking dead: an in-flight touch_instance would
            # resurrect the instance (touch sets alive=True)
            self._hb_thread.join(timeout=5)
        self.scheduler.shutdown()
        self.data_manager.shutdown()
        close = getattr(self.executor, "close", None)
        if close is not None:
            close()
        residency = getattr(self.executor, "residency", None)
        if residency is not None:
            residency.close()
        self.store.set_instance_alive(self.instance_id, False)

    # -- segment lifecycle -> HBM residency (data-manager listener) ----------
    def segment_added(self, table: str, segment) -> None:
        """Prefetch hook: stage new/reloaded immutable segments in the
        background so the table's first query pays no H2D (residency skips
        mutable segments and stops at the budget instead of evicting).
        When the added segment is the sealed replacement of a consuming
        one, the mutable resident's chunks are dead weight — evict them
        (in-flight queries keep their snapshot via python refs)."""
        residency = getattr(self.executor, "residency", None)
        if residency is None:
            return
        if not getattr(segment, "is_mutable", False):
            from pinot_tpu.engine.mutable_staging import resident_name

            residency.evict(resident_name(segment.segment_name))
        residency.prefetch(segment)

    def segment_removed(self, table: str, segment_name: str) -> None:
        """Eviction hook: an unassigned segment's HBM must be reclaimed —
        refcounts protect in-flight readers, the residency entry must go."""
        evict = getattr(self.executor, "evict_segment", None)
        if evict is not None:
            evict(segment_name)

    def _upsert_manager_for_locked(self, table: str):
        """TableUpsertMetadataManager for upsert-enabled realtime tables
        (ref: TableUpsertMetadataManager creation in RealtimeTableDataManager)."""
        if table in self._upsert_managers:
            return self._upsert_managers[table]
        from pinot_tpu.spi.table import UpsertMode

        cfg = self.store.get_table_config(table)
        if cfg is None:
            # config not visible yet: decide on a later reconcile instead of
            # caching a permanent 'no upsert'
            return None
        mgr = None
        if cfg.upsert_config is not None \
                and cfg.upsert_config.mode is not UpsertMode.NONE:
            schema = self.store.get_schema(cfg.table_name)
            if schema is None:
                return None  # schema lag: retry on the next reconcile
            if schema.primary_key_columns:
                from pinot_tpu.segment.upsert import TableUpsertMetadataManager

                cmp_col = (cfg.upsert_config.comparison_column
                           or cfg.validation_config.time_column_name)
                mgr = TableUpsertMetadataManager(
                    schema.primary_key_columns, cmp_col,
                    cfg.upsert_config.mode)
        self._upsert_managers[table] = mgr
        return mgr

    # -- state transitions ---------------------------------------------------
    def _on_ideal_state_change(self, path: str, value) -> None:
        if not self._started:
            return
        table = path.split("/", 1)[1]
        try:
            self._reconcile_table(table)
        except Exception:
            log.exception("[%s] reconcile failed for %s",
                          self.instance_id, table)

    def _reconcile_table(self, table: str) -> None:
        with self._reconcile_lock:
            self._reconcile_table_locked(table)

    def _reconcile_table_locked(self, table: str) -> None:
        ideal = self.store.get_ideal_state(table)
        realtime = table_type_from_name(table) is TableType.REALTIME
        tdm = self.data_manager.get_or_create(
            table, realtime=realtime,
            upsert_manager=self._upsert_manager_for_locked(table) if realtime
            else None)

        my_segments = {seg: states[self.instance_id]
                       for seg, states in ideal.items()
                       if self.instance_id in states}

        # drop segments no longer assigned to me
        for seg in tdm.segment_names():
            if seg not in my_segments:
                tdm.remove_segment(seg)
                self.store.report_instance_state(table, seg,
                                                 self.instance_id, "OFFLINE")

        for seg, target in my_segments.items():
            if target == ONLINE:
                self._ensure_online(table, tdm, seg)
            elif target == CONSUMING:
                self._ensure_consuming(table, tdm, seg)

    def _ensure_online(self, table: str, tdm, seg: str) -> None:
        if isinstance(tdm, RealtimeTableDataManager):
            mgr = tdm.consuming_manager(seg)
            if mgr is not None:
                # CONSUMING -> ONLINE flip arrived before the local consumer
                # finished; its terminal callback completes the swap
                return
        if tdm.has_segment(seg):
            return
        md = self.store.get_segment_metadata(table, seg)
        if md is None or not md.download_url:
            log.warning("[%s] no download url for %s/%s",
                        self.instance_id, table, seg)
            return
        # deep-store resolution through the PinotFS registry (ref:
        # downloadSegmentFromDeepStore, BaseTableDataManager.java:388) —
        # local URIs serve in place, remote schemes materialize under the
        # server's segment dir
        from pinot_tpu.spi.filesystem import fetch_segment

        try:
            local = fetch_segment(md.download_url,
                                  os.path.join(self.segment_dir, table))
        except Exception:
            log.exception("[%s] deep-store fetch failed for %s/%s (%s)",
                          self.instance_id, table, seg, md.download_url)
            return
        if isinstance(tdm, RealtimeTableDataManager):
            # upsert tables must register downloaded keys (on_sealed handles
            # both the upsert and plain realtime cases)
            tdm.on_sealed(seg, local, partition=md.partition)
        else:
            tdm.add_segment_from_dir(local)
        self.store.report_instance_state(table, seg, self.instance_id, ONLINE)

    def _ensure_consuming(self, table: str, tdm, seg: str) -> None:
        assert isinstance(tdm, RealtimeTableDataManager), table
        if tdm.consuming_manager(seg) is not None or tdm.has_segment(seg):
            return
        cfg = self.store.get_table_config(table)
        schema = self.store.get_schema(cfg.table_name)
        md = self.store.get_segment_metadata(table, seg)
        if cfg is None or schema is None or md is None:
            log.warning("[%s] missing config for consuming %s/%s",
                        self.instance_id, table, seg)
            return
        start = StreamOffset.parse(md.start_offset or "0")

        mgr = RealtimeSegmentDataManager(
            seg, cfg, schema, partition=md.partition or 0,
            start_offset=start, protocol=self.completion_protocol,
            instance_id=self.instance_id,
            output_dir=f"{self.segment_dir}/{self.instance_id}/{table}",
            on_terminal=lambda m, t=table, td=tdm: self._on_consumer_done(
                t, td, m))
        tdm.add_consuming(mgr)
        self.store.report_instance_state(table, seg, self.instance_id,
                                         CONSUMING)
        mgr.start(tick_seconds=self.consumer_tick_s)

    def _on_consumer_done(self, table: str, tdm, mgr) -> None:
        """Terminal consumer states (ref: CONSUMING->ONLINE transition +
        the KEEP/DISCARD commit-protocol outcomes)."""
        seg = mgr.segment_name
        if tdm.consuming_manager(seg) is not mgr:
            # unassigned (or replaced) while finishing: do not resurrect
            return
        try:
            if mgr.state is ConsumerState.COMMITTED:
                tdm.on_sealed(seg, mgr._committed_dir)
            elif mgr.state is ConsumerState.RETAINING:
                # KEEP: build locally at the committed offset, swap in place
                md, seg_dir = mgr.build_segment()
                tdm.on_sealed(seg, seg_dir)
            elif mgr.state is ConsumerState.DISCARDED:
                zk = self.store.get_segment_metadata(table, seg)
                if zk and zk.download_url:
                    # same PinotFS resolution as _ensure_online (http(s)
                    # deep stores must materialize locally here too)
                    from pinot_tpu.spi.filesystem import fetch_segment

                    local = fetch_segment(
                        zk.download_url,
                        os.path.join(self.segment_dir, table))
                    tdm.on_sealed(seg, local)
                else:
                    # winner's metadata not visible yet: drop the consumer
                    # entry so a later reconcile can download it ONLINE
                    tdm.drop_consumer(seg)
                    tdm.remove_segment(seg)
                    return
            else:  # ERROR
                log.error("[%s] consumer for %s ended in %s",
                          self.instance_id, seg, mgr.state)
                return
            self.store.report_instance_state(table, seg, self.instance_id,
                                             ONLINE)
            # pick up the successor CONSUMING segment promptly
            self._reconcile_table(table)
        except Exception:
            log.exception("[%s] seal handling failed for %s",
                          self.instance_id, seg)

    # -- reload (ref: SegmentMessageHandlerFactory refresh/reload) ----------
    def _on_reload_request(self, path: str, _value) -> None:
        table = path.split("/", 1)[-1]
        tdm = self.data_manager.get(table)
        if tdm is None:
            return
        cfg = self.store.get_table_config(table)
        if cfg is None:
            return
        from pinot_tpu.segment.preprocessor import reload_segment

        acquired = tdm.acquire_segments(None)
        try:
            for holder in acquired:
                seg = holder.segment
                if getattr(seg, "is_mutable", False):
                    continue  # consuming segments rebuild indexes at seal
                try:
                    added = reload_segment(tdm, seg, cfg.indexing_config)
                    if added:
                        log.info("[%s] reloaded %s/%s: %s",
                                 self.instance_id, table,
                                 seg.segment_name, added)
                except Exception:
                    log.exception("[%s] reload failed for %s/%s",
                                  self.instance_id, table, seg.segment_name)
        finally:
            tdm.release_segments(acquired)

    # -- query path (ref: InstanceRequestHandler.channelRead0:90) -----------
    def execute_query(self, ctx: QueryContext, table: str,
                      segment_names: Optional[List[str]] = None) -> DataTable:
        if not self._queries_enabled:
            return DataTable.for_exception(
                f"server {self.instance_id} is shut down")
        submit_t = time.perf_counter()
        # the shape key feeds the SEWF policy's per-shape latency EWMAs:
        # same table + same SQL text = same expected work
        future = self.scheduler.submit(
            lambda: self._execute(ctx, table, segment_names, submit_t),
            table=table, shape=(table, ctx.sql))
        return future.result()

    def _execute(self, ctx: QueryContext, table: str,
                 segment_names: Optional[List[str]],
                 submit_t: float) -> DataTable:
        from pinot_tpu.spi.metrics import ServerMeter, ServerQueryPhase

        wait_ms = (time.perf_counter() - submit_t) * 1e3
        self.metrics.timer(ServerQueryPhase.SCHEDULER_WAIT).update_ms(wait_ms)
        self.metrics.meter(ServerMeter.QUERIES).mark()
        tdm = self.data_manager.get(table)
        if tdm is None:
            self.metrics.meter(ServerMeter.QUERY_EXCEPTIONS).mark()
            return DataTable.for_exception(
                f"table {table} not hosted on {self.instance_id}")
        acquired = tdm.acquire_segments(segment_names)
        t0 = time.perf_counter()
        try:
            segments = [s.segment for s in acquired]
            if not segments:
                self.metrics.meter(ServerMeter.QUERY_EXCEPTIONS).mark()
                return DataTable.for_exception(
                    f"no segments of {table} on {self.instance_id}")
            dt = self.executor.execute_instance(ctx, segments)
            exec_ms = (time.perf_counter() - t0) * 1e3
            # phase timings travel in the DataTable stats (ref: the
            # TimerContext values at ServerQueryExecutorV1Impl:122-303)
            dt.stats.add_phase_ms(ServerQueryPhase.SCHEDULER_WAIT, wait_ms)
            dt.stats.add_phase_ms(ServerQueryPhase.QUERY_EXECUTION, exec_ms)
            if dt.stats.spans:
                # scheduler-queue wait happened before the executor's
                # span tree opened; retroactively attribute it as the
                # root's FIRST child (pure queue time) so the tree
                # accounts the full server-side lifecycle
                from pinot_tpu.common.tracing import attach_root_child

                attach_root_child(dt.stats, "SchedulerQueue",
                                  wall_ms=wait_ms, queue_ms=wait_ms,
                                  front=True)
            self.metrics.timer(
                ServerQueryPhase.QUERY_EXECUTION).update_ms(exec_ms)
            self.metrics.meter(ServerMeter.DOCS_SCANNED).mark(
                dt.stats.num_docs_scanned)
            self.metrics.meter(ServerMeter.SEGMENTS_PRUNED).mark(
                dt.stats.num_segments_pruned)
            return dt
        except Exception as e:  # query errors travel in the DataTable
            log.debug("[%s] query failed", self.instance_id, exc_info=True)
            self.metrics.meter(ServerMeter.QUERY_EXCEPTIONS).mark()
            return DataTable.for_exception(str(e))
        finally:
            tdm.release_segments(acquired)

    def execute_query_streaming(self, ctx: QueryContext, table: str,
                                segment_names: Optional[List[str]] = None):
        """Selection queries stream one DataTable block PER SEGMENT (ref:
        StreamingSelectionOnlyOperator feeding GrpcQueryServer.submit) so
        the broker can stop pulling once LIMIT rows arrived. Generator of
        DataTables; non-selection shapes yield the single combined block."""
        if not self._queries_enabled:
            yield DataTable.for_exception(
                f"server {self.instance_id} is shut down")
            return
        if not ctx.is_selection:
            yield self.execute_query(ctx, table, segment_names)
            return
        tdm = self.data_manager.get(table)
        if tdm is None:
            yield DataTable.for_exception(
                f"table {table} not hosted on {self.instance_id}")
            return
        acquired = tdm.acquire_segments(segment_names)
        try:
            if not acquired:
                yield DataTable.for_exception(
                    f"no segments of {table} on {self.instance_id}")
                return
            # prune ONCE across the acquired set: the per-segment
            # execute_instance would otherwise keep-one-fallback every
            # prunable segment into a scan
            from pinot_tpu.engine.pruner import prune_segments

            kept = prune_segments(
                ctx, [h.segment for h in acquired]) or \
                [acquired[0].segment]
            for segment in kept:
                yield self.executor.execute_instance(ctx, [segment])
        except Exception as e:  # noqa: BLE001 — errors travel in-band
            log.debug("[%s] streaming query failed", self.instance_id,
                      exc_info=True)
            yield DataTable.for_exception(str(e))
        finally:
            tdm.release_segments(acquired)

    # -- admin (ref: TablesResource) ----------------------------------------
    def hosted_tables(self) -> List[str]:
        return self.data_manager.table_names()

    def hosted_segments(self, table: str) -> List[str]:
        tdm = self.data_manager.get(table)
        return tdm.segment_names() if tdm else []

    def table_size(self, table: str) -> Dict[str, Any]:
        """On-disk bytes per hosted segment (ref: TableSizeResource);
        segments that vanish mid-walk are omitted, not reported as 0."""
        tdm = self.data_manager.get(table)
        if tdm is None:
            return {"tableName": table, "segments": {}, "totalBytes": 0}
        sizes: Dict[str, int] = {}
        for name in tdm.segment_names():
            seg = None
            acquired = tdm.acquire_segments([name])
            if not acquired:
                continue  # deleted concurrently: omit (ref: missing segs)
            try:
                seg_dir = getattr(acquired[0].segment, "segment_dir", None)
                total = 0
                if seg_dir and os.path.isdir(seg_dir):
                    for root, _dirs, files in os.walk(seg_dir):
                        total += sum(
                            os.path.getsize(os.path.join(root, f))
                            for f in files)
                sizes[name] = total
            finally:
                tdm.release_segments(acquired)
        return {"tableName": table, "segments": sizes,
                "totalBytes": sum(sizes.values())}

    def evict_staged(self, segment_name: str) -> Dict[str, Any]:
        """Admin force-eviction of one staged resident (REST
        ``POST /debug/memory/evict/<name>``); reports what remains."""
        evict = getattr(self.executor, "evict_segment", None)
        if evict is not None:
            evict(segment_name)
        residency = getattr(self.executor, "residency", None)
        return {"evicted": segment_name,
                "stagedBytes": (residency.staged_bytes()
                                if residency is not None else 0)}

    def demote_staged(self, name: str) -> Dict[str, Any]:
        """Admin force-demotion of one resident to the host-RAM tier
        (REST ``POST /debug/memory/demote/<name>``): its device arrays
        D2H-snapshot into the host tier and the next query promotes them
        with a plain H2D instead of rebuilding. Refused (demoted=False)
        when the resident is pinned by an in-flight query."""
        residency = getattr(self.executor, "residency", None)
        if residency is None:
            return {"demoted": False, "reason": "no residency manager"}
        ok = residency.demote(name)
        return {"demoted": bool(ok), "name": name,
                "stagedBytes": residency.staged_bytes(),
                "hostBytes": residency.host_bytes()}

    def launch_debug(self) -> Dict[str, Any]:
        """Launch-coalescing state for ``GET /debug/launches``: requests vs
        device launches, coalesced/deduped/batched counts, queue waits, and
        the live dispatcher queue depth (empty for host-only executors)."""
        launcher = getattr(self.executor, "launcher", None)
        if launcher is None:
            return {"enabled": False}
        out: Dict[str, Any] = {"enabled": True}
        out.update(launcher.snapshot())
        return out

    def scheduler_debug(self) -> Dict[str, Any]:
        """Scheduler-tier state for ``GET /debug/scheduler``: dispatch
        policy + queue depth, admission bounds/counters, the launch
        dispatcher's adaptive-window state, and the per-segment kernel
        single-flight counters — the millions-of-users ops view."""
        out: Dict[str, Any] = {"scheduler": self.scheduler.stats_snapshot()}
        admission = getattr(self.executor, "admission", None)
        if admission is not None:
            out["admission"] = admission.snapshot()
        launcher = getattr(self.executor, "launcher", None)
        if launcher is not None:
            snap = launcher.snapshot()
            out["launchWindow"] = {
                k: snap.get(k) for k in
                ("windowMaxMs", "windowHotMs", "arrivalEwmaMs",
                 "windowWaits", "windowGathered", "windowLastMs",
                 "queued")}
        flight = getattr(self.executor, "_kernel_flight", None)
        if flight is not None:
            out["kernelFlight"] = flight.snapshot()
        qflight = getattr(self.executor, "_query_flight", None)
        if qflight is not None:
            out["queryFlight"] = qflight.snapshot()
        return out

    def queries_debug(self) -> Dict[str, Any]:
        """``GET /debug/queries``: currently-running queries (id, sql,
        phase, elapsed, pins held), the completed ring buffer, and the
        slow-query log — full span trees retained for over-threshold
        queries even when trace/sampling missed them
        (``pinot.server.query.slow.threshold.ms``)."""
        registry = getattr(self.executor, "queries", None)
        if registry is None:
            return {"enabled": False}
        out: Dict[str, Any] = {"instance": self.instance_id}
        out.update(registry.snapshot())
        return out

    def telemetry_debug(self) -> Dict[str, Any]:
        """``GET /debug/telemetry``: the continuous-telemetry view —
        windowed (table, phase) latency histograms with sliding AND
        lifetime quantiles, plus the gauge-history rings (staged/host
        bytes, queue depths, arrival EWMA, rejection counters)."""
        from pinot_tpu.common.telemetry import TELEMETRY

        return TELEMETRY.snapshot()

    def slo_debug(self) -> Dict[str, Any]:
        """``GET /debug/slo``: per-table latency/error objectives + the
        short/long-window burn rates."""
        from pinot_tpu.common.telemetry import TELEMETRY

        return TELEMETRY.slo_snapshot()

    def freshness_debug(self) -> Dict[str, Any]:
        """``GET /debug/freshness``: per-table ingest-to-queryable
        histograms (each sample: one row's append -> first covering
        watermark) + the freshness objective/burn when configured."""
        from pinot_tpu.common.telemetry import TELEMETRY

        return TELEMETRY.freshness_snapshot()

    def flightrecorder_debug(self) -> Dict[str, Any]:
        """``GET /debug/flightrecorder``: the black box — frozen bundle
        index, the last post-mortem bundle, live ring occupancy, and the
        anomaly-event totals."""
        from pinot_tpu.common.telemetry import TELEMETRY

        return TELEMETRY.recorder.snapshot()

    def pallas_debug(self) -> Dict[str, Any]:
        """``GET /debug/pallas``: the per-shape blocklist (spec + the
        reason each shape declines with — ``pallas_shape_blocked`` for
        runtime lowering failures, ``pallas_preflight_<rule>`` for
        preflight-seeded predictions) plus the last preflight verdict
        table run against this executor (tools/preflight.py). A chip
        that fell over mid-round keeps its lessons visible here — and,
        with ``pinot.server.query.pallas.blocklist.path`` set, across
        restarts."""
        bl = getattr(self.executor, "_pallas_blocked", None)
        out: Dict[str, Any] = {
            "blocklist": bl.snapshot() if hasattr(bl, "snapshot") else [],
            "blockedShapes": len(bl) if bl is not None else 0,
        }
        path = getattr(bl, "_path", None)
        if path:
            out["blocklistPath"] = path
        verdicts = getattr(self.executor, "preflight_verdicts", None)
        out["preflight"] = verdicts if verdicts is not None else {
            "run": False}
        return out

    def memory_debug(self) -> Dict[str, Any]:
        """Bytes-accurate HBM residency + native mmap accounting
        (ref: MmapDebugResource). Per resident: device bytes, pin count,
        staged column/packed/value array counts; plus the budget, fleet
        total/peak, and the hit/miss/eviction/spill counters."""
        from pinot_tpu import native

        out: Dict[str, Any] = {"stagedSegments": {}}
        residency = getattr(self.executor, "residency", None)
        if residency is not None:
            out.update(residency.snapshot())
        out["nativeMmapBuffers"] = native.mmap_buffer_count()
        return out
