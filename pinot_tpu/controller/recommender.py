"""Config recommendation engine: workload -> indexing suggestions.

Re-design of the reference's rule-based recommender
(``pinot-controller/.../recommender/`` — ~60 classes of rules run by
RecommenderDriver over a RuleEngine InputManager): a compact rule set over
a parsed query workload + schema. Each rule inspects predicate/group-by
frequencies extracted from the SQL (the InputManager's "FixedLenBitset"
per-column usage maps collapse to plain Counters here) and emits config
fragments with human-readable reasons.

Rules (reference analogues):
- inverted index   <- frequent EQ/IN/range dict-column filters
  (InvertedSortedIndexJointRule)
- sorted column    <- the single most filtered column
- bloom filter     <- selective EQ filters (BloomFilterRule)
- range index      <- RANGE predicates on raw numeric columns
  (RangeIndexRule)
- no-dictionary    <- metric columns never filtered/grouped
  (NoDictionaryOnHeapDictionaryJointRule)
- json/text index  <- JSON_MATCH / TEXT_MATCH usage
- partitioning     <- dominant single-column EQ workloads
  (KafkaPartitionRule / SegmentPartitionRule flavor)
- star-tree        <- recurring (group-by set, aggregation) shapes
  (AggregateMetricsRule + star-tree generation)
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.query.context import QueryContext, compile_query
from pinot_tpu.query.expressions import (
    FilterNode,
    FilterOp,
    Identifier,
    PredicateType,
)
from pinot_tpu.spi.data import FieldType, Schema

# workload-share thresholds (the reference tunes these per rule; one knob
# per rule keeps the engine inspectable)
INVERTED_MIN_SHARE = 0.2
BLOOM_MIN_SHARE = 0.3
PARTITION_MIN_SHARE = 0.5
STARTREE_MIN_SHARE = 0.3


def _walk_predicates(node: Optional[FilterNode]):
    if node is None:
        return
    if node.op in (FilterOp.AND, FilterOp.OR, FilterOp.NOT):
        for c in node.children:
            yield from _walk_predicates(c)
        return
    yield node.predicate


class WorkloadStats:
    """Per-column usage counters over the parsed workload
    (the InputManager analogue)."""

    def __init__(self):
        self.num_queries = 0
        self.eq_filters = Counter()      # EQ/IN
        self.range_filters = Counter()
        self.regex_filters = Counter()
        self.text_filters = Counter()
        self.json_filters = Counter()
        self.group_by_sets = Counter()   # frozenset of group columns
        self.group_by_cols = Counter()
        self.agg_pairs = Counter()       # (fn, column) on group-by queries
        self.selected = Counter()        # any reference at all

    def add(self, ctx: QueryContext) -> None:
        self.num_queries += 1
        for col in ctx.referenced_columns():
            self.selected[col] += 1
        for p in _walk_predicates(ctx.filter):
            if not isinstance(p.lhs, Identifier):
                continue
            col = p.lhs.name
            if p.type in (PredicateType.EQ, PredicateType.IN):
                self.eq_filters[col] += 1
            elif p.type is PredicateType.RANGE:
                self.range_filters[col] += 1
            elif p.type is PredicateType.REGEXP_LIKE:
                self.regex_filters[col] += 1
            elif p.type is PredicateType.TEXT_MATCH:
                self.text_filters[col] += 1
            elif p.type is PredicateType.JSON_MATCH:
                self.json_filters[col] += 1
        if ctx.group_by:
            cols = tuple(sorted(e.name for e in ctx.group_by
                                if isinstance(e, Identifier)))
            if cols:
                self.group_by_sets[cols] += 1
                for c in cols:
                    self.group_by_cols[c] += 1
            for fn in ctx.aggregations:
                from pinot_tpu.engine.aggregates import agg_value_expr

                v = agg_value_expr(fn)
                if v is None:
                    col = "*"  # count(*)
                elif isinstance(v, Identifier) and not v.name.startswith("$"):
                    col = v.name
                else:
                    continue  # expression arg: not a star-tree metric pair
                # scoped to the group set: pairs from OTHER group-bys must
                # not leak into a tree recommended for this set
                self.agg_pairs[(cols, fn.name.upper(), col)] += 1


def recommend(schema: Schema, queries: List[str],
              qps: float = 0.0) -> Dict[str, Any]:
    """-> {"recommendations": {...config fragments...},
    "reasons": [...], "skipped": [unparseable sql]} ."""
    stats = WorkloadStats()
    skipped: List[str] = []
    for sql in queries:
        try:
            stats.add(compile_query(sql))
        except Exception:
            skipped.append(sql)
    n = max(stats.num_queries, 1)
    dims = {fs.name for fs in schema.field_specs
            if fs.field_type is not FieldType.METRIC}
    metrics = {fs.name for fs in schema.field_specs
               if fs.field_type is FieldType.METRIC}
    known = {fs.name for fs in schema.field_specs}

    rec: Dict[str, Any] = {}
    reasons: List[str] = []

    # inverted index + sorted column (InvertedSortedIndexJointRule)
    inv = [c for c, k in stats.eq_filters.most_common()
           if k / n >= INVERTED_MIN_SHARE and c in dims]
    if inv:
        sorted_col, rest = inv[0], inv[1:]
        rec["sortedColumn"] = [sorted_col]
        reasons.append(f"{sorted_col}: most-filtered column "
                       f"({stats.eq_filters[sorted_col]}/{n} queries) "
                       f"-> sorted column")
        if rest:
            rec["invertedIndexColumns"] = rest
            reasons.append(f"{rest}: EQ/IN filtered in >="
                           f"{INVERTED_MIN_SHARE:.0%} of queries "
                           f"-> inverted index")

    # bloom filters on selective EQ columns
    bloom = [c for c, k in stats.eq_filters.items()
             if k / n >= BLOOM_MIN_SHARE and c in known]
    if bloom:
        rec["bloomFilterColumns"] = sorted(bloom)
        reasons.append(f"{sorted(bloom)}: frequent EQ filters -> bloom "
                       "filter enables server-side segment pruning")

    # range index on numeric range-filtered columns
    rng = [c for c, k in stats.range_filters.items() if c in known]
    if rng:
        rec["rangeIndexColumns"] = sorted(rng)
        reasons.append(f"{sorted(rng)}: RANGE predicates -> range index")

    # text/json/fst indexes
    if stats.text_filters:
        rec["textIndexColumns"] = sorted(stats.text_filters)
        reasons.append(f"{sorted(stats.text_filters)}: TEXT_MATCH -> "
                       "tokenized text index")
    if stats.json_filters:
        rec["jsonIndexColumns"] = sorted(stats.json_filters)
        reasons.append(f"{sorted(stats.json_filters)}: JSON_MATCH -> "
                       "JSON flattening index")
    if stats.regex_filters:
        rec["fstIndexColumns"] = sorted(stats.regex_filters)
        reasons.append(f"{sorted(stats.regex_filters)}: REGEXP_LIKE -> "
                       "FST prefix index")

    # no-dictionary for unfiltered, ungrouped metrics
    nodict = [m for m in sorted(metrics)
              if not stats.eq_filters[m] and not stats.range_filters[m]
              and not stats.group_by_cols[m]]
    if nodict:
        rec["noDictionaryColumns"] = nodict
        reasons.append(f"{nodict}: metrics never filtered/grouped -> raw "
                       "encoding (saves the dictionary + gather)")

    # partitioning for dominant single-column EQ workloads at QPS
    part = [c for c, k in stats.eq_filters.items()
            if k / n >= PARTITION_MIN_SHARE and c in dims]
    if part and qps >= 100:
        col = part[0]
        rec["segmentPartitionConfig"] = {
            "columnPartitionMap": {col: {"functionName": "Murmur",
                                         "numPartitions": 8}}}
        reasons.append(f"{col}: EQ-filtered in >={PARTITION_MIN_SHARE:.0%} "
                       f"of a {qps:.0f}-QPS workload -> Murmur partitioning "
                       "for broker partition pruning")

    # star-tree for a recurring (group set, SUM/COUNT aggregations) shape
    if stats.group_by_sets:
        (top_set, hits) = stats.group_by_sets.most_common(1)[0]
        if hits / n >= STARTREE_MIN_SHARE:
            pairs = sorted({f"{fn}__{col}" for (gset, fn, col), k
                            in stats.agg_pairs.items()
                            if gset == top_set
                            and fn in ("SUM", "COUNT", "MIN", "MAX")})
            if pairs:
                rec["starTreeIndexConfigs"] = [{
                    "dimensionsSplitOrder": list(top_set),
                    "functionColumnPairs": pairs,
                    "maxLeafRecords": 10_000}]
                reasons.append(
                    f"group-by {list(top_set)} appears in {hits}/{n} "
                    f"queries with {pairs} -> star-tree pre-aggregation")

    return {"recommendations": rec, "reasons": reasons, "skipped": skipped,
            "numQueriesParsed": stats.num_queries}
