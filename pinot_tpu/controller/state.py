"""Cluster state store: the Helix/ZooKeeper role.

Re-design of the reference's control plane (Apache Helix on ZK,
SURVEY.md §1 cross-cutting): a strongly-consistent in-process property store
holding schemas, table configs, segment metadata (the ``SegmentZKMetadata``
analogue), IdealState / ExternalView maps, and the instance registry — with
path-prefix watches so brokers/servers react to changes the way Helix
spectators/participants react to ZK callbacks. Snapshot persistence gives
the ZK durability property for single-host deployments; multi-host
deployments put this store behind the gRPC control service.

All mutations are serialized under one lock and bump a monotonically
increasing version (the ZK zxid analogue); watchers fire outside the lock
in mutation order (ref: ClusterChangeMediator dedup/serialize behavior).
"""

from __future__ import annotations

import json
import os
import threading

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from pinot_tpu.spi.data import Schema
from pinot_tpu.spi.table import TableConfig


# segment states in IdealState/ExternalView
# (ref: SegmentOnlineOfflineStateModelFactory.java:53)
ONLINE = "ONLINE"
CONSUMING = "CONSUMING"
OFFLINE = "OFFLINE"
ERROR = "ERROR"


@dataclass
class SegmentZKMetadata:
    """Ref: pinot-common/.../metadata/segment/SegmentZKMetadata."""

    segment_name: str
    table_name: str  # with type suffix
    status: str = ONLINE              # ONLINE | CONSUMING | OFFLINE
    download_url: str = ""            # deep-store location
    crc: int = 0
    creation_time_ms: int = 0
    push_time_ms: int = 0
    start_time: Optional[int] = None  # time-column units
    end_time: Optional[int] = None
    total_docs: int = 0
    # realtime (LLC) checkpoint
    start_offset: Optional[str] = None
    end_offset: Optional[str] = None
    partition: Optional[int] = None
    sequence: Optional[int] = None
    # column -> {functionName, numPartitions, partitions} for broker-side
    # partition pruning (ref: SegmentZKMetadata partitionMetadata)
    partition_metadata: Dict[str, Any] = field(default_factory=dict)
    custom: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "segmentName": self.segment_name,
            "tableName": self.table_name,
            "status": self.status,
            "downloadUrl": self.download_url,
            "crc": self.crc,
            "creationTimeMs": self.creation_time_ms,
            "pushTimeMs": self.push_time_ms,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "totalDocs": self.total_docs,
            "startOffset": self.start_offset,
            "endOffset": self.end_offset,
            "partition": self.partition,
            "sequence": self.sequence,
            "partitionMetadata": self.partition_metadata,
            "custom": self.custom,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SegmentZKMetadata":
        return cls(
            segment_name=d["segmentName"], table_name=d["tableName"],
            status=d.get("status", ONLINE),
            download_url=d.get("downloadUrl", ""), crc=d.get("crc", 0),
            creation_time_ms=d.get("creationTimeMs", 0),
            push_time_ms=d.get("pushTimeMs", 0),
            start_time=d.get("startTime"), end_time=d.get("endTime"),
            total_docs=d.get("totalDocs", 0),
            start_offset=d.get("startOffset"), end_offset=d.get("endOffset"),
            partition=d.get("partition"), sequence=d.get("sequence"),
            partition_metadata=d.get("partitionMetadata", {}),
            custom=d.get("custom", {}),
        )


@dataclass
class InstanceInfo:
    """Ref: Helix InstanceConfig + LiveInstance."""

    instance_id: str
    instance_type: str          # BROKER | SERVER | CONTROLLER | MINION
    host: str = "localhost"
    port: int = 0
    tags: List[str] = field(default_factory=lambda: ["DefaultTenant"])
    alive: bool = True
    # last heartbeat (ms since epoch); the ephemeral-znode liveness analogue
    heartbeat_ms: int = 0
    # fault-domain label from the environment provider SPI
    # (spi/environment.py; ref: AzureEnvironmentProvider platformFaultDomain)
    failure_domain: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"instanceId": self.instance_id,
                "type": self.instance_type, "host": self.host,
                "port": self.port, "tags": self.tags, "alive": self.alive,
                "heartbeatMs": self.heartbeat_ms,
                "failureDomain": self.failure_domain}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "InstanceInfo":
        return cls(d["instanceId"], d["type"], d.get("host", "localhost"),
                   d.get("port", 0), d.get("tags", ["DefaultTenant"]),
                   d.get("alive", True), d.get("heartbeatMs", 0),
                   d.get("failureDomain"))


Watcher = Callable[[str, Any], None]


class ClusterStateStore:
    """The single source of truth for cluster metadata.

    Paths (ZK-layout analogue):
      schemas/<name>, tables/<nameWithType>,
      segments/<table>/<segment>           (SegmentZKMetadata),
      idealstate/<table>                   ({segment: {instance: state}}),
      externalview/<table>,
      instances/<id>
    """

    def __init__(self, snapshot_path: Optional[str] = None):
        self._lock = threading.RLock()
        self._data: Dict[str, Any] = {}
        self._version = 0
        self._watchers: List[Tuple[str, Watcher]] = []  # guarded-by: _lock
        self._snapshot_path = snapshot_path
        # mutation-ordered notification queue drained under _notify_lock so
        # watchers observe updates in version order even when mutators race
        # (the ClusterChangeMediator serialization property)
        self._pending: List[Tuple[str, Any]] = []
        # bounded mutation log for remote-replica polling
        self._mutation_log: List[Tuple[int, str, Any]] = []
        # RLock: a watcher may mutate the store, re-entering the drain
        self._notify_lock = threading.RLock()
        if snapshot_path and os.path.isfile(snapshot_path):
            with open(snapshot_path) as f:
                payload = json.load(f)
            self._data = payload["data"]
            self._version = payload["version"]

    @staticmethod
    def _copy(v: Any) -> Any:
        return json.loads(json.dumps(v)) if isinstance(v, (dict, list)) else v

    # -- raw property store --------------------------------------------------
    def get(self, path: str, default: Any = None) -> Any:
        with self._lock:
            v = self._data.get(path, default)
        return self._copy(v)

    def set(self, path: str, value: Any) -> int:
        value = self._copy(value)  # detach from the caller's object
        with self._lock:
            self._data[path] = value
            self._version += 1
            v = self._version
            self._log_locked(path, value)
            self._persist_locked()
            self._pending.append((path, value))
        self._drain_notifications()
        return v

    def compare_and_set(self, path: str, expected: Any, value: Any) -> bool:
        """CAS on the current value — the remote-store client's atomic
        update primitive (the ZK setData-with-version analogue)."""
        value = self._copy(value)
        with self._lock:
            cur = self._data.get(path)
            if cur != expected:
                return False
            self._data[path] = value
            self._version += 1
            self._log_locked(path, value)
            self._persist_locked()
            self._pending.append((path, value))
        self._drain_notifications()
        return True

    def update(self, path: str, fn: Callable[[Any], Any],
               default: Any = None) -> Any:
        """Atomic read-modify-write (the ZK CAS-retry analogue)."""
        with self._lock:
            cur = self._data.get(path, default)
            new = self._copy(fn(self._copy(cur)))
            self._data[path] = new
            self._version += 1
            self._log_locked(path, new)
            self._persist_locked()
            self._pending.append((path, new))
        self._drain_notifications()
        return self._copy(new)

    def delete(self, path: str) -> None:
        with self._lock:
            existed = path in self._data
            self._data.pop(path, None)
            if existed:
                self._version += 1
                self._log_locked(path, None)
                self._persist_locked()
                self._pending.append((path, None))
        if existed:
            self._drain_notifications()

    # -- mutation log (remote-replica sync; ref: the ZK transaction log) ----
    _LOG_CAP = 10_000

    def _log_locked(self, path: str, value: Any) -> None:
        self._mutation_log.append((self._version, path, value))
        if len(self._mutation_log) > self._LOG_CAP:
            del self._mutation_log[: len(self._mutation_log) - self._LOG_CAP]

    def mutations_since(self, since_version: int):
        """(current_version, [(version, path, value)...]) after
        ``since_version``, or (current_version, None) when the log no
        longer reaches back that far (caller must full-resync)."""
        with self._lock:
            if since_version >= self._version:
                return self._version, []
            if (not self._mutation_log
                    or self._mutation_log[0][0] > since_version + 1):
                return self._version, None
            out = [(v, p, self._copy(val))
                   for v, p, val in self._mutation_log
                   if v > since_version]
            return self._version, out

    def snapshot_data(self):
        """(version, full data dict) for remote full-resyncs."""
        with self._lock:
            return self._version, {k: self._copy(v)
                                   for k, v in self._data.items()}

    def children(self, prefix: str) -> List[str]:
        prefix = prefix.rstrip("/") + "/"
        with self._lock:
            keys = [k for k in self._data if k.startswith(prefix)]
        return sorted(keys)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    # -- watches -------------------------------------------------------------
    def watch(self, prefix: str, watcher: Watcher) -> None:
        """Watcher fires for every mutation under ``prefix``
        (ref: Helix spectator callbacks routed via ClusterChangeMediator)."""
        with self._lock:
            self._watchers.append((prefix, watcher))

    def _drain_notifications(self) -> None:
        """Deliver queued notifications in mutation order. One thread drains
        at a time; a mutator racing past a draining thread leaves its event
        in the queue for the drainer."""
        while True:
            with self._notify_lock:
                with self._lock:
                    if not self._pending:
                        return
                    batch, self._pending = self._pending, []
                    # snapshot under the same lock watch() appends under:
                    # a registration racing the drain sees either the whole
                    # batch or none of it, never a torn list copy
                    watchers = list(self._watchers)
                for path, value in batch:
                    for prefix, w in watchers:
                        if path.startswith(prefix):
                            try:
                                w(path, self._copy(value))
                            except Exception:  # must not poison the store
                                import logging

                                logging.getLogger(__name__).exception(
                                    "watcher failed for %s", path)

    def _persist_locked(self) -> None:
        if not self._snapshot_path:
            return
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": self._version, "data": self._data}, f)
        os.replace(tmp, self._snapshot_path)

    # -- typed accessors (ref: ZKMetadataProvider) ---------------------------
    def add_schema(self, schema: Schema) -> None:
        self.set(f"schemas/{schema.schema_name}", schema.to_dict())

    def get_schema(self, name: str) -> Optional[Schema]:
        d = self.get(f"schemas/{name}")
        return Schema.from_dict(d) if d else None

    def schema_names(self) -> List[str]:
        return [p.split("/", 1)[1] for p in self.children("schemas")]

    def add_table_config(self, config: TableConfig) -> None:
        self.set(f"tables/{config.table_name_with_type}", config.to_dict())

    def get_table_config(self, name_with_type: str) -> Optional[TableConfig]:
        d = self.get(f"tables/{name_with_type}")
        return TableConfig.from_dict(d) if d else None

    def table_names(self) -> List[str]:
        return [p.split("/", 1)[1] for p in self.children("tables")]

    def delete_table(self, name_with_type: str) -> None:
        for p in self.children(f"segments/{name_with_type}"):
            self.delete(p)
        self.delete(f"idealstate/{name_with_type}")
        self.delete(f"externalview/{name_with_type}")
        self.delete(f"tables/{name_with_type}")

    # segments
    def set_segment_metadata(self, md: SegmentZKMetadata) -> None:
        self.set(f"segments/{md.table_name}/{md.segment_name}", md.to_dict())

    def get_segment_metadata(self, table: str,
                             segment: str) -> Optional[SegmentZKMetadata]:
        d = self.get(f"segments/{table}/{segment}")
        return SegmentZKMetadata.from_dict(d) if d else None

    def segment_names(self, table: str) -> List[str]:
        return [p.rsplit("/", 1)[1]
                for p in self.children(f"segments/{table}")]

    def segment_metadata_list(self, table: str) -> List[SegmentZKMetadata]:
        return [SegmentZKMetadata.from_dict(self.get(p))
                for p in self.children(f"segments/{table}")]

    def delete_segment(self, table: str, segment: str) -> None:
        self.delete(f"segments/{table}/{segment}")

    # ideal state / external view: {segment: {instance: state}}
    def get_ideal_state(self, table: str) -> Dict[str, Dict[str, str]]:
        return self.get(f"idealstate/{table}", {}) or {}

    def set_ideal_state(self, table: str,
                        state: Dict[str, Dict[str, str]]) -> None:
        self.set(f"idealstate/{table}", state)

    def update_ideal_state(self, table: str,
                           fn: Callable[[Dict[str, Dict[str, str]]],
                                        Dict[str, Dict[str, str]]]) -> Dict:
        return self.update(f"idealstate/{table}", fn, default={})

    def get_external_view(self, table: str) -> Dict[str, Dict[str, str]]:
        return self.get(f"externalview/{table}", {}) or {}

    def report_instance_state(self, table: str, segment: str,
                              instance: str, state: str) -> None:
        """Server-side state report (the Helix current-state -> EV rollup)."""

        def apply(ev):
            ev = ev or {}
            seg = ev.setdefault(segment, {})
            if state == OFFLINE:
                seg.pop(instance, None)
                if not seg:
                    ev.pop(segment, None)
            else:
                seg[instance] = state
            return ev

        self.update(f"externalview/{table}", apply, default={})

    # instance partitions (ref: InstancePartitions.java — persisted
    # replica-group layout the broker's replica-group selectors read)
    def set_instance_partitions(self, table: str,
                                groups: List[List[str]]) -> None:
        self.set(f"instancepartitions/{table}", [list(g) for g in groups])

    def get_instance_partitions(self, table: str
                                ) -> Optional[List[List[str]]]:
        return self.get(f"instancepartitions/{table}")

    # instances
    def register_instance(self, info: InstanceInfo) -> None:
        self.set(f"instances/{info.instance_id}", info.to_dict())

    def get_instance(self, instance_id: str) -> Optional[InstanceInfo]:
        d = self.get(f"instances/{instance_id}")
        return InstanceInfo.from_dict(d) if d else None

    def instances(self, instance_type: Optional[str] = None,
                  only_alive: bool = False) -> List[InstanceInfo]:
        out = []
        for p in self.children("instances"):
            info = InstanceInfo.from_dict(self.get(p))
            if instance_type and info.instance_type != instance_type:
                continue
            if only_alive and not info.alive:
                continue
            out.append(info)
        return out

    def set_instance_alive(self, instance_id: str, alive: bool) -> None:
        def apply(d):
            if d:
                d["alive"] = alive
            return d

        self.update(f"instances/{instance_id}", apply)

    def touch_instance(self, instance_id: str,
                       now_ms: Optional[int] = None) -> None:
        """Heartbeat (the ephemeral-znode keepalive analogue): refreshes
        heartbeatMs and revives a dead-marked instance."""
        import time as _time

        now_ms = now_ms if now_ms is not None else int(_time.time() * 1000)

        def apply(d):
            if d:
                d["heartbeatMs"] = now_ms
                d["alive"] = True
            return d

        self.update(f"instances/{instance_id}", apply)
