"""Minion task orchestration: task generation, queueing, status tracking.

Re-design of ``pinot-controller/.../helix/core/minion/PinotTaskManager.java``
(per-table task generation from TableConfig's taskTypeConfigsMap) +
``PinotHelixTaskResourceManager`` (the Helix task-queue wrapper): tasks are
persisted in the cluster state store under ``tasks/``, minions poll for
work, and per-(table, taskType) watermarks live under
``minionTaskMetadata/`` (ref: MinionTaskMetadataUtils /
RealtimeToOfflineSegmentsTaskMetadata).
"""

from __future__ import annotations

import logging
import time
import uuid

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pinot_tpu.controller.state import CONSUMING, ONLINE, ClusterStateStore
from pinot_tpu.segment.processing import TIME_UNIT_MS
from pinot_tpu.spi.table import TableType, table_type_from_name

log = logging.getLogger(__name__)

# task states (ref: Helix TaskState via PinotHelixTaskResourceManager)
WAITING = "WAITING"
IN_PROGRESS = "IN_PROGRESS"
COMPLETED = "COMPLETED"
ERROR = "ERROR"

MERGE_ROLLUP_TASK = "MergeRollupTask"
REALTIME_TO_OFFLINE_TASK = "RealtimeToOfflineSegmentsTask"
PURGE_TASK = "PurgeTask"
CONVERT_TO_RAW_TASK = "ConvertToRawIndexTask"
SEGMENT_GENERATION_AND_PUSH_TASK = "SegmentGenerationAndPushTask"

# stop regenerating a unit of work after this many ERROR attempts; pruning
# terminal records after the TTL both bounds state-store growth and acts as
# a coarse retry backoff (the attempt counter resets once records age out)
MAX_TASK_ATTEMPTS = 3
TERMINAL_TASK_TTL_MS = 24 * 3_600_000

_PERIOD_MS = {"m": 60_000, "h": 3_600_000, "d": 86_400_000}


def parse_period_ms(period: str, default_ms: int) -> int:
    """'1d' / '6h' / '30m' -> milliseconds (ref: TimeUtils.convertPeriodToMillis)."""
    if not period:
        return default_ms
    period = period.strip().lower()
    try:
        return int(period[:-1]) * _PERIOD_MS[period[-1]]
    except (KeyError, ValueError, IndexError):
        return default_ms


@dataclass
class PinotTaskConfig:
    """One unit of minion work (ref: PinotTaskConfig.java)."""

    task_id: str
    task_type: str
    table: str                      # table name with type
    configs: Dict[str, str] = field(default_factory=dict)
    input_segments: List[str] = field(default_factory=list)
    status: str = WAITING
    worker: Optional[str] = None
    error: Optional[str] = None
    output_segments: List[str] = field(default_factory=list)
    created_ms: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "taskId": self.task_id, "taskType": self.task_type,
            "tableName": self.table, "configs": dict(self.configs),
            "inputSegments": list(self.input_segments),
            "status": self.status, "worker": self.worker,
            "error": self.error,
            "outputSegments": list(self.output_segments),
            "createdMs": self.created_ms,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PinotTaskConfig":
        return cls(task_id=d["taskId"], task_type=d["taskType"],
                   table=d["tableName"], configs=d.get("configs", {}),
                   input_segments=d.get("inputSegments", []),
                   status=d.get("status", WAITING), worker=d.get("worker"),
                   error=d.get("error"),
                   output_segments=d.get("outputSegments", []),
                   created_ms=d.get("createdMs", 0))


class PinotTaskManager:
    """Generates + tracks minion tasks over the cluster state store."""

    def __init__(self, store: ClusterStateStore):
        self.store = store  # race-ok: delegates_locking

    # -- queue ---------------------------------------------------------------
    def _path(self, task_id: str) -> str:
        return f"tasks/{task_id}"

    def submit(self, task: PinotTaskConfig) -> str:
        task.created_ms = int(time.time() * 1000)
        self.store.set(self._path(task.task_id), task.to_dict())
        return task.task_id

    def get(self, task_id: str) -> Optional[PinotTaskConfig]:
        d = self.store.get(self._path(task_id))
        return PinotTaskConfig.from_dict(d) if d else None

    def list_tasks(self, table: Optional[str] = None,
                   task_type: Optional[str] = None,
                   status: Optional[str] = None) -> List[PinotTaskConfig]:
        out = []
        for key in self.store.children("tasks"):
            t = self.get(key.split("/", 1)[1])
            if t is None:
                continue
            if table and t.table != table:
                continue
            if task_type and t.task_type != task_type:
                continue
            if status and t.status != status:
                continue
            out.append(t)
        return sorted(out, key=lambda t: t.created_ms)

    def poll(self, worker_id: str) -> Optional[PinotTaskConfig]:
        """Claim the oldest WAITING task (minion work loop)."""
        for t in self.list_tasks(status=WAITING):
            claimed = {"ok": False}

            def apply(d):
                if d and d.get("status") == WAITING:
                    d = dict(d, status=IN_PROGRESS, worker=worker_id)
                    claimed["ok"] = True
                return d

            self.store.update(self._path(t.task_id), apply)
            if claimed["ok"]:
                return self.get(t.task_id)
        return None

    def report(self, task_id: str, status: str,
               output_segments: Optional[List[str]] = None,
               error: Optional[str] = None) -> None:
        def apply(d):
            if d:
                d = dict(d, status=status, error=error,
                         outputSegments=list(output_segments or []))
            return d

        self.store.update(self._path(task_id), apply)

    def prune_terminal_tasks(self, now_ms: int) -> int:
        """Drop COMPLETED/ERROR records older than the TTL (bounded state)."""
        n = 0
        for key in self.store.children("tasks"):
            t = self.get(key.split("/", 1)[1])
            if t and t.status in (COMPLETED, ERROR) and \
                    now_ms - t.created_ms > TERMINAL_TASK_TTL_MS:
                self.store.delete(self._path(t.task_id))
                n += 1
        return n

    def error_attempts(self, table: str, task_type: str,
                       configs_match: Optional[Dict[str, str]] = None,
                       input_segments: Optional[List[str]] = None) -> int:
        """How many times this unit of work has already ended in ERROR."""
        n = 0
        for t in self.list_tasks(table=table, task_type=task_type,
                                 status=ERROR):
            if configs_match and any(t.configs.get(k) != v
                                     for k, v in configs_match.items()):
                continue
            if input_segments is not None and \
                    t.input_segments != input_segments:
                continue
            n += 1
        return n

    # -- per-(table, type) watermarks ----------------------------------------
    def get_watermark_ms(self, table: str, task_type: str) -> Optional[int]:
        return self.store.get(f"minionTaskMetadata/{table}/{task_type}")

    def set_watermark_ms(self, table: str, task_type: str, wm: int) -> None:
        self.store.set(f"minionTaskMetadata/{table}/{task_type}", int(wm))

    # -- generation (ref: per-task generators under helix/core/minion/generator)
    def generate_tasks(self, now_ms: Optional[int] = None) -> List[str]:
        """Scan every table's taskTypeConfigsMap and emit new tasks; skips a
        (table, type) that still has WAITING/IN_PROGRESS work."""
        now_ms = now_ms or int(time.time() * 1000)
        self.prune_terminal_tasks(now_ms)
        created: List[str] = []
        for table in self.store.table_names():
            cfg = self.store.get_table_config(table)
            if cfg is None or not cfg.task_config:
                continue
            for task_type, tconf in cfg.task_config.items():
                if self.list_tasks(table=table, task_type=task_type,
                                   status=WAITING) or \
                        self.list_tasks(table=table, task_type=task_type,
                                        status=IN_PROGRESS):
                    continue
                gen = _GENERATORS.get(task_type)
                if gen is None:
                    log.warning("no generator for task type %s", task_type)
                    continue
                for task in gen(self, table, cfg, tconf, now_ms):
                    created.append(self.submit(task))
        return created


# --------------------------------------------------------------------------
# generators
# --------------------------------------------------------------------------

def _new_id(task_type: str) -> str:
    return f"Task_{task_type}_{uuid.uuid4().hex[:12]}"


def _segment_time_bounds_ms(md, time_unit_ms: int):
    if md.start_time is None or md.end_time is None:
        return None
    return md.start_time * time_unit_ms, md.end_time * time_unit_ms


def _generate_merge_rollup(mgr: PinotTaskManager, table: str, cfg,
                           tconf: Dict[str, str], now_ms: int):
    """Merge ONLINE segments bucket by bucket behind a buffer window
    (ref: MergeRollupTaskGenerator watermark walk)."""
    if table_type_from_name(table) is not TableType.OFFLINE:
        return
    unit_ms = TIME_UNIT_MS.get(cfg.validation_config.time_type.upper(), 1)
    bucket_ms = parse_period_ms(tconf.get("bucketTimePeriod", "1d"), 86_400_000)
    buffer_ms = parse_period_ms(tconf.get("bufferTimePeriod", "0d"), 0)
    max_segs = int(tconf.get("maxNumSegmentsPerTask", "100"))

    candidates = []
    for md in mgr.store.segment_metadata_list(table):
        if md.status != ONLINE or md.segment_name.startswith("merged_"):
            continue
        bounds = _segment_time_bounds_ms(md, unit_ms)
        if bounds is None:
            continue
        candidates.append((md, bounds))
    if not candidates:
        return

    wm = mgr.get_watermark_ms(table, MERGE_ROLLUP_TASK)
    if wm is None:
        wm = (min(b[0] for _, b in candidates) // bucket_ms) * bucket_ms
    while wm + bucket_ms <= now_ms - buffer_ms:
        in_bucket = [md.segment_name for md, (s, e) in candidates
                     if s < wm + bucket_ms and e >= wm]
        if len(in_bucket) >= 2:
            # The watermark only advances once the bucket drains (inputs
            # merged away by a COMPLETED task) or is poisoned (retry cap).
            # Advancing at scheduling time would skip the bucket forever on
            # task ERROR; draining also re-queues truncated-off segments
            # while >= 2 of them remain (a lone leftover stays unmerged —
            # there is nothing to merge it with).
            attempts = mgr.error_attempts(
                table, MERGE_ROLLUP_TASK,
                configs_match={"windowStartMs": str(wm)})
            if attempts < MAX_TASK_ATTEMPTS:
                yield PinotTaskConfig(
                    task_id=_new_id(MERGE_ROLLUP_TASK),
                    task_type=MERGE_ROLLUP_TASK, table=table,
                    configs=dict(tconf, windowStartMs=str(wm),
                                 windowEndMs=str(wm + bucket_ms),
                                 bucketTimeMs=str(bucket_ms)),
                    input_segments=in_bucket[:max_segs])
                return  # one bucket per generation round
            log.error("MergeRollup bucket [%d, %d) of %s failed %d times; "
                      "skipping it", wm, wm + bucket_ms, table, attempts)
        wm += bucket_ms
        mgr.set_watermark_ms(table, MERGE_ROLLUP_TASK, wm)


def _generate_realtime_to_offline(mgr: PinotTaskManager, table: str, cfg,
                                  tconf: Dict[str, str], now_ms: int):
    """Move a completed realtime window into the OFFLINE table
    (ref: RealtimeToOfflineSegmentsTaskGenerator)."""
    if table_type_from_name(table) is not TableType.REALTIME:
        return
    unit_ms = TIME_UNIT_MS.get(cfg.validation_config.time_type.upper(), 1)
    bucket_ms = parse_period_ms(tconf.get("bucketTimePeriod", "1d"), 86_400_000)
    buffer_ms = parse_period_ms(tconf.get("bufferTimePeriod", "0d"), 0)

    completed = []
    for md in mgr.store.segment_metadata_list(table):
        if md.status == CONSUMING:
            continue
        bounds = _segment_time_bounds_ms(md, unit_ms)
        if bounds is None:
            continue
        completed.append((md, bounds))
    if not completed:
        return

    wm = mgr.get_watermark_ms(table, REALTIME_TO_OFFLINE_TASK)
    if wm is None:
        wm = (min(b[0] for _, b in completed) // bucket_ms) * bucket_ms
    window_end = wm + bucket_ms
    if window_end > now_ms - buffer_ms:
        return
    # every completed segment overlapping the window must exist; consuming
    # segments overlapping the window block the task (data not committed yet)
    for md in mgr.store.segment_metadata_list(table):
        if md.status == CONSUMING and md.start_time is not None:
            s = md.start_time * unit_ms
            if s < window_end:
                return
    in_window = [md.segment_name for md, (s, e) in completed
                 if s < window_end and e >= wm]
    if not in_window:
        mgr.set_watermark_ms(table, REALTIME_TO_OFFLINE_TASK, window_end)
        return
    if mgr.error_attempts(table, REALTIME_TO_OFFLINE_TASK,
                          configs_match={"windowStartMs": str(wm)}) \
            >= MAX_TASK_ATTEMPTS:
        # do NOT skip the window (that would drop data from the offline
        # table); stop regenerating until the ERROR records age out
        log.error("RealtimeToOffline window [%d, %d) of %s failed %d+ "
                  "times; awaiting operator attention", wm, window_end,
                  table, MAX_TASK_ATTEMPTS)
        return
    yield PinotTaskConfig(
        task_id=_new_id(REALTIME_TO_OFFLINE_TASK),
        task_type=REALTIME_TO_OFFLINE_TASK, table=table,
        configs=dict(tconf, windowStartMs=str(wm),
                     windowEndMs=str(window_end)),
        input_segments=in_window)


def _generate_purge(mgr: PinotTaskManager, table: str, cfg,
                    tconf: Dict[str, str], now_ms: int):
    """One purge pass per un-purged segment (ref: PurgeTaskGenerator)."""
    # one scan of the ERROR records, not one list_tasks per candidate
    attempts: Dict[str, int] = {}
    for t in mgr.list_tasks(table=table, task_type=PURGE_TASK, status=ERROR):
        for seg in t.input_segments:
            attempts[seg] = attempts.get(seg, 0) + 1
    for md in mgr.store.segment_metadata_list(table):
        if md.status != ONLINE or md.segment_name.startswith("purged_"):
            continue
        if attempts.get(md.segment_name, 0) >= MAX_TASK_ATTEMPTS:
            continue  # poisoned segment: stop regenerating every cycle
        yield PinotTaskConfig(
            task_id=_new_id(PURGE_TASK), task_type=PURGE_TASK, table=table,
            configs=dict(tconf), input_segments=[md.segment_name])
        return


def _generate_convert_to_raw(mgr: PinotTaskManager, table: str, cfg,
                             tconf: Dict[str, str], now_ms: int):
    """One conversion per not-yet-converted ONLINE segment (ref:
    ConvertToRawIndexTaskGenerator — skips segments whose custom map
    records the conversion). Poisoned segments (MAX_TASK_ATTEMPTS errors)
    are skipped so one bad segment cannot block the rest forever."""
    want = ",".join(sorted(c.strip() for c in
                           tconf.get("columnsToConvert", "").split(",")
                           if c.strip()))
    for md in mgr.store.segment_metadata_list(table):
        if md.status != ONLINE:
            continue
        done = md.custom.get("convertToRawDone")
        # reconvert when the requested column set CHANGED (the recorded
        # value is the converted set, compared — not just truthiness)
        if done is not None and done == (want or "*"):
            continue
        if mgr.error_attempts(table, CONVERT_TO_RAW_TASK,
                              input_segments=[md.segment_name]) \
                >= MAX_TASK_ATTEMPTS:
            continue
        yield PinotTaskConfig(
            task_id=_new_id(CONVERT_TO_RAW_TASK),
            task_type=CONVERT_TO_RAW_TASK, table=table,
            configs=dict(tconf), input_segments=[md.segment_name])
        return  # one at a time, like the purge generator


def ingested_files_path(table: str) -> str:
    return f"minionTaskMetadata/{table}/{SEGMENT_GENERATION_AND_PUSH_TASK}.files"


def _generate_segment_generation_and_push(mgr: PinotTaskManager, table: str,
                                          cfg, tconf: Dict[str, str],
                                          now_ms: int):
    """Batch-ingest landing files not yet successfully processed (ref:
    SegmentGenerationAndPushTaskGenerator scanning inputDirURI). The
    processed set {filename: mtime} is recorded by the EXECUTOR on
    success — never at generation time, so task ERRORs retry (up to
    MAX_TASK_ATTEMPTS per file set) instead of silently dropping files;
    the (name, mtime) key also survives same-millisecond arrivals.
    Landing files are treated as immutable once written (the reference's
    batch-input convention); a rewritten file re-ingests whole."""
    import json as _json
    import os

    input_dir = tconf.get("inputDirURI", "")
    if not input_dir or not os.path.isdir(input_dir):
        return
    processed = mgr.store.get(ingested_files_path(table)) or {}
    fresh = []
    mtimes: Dict[str, int] = {}
    for entry in sorted(os.listdir(input_dir)):
        path = os.path.join(input_dir, entry)
        try:
            if not os.path.isfile(path):
                continue
            mtime = int(os.path.getmtime(path) * 1000)
        except FileNotFoundError:
            continue  # deleted mid-scan: a producer race, not an error
        if processed.get(entry) != mtime:
            fresh.append(path)
            mtimes[entry] = mtime
    if not fresh:
        return
    key = ",".join(sorted(os.path.basename(f) for f in fresh))
    if mgr.error_attempts(table, SEGMENT_GENERATION_AND_PUSH_TASK,
                          configs_match={"fileSetKey": key}) \
            >= MAX_TASK_ATTEMPTS:
        return  # poisoned file set: stop regenerating every cycle
    yield PinotTaskConfig(
        task_id=_new_id(SEGMENT_GENERATION_AND_PUSH_TASK),
        task_type=SEGMENT_GENERATION_AND_PUSH_TASK, table=table,
        configs=dict(tconf, inputFiles=_json.dumps(fresh),
                     # generation-time mtimes: success recording must match
                     # the content that was READ, not a later re-stat
                     inputFileMtimes=_json.dumps(mtimes),
                     fileSetKey=key))


_GENERATORS = {
    MERGE_ROLLUP_TASK: _generate_merge_rollup,
    REALTIME_TO_OFFLINE_TASK: _generate_realtime_to_offline,
    PURGE_TASK: _generate_purge,
    CONVERT_TO_RAW_TASK: _generate_convert_to_raw,
    SEGMENT_GENERATION_AND_PUSH_TASK: _generate_segment_generation_and_push,
}
