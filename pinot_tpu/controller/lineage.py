"""Segment lineage: atomic segment-replacement protocol.

Re-design of ``pinot-common/.../lineage/SegmentLineage.java`` +
``SegmentLineageUtils`` (the replace-segments protocol minion tasks use so
queries never see both the inputs and outputs of a merge/rollup): a lineage
entry records ``segments_from -> segments_to`` with a state machine

    IN_PROGRESS  (startReplaceSegments: outputs uploading, hide them)
    COMPLETED    (endReplaceSegments:   outputs live, hide the inputs)
    REVERTED     (revertReplaceSegments: forget the outputs)

Routing applies the same visibility rule as the reference's
``SegmentLineageUtils.filterSegmentsBasedOnLineageInPlace``: hide
``segments_to`` of IN_PROGRESS/REVERTED entries and ``segments_from`` of
COMPLETED entries.
"""

from __future__ import annotations

import itertools
import time

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

IN_PROGRESS = "IN_PROGRESS"
COMPLETED = "COMPLETED"
REVERTED = "REVERTED"

_counter = itertools.count()


@dataclass
class LineageEntry:
    entry_id: str
    segments_from: List[str]
    segments_to: List[str]
    state: str = IN_PROGRESS
    timestamp_ms: int = 0

    def to_dict(self) -> Dict:
        return {"id": self.entry_id, "segmentsFrom": self.segments_from,
                "segmentsTo": self.segments_to, "state": self.state,
                "timestampMs": self.timestamp_ms}

    @classmethod
    def from_dict(cls, d: Dict) -> "LineageEntry":
        return cls(d["id"], list(d["segmentsFrom"]), list(d["segmentsTo"]),
                   d.get("state", IN_PROGRESS), d.get("timestampMs", 0))


class SegmentLineageManager:
    """Controller-side lineage book-keeping over the state store."""

    def __init__(self, store):
        self.store = store  # race-ok: delegates_locking

    def _path(self, table: str) -> str:
        return f"lineage/{table}"

    def _load(self, table: str) -> List[LineageEntry]:
        raw = self.store.get(self._path(table)) or []
        return [LineageEntry.from_dict(d) for d in raw]

    def _mutate(self, table: str, fn) -> None:
        """Atomic read-modify-write through the store's update() — a
        concurrent end_replace and cleanup must never lose each other's
        state flips (the protocol's whole point is swap atomicity)."""

        def apply(raw):
            entries = [LineageEntry.from_dict(d) for d in (raw or [])]
            return [e.to_dict() for e in fn(entries)]

        self.store.update(self._path(table), apply, default=[])

    # -- protocol (ref: PinotSegmentRestletResource start/end/revert) -------
    def start_replace(self, table: str, segments_from: List[str],
                      segments_to: List[str]) -> str:
        entry = LineageEntry(
            entry_id=f"lin_{int(time.time() * 1000)}_{next(_counter)}",
            segments_from=list(segments_from),
            segments_to=list(segments_to),
            state=IN_PROGRESS,
            timestamp_ms=int(time.time() * 1000))

        def apply(entries):
            active: Set[str] = set()
            for e in entries:
                if e.state == IN_PROGRESS:
                    active.update(e.segments_from)
            overlap = active & set(segments_from)
            if overlap:
                raise ValueError(
                    f"segments already in an in-progress replacement: "
                    f"{sorted(overlap)}")
            return entries + [entry]

        self._mutate(table, apply)
        return entry.entry_id

    def end_replace(self, table: str, entry_id: str) -> None:
        self._set_state(table, entry_id, from_state=IN_PROGRESS,
                        to_state=COMPLETED)

    def revert_replace(self, table: str, entry_id: str) -> None:
        self._set_state(table, entry_id, from_state=IN_PROGRESS,
                        to_state=REVERTED)

    def _set_state(self, table: str, entry_id: str, from_state: str,
                   to_state: str) -> None:
        def apply(entries):
            for e in entries:
                if e.entry_id == entry_id:
                    if e.state != from_state:
                        raise ValueError(
                            f"lineage entry {entry_id} is {e.state}, "
                            f"not {from_state}")
                    e.state = to_state
                    return entries
            raise KeyError(f"no lineage entry {entry_id} for {table}")

        self._mutate(table, apply)

    def entries(self, table: str) -> List[LineageEntry]:
        return self._load(table)

    # -- stale-entry cleanup (ref: RetentionManager's lineage GC) -----------
    def cleanup(self, table: str, max_age_ms: int = 24 * 3_600_000,
                now_ms: Optional[int] = None) -> List[str]:
        """Auto-revert IN_PROGRESS entries older than ``max_age_ms`` (the
        minion died mid-replacement: free its inputs for a retry, keep its
        half-uploaded outputs hidden) and drop terminal entries of that age
        whose visibility effect has been realized (COMPLETED inputs /
        REVERTED outputs no longer in the segment list). Returns the ids of
        entries touched."""
        import time as _time

        now = int(_time.time() * 1000) if now_ms is None else now_ms
        # read-only pre-check: a no-op cleanup must not bump the store
        # version (every write invalidates broker lineage caches)
        if not any(now - e.timestamp_ms > max_age_ms
                   for e in self._load(table)):
            return []
        live = set(self.store.segment_names(table))
        touched: List[str] = []

        def apply(entries):
            touched.clear()
            kept: List[LineageEntry] = []
            for e in entries:
                age = now - e.timestamp_ms
                if age <= max_age_ms:
                    kept.append(e)
                    continue
                if e.state == IN_PROGRESS:
                    e.state = REVERTED
                    touched.append(e.entry_id)
                    kept.append(e)
                elif e.state == COMPLETED \
                        and not (set(e.segments_from) & live):
                    touched.append(e.entry_id)  # effect realized: drop
                elif e.state == REVERTED \
                        and not (set(e.segments_to) & live):
                    touched.append(e.entry_id)
                else:
                    kept.append(e)
            return kept

        self._mutate(table, apply)
        return list(touched)

    # -- visibility (ref: filterSegmentsBasedOnLineageInPlace) --------------
    def hidden_segments(self, table: str) -> Set[str]:
        hidden: Set[str] = set()
        for e in self._load(table):
            if e.state == COMPLETED:
                hidden.update(e.segments_from)
            else:  # IN_PROGRESS outputs are not yet queryable; REVERTED ever
                hidden.update(e.segments_to)
        return hidden
