"""Segment assignment + rebalance.

Re-design of ``pinot-controller/.../helix/core/assignment/segment/*``
(``SegmentAssignment.java:39``: balanced / replica-group / partitioned
strategies) and ``rebalance/TableRebalancer.java:108`` (target recompute +
minimum-available-replicas movement plan).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from pinot_tpu.controller.state import (
    CONSUMING,
    ONLINE,
    ClusterStateStore,
    InstanceInfo,
)


class SegmentAssignment:
    """Ref: SegmentAssignment.java:39."""

    def assign(self, segment: str, current: Dict[str, Dict[str, str]],
               instances: List[str], replication: int) -> List[str]:
        raise NotImplementedError


class BalancedSegmentAssignment(SegmentAssignment):
    """Least-loaded placement; with instance fault domains known, replicas
    of one segment spread across DISTINCT failure domains first (the
    environment-provider integration — ref: pinot-environment's
    platformFaultDomain feeding instance assignment)."""

    def __init__(self, domains: Optional[Dict[str, str]] = None):
        # instance id -> failure domain (absent/None = its own domain)
        self._domains = domains or {}

    def assign(self, segment, current, instances, replication):
        if not instances:
            raise ValueError("no server instances to assign to")
        load = {i: 0 for i in instances}
        for seg_map in current.values():
            for inst in seg_map:
                if inst in load:
                    load[inst] += 1
        ranked = sorted(instances, key=lambda i: (load[i], i))
        n = min(replication, len(ranked))
        if not self._domains:
            return ranked[:n]
        # greedy domain-aware pick: an unused failure domain beats load
        # rank; fall back to used domains once every domain is covered
        chosen: List[str] = []
        used_domains = set()
        pool = list(ranked)
        while len(chosen) < n and pool:
            pick = next(
                (i for i in pool
                 if self._domains.get(i, i) not in used_domains),
                pool[0])
            pool.remove(pick)
            chosen.append(pick)
            used_domains.add(self._domains.get(pick, pick))
        return chosen


class ReplicaGroupSegmentAssignment(SegmentAssignment):
    """Instances pre-split into ``replication`` groups; each segment takes
    one instance per group (ref: ReplicaGroupSegmentAssignmentStrategy).
    ``groups`` may be the table's PERSISTED instance partitions (the broker's
    replica-group selectors read the same layout — InstancePartitions)."""

    def __init__(self, num_replica_groups: int,
                 groups: Optional[List[List[str]]] = None):
        self.num_replica_groups = num_replica_groups
        self._groups = groups

    def assign(self, segment, current, instances, replication):
        if not instances:
            raise ValueError("no server instances to assign to")
        groups = self._groups or compute_instance_partitions(
            instances, self.num_replica_groups)
        seg_index = len(current)
        out = []
        for g in groups[: replication]:
            if g:
                out.append(g[seg_index % len(g)])
        return out


def compute_instance_partitions(instances: List[str],
                                num_groups: int) -> List[List[str]]:
    """Deterministic instance -> replica-group split (ref:
    InstanceReplicaGroupPartitionSelector): sorted instances dealt
    round-robin into ``num_groups`` groups."""
    groups: List[List[str]] = [[] for _ in range(max(num_groups, 1))]
    for i, inst in enumerate(sorted(instances)):
        groups[i % max(num_groups, 1)].append(inst)
    return groups


class PartitionedReplicaGroupAssignment(SegmentAssignment):
    """Partition-aware: a segment of stream/partition P lands on the
    instances owning P (ref: RealtimeSegmentAssignment partition mode)."""

    def __init__(self, num_replica_groups: int = 1):
        self.num_replica_groups = num_replica_groups

    def assign(self, segment, current, instances, replication,
               partition: Optional[int] = None):
        if partition is None:
            partition = _partition_from_llc_name(segment)
        groups = compute_instance_partitions(instances,
                                             self.num_replica_groups)
        out = []
        for g in groups[: replication]:
            if g:
                out.append(g[partition % len(g)])
        return out


def _partition_from_llc_name(segment: str) -> int:
    """LLC name: table__partition__sequence__creationTime
    (ref: LLCSegmentName)."""
    parts = segment.split("__")
    if len(parts) >= 3:
        try:
            return int(parts[1])
        except ValueError:
            pass
    return 0


def assignment_for_table(store: ClusterStateStore, table: str,
                         tag: Optional[str] = None) -> Tuple[List[str], int]:
    """(eligible server instance ids, replication) for a table."""
    cfg = store.get_table_config(table)
    if cfg is None:
        raise KeyError(f"no table config for {table}")
    servers = [i.instance_id for i in store.instances("SERVER", only_alive=True)
               if tag is None or tag in i.tags]
    return servers, cfg.replication


# --------------------------------------------------------------------------
# rebalance (ref: TableRebalancer.java:108)
# --------------------------------------------------------------------------

def compute_target_assignment(
        current: Dict[str, Dict[str, str]], instances: List[str],
        replication: int,
        groups: Optional[List[List[str]]] = None,
        domains: Optional[Dict[str, str]] = None
        ) -> Dict[str, Dict[str, str]]:
    """Target for all segments (CONSUMING segments keep their state label).
    ``groups`` switches to replica-group placement so rebalance preserves
    the persisted instance-partition layout strict routing depends on;
    ``domains`` keeps the fault-domain replica spread through rebalance."""
    strategy: SegmentAssignment = (
        ReplicaGroupSegmentAssignment(len(groups), groups=groups)
        if groups else BalancedSegmentAssignment(domains=domains))
    target: Dict[str, Dict[str, str]] = {}
    for segment in sorted(current):
        state = CONSUMING if CONSUMING in current[segment].values() else ONLINE
        chosen = strategy.assign(segment, target, instances, replication)
        target[segment] = {inst: state for inst in chosen}
    return target


def rebalance_steps(current: Dict[str, Dict[str, str]],
                    target: Dict[str, Dict[str, str]]
                    ) -> List[Dict[str, Dict[str, str]]]:
    """Make-before-break movement plan (the no-downtime invariant, ref:
    TableRebalancer minAvailableReplicas): step 1 adds every target replica
    alongside the current ones; the caller waits for ExternalView
    convergence, then step 2 drops the non-target replicas. Every segment
    keeps >= its current replica count serving throughout."""
    union: Dict[str, Dict[str, str]] = {}
    for segment in set(current) | set(target):
        merged = dict(current.get(segment, {}))
        merged.update(target.get(segment, {}))
        union[segment] = merged
    steps: List[Dict[str, Dict[str, str]]] = []
    if union != current:
        steps.append(union)
    if target != union:
        steps.append({s: dict(m) for s, m in target.items()})
    return steps
