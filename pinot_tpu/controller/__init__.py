"""Controller: cluster state store (the Helix/ZK role), segment assignment,
segment-completion FSM, LLC realtime manager, periodic maintenance
(ref: pinot-controller)."""

from pinot_tpu.controller.state import (
    CONSUMING,
    ERROR,
    OFFLINE,
    ONLINE,
    ClusterStateStore,
    InstanceInfo,
    SegmentZKMetadata,
)
from pinot_tpu.controller.assignment import (
    BalancedSegmentAssignment,
    PartitionedReplicaGroupAssignment,
    ReplicaGroupSegmentAssignment,
    SegmentAssignment,
    compute_target_assignment,
    rebalance_steps,
)
from pinot_tpu.controller.completion import FsmState, SegmentCompletionManager
from pinot_tpu.controller.llc import (
    LLCRealtimeSegmentManager,
    llc_segment_name,
    parse_llc_name,
)
from pinot_tpu.controller.controller import Controller

__all__ = [
    "CONSUMING", "ERROR", "OFFLINE", "ONLINE",
    "ClusterStateStore", "InstanceInfo", "SegmentZKMetadata",
    "BalancedSegmentAssignment", "PartitionedReplicaGroupAssignment",
    "ReplicaGroupSegmentAssignment", "SegmentAssignment",
    "compute_target_assignment", "rebalance_steps",
    "FsmState", "SegmentCompletionManager",
    "LLCRealtimeSegmentManager", "llc_segment_name", "parse_llc_name",
    "Controller",
]
