"""Controller: the cluster-mutation API + periodic maintenance tasks.

Re-design of ``pinot-controller/.../helix/core/PinotHelixResourceManager.java:144``
(table/schema/segment/instance management) and the
``ControllerPeriodicTask`` framework (``RetentionManager``,
``RealtimeSegmentValidationManager``, ``SegmentStatusChecker`` —
``helix/core/periodictask/`` + ``validation/*``).
"""

from __future__ import annotations

import logging
import threading
import time

from dataclasses import dataclass
from typing import Dict, List, Optional

from pinot_tpu.controller.assignment import (
    BalancedSegmentAssignment,
    ReplicaGroupSegmentAssignment,
    SegmentAssignment,
    assignment_for_table,
    compute_instance_partitions,
    compute_target_assignment,
    rebalance_steps,
)
from pinot_tpu.controller.completion import SegmentCompletionManager
from pinot_tpu.controller.llc import LLCRealtimeSegmentManager, parse_llc_name
from pinot_tpu.controller.state import (
    CONSUMING,
    OFFLINE,
    ONLINE,
    ClusterStateStore,
    InstanceInfo,
    SegmentZKMetadata,
)
from pinot_tpu.ingestion.stream import StreamOffset
from pinot_tpu.segment.metadata import SegmentMetadata
from pinot_tpu.spi.data import Schema
from pinot_tpu.spi.table import TableConfig, TableType, table_type_from_name

log = logging.getLogger(__name__)

_RETENTION_UNIT_MS = {
    "MILLISECONDS": 1, "SECONDS": 1000, "MINUTES": 60_000,
    "HOURS": 3_600_000, "DAYS": 86_400_000,
}


class Controller:
    """Single-controller deployment (the reference's lead-controller mode).

    Owns: state store mutations, segment completion FSM, LLC manager,
    periodic tasks. The HTTP/gRPC API layer wraps this object.
    """

    def __init__(self, store: Optional[ClusterStateStore] = None,
                 controller_id: str = "controller_0",
                 llc_seed: Optional[str] = None):
        from pinot_tpu.controller.tasks import PinotTaskManager
        from pinot_tpu.spi.metrics import MetricsRegistry

        self.store = store or ClusterStateStore()  # race-ok: delegates_locking
        self.metrics = MetricsRegistry(role="controller")
        self.metrics.gauge("tables", lambda: len(self.store.table_names()))
        self.metrics.gauge("segments", lambda: sum(
            len(self.store.segment_names(t))
            for t in self.store.table_names()))
        self.metrics.gauge("live_servers", lambda: len(
            self.store.instances("SERVER", only_alive=True)))
        self.controller_id = controller_id
        self.task_manager = PinotTaskManager(self.store)
        self.llc = LLCRealtimeSegmentManager(self.store, seed=llc_seed)
        self.completion = SegmentCompletionManager(
            num_replicas_provider=self._num_replicas_for_segment,
            commit_handler=self._on_segment_commit)
        # segment -> table (FSM aid); filled from the REST path and the
        # controller-periodic repair loop, so every access takes the lock
        self._lock = threading.Lock()
        self._segment_tables: Dict[str, str] = {}  # guarded-by: _lock
        self._periodic_stop = threading.Event()
        self._periodic_thread: Optional[threading.Thread] = None
        self.store.register_instance(
            InstanceInfo(controller_id, "CONTROLLER"))

    # -- schema / table management (ref: PinotHelixResourceManager) ---------
    def add_schema(self, schema: Schema) -> None:
        self.store.add_schema(schema)

    def add_table(self, config: TableConfig) -> None:
        """Ref: addTable: validate, create ideal state, realtime setup."""
        name = config.table_name_with_type
        if self.store.get_table_config(name) is not None:
            raise ValueError(f"table {name} already exists")
        if self.store.get_schema(config.table_name) is None:
            raise ValueError(f"no schema named {config.table_name!r} — "
                             "add the schema first")
        self.store.add_table_config(config)
        self.store.set_ideal_state(name, {})
        if config.routing_config.instance_selector_type != "balanced":
            # replica-group routing: persist the instance partitions so the
            # assignment AND the broker selectors share one layout
            # (ref: InstancePartitionsUtils.persistInstancePartitions)
            servers = [i.instance_id
                       for i in self.store.instances("SERVER",
                                                     only_alive=True)]
            if not servers:
                raise ValueError(
                    f"replica-group table {name} needs live servers at "
                    "creation time (instance partitions are computed here)")
            self.store.set_instance_partitions(
                name, compute_instance_partitions(servers,
                                                  config.replication))
        if config.table_type is TableType.REALTIME:
            if config.stream_config is None:
                raise ValueError("realtime table needs a stream config")
            consuming = self.llc.setup_new_table(name)
            with self._lock:
                for seg in consuming:
                    self._segment_tables[seg] = name

    def update_table(self, config: TableConfig) -> None:
        """Replace an existing table's config (ref: updateTableConfig —
        PUT /tables/{name}); pair with reload_table to apply new indexes."""
        name = config.table_name_with_type
        if self.store.get_table_config(name) is None:
            raise KeyError(f"no such table {name}")
        self.store.add_table_config(config)

    def reload_table(self, name_with_type: str) -> None:
        """Ask every server hosting the table to reload its segments —
        rebuilding any newly-configured indexes in place (ref: the reload
        message path, PinotSegmentRestletResource.reloadAllSegments ->
        SegmentMessageHandlerFactory)."""
        if self.store.get_table_config(name_with_type) is None:
            raise KeyError(f"no such table {name_with_type}")
        self.store.update(f"reloadrequests/{name_with_type}",
                          lambda v: (v or 0) + 1)

    def delete_table(self, name_with_type: str) -> None:
        self.store.delete_table(name_with_type)

    def table_names(self) -> List[str]:
        return self.store.table_names()

    # -- offline segment upload (ref: addNewSegment + upload resource) ------
    def add_segment(self, table_with_type: str, metadata: SegmentMetadata,
                    download_url: str) -> None:
        """Segment push: record ZK metadata + assign to servers."""
        cfg = self.store.get_table_config(table_with_type)
        if cfg is None:
            raise KeyError(f"no such table {table_with_type}")
        partition_meta = {
            cm.name: {"functionName": cm.partition_function,
                      "numPartitions": cm.num_partitions,
                      "partitions": list(cm.partitions)}
            for cm in metadata.columns.values() if cm.partition_function}
        zk = SegmentZKMetadata(
            segment_name=metadata.segment_name, table_name=table_with_type,
            status=ONLINE, download_url=download_url, crc=metadata.crc,
            creation_time_ms=metadata.creation_time_ms,
            push_time_ms=int(time.time() * 1000),
            start_time=metadata.min_time, end_time=metadata.max_time,
            total_docs=metadata.num_docs,
            partition_metadata=partition_meta)
        self.store.set_segment_metadata(zk)

        servers, replication = assignment_for_table(self.store, table_with_type)
        groups = self.store.get_instance_partitions(table_with_type)
        # environment-provider integration: replicas spread across distinct
        # failure domains when servers report them (spi/environment.py)
        domains = {i.instance_id: i.failure_domain
                   for i in self.store.instances("SERVER")
                   if i.failure_domain}
        strategy: SegmentAssignment = (
            ReplicaGroupSegmentAssignment(len(groups), groups=groups)
            if groups else BalancedSegmentAssignment(domains=domains))

        def apply(ideal):
            ideal = ideal or {}
            chosen = strategy.assign(metadata.segment_name, ideal, servers,
                                     replication)
            ideal[metadata.segment_name] = {i: ONLINE for i in chosen}
            return ideal

        self.store.update_ideal_state(table_with_type, apply)

    # -- segment lineage (ref: start/end/revertReplaceSegments REST) --------
    def start_replace_segments(self, table: str, segments_from: List[str],
                               segments_to: List[str]) -> str:
        from pinot_tpu.controller.lineage import SegmentLineageManager

        return SegmentLineageManager(self.store).start_replace(
            table, segments_from, segments_to)

    def end_replace_segments(self, table: str, entry_id: str) -> None:
        from pinot_tpu.controller.lineage import SegmentLineageManager

        SegmentLineageManager(self.store).end_replace(table, entry_id)

    def revert_replace_segments(self, table: str, entry_id: str) -> None:
        from pinot_tpu.controller.lineage import SegmentLineageManager

        SegmentLineageManager(self.store).revert_replace(table, entry_id)

    def delete_segment(self, table: str, segment: str) -> None:
        self.store.delete_segment(table, segment)

        def apply(ideal):
            ideal = ideal or {}
            ideal.pop(segment, None)
            return ideal

        self.store.update_ideal_state(table, apply)

    # -- instances ----------------------------------------------------------
    def register_instance(self, info: InstanceInfo) -> None:
        self.store.register_instance(info)

    def update_instance_tags(self, instance_id: str,
                             tags: List[str]) -> None:
        """Re-tag an instance (ref: PinotInstanceRestletResource
        updateInstanceTags — the tenant-membership mutation). Atomic
        read-modify-write on the store so a concurrent heartbeat's
        heartbeatMs is never clobbered by a stale snapshot."""
        if self.store.get_instance(instance_id) is None:
            raise KeyError(f"unknown instance {instance_id!r}")

        def apply(d):
            if d:
                d["tags"] = list(tags)
            return d

        self.store.update(f"instances/{instance_id}", apply)

    # -- segment completion plumbing ----------------------------------------
    def _num_replicas_for_segment(self, segment_name: str) -> int:
        table = self._table_of(segment_name)
        if table:
            ideal = self.store.get_ideal_state(table)
            if segment_name in ideal:
                return max(len(ideal[segment_name]), 1)
        return 1

    def _table_of(self, segment_name: str) -> Optional[str]:
        with self._lock:
            t = self._segment_tables.get(segment_name)
        if t:
            return t
        try:
            raw, _, _ = parse_llc_name(segment_name)
        except ValueError:
            return None
        name = raw + "_REALTIME"
        if self.store.get_table_config(name) is not None:
            with self._lock:
                self._segment_tables[segment_name] = name
            return name
        return None

    def _on_segment_commit(self, segment_name: str, instance: str,
                           offset: StreamOffset, location: str,
                           metadata: SegmentMetadata) -> None:
        """Commit handler invoked by the completion FSM (ref:
        commitSegmentMetadata:508)."""
        table = self._table_of(segment_name)
        if table is None:
            raise KeyError(f"cannot resolve table for {segment_name}")
        new_consuming = self.llc.commit_segment(
            table, segment_name, offset, location, metadata)
        with self._lock:
            self._segment_tables[new_consuming] = table

    # -- rebalance (ref: TableRebalancer) -----------------------------------
    def rebalance_table(self, table: str, dry_run: bool = False,
                        convergence_timeout_s: float = 30.0,
                        best_effort: bool = True) -> List[Dict]:
        """Make-before-break: after each intermediate step, wait for the
        ExternalView to converge before dropping old replicas (ref:
        TableRebalancer EV-convergence wait + bestEffort flag). With
        ``best_effort`` a convergence timeout proceeds anyway (the
        standalone/test mode where no live servers report EV); without it,
        the rebalance raises and leaves the added replicas in place."""
        servers, replication = assignment_for_table(self.store, table)
        current = self.store.get_ideal_state(table)
        # replica-group tables recompute (and re-persist) their instance
        # partitions so the target layout and the broker selectors stay in
        # lockstep (ref: TableRebalancer reassignInstances)
        groups = None
        if self.store.get_instance_partitions(table) is not None and servers:
            groups = compute_instance_partitions(servers, replication)
            if not dry_run:
                self.store.set_instance_partitions(table, groups)
        target = compute_target_assignment(
            current, servers, replication, groups=groups,
            domains={i.instance_id: i.failure_domain
                     for i in self.store.instances("SERVER")
                     if i.failure_domain})
        steps = rebalance_steps(current, target)
        if dry_run:
            return steps
        for i, step in enumerate(steps):
            self.store.set_ideal_state(table, step)
            if i == len(steps) - 1:
                break
            if not self._wait_external_view(table, step,
                                            convergence_timeout_s):
                if not best_effort:
                    raise RuntimeError(
                        f"rebalance of {table} stalled: ExternalView did not "
                        f"converge within {convergence_timeout_s}s")
                log.warning("rebalance %s: EV convergence timeout, "
                            "proceeding best-effort", table)
        return steps

    def _wait_external_view(self, table: str, ideal: Dict,
                            timeout_s: float,
                            poll_s: float = 0.05) -> bool:
        deadline = time.monotonic() + timeout_s
        while True:
            ev = self.store.get_external_view(table)
            ok = all(ev.get(seg, {}).get(inst) == st
                     for seg, m in ideal.items()
                     for inst, st in m.items() if st != OFFLINE)
            if ok:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    # -- periodic tasks (ref: ControllerPeriodicTask framework) -------------
    def run_retention_manager(self, now_ms: Optional[int] = None) -> List[str]:
        """Delete segments past the table's retention
        (ref: RetentionManager + SegmentDeletionManager)."""
        from pinot_tpu.controller.lineage import SegmentLineageManager

        now_ms = now_ms or int(time.time() * 1000)
        deleted = []
        lineage = SegmentLineageManager(self.store)
        for table in self.store.table_names():
            # lineage GC rides retention (ref: RetentionManager's
            # manageSegmentLineageCleanupForTable)
            lineage.cleanup(table, now_ms=now_ms)
            cfg = self.store.get_table_config(table)
            vc = cfg.validation_config
            if not vc.retention_time_unit or not vc.retention_time_value:
                continue
            unit_ms = _RETENTION_UNIT_MS.get(vc.retention_time_unit.upper())
            if unit_ms is None:
                continue
            cutoff = now_ms - vc.retention_time_value * unit_ms
            time_unit_ms = _RETENTION_UNIT_MS.get(vc.time_type.upper(), 1)
            for md in self.store.segment_metadata_list(table):
                if md.status == CONSUMING or md.end_time is None:
                    continue
                if md.end_time * time_unit_ms < cutoff:
                    self.delete_segment(table, md.segment_name)
                    deleted.append(md.segment_name)
        return deleted

    def run_realtime_validation(self) -> List[str]:
        """Repair dead CONSUMING segments
        (ref: RealtimeSegmentValidationManager)."""
        created = []
        for table in self.store.table_names():
            if table_type_from_name(table) is TableType.REALTIME:
                fresh = self.llc.ensure_all_partitions_consuming(table)
                with self._lock:
                    for seg in fresh:
                        self._segment_tables[seg] = table
                created.extend(fresh)
        return created

    def run_segment_status_check(self) -> Dict[str, Dict[str, int]]:
        """Per-table ideal-vs-external-view convergence report
        (ref: SegmentStatusChecker)."""
        report = {}
        for table in self.store.table_names():
            ideal = self.store.get_ideal_state(table)
            ev = self.store.get_external_view(table)
            missing = sum(1 for seg, m in ideal.items()
                          for inst, st in m.items()
                          if st != OFFLINE and ev.get(seg, {}).get(inst) != st)
            report[table] = {
                "segments": len(ideal),
                "replicasExpected": sum(len(m) for m in ideal.values()),
                "replicasMissing": missing,
            }
        return report

    def run_task_generation(self) -> List[str]:
        """Emit minion tasks for every table with a taskTypeConfigsMap
        (ref: PinotTaskManager cron-able generation)."""
        return self.task_manager.generate_tasks()

    def run_segment_relocation(self,
                               now_ms: Optional[int] = None) -> List[str]:
        """Move aged segments to their tier's tagged servers
        (ref: SegmentRelocator periodic task; controller/tiers.py)."""
        from pinot_tpu.controller.tiers import SegmentRelocator

        relocator = SegmentRelocator(self.store)
        moved = []
        for table in self.store.table_names():
            moved.extend(relocator.relocate_table(table, now_ms=now_ms))
        return moved

    def run_liveness_check(self, timeout_ms: int = 10_000,
                           now_ms: Optional[int] = None) -> List[str]:
        """Automatic failure detection (the Helix ephemeral-znode liveness
        analogue): instances whose heartbeat went stale are marked dead so
        routing excludes them; a fresh heartbeat revives them
        (store.touch_instance). Instances that never heartbeat (embedded
        tests drive liveness manually) are left alone. Returns the newly
        dead instance ids."""
        import time as _time

        now_ms = now_ms if now_ms is not None else int(_time.time() * 1000)
        newly_dead = []
        for info in self.store.instances():
            if not info.heartbeat_ms:
                continue  # never heartbeated: liveness managed manually
            stale = now_ms - info.heartbeat_ms > timeout_ms
            if stale and info.alive:
                log.warning("instance %s heartbeat stale (%dms) — marking "
                            "dead", info.instance_id,
                            now_ms - info.heartbeat_ms)
                self.store.set_instance_alive(info.instance_id, False)
                newly_dead.append(info.instance_id)
        return newly_dead

    def start_periodic_tasks(self, interval_s: float = 5.0) -> None:
        def loop():
            while not self._periodic_stop.wait(interval_s):
                try:
                    self.run_liveness_check()
                    self.run_retention_manager()
                    self.run_realtime_validation()
                    self.run_task_generation()
                    self.run_segment_relocation()
                except Exception:
                    log.exception("periodic task failed")

        self._periodic_thread = threading.Thread(
            target=loop, daemon=True, name="controller-periodic")
        self._periodic_thread.start()

    def stop(self) -> None:
        self._periodic_stop.set()
        if self._periodic_thread is not None:
            self._periodic_thread.join(timeout=10)
