"""Controller-side segment completion FSM: commit arbitration.

Re-design of ``pinot-controller/.../realtime/SegmentCompletionManager.java:59``:
replicas of a CONSUMING segment report ``segmentConsumed(offset)``; the
manager holds them until a quorum window passes, elects the replica with the
highest offset as the committer, tells laggards to CATCHUP, and guards that
exactly one replica runs the split commit. Non-winners get KEEP (retain
their local build) or DISCARD (download from deep store) when the winner's
commit lands at a different offset.
"""

from __future__ import annotations

import enum
import threading
import time

from dataclasses import dataclass, field
from typing import Dict, Optional

from pinot_tpu.ingestion.realtime import (
    CompletionReply,
    CompletionResponse,
    SegmentCompletionProtocol,
)
from pinot_tpu.ingestion.stream import StreamOffset


class FsmState(enum.Enum):
    """Ref: SegmentCompletionManager.State."""

    HOLDING = "HOLDING"
    COMMITTER_DECIDED = "COMMITTER_DECIDED"
    COMMITTER_NOTIFIED = "COMMITTER_NOTIFIED"
    COMMITTER_UPLOADING = "COMMITTER_UPLOADING"
    COMMITTING = "COMMITTING"
    COMMITTED = "COMMITTED"
    ABORTED = "ABORTED"


@dataclass
class _SegmentFsm:
    segment_name: str
    num_replicas: int
    state: FsmState = FsmState.HOLDING
    offsets: Dict[str, StreamOffset] = field(default_factory=dict)
    committer: Optional[str] = None
    committed_offset: Optional[StreamOffset] = None
    first_consumed_ms: float = 0.0
    committed_ms: float = 0.0
    elected_ms: float = 0.0
    winner_offset: Optional[StreamOffset] = None


class SegmentCompletionManager(SegmentCompletionProtocol):
    """One per controller. Thread-safe: server RPCs arrive concurrently.

    ``commit_handler(segment_name, instance, offset, location, metadata)``
    is invoked under COMMITTED transition to flip cluster metadata (wired to
    the LLC realtime manager).
    """

    # how long to keep HOLDing for more replicas before electing a committer
    # (ref: SegmentCompletionManager MAX_MILLIS_TO_WAIT_FOR_ALL_SEGMENTS)
    # grace window during which a COMMITTED FSM keeps answering laggard
    # replicas with KEEP/DISCARD before being pruned (ref: the reference
    # expires completed FSMs after MAX_COMMIT_TIME)
    COMMITTED_TTL_S = 300.0
    # max time an elected committer may take before the election re-opens
    # (ref: SegmentCompletionManager MAX_COMMIT_TIME_FOR_ALL_SEGMENTS_SECONDS
    # = 1800s); without this, a committer that crashes before calling
    # segment_stopped_consuming would leave peers at HOLD forever
    MAX_COMMIT_TIME_S = 1800.0

    def __init__(self, num_replicas_provider=None, hold_window_s: float = 0.2,
                 commit_handler=None, max_commit_time_s: float = None):
        self._fsms: Dict[str, _SegmentFsm] = {}
        self._lock = threading.Lock()
        self._hold_window_s = hold_window_s
        self._max_commit_time_s = (self.MAX_COMMIT_TIME_S
                                   if max_commit_time_s is None
                                   else max_commit_time_s)
        self._num_replicas_provider = num_replicas_provider or (lambda seg: 1)
        self._commit_handler = commit_handler

    def _fsm(self, segment_name: str) -> _SegmentFsm:
        fsm = self._fsms.get(segment_name)
        if fsm is None:
            self._prune_locked()
            fsm = _SegmentFsm(segment_name,
                              self._num_replicas_provider(segment_name))
            fsm.first_consumed_ms = time.monotonic()
            self._fsms[segment_name] = fsm
        return fsm

    def _prune_locked(self) -> None:
        now = time.monotonic()
        for name in [n for n, f in self._fsms.items()
                     if f.state is FsmState.COMMITTED
                     and now - f.committed_ms > self.COMMITTED_TTL_S]:
            del self._fsms[name]

    # -- protocol ------------------------------------------------------------
    def segment_consumed(self, segment_name: str, instance: str,
                         offset: StreamOffset) -> CompletionReply:
        with self._lock:
            fsm = self._fsm(segment_name)
            fsm.offsets[instance] = offset

            if fsm.state is FsmState.COMMITTED:
                # a winner already committed: same offset -> KEEP the local
                # build; different -> DISCARD and download (ref: :59 FSM)
                if offset == fsm.committed_offset:
                    return CompletionReply(CompletionResponse.KEEP)
                return CompletionReply(CompletionResponse.DISCARD)

            if fsm.state in (FsmState.COMMITTER_DECIDED,
                             FsmState.COMMITTER_NOTIFIED,
                             FsmState.COMMITTER_UPLOADING,
                             FsmState.COMMITTING):
                # committer timed out (crashed without segment_stopped_
                # consuming): re-open the election so ingestion can't stall.
                # The committer itself reporting again proves it's alive —
                # never re-elect on its own call.
                if (fsm.state is not FsmState.COMMITTING
                        and instance != fsm.committer
                        and time.monotonic() - fsm.elected_ms
                        > self._max_commit_time_s):
                    fsm.offsets.pop(fsm.committer, None)
                    fsm.state = FsmState.HOLDING
                    fsm.committer = None
                    fsm.winner_offset = None
                elif instance == fsm.committer:
                    return CompletionReply(CompletionResponse.COMMIT)
                else:
                    if offset < fsm.winner_offset:
                        return CompletionReply(
                            CompletionResponse.CATCHUP,
                            target_offset=fsm.winner_offset)
                    return CompletionReply(CompletionResponse.HOLD)

            # HOLDING: wait for all replicas or the hold window
            all_reported = len(fsm.offsets) >= fsm.num_replicas
            window_over = (time.monotonic() - fsm.first_consumed_ms
                           >= self._hold_window_s)
            if not (all_reported or window_over):
                return CompletionReply(CompletionResponse.HOLD)

            # elect: highest offset wins; offset ties break by instance id
            # (deterministic across controllers)
            winner = max(fsm.offsets.items(),
                         key=lambda kv: (kv[1].value, kv[0]))
            fsm.winner_offset = winner[1]
            fsm.committer = winner[0]
            fsm.state = FsmState.COMMITTER_DECIDED
            fsm.elected_ms = time.monotonic()
            if instance == fsm.committer:
                fsm.state = FsmState.COMMITTER_NOTIFIED
                return CompletionReply(CompletionResponse.COMMIT)
            if offset < fsm.winner_offset:
                return CompletionReply(CompletionResponse.CATCHUP,
                                       target_offset=fsm.winner_offset)
            return CompletionReply(CompletionResponse.HOLD)

    def segment_commit_start(self, segment_name: str, instance: str,
                             offset: StreamOffset) -> CompletionReply:
        with self._lock:
            fsm = self._fsms.get(segment_name)
            if fsm is None or fsm.committer != instance:
                return CompletionReply(CompletionResponse.HOLD)
            if fsm.state is FsmState.COMMITTED:
                return CompletionReply(CompletionResponse.KEEP)
            if offset != fsm.winner_offset:
                # committer diverged from its own reported offset — re-elect
                fsm.state = FsmState.HOLDING
                fsm.committer = None
                return CompletionReply(CompletionResponse.HOLD)
            fsm.state = FsmState.COMMITTER_UPLOADING
            return CompletionReply(CompletionResponse.COMMIT)

    def segment_commit_upload(self, segment_name: str, instance: str,
                              segment_dir: str) -> str:
        # deep-store upload is delegated to the commit handler at commit-end;
        # the local dir is the staging location
        return segment_dir

    def segment_commit_end(self, segment_name: str, instance: str,
                           offset: StreamOffset, location: str,
                           metadata) -> CompletionReply:
        with self._lock:
            fsm = self._fsms.get(segment_name)
            if fsm is None or fsm.committer != instance:
                return CompletionReply(CompletionResponse.HOLD)
            fsm.state = FsmState.COMMITTING
        # metadata flip outside the FSM lock (it touches the state store)
        if self._commit_handler is not None:
            self._commit_handler(segment_name, instance, offset, location,
                                 metadata)
        with self._lock:
            fsm.state = FsmState.COMMITTED
            fsm.committed_offset = offset
            fsm.committed_ms = time.monotonic()
        return CompletionReply(CompletionResponse.COMMIT)

    def segment_stopped_consuming(self, segment_name: str, instance: str,
                                  reason: str) -> None:
        with self._lock:
            fsm = self._fsms.get(segment_name)
            if fsm is None or fsm.state is FsmState.COMMITTED:
                return
            # a dead replica must not stay electable: drop its offset, and
            # re-open the election if it was (or would become) the winner
            fsm.offsets.pop(instance, None)
            if fsm.committer == instance or fsm.state is FsmState.HOLDING:
                fsm.state = FsmState.HOLDING
                fsm.committer = None
                fsm.winner_offset = None

    # -- introspection -------------------------------------------------------
    def fsm_state(self, segment_name: str) -> Optional[FsmState]:
        with self._lock:
            fsm = self._fsms.get(segment_name)
            return fsm.state if fsm else None

    def forget(self, segment_name: str) -> None:
        with self._lock:
            self._fsms.pop(segment_name, None)
