"""LLC realtime segment manager: CONSUMING segment lifecycle.

Re-design of ``pinot-controller/.../realtime/PinotLLCRealtimeSegmentManager.java:119``:
creates one CONSUMING segment per stream partition on table setup
(``setUpNewTable:287``), flips it ONLINE + creates the next sequence on
commit (``commitSegmentMetadata:508``), and repairs dead consumption
(``ensureAllPartitionsConsuming``, doc at :108-113).

LLC segment names follow the reference: ``table__partition__sequence__seed``
(ref: LLCSegmentName).
"""

from __future__ import annotations

import time

from typing import Dict, List, Optional

from pinot_tpu.controller.assignment import (
    PartitionedReplicaGroupAssignment,
    assignment_for_table,
)
from pinot_tpu.controller.state import (
    CONSUMING,
    ONLINE,
    ClusterStateStore,
    SegmentZKMetadata,
)
from pinot_tpu.ingestion.stream import StreamOffset, create_consumer_factory
from pinot_tpu.segment.metadata import SegmentMetadata


def llc_segment_name(table_raw: str, partition: int, sequence: int,
                     seed: Optional[str] = None) -> str:
    seed = seed or time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    return f"{table_raw}__{partition}__{sequence}__{seed}"


def parse_llc_name(segment_name: str):
    """-> (table, partition, sequence) (ref: LLCSegmentName)."""
    parts = segment_name.split("__")
    if len(parts) < 4:
        raise ValueError(f"not an LLC segment name: {segment_name!r}")
    return parts[0], int(parts[1]), int(parts[2])


class LLCRealtimeSegmentManager:
    """One per controller."""

    def __init__(self, store: ClusterStateStore, seed: Optional[str] = None):
        self.store = store
        self._seed = seed  # fixed seed for deterministic tests

    # -- table setup (ref: setUpNewTable:287) -------------------------------
    def setup_new_table(self, table_with_type: str) -> List[str]:
        cfg = self.store.get_table_config(table_with_type)
        if cfg is None or cfg.stream_config is None:
            raise ValueError(f"{table_with_type} is not a realtime table")
        factory = create_consumer_factory(cfg.stream_config)
        meta_provider = factory.create_metadata_provider()
        try:
            n_parts = meta_provider.partition_count()
            created = []
            for p in range(n_parts):
                start = meta_provider.earliest_offset(p)
                created.append(self._create_consuming_segment(
                    table_with_type, p, 0, start))
            return created
        finally:
            meta_provider.close()  # network providers hold a socket

    def _create_consuming_segment(self, table: str, partition: int,
                                  sequence: int,
                                  start_offset: StreamOffset) -> str:
        cfg = self.store.get_table_config(table)
        raw = cfg.table_name
        name = llc_segment_name(raw, partition, sequence, self._seed)
        md = SegmentZKMetadata(
            segment_name=name, table_name=table, status=CONSUMING,
            creation_time_ms=int(time.time() * 1000),
            start_offset=str(start_offset), partition=partition,
            sequence=sequence)
        self.store.set_segment_metadata(md)

        servers, replication = assignment_for_table(self.store, table)
        strategy = PartitionedReplicaGroupAssignment(
            num_replica_groups=max(min(replication, len(servers)), 1))
        chosen = strategy.assign(name, self.store.get_ideal_state(table),
                                 servers, replication, partition=partition)

        def apply(ideal):
            ideal = ideal or {}
            ideal[name] = {inst: CONSUMING for inst in chosen}
            return ideal

        self.store.update_ideal_state(table, apply)
        return name

    # -- commit (ref: commitSegmentMetadata:508) ----------------------------
    def commit_segment(self, table: str, segment_name: str,
                       end_offset: StreamOffset, download_url: str,
                       segment_metadata: Optional[SegmentMetadata] = None) -> str:
        """Flip CONSUMING -> ONLINE (same instances), record the offset
        checkpoint, create the next CONSUMING sequence. Returns the new
        consuming segment's name."""
        zk = self.store.get_segment_metadata(table, segment_name)
        if zk is None:
            raise KeyError(f"unknown segment {segment_name}")
        zk.status = ONLINE
        zk.end_offset = str(end_offset)
        zk.download_url = download_url
        zk.push_time_ms = int(time.time() * 1000)
        if segment_metadata is not None:
            zk.total_docs = segment_metadata.num_docs
            zk.crc = segment_metadata.crc
            zk.start_time = segment_metadata.min_time
            zk.end_time = segment_metadata.max_time
        self.store.set_segment_metadata(zk)

        def apply(ideal):
            ideal = ideal or {}
            seg = ideal.get(segment_name, {})
            ideal[segment_name] = {inst: ONLINE for inst in seg}
            return ideal

        self.store.update_ideal_state(table, apply)

        _, partition, sequence = parse_llc_name(segment_name)
        return self._create_consuming_segment(
            table, partition, sequence + 1, end_offset)

    # -- repair (ref: ensureAllPartitionsConsuming :108-113) ----------------
    def ensure_all_partitions_consuming(self, table: str) -> List[str]:
        """Every stream partition must have exactly one CONSUMING segment;
        recreate any that died (committed without successor, errored, or
        never created after partition expansion)."""
        cfg = self.store.get_table_config(table)
        if cfg is None or cfg.stream_config is None:
            return []
        factory = create_consumer_factory(cfg.stream_config)
        # ONE provider per repair pass, closed when done — this runs every
        # validation cycle, and network providers (kafka wire) hold sockets
        meta_provider = factory.create_metadata_provider()
        try:
            n_parts = meta_provider.partition_count()

            consuming: Dict[int, str] = {}
            latest: Dict[int, SegmentZKMetadata] = {}
            for md in self.store.segment_metadata_list(table):
                if md.partition is None:
                    continue
                if md.status == CONSUMING:
                    consuming[md.partition] = md.segment_name
                prev = latest.get(md.partition)
                if prev is None or (md.sequence or 0) > (prev.sequence or 0):
                    latest[md.partition] = md

            created = []
            for p in range(n_parts):
                if p in consuming:
                    continue
                last = latest.get(p)
                if last is None:
                    start = meta_provider.earliest_offset(p)
                    created.append(self._create_consuming_segment(
                        table, p, 0, start))
                else:
                    start = (StreamOffset.parse(last.end_offset)
                             if last.end_offset else
                             StreamOffset.parse(last.start_offset or "0"))
                    created.append(self._create_consuming_segment(
                        table, p, (last.sequence or 0) + 1, start))
            return created
        finally:
            meta_provider.close()
