"""Tiered storage: age-based segment relocation to tagged servers.

Re-design of the reference's tier model (``pinot-spi/.../config/table/
TierConfig.java``, ``pinot-common/.../tier/TierFactory`` +
``TimeBasedTierSegmentSelector``, applied by the controller's
``SegmentRelocator``): a table declares ordered tiers, each selecting
segments older than a threshold and naming the server tag that should hold
them; the relocator periodic task recomputes each segment's target tier and
rewrites IdealState entries whose instances don't match the tier's tag.

Age here is measured from the segment's push/creation wall-clock time (the
reference converts the time-column end time to millis; raw time-column
units are not globally convertible in this build, and push age is the
operational quantity tiering actually manages).
"""

from __future__ import annotations

import re
import time

from dataclasses import dataclass
from typing import Dict, List, Optional

_AGE_RE = re.compile(r"^(\d+)\s*(ms|s|m|h|d)$", re.I)
_UNIT_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
            "d": 86_400_000}


def parse_age_ms(text: str) -> int:
    m = _AGE_RE.match(str(text).strip())
    if not m:
        raise ValueError(f"bad segmentAge {text!r} (want e.g. '7d', '24h')")
    return int(m.group(1)) * _UNIT_MS[m.group(2).lower()]


@dataclass
class TierConfig:
    """One tier (ref: TierConfig.java JSON layout)."""

    name: str
    segment_age: str = "0d"            # segments OLDER than this belong here
    server_tag: str = "DefaultTenant"
    segment_selector_type: str = "time"
    storage_type: str = "pinot_server"

    def to_dict(self) -> Dict:
        return {"name": self.name, "segmentSelectorType":
                self.segment_selector_type, "segmentAge": self.segment_age,
                "storageType": self.storage_type,
                "serverTag": self.server_tag}

    @classmethod
    def from_dict(cls, d: Dict) -> "TierConfig":
        return cls(name=d["name"],
                   segment_age=d.get("segmentAge", "0d"),
                   server_tag=d.get("serverTag", "DefaultTenant"),
                   segment_selector_type=d.get("segmentSelectorType", "time"),
                   storage_type=d.get("storageType", "pinot_server"))


def target_tier(tiers: List[TierConfig], age_ms: int) -> Optional[TierConfig]:
    """The matching tier with the LARGEST age threshold the segment exceeds
    (ref: TierConfigUtils.getSortedTiers — most specific tier wins)."""
    best: Optional[TierConfig] = None
    best_age = -1
    for t in tiers:
        if t.segment_selector_type.lower() != "time":
            continue
        thresh = parse_age_ms(t.segment_age)
        if age_ms >= thresh and thresh > best_age:
            best = t
            best_age = thresh
    return best


class SegmentRelocator:
    """Controller periodic task (ref: helix/core/relocation/SegmentRelocator)."""

    def __init__(self, store):
        self.store = store

    def relocate_table(self, table: str,
                       now_ms: Optional[int] = None) -> List[str]:
        """-> names of segments whose IdealState moved to a new tier's
        servers. The server reconcile loop then downloads/drops per the
        updated map, and the external view follows."""
        cfg = self.store.get_table_config(table)
        tiers = [TierConfig.from_dict(d)
                 for d in (cfg.tier_configs or [])] if cfg else []
        if not tiers:
            return []
        now = int(time.time() * 1000) if now_ms is None else now_ms
        servers_by_tag: Dict[str, List[str]] = {}
        for inst in self.store.instances("SERVER"):
            if not inst.alive:
                continue
            for tag in inst.tags:
                servers_by_tag.setdefault(tag, []).append(inst.instance_id)

        import zlib

        replication = cfg.replication if cfg else 1
        moved: List[str] = []

        def apply(ideal):
            # atomic read-modify-write under the store lock: a segment
            # uploaded concurrently must not be clobbered out of the map
            moved.clear()
            ideal = dict(ideal or {})
            for segment, inst_map in list(ideal.items()):
                md = self.store.get_segment_metadata(table, segment)
                if md is None or md.status != "ONLINE":
                    continue
                ts = md.push_time_ms or md.creation_time_ms
                if not ts:
                    continue
                tier = target_tier(tiers, now - ts)
                if tier is None:
                    continue
                pool = sorted(servers_by_tag.get(tier.server_tag, []))
                if not pool:
                    continue  # no server carries the tag: leave alone
                if set(inst_map.keys()) <= set(pool):
                    continue  # already on the tier
                n = min(replication, len(pool))
                # stable choice: crc-offset round robin keeps segments
                # spread (process-salted hash() reshuffles every restart)
                start = zlib.crc32(segment.encode("utf-8")) % len(pool)
                chosen = [pool[(start + i) % len(pool)] for i in range(n)]
                ideal[segment] = {inst: "ONLINE" for inst in chosen}
                moved.append(segment)
            return ideal

        self.store.update_ideal_state(table, apply)
        return list(moved)
