"""QueryContext: the compiled server-side query representation.

Re-design of ``pinot-core/.../query/request/context/QueryContext.java:72`` +
``QueryContextConverterUtils``: built from a parsed query, it resolves
aliases/ordinals, extracts the aggregation functions (including inside
post-aggregation arithmetic), and exposes everything the plan maker needs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    Function,
    Identifier,
    Literal,
    OrderByExpr,
    STAR,
)
from pinot_tpu.query.parser import ParsedQuery, SqlParseError, parse_sql


class AggregationFunctionType(Enum):
    """Canonical aggregation function list
    (ref: pinot-segment-spi AggregationFunctionType.java)."""

    COUNT = "count"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    AVG = "avg"
    MINMAXRANGE = "minmaxrange"
    SUMPRECISION = "sumprecision"
    MODE = "mode"
    DISTINCTCOUNT = "distinctcount"
    DISTINCTCOUNTBITMAP = "distinctcountbitmap"
    DISTINCTCOUNTHLL = "distinctcounthll"
    DISTINCTCOUNTRAWHLL = "distinctcountrawhll"
    SEGMENTPARTITIONEDDISTINCTCOUNT = "segmentpartitioneddistinctcount"
    PERCENTILE = "percentile"
    PERCENTILEEST = "percentileest"
    PERCENTILETDIGEST = "percentiletdigest"
    DISTINCTCOUNTTHETASKETCH = "distinctcountthetasketch"
    DISTINCTCOUNTRAWTHETASKETCH = "distinctcountrawthetasketch"
    IDSET = "idset"
    LASTWITHTIME = "lastwithtime"
    FIRSTWITHTIME = "firstwithtime"
    STUNION = "stunion"
    ST_UNION = "st_union"
    # MV variants
    COUNTMV = "countmv"
    SUMMV = "summv"
    MINMV = "minmv"
    MAXMV = "maxmv"
    AVGMV = "avgmv"
    MINMAXRANGEMV = "minmaxrangemv"
    DISTINCTCOUNTMV = "distinctcountmv"
    DISTINCTCOUNTHLLMV = "distinctcounthllmv"
    PERCENTILEMV = "percentilemv"
    PERCENTILEESTMV = "percentileestmv"
    PERCENTILETDIGESTMV = "percentiletdigestmv"

    @classmethod
    def names(cls) -> set:
        return {m.value for m in cls}

    @classmethod
    def from_name(cls, name: str) -> "AggregationFunctionType":
        n = name.lower()
        # percentile variants carry the percentile in the name: percentile95
        for prefix in ("percentiletdigest", "percentileest", "percentile"):
            if n.startswith(prefix) and n[len(prefix):].isdigit():
                return cls(prefix)
        return cls(n)


def _is_agg_name(name: str) -> bool:
    n = name.lower()
    if n in AggregationFunctionType.names():
        return True
    for prefix in ("percentiletdigest", "percentileest", "percentile"):
        if n.startswith(prefix) and n[len(prefix):].isdigit():
            return True
    return False


@dataclass
class QueryContext:
    """Ref: QueryContext.java:72."""

    table_name: str
    select_expressions: List[Expr]
    aliases: List[Optional[str]]
    distinct: bool
    filter: Optional[FilterNode]
    group_by: List[Expr]
    having: Optional[FilterNode]
    order_by: List[OrderByExpr]
    limit: int
    offset: int
    options: Dict[str, str] = field(default_factory=dict)

    explain: bool = False  # EXPLAIN PLAN FOR
    # derived (filled by build):
    aggregations: List[Function] = field(default_factory=list)
    # original SQL text when compiled from SQL (caching/diagnostics key)
    sql: Optional[str] = None

    @property
    def is_aggregation(self) -> bool:
        return bool(self.aggregations)

    @property
    def is_group_by(self) -> bool:
        return bool(self.group_by)

    @property
    def is_selection(self) -> bool:
        return not self.aggregations and not self.distinct

    def referenced_columns(self) -> List[str]:
        """All physical columns the query touches (staging set)."""
        cols: List[str] = []
        for e in self.select_expressions:
            cols.extend(e.columns())
        if self.filter is not None:
            cols.extend(self.filter.columns())
        for e in self.group_by:
            cols.extend(e.columns())
        if self.having is not None:
            cols.extend(self.having.columns())
        for ob in self.order_by:
            cols.extend(ob.expr.columns())
        seen, out = set(), []
        for c in cols:
            if c != "*" and c not in seen:
                seen.add(c)
                out.append(c)
        return out

    def timeout_ms(self, default: int) -> int:
        return int(self.options.get("timeoutMs", default))

    @property
    def trace_enabled(self) -> bool:
        """OPTION(trace=true) — request-scoped tracing: the query records
        a full lifecycle span tree (common/tracing.py) returned in
        ``traceInfo`` (ref: trace flag at BaseBrokerRequestHandler).
        Untraced queries may still be sampled server-side via
        ``pinot.server.query.trace.sample``."""
        return self.options.get("trace", "").lower() == "true"

    @property
    def request_id(self) -> Optional[str]:
        """OPTION(requestId=...) — client-supplied correlation id,
        surfaced in ``/debug/queries`` and the slow-query log (ref: the
        requestId threaded through BaseBrokerRequestHandler)."""
        return self.options.get("requestId")

    def __str__(self) -> str:
        return (f"QueryContext(table={self.table_name}, "
                f"select={[str(e) for e in self.select_expressions]}, "
                f"filter={self.filter}, groupBy={[str(e) for e in self.group_by]}, "
                f"limit={self.limit})")


def _collect_aggregations(expr: Expr, out: List[Function]) -> None:
    """Find aggregation sub-expressions (depth-first, dedup by equality)."""
    if isinstance(expr, Function):
        if _is_agg_name(expr.name):
            if expr not in out:
                out.append(expr)
            return  # no nested aggs inside an agg
        for a in expr.args:
            _collect_aggregations(a, out)


def _resolve_alias(expr: Expr, alias_map: Dict[str, Expr],
                   select_exprs: List[Expr], top_level: bool = True) -> Expr:
    """Aliases anywhere; 1-based ordinals ONLY as a whole top-level GROUP BY /
    ORDER BY item (``ORDER BY a + 1`` is arithmetic, not an ordinal)
    (ref: rewriters AliasApplier / OrdinalsUpdater)."""
    if isinstance(expr, Identifier) and expr.name in alias_map:
        return alias_map[expr.name]
    if (top_level and isinstance(expr, Literal)
            and type(expr.value) is int):  # bool is not an ordinal
        ordinal = expr.value
        if 1 <= ordinal <= len(select_exprs):
            return select_exprs[ordinal - 1]
        raise SqlParseError(f"ordinal {ordinal} out of range")
    if isinstance(expr, Function):
        return Function(expr.name,
                        tuple(_resolve_alias(a, alias_map, select_exprs, False)
                              for a in expr.args))
    return expr


def _resolve_filter_aliases(node: FilterNode, alias_map: Dict[str, Expr],
                            select_exprs: List[Expr]) -> FilterNode:
    if node.predicate is not None:
        p = node.predicate
        # aliases only — ordinals are not meaningful in HAVING
        new_lhs = _resolve_alias(p.lhs, alias_map, select_exprs, top_level=False)
        if new_lhs is not p.lhs:
            from dataclasses import replace
            return FilterNode.pred(replace(p, lhs=new_lhs))
        return node
    return FilterNode(node.op,
                      children=tuple(_resolve_filter_aliases(c, alias_map, select_exprs)
                                     for c in node.children),
                      predicate=None)


def build_query_context(parsed: ParsedQuery) -> QueryContext:
    """Ref: QueryContextConverterUtils.getQueryContext."""
    select_exprs = [e for e, _ in parsed.select]
    aliases = [a for _, a in parsed.select]
    alias_map: Dict[str, Expr] = {
        a: e for e, a in parsed.select if a is not None}

    # ordinals must be resolved BEFORE constant folding ('ORDER BY 1 + 1' is
    # a constant sort key, not ordinal 2), so folding happens here, after
    # _resolve_alias, not in the optimizer
    from pinot_tpu.query.expressions import fold_constants

    group_by = [fold_constants(_resolve_alias(e, alias_map, select_exprs))
                for e in parsed.group_by]
    order_by = [OrderByExpr(fold_constants(
                    _resolve_alias(ob.expr, alias_map, select_exprs)),
                            ob.ascending)
                for ob in parsed.order_by]
    having = (_resolve_filter_aliases(parsed.having, alias_map, select_exprs)
              if parsed.having is not None else None)

    ctx = QueryContext(
        table_name=parsed.table,
        select_expressions=select_exprs,
        aliases=aliases,
        distinct=parsed.distinct,
        filter=parsed.where,
        group_by=group_by,
        having=having,
        order_by=order_by,
        limit=parsed.limit,
        offset=parsed.offset,
        options=dict(parsed.options),
        explain=parsed.explain,
    )

    aggs: List[Function] = []
    for e in select_exprs:
        _collect_aggregations(e, aggs)
    if having is not None:
        for p in having.predicates():
            _collect_aggregations(p.lhs, aggs)
    for ob in order_by:
        _collect_aggregations(ob.expr, aggs)
    ctx.aggregations = aggs

    if ctx.distinct and aggs:
        raise SqlParseError("DISTINCT with aggregations is not supported")
    if group_by and not aggs:
        # GROUP BY without aggregations == SELECT DISTINCT over the group
        # expressions (the reference's PQL->SQL group-by semantics)
        group_keys = {str(e) for e in group_by}
        for e in select_exprs:
            if str(e) not in group_keys:
                raise SqlParseError(
                    f"non-aggregate select expression {e} must appear in "
                    f"GROUP BY")
        ctx.distinct = True
        ctx.group_by = []
    return ctx


def compile_query(sql: str) -> QueryContext:
    """SQL -> optimized QueryContext (parse + optimize + context build)."""
    from pinot_tpu.query.optimizer import optimize

    parsed = parse_sql(sql)
    parsed = optimize(parsed)
    ctx = build_query_context(parsed)
    ctx.sql = sql
    return ctx
