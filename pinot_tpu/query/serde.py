"""Query-context wire serde: the InstanceRequest payload.

Re-design of the reference's thrift request model
(``pinot-common/src/thrift/query.thrift:25`` — ``PinotQuery`` /
``InstanceRequest`` shipped broker->server over Netty): expressions, filter
trees, and the full QueryContext round-trip through JSON dicts, so the
broker can ship the *compiled* query (including time-boundary filters the
SQL string never contained) to remote servers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    FilterOp,
    Function,
    Identifier,
    Literal,
    OrderByExpr,
    Predicate,
    PredicateType,
)


# -- expressions -----------------------------------------------------------

def expr_to_dict(e: Expr) -> Dict[str, Any]:
    if isinstance(e, Identifier):
        return {"t": "id", "name": e.name}
    if isinstance(e, Literal):
        return {"t": "lit", "value": e.value}
    if isinstance(e, Function):
        return {"t": "fn", "name": e.name,
                "args": [expr_to_dict(a) for a in e.args]}
    # CaseFilterExpr etc. are parser-internal and never reach the wire
    raise TypeError(f"cannot serialize expression {e!r}")


def expr_from_dict(d: Dict[str, Any]) -> Expr:
    t = d["t"]
    if t == "id":
        return Identifier(d["name"])
    if t == "lit":
        return Literal(d["value"])
    if t == "fn":
        return Function(d["name"], [expr_from_dict(a) for a in d["args"]])
    raise ValueError(f"unknown expression tag {t!r}")


# -- predicates / filters ---------------------------------------------------

def predicate_to_dict(p: Predicate) -> Dict[str, Any]:
    return {
        "type": p.type.value,
        "lhs": expr_to_dict(p.lhs),
        "values": list(p.values),
        "lower": p.lower,
        "upper": p.upper,
        "lowerInclusive": p.lower_inclusive,
        "upperInclusive": p.upper_inclusive,
    }


def predicate_from_dict(d: Dict[str, Any]) -> Predicate:
    return Predicate(
        type=PredicateType(d["type"]),
        lhs=expr_from_dict(d["lhs"]),
        values=tuple(d.get("values", [])),
        lower=d.get("lower"),
        upper=d.get("upper"),
        lower_inclusive=d.get("lowerInclusive", False),
        upper_inclusive=d.get("upperInclusive", False),
    )


def filter_to_dict(node: Optional[FilterNode]) -> Optional[Dict[str, Any]]:
    if node is None:
        return None
    d: Dict[str, Any] = {"op": node.op.value}
    if node.predicate is not None:
        d["predicate"] = predicate_to_dict(node.predicate)
    if node.children:
        d["children"] = [filter_to_dict(c) for c in node.children]
    return d


def filter_from_dict(d: Optional[Dict[str, Any]]) -> Optional[FilterNode]:
    if d is None:
        return None
    return FilterNode(
        FilterOp(d["op"]),
        children=tuple(filter_from_dict(c) for c in d.get("children", [])),
        predicate=(predicate_from_dict(d["predicate"])
                   if d.get("predicate") else None),
    )


# -- query context ----------------------------------------------------------

def context_to_dict(ctx: QueryContext) -> Dict[str, Any]:
    return {
        "tableName": ctx.table_name,
        "select": [expr_to_dict(e) for e in ctx.select_expressions],
        "aliases": list(ctx.aliases),
        "distinct": ctx.distinct,
        "filter": filter_to_dict(ctx.filter),
        "groupBy": [expr_to_dict(e) for e in ctx.group_by],
        "having": filter_to_dict(ctx.having),
        "orderBy": [{"expr": expr_to_dict(ob.expr), "asc": ob.ascending}
                    for ob in ctx.order_by],
        "limit": ctx.limit,
        "offset": ctx.offset,
        "options": dict(ctx.options),
        "aggregations": [expr_to_dict(f) for f in ctx.aggregations],
    }


def context_from_dict(d: Dict[str, Any]) -> QueryContext:
    return QueryContext(
        table_name=d["tableName"],
        select_expressions=[expr_from_dict(e) for e in d["select"]],
        aliases=list(d["aliases"]),
        distinct=d["distinct"],
        filter=filter_from_dict(d.get("filter")),
        group_by=[expr_from_dict(e) for e in d.get("groupBy", [])],
        having=filter_from_dict(d.get("having")),
        order_by=[OrderByExpr(expr_from_dict(ob["expr"]), ob["asc"])
                  for ob in d.get("orderBy", [])],
        limit=d["limit"],
        offset=d["offset"],
        options=d.get("options", {}),
        aggregations=[expr_from_dict(f) for f in d.get("aggregations", [])],
    )
