"""Query layer: SQL parser, expression model, query context, optimizer
(ref: pinot-common sql/ + request context, pinot-core query/optimizer)."""

from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    FilterOp,
    Function,
    Identifier,
    Literal,
    OrderByExpr,
    Predicate,
    PredicateType,
    STAR,
)
from pinot_tpu.query.parser import ParsedQuery, SqlParseError, parse_sql
from pinot_tpu.query.context import (
    AggregationFunctionType,
    QueryContext,
    build_query_context,
    compile_query,
)

__all__ = [
    "Expr", "FilterNode", "FilterOp", "Function", "Identifier", "Literal",
    "OrderByExpr", "Predicate", "PredicateType", "STAR",
    "ParsedQuery", "SqlParseError", "parse_sql",
    "AggregationFunctionType", "QueryContext", "build_query_context",
    "compile_query",
]
