"""SQL parser: SQL text -> parsed query AST.

Re-design of the reference's Calcite-based parser
(``pinot-common/.../sql/parsers/CalciteSqlParser.java:67``) as a hand-written
lexer + recursive-descent parser for the Pinot SQL dialect:

    SELECT [DISTINCT] select_list FROM table
    [WHERE bool_expr] [GROUP BY expr_list] [HAVING bool_expr]
    [ORDER BY expr [ASC|DESC], ...] [LIMIT n [OFFSET m] | LIMIT m, n]
    [OPTION(k=v, ...)]

Operators compile to canonical function calls (``a + b`` -> ``plus(a,b)``)
and comparisons compile to the Predicate model, exactly as the reference
normalizes through its thrift ``PinotQuery`` AST.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    Function,
    Identifier,
    Literal,
    OrderByExpr,
    Predicate,
    PredicateType,
    STAR,
    fold_constants,
)


class SqlParseError(Exception):
    pass


# --------------------------------------------------------------------------
# Lexer
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<number>\d+\.\d*([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?|\d+([eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_$][A-Za-z0-9_$]*)
  | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\*|\+|-|/|%|\.)
""", re.VERBOSE)


@dataclass
class Token:
    kind: str   # number | string | ident | qident | op | eof
    text: str
    pos: int

    @property
    def upper(self) -> str:
        return self.text.upper()


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlParseError(f"unexpected character {sql[pos]!r} at position {pos}")
        kind = m.lastgroup
        # number group has inner groups; find the outer kind
        for k in ("ws", "number", "string", "qident", "ident", "op"):
            if m.group(k) is not None:
                kind = k
                break
        if kind != "ws":
            tokens.append(Token(kind, m.group(kind), pos))
        pos = m.end()
    tokens.append(Token("eof", "", n))
    return tokens


_KEYWORDS = {
    "SELECT", "DISTINCT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER",
    "LIMIT", "OFFSET", "OPTION", "AND", "OR", "NOT", "IN", "BETWEEN", "LIKE",
    "IS", "NULL", "TRUE", "FALSE", "AS", "ASC", "DESC", "CASE", "WHEN",
    "THEN", "ELSE", "END",
}

# function-call predicates: f(col, literal) used in WHERE position
_PREDICATE_FUNCTIONS = {
    "regexp_like": PredicateType.REGEXP_LIKE,
    "text_match": PredicateType.TEXT_MATCH,
    "json_match": PredicateType.JSON_MATCH,
}


@dataclass
class ParsedQuery:
    """Raw parse result (the analogue of the thrift PinotQuery,
    ref: pinot-common/src/thrift/query.thrift:25)."""

    table: str
    select: List[Tuple[Expr, Optional[str]]]  # (expr, alias)
    distinct: bool = False
    where: Optional[FilterNode] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[FilterNode] = None
    order_by: List[OrderByExpr] = field(default_factory=list)
    limit: int = 10
    offset: int = 0
    options: Dict[str, str] = field(default_factory=dict)
    explain: bool = False  # EXPLAIN PLAN FOR <sql>


class _Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_keyword(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in words

    def accept_keyword(self, *words: str) -> bool:
        if self.at_keyword(*words):
            self.next()
            return True
        return False

    def expect_keyword(self, word: str) -> None:
        if not self.accept_keyword(word):
            t = self.peek()
            raise SqlParseError(f"expected {word} at position {t.pos}, got {t.text!r}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.text in ops

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            t = self.peek()
            raise SqlParseError(f"expected {op!r} at position {t.pos}, got {t.text!r}")

    # -- entry -------------------------------------------------------------
    def parse(self) -> ParsedQuery:
        self.expect_keyword("SELECT")
        distinct = self.accept_keyword("DISTINCT")
        select = self.parse_select_list()
        self.expect_keyword("FROM")
        table = self.parse_table_name()
        where = group_by = having = None
        order_by: List[OrderByExpr] = []
        limit, offset = 10, 0
        options: Dict[str, str] = {}
        if self.accept_keyword("WHERE"):
            where = self.parse_bool_expr()
        if self.accept_keyword("GROUP"):
            self.expect_keyword("BY")
            group_by = self.parse_expr_list()
        if self.accept_keyword("HAVING"):
            having = self.parse_bool_expr()
        if self.accept_keyword("ORDER"):
            self.expect_keyword("BY")
            order_by = self.parse_order_list()
        if self.accept_keyword("LIMIT"):
            a = self.parse_int()
            if self.accept_op(","):
                offset, limit = a, self.parse_int()  # MySQL style LIMIT off, n
            elif self.accept_keyword("OFFSET"):
                limit, offset = a, self.parse_int()
            else:
                limit = a
        if self.accept_keyword("OPTION"):
            self.expect_op("(")
            while not self.accept_op(")"):
                k = self.next().text
                self.expect_op("=")
                v = self.next().text
                if v.startswith("'"):
                    v = v[1:-1].replace("''", "'")
                options[k] = v
                self.accept_op(",")
        t = self.peek()
        if t.kind != "eof":
            raise SqlParseError(f"unexpected trailing input at position {t.pos}: {t.text!r}")
        return ParsedQuery(table=table, select=select, distinct=distinct,
                           where=where, group_by=group_by or [], having=having,
                           order_by=order_by, limit=limit, offset=offset,
                           options=options)

    def parse_table_name(self) -> str:
        parts = [self.parse_identifier_token()]
        while self.accept_op("."):
            parts.append(self.parse_identifier_token())
        return ".".join(parts)

    def parse_identifier_token(self) -> str:
        t = self.next()
        if t.kind == "qident":
            return t.text[1:-1].replace('""', '"')
        if t.kind == "ident":
            return t.text
        raise SqlParseError(f"expected identifier at position {t.pos}, got {t.text!r}")

    def parse_int(self) -> int:
        t = self.next()
        if t.kind != "number" or not t.text.isdigit():
            raise SqlParseError(f"expected integer at position {t.pos}, "
                                f"got {t.text!r}")
        return int(t.text)

    # -- select list ---------------------------------------------------------
    def parse_select_list(self) -> List[Tuple[Expr, Optional[str]]]:
        items: List[Tuple[Expr, Optional[str]]] = []
        while True:
            expr = self.parse_expr()
            alias = None
            if self.accept_keyword("AS"):
                alias = self.parse_identifier_token()
            elif (self.peek().kind in ("ident", "qident")
                  and self.peek().upper not in _KEYWORDS):
                alias = self.parse_identifier_token()
            items.append((expr, alias))
            if not self.accept_op(","):
                break
        return items

    def parse_expr_list(self) -> List[Expr]:
        out = [self.parse_expr()]
        while self.accept_op(","):
            out.append(self.parse_expr())
        return out

    def parse_order_list(self) -> List[OrderByExpr]:
        out = []
        while True:
            e = self.parse_expr()
            asc = True
            if self.accept_keyword("DESC"):
                asc = False
            else:
                self.accept_keyword("ASC")
            out.append(OrderByExpr(e, asc))
            if not self.accept_op(","):
                break
        return out

    # -- boolean expressions -------------------------------------------------
    def parse_bool_expr(self) -> FilterNode:
        return self.parse_or()

    def parse_or(self) -> FilterNode:
        left = self.parse_and()
        children = [left]
        while self.accept_keyword("OR"):
            children.append(self.parse_and())
        return children[0] if len(children) == 1 else FilterNode.or_(children)

    def parse_and(self) -> FilterNode:
        children = [self.parse_not()]
        while self.accept_keyword("AND"):
            children.append(self.parse_not())
        return children[0] if len(children) == 1 else FilterNode.and_(children)

    def parse_not(self) -> FilterNode:
        if self.accept_keyword("NOT"):
            return FilterNode.not_(self.parse_not())
        return self.parse_bool_primary()

    def parse_bool_primary(self) -> FilterNode:
        if self.at_op("("):
            # ambiguous: grouped boolean vs parenthesized arithmetic.
            # Try boolean group; backtrack if it turns out to be arithmetic.
            save = self.i
            try:
                self.expect_op("(")
                node = self.parse_bool_expr()
                self.expect_op(")")
                # if a comparison/arith operator follows, it was arithmetic
                if not (self.at_op("=", "!=", "<>", "<", "<=", ">", ">=", "+",
                                   "-", "*", "/", "%")
                        or self.at_keyword("BETWEEN", "IN", "LIKE", "IS", "NOT")):
                    return node
            except SqlParseError:
                pass
            self.i = save
        return self.parse_predicate()

    def parse_predicate(self) -> FilterNode:
        lhs = self.parse_expr()

        # function-call predicates: regexp_like(col, 're'), text_match(...)
        if isinstance(lhs, Function) and lhs.name in _PREDICATE_FUNCTIONS:
            ptype = _PREDICATE_FUNCTIONS[lhs.name]
            if len(lhs.args) != 2 or not isinstance(lhs.args[1], Literal):
                raise SqlParseError(f"{lhs.name} expects (expr, literal)")
            return FilterNode.pred(Predicate(
                ptype, lhs.args[0], values=(lhs.args[1].value,)))

        negate = False
        if self.accept_keyword("NOT"):
            negate = True

        if self.accept_keyword("IN"):
            self.expect_op("(")
            values = [self.parse_literal_value()]
            while self.accept_op(","):
                values.append(self.parse_literal_value())
            self.expect_op(")")
            ptype = PredicateType.NOT_IN if negate else PredicateType.IN
            return FilterNode.pred(Predicate(ptype, lhs, values=tuple(values)))

        if self.accept_keyword("BETWEEN"):
            lo = self.parse_literal_value()
            self.expect_keyword("AND")
            hi = self.parse_literal_value()
            node = FilterNode.pred(Predicate(
                PredicateType.RANGE, lhs, lower=lo, upper=hi,
                lower_inclusive=True, upper_inclusive=True))
            return FilterNode.not_(node) if negate else node

        if self.accept_keyword("LIKE"):
            pattern = self.parse_literal_value()
            node = FilterNode.pred(Predicate(
                PredicateType.LIKE, lhs, values=(pattern,)))
            return FilterNode.not_(node) if negate else node

        if negate:
            raise SqlParseError("expected IN/BETWEEN/LIKE after NOT")

        if self.accept_keyword("IS"):
            is_not = self.accept_keyword("NOT")
            self.expect_keyword("NULL")
            ptype = PredicateType.IS_NOT_NULL if is_not else PredicateType.IS_NULL
            return FilterNode.pred(Predicate(ptype, lhs))

        for op in ("=", "!=", "<>", "<=", ">=", "<", ">"):
            if self.accept_op(op):
                rhs = self.parse_expr()
                return self._comparison(op, lhs, rhs)

        raise SqlParseError(
            f"expected predicate operator at position {self.peek().pos}, "
            f"got {self.peek().text!r}")

    _SWAP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}

    def _comparison(self, op: str, lhs: Expr, rhs: Expr) -> FilterNode:
        # fold constant arithmetic so 'b > 2 + 3' has a literal rhs
        lhs, rhs = fold_constants(lhs), fold_constants(rhs)
        # normalize to expr-vs-literal (swap '5 < col' -> 'col > 5')
        if isinstance(lhs, Literal) and not isinstance(rhs, Literal):
            lhs, rhs = rhs, lhs
            op = self._SWAP.get(op, op)
        if not isinstance(rhs, Literal):
            raise SqlParseError(
                f"comparison right-hand side must be a literal, got {rhs}")
        v = rhs.value
        if op == "=":
            return FilterNode.pred(Predicate(PredicateType.EQ, lhs, values=(v,)))
        if op in ("!=", "<>"):
            return FilterNode.pred(Predicate(PredicateType.NOT_EQ, lhs, values=(v,)))
        if op == ">":
            return FilterNode.pred(Predicate(PredicateType.RANGE, lhs, lower=v))
        if op == ">=":
            return FilterNode.pred(Predicate(PredicateType.RANGE, lhs, lower=v,
                                             lower_inclusive=True))
        if op == "<":
            return FilterNode.pred(Predicate(PredicateType.RANGE, lhs, upper=v))
        return FilterNode.pred(Predicate(PredicateType.RANGE, lhs, upper=v,
                                         upper_inclusive=True))

    def parse_literal_value(self) -> Any:
        e = self.parse_expr()
        if not isinstance(e, Literal):
            raise SqlParseError(f"expected literal, got {e}")
        return e.value

    # -- value expressions ---------------------------------------------------
    def parse_expr(self) -> Expr:
        return self.parse_add()

    def parse_add(self) -> Expr:
        left = self.parse_mul()
        while self.at_op("+", "-"):
            op = self.next().text
            right = self.parse_mul()
            left = Function("plus" if op == "+" else "minus", (left, right))
        return left

    def parse_mul(self) -> Expr:
        left = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().text
            right = self.parse_unary()
            name = {"*": "times", "/": "divide", "%": "mod"}[op]
            left = Function(name, (left, right))
        return left

    def parse_unary(self) -> Expr:
        if self.accept_op("-"):
            inner = self.parse_unary()
            if isinstance(inner, Literal) and isinstance(inner.value, (int, float)):
                return Literal(-inner.value)
            return Function("minus", (Literal(0), inner))
        return self.parse_primary()

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            text = t.text
            if "." in text or "e" in text.lower():
                return Literal(float(text))
            return Literal(int(text))
        if t.kind == "string":
            self.next()
            return Literal(t.text[1:-1].replace("''", "'"))
        if t.kind == "op" and t.text == "(":
            self.next()
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "op" and t.text == "*":
            self.next()
            return STAR
        if t.kind == "qident":
            self.next()
            return Identifier(t.text[1:-1].replace('""', '"'))
        if t.kind == "ident":
            up = t.upper
            if up == "NULL":
                self.next()
                return Literal(None)
            if up == "TRUE":
                self.next()
                return Literal(True)
            if up == "FALSE":
                self.next()
                return Literal(False)
            if up == "CASE":
                return self.parse_case()
            self.next()
            if self.at_op("("):
                return self.parse_function_call(t.text)
            return Identifier(t.text)
        raise SqlParseError(f"unexpected token {t.text!r} at position {t.pos}")

    def parse_function_call(self, name: str) -> Expr:
        self.expect_op("(")
        if self.accept_op(")"):
            return Function(name, ())
        if self.accept_keyword("DISTINCT"):
            # COUNT(DISTINCT x) -> distinctcount(x), like the reference rewrite
            args = self.parse_expr_list()
            self.expect_op(")")
            if name.lower() == "count":
                return Function("distinctcount", args)
            raise SqlParseError(f"DISTINCT not supported inside {name}")
        args = self.parse_expr_list()
        self.expect_op(")")
        return Function(name, args)

    def parse_case(self) -> Expr:
        """CASE WHEN cond THEN v [...] [ELSE v] END ->
        case(cond1, v1, cond2, v2, ..., else)."""
        self.expect_keyword("CASE")
        args: List[Expr] = []
        while self.accept_keyword("WHEN"):
            cond = self.parse_bool_expr()
            self.expect_keyword("THEN")
            val = self.parse_expr()
            args.append(_FilterExpr(cond))
            args.append(val)
        if self.accept_keyword("ELSE"):
            args.append(self.parse_expr())
        else:
            args.append(Literal(None))
        self.expect_keyword("END")
        return Function("case", args)


@dataclass(frozen=True)
class _FilterExpr(Expr):
    """A boolean filter used in expression position (CASE WHEN)."""

    filter: FilterNode

    def _collect_columns(self, out) -> None:
        out.extend(self.filter.columns())

    def __str__(self) -> str:
        return str(self.filter)


_EXPLAIN_RE = re.compile(r"^\s*EXPLAIN\s+PLAN\s+FOR\s+", re.I)


def parse_sql(sql: str) -> ParsedQuery:
    """Public entry (ref: CalciteSqlParser.compileToPinotQuery; EXPLAIN
    PLAN FOR wraps any query, ref: the SqlCompilationException-free
    explain path)."""
    text = sql.strip().rstrip(";")
    m = _EXPLAIN_RE.match(text)
    explain = m is not None
    if explain:
        text = text[m.end():]
    q = _Parser(text).parse()
    q.explain = explain
    return q


def parse_expression(text: str) -> Expr:
    """Parse a standalone value expression (ingestion transform configs,
    ref: ExpressionTransformer function-evaluator column expressions)."""
    p = _Parser(text.strip())
    e = p.parse_expr()
    if p.peek().kind != "eof":
        raise SqlParseError(f"trailing input in expression: {text!r}")
    return e


def parse_filter_expression(text: str) -> FilterNode:
    """Parse a standalone boolean expression (ingestion filter configs,
    ref: FilterTransformer)."""
    p = _Parser(text.strip())
    node = p.parse_bool_expr()
    if p.peek().kind != "eof":
        raise SqlParseError(f"trailing input in filter: {text!r}")
    return node
