"""Expression + filter AST.

Re-design of the reference's request-context model
(``pinot-common/.../common/request/context/ExpressionContext.java``,
``FilterContext.java``, the ``Predicate`` hierarchy): a small, hashable AST
the planner compiles into device kernels. Hashability matters: the engine's
jit cache is keyed on (filter structure, agg structure), so expressions must
be stable dict keys.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, List, Optional, Tuple


# --------------------------------------------------------------------------
# Expressions
# --------------------------------------------------------------------------

class Expr:
    """Base expression node."""

    def columns(self) -> List[str]:
        """All identifier names referenced (planner uses this for staging)."""
        out: List[str] = []
        self._collect_columns(out)
        return out

    def _collect_columns(self, out: List[str]) -> None:
        pass


@dataclass(frozen=True)
class Identifier(Expr):
    name: str

    def _collect_columns(self, out: List[str]) -> None:
        out.append(self.name)

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Literal(Expr):
    value: Any  # int | float | str | bool | None (NULL)

    @property
    def is_null(self) -> bool:
        return self.value is None

    def __str__(self) -> str:
        if isinstance(self.value, str):
            escaped = self.value.replace("'", "''")
            return f"'{escaped}'"
        return str(self.value)


@dataclass(frozen=True)
class Function(Expr):
    """Function call; also represents operators (plus/minus/times/divide...),
    matching the reference's canonical function-call form
    (CalciteSqlParser compiles ``a + b`` to ``plus(a, b)``)."""

    name: str  # canonical lower-case name
    args: Tuple[Expr, ...]

    def __init__(self, name: str, args):
        object.__setattr__(self, "name", name.lower())
        object.__setattr__(self, "args", tuple(args))

    def _collect_columns(self, out: List[str]) -> None:
        for a in self.args:
            a._collect_columns(out)

    def __str__(self) -> str:
        return f"{self.name}({','.join(str(a) for a in self.args)})"


STAR = Identifier("*")


_FOLDABLE = {
    "plus": lambda a, b: a + b,
    "minus": lambda a, b: a - b,
    "times": lambda a, b: a * b,
    "divide": lambda a, b: a / b,
    "mod": lambda a, b: a % b,
}


def fold_constants(expr: Expr) -> Expr:
    """Evaluate literal-only arithmetic sub-trees
    (ref: CompileTimeFunctionsInvoker)."""
    if not isinstance(expr, Function):
        return expr
    args = tuple(fold_constants(a) for a in expr.args)
    expr = Function(expr.name, args)
    fn = _FOLDABLE.get(expr.name)
    if fn is not None and all(isinstance(a, Literal) and not a.is_null
                              and isinstance(a.value, (int, float, bool))
                              for a in args):
        try:
            return Literal(fn(args[0].value, args[1].value))
        except ZeroDivisionError:
            return expr
    return expr


# Canonical keys for pre-aggregable arithmetic (star-tree expression
# function-column pairs, ref: AggregationFunctionColumnPair over the
# StarTreeV2 builder's derived columns): plus/minus/times over columns and
# numeric literals. Commutative operands sort lexically so
# ``sum(a * b)`` and ``SUM__b*a`` resolve to ONE stored pair. Divide is
# excluded on purpose — float division breaks the exact-integer pre-agg
# contract the tree metrics rely on (and '/' is not filename-safe for the
# per-pair metric files).
_ARITH_KEY_OPS = {"plus": "+", "minus": "-", "times": "*"}
_ARITH_COMMUTATIVE = {"plus", "times"}


def canonical_arith_key(e: Expr) -> Optional[str]:
    """Deterministic key for a +/-/* expression over identifiers and
    numeric literals — the star-tree derived-pair namespace — or None when
    the expression is not pre-aggregable (division, transforms, MV,
    virtual columns). A bare identifier canonicalizes to its name, so the
    key space is a strict superset of plain column pairs."""
    if isinstance(e, Identifier):
        if e.name == "*" or e.name.startswith("$"):
            return None
        return e.name
    if isinstance(e, Literal):
        if isinstance(e.value, bool) or not isinstance(e.value, (int, float)):
            return None
        return str(e.value)
    if isinstance(e, Function):
        sym = _ARITH_KEY_OPS.get(e.name)
        if sym is None or len(e.args) != 2:
            return None
        parts = [canonical_arith_key(a) for a in e.args]
        if any(p is None for p in parts):
            return None
        if e.name in _ARITH_COMMUTATIVE:
            parts.sort()
        return f"({parts[0]}{sym}{parts[1]})"
    return None


# --------------------------------------------------------------------------
# Filter tree
# --------------------------------------------------------------------------

class PredicateType(Enum):
    EQ = "EQ"
    NOT_EQ = "NOT_EQ"
    IN = "IN"
    NOT_IN = "NOT_IN"
    RANGE = "RANGE"
    REGEXP_LIKE = "REGEXP_LIKE"
    LIKE = "LIKE"            # rewritten to REGEXP_LIKE by the optimizer
    TEXT_MATCH = "TEXT_MATCH"
    JSON_MATCH = "JSON_MATCH"
    IS_NULL = "IS_NULL"
    IS_NOT_NULL = "IS_NOT_NULL"


@dataclass(frozen=True)
class Predicate:
    """Leaf predicate over one expression (ref: request/context/predicate/*).

    RANGE uses (lower, upper, lower_inclusive, upper_inclusive) with None for
    unbounded — the single representation for >, >=, <, <=, BETWEEN (the
    reference encodes the same as a range string ``(lo,hi]``).
    """

    type: PredicateType
    lhs: Expr
    values: Tuple[Any, ...] = ()
    lower: Any = None
    upper: Any = None
    lower_inclusive: bool = False
    upper_inclusive: bool = False

    @property
    def value(self) -> Any:
        return self.values[0] if self.values else None

    def __str__(self) -> str:
        t = self.type
        if t in (PredicateType.EQ, PredicateType.NOT_EQ):
            op = "=" if t is PredicateType.EQ else "!="
            return f"{self.lhs} {op} {self.value!r}"
        if t in (PredicateType.IN, PredicateType.NOT_IN):
            return f"{self.lhs} {t.value} {self.values!r}"
        if t is PredicateType.RANGE:
            lb = "[" if self.lower_inclusive else "("
            ub = "]" if self.upper_inclusive else ")"
            lo = "*" if self.lower is None else repr(self.lower)
            hi = "*" if self.upper is None else repr(self.upper)
            return f"{self.lhs} IN {lb}{lo},{hi}{ub}"
        if t in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
            return f"{self.lhs} {t.value}"
        return f"{t.value}({self.lhs}, {self.values!r})"


class FilterOp(Enum):
    AND = "AND"
    OR = "OR"
    NOT = "NOT"
    PREDICATE = "PREDICATE"


@dataclass(frozen=True)
class FilterNode:
    """Ref: FilterContext.java — AND/OR/NOT tree with Predicate leaves."""

    op: FilterOp
    children: Tuple["FilterNode", ...] = ()
    predicate: Optional[Predicate] = None

    def __init__(self, op: FilterOp, children=(), predicate: Optional[Predicate] = None):
        object.__setattr__(self, "op", op)
        object.__setattr__(self, "children", tuple(children))
        object.__setattr__(self, "predicate", predicate)

    @classmethod
    def pred(cls, predicate: Predicate) -> "FilterNode":
        return cls(FilterOp.PREDICATE, predicate=predicate)

    @classmethod
    def and_(cls, children) -> "FilterNode":
        return cls(FilterOp.AND, children=children)

    @classmethod
    def or_(cls, children) -> "FilterNode":
        return cls(FilterOp.OR, children=children)

    @classmethod
    def not_(cls, child: "FilterNode") -> "FilterNode":
        return cls(FilterOp.NOT, children=(child,))

    def columns(self) -> List[str]:
        out: List[str] = []
        self._collect(out)
        return out

    def _collect(self, out: List[str]) -> None:
        if self.predicate is not None:
            out.extend(self.predicate.lhs.columns())
        for c in self.children:
            c._collect(out)

    def predicates(self) -> List[Predicate]:
        out: List[Predicate] = []
        if self.predicate is not None:
            out.append(self.predicate)
        for c in self.children:
            out.extend(c.predicates())
        return out

    def __str__(self) -> str:
        if self.op is FilterOp.PREDICATE:
            return str(self.predicate)
        if self.op is FilterOp.NOT:
            return f"NOT ({self.children[0]})"
        sep = f" {self.op.value} "
        return "(" + sep.join(str(c) for c in self.children) + ")"


# --------------------------------------------------------------------------
# Order-by
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class OrderByExpr:
    expr: Expr
    ascending: bool = True

    def __str__(self) -> str:
        return f"{self.expr} {'ASC' if self.ascending else 'DESC'}"
