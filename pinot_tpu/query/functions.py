"""Scalar function registry: row-level functions for ingestion + query.

Re-design of ``pinot-common/.../function/FunctionRegistry.java:42`` +
``scalar/*`` (DateTime/String/Json/Array functions, annotation-scanned
``@ScalarFunction``): a name -> callable registry usable from the ingestion
transformer pipeline (ExpressionTransformer) and from query-time scalar
evaluation fallbacks. Registration mirrors the reference's annotation scan
with a decorator.
"""

from __future__ import annotations

import datetime as _dt
import functools
import json as _json
import math
import re

from typing import Any, Callable, Dict, List, Optional

from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    FilterOp,
    Function,
    Identifier,
    Literal,
    Predicate,
    PredicateType,
)

_REGISTRY: Dict[str, Callable] = {}


def scalar_function(name: Optional[str] = None, aliases: List[str] = ()):
    """Ref: @ScalarFunction annotation."""

    def wrap(fn: Callable) -> Callable:
        _REGISTRY[(name or fn.__name__).lower()] = fn
        for a in aliases:
            _REGISTRY[a.lower()] = fn
        return fn

    return wrap


def lookup(name: str) -> Optional[Callable]:
    return _REGISTRY.get(name.lower())


def registered_functions() -> List[str]:
    return sorted(_REGISTRY)


# --------------------------------------------------------------------------
# row-level expression evaluation
# --------------------------------------------------------------------------

class EvalError(Exception):
    pass


def eval_scalar(expr: Expr, env: Dict[str, Any]) -> Any:
    """Evaluate an expression over one row env (ref: InbuiltFunctionEvaluator)."""
    if isinstance(expr, Literal):
        return expr.value
    if isinstance(expr, Identifier):
        if expr.name not in env:
            raise EvalError(f"unknown field {expr.name!r}")
        return env[expr.name]
    if isinstance(expr, Function):
        args = [eval_scalar(a, env) for a in expr.args]
        fn = _REGISTRY.get(expr.name)
        if fn is None:
            raise EvalError(f"unknown function {expr.name!r}")
        if any(a is None for a in args):
            # null propagates (ref: FunctionInvoker — non-nullable
            # parameters skip invocation and yield null)
            return None
        return fn(*args)
    raise EvalError(f"cannot evaluate {expr!r}")


def eval_row_filter(node: FilterNode, env: Dict[str, Any]) -> bool:
    """Row-level boolean filter (ingestion FilterTransformer; ref:
    pinot-segment-local recordtransformer/FilterTransformer)."""
    if node.op is FilterOp.AND:
        return all(eval_row_filter(c, env) for c in node.children)
    if node.op is FilterOp.OR:
        return any(eval_row_filter(c, env) for c in node.children)
    if node.op is FilterOp.NOT:
        return not eval_row_filter(node.children[0], env)
    return _eval_row_predicate(node.predicate, env)


def _eval_row_predicate(p: Predicate, env: Dict[str, Any]) -> bool:
    v = eval_scalar(p.lhs, env)
    t = p.type
    if t is PredicateType.IS_NULL:
        return v is None
    if t is PredicateType.IS_NOT_NULL:
        return v is not None
    if v is None:
        return False
    if t is PredicateType.EQ:
        return _loose_eq(v, p.value)
    if t is PredicateType.NOT_EQ:
        return not _loose_eq(v, p.value)
    if t is PredicateType.IN:
        return any(_loose_eq(v, x) for x in p.values)
    if t is PredicateType.NOT_IN:
        return not any(_loose_eq(v, x) for x in p.values)
    if t is PredicateType.RANGE:
        if p.lower is not None:
            if p.lower_inclusive:
                if not v >= _coerce_like(v, p.lower):
                    return False
            elif not v > _coerce_like(v, p.lower):
                return False
        if p.upper is not None:
            if p.upper_inclusive:
                if not v <= _coerce_like(v, p.upper):
                    return False
            elif not v < _coerce_like(v, p.upper):
                return False
        return True
    if t is PredicateType.REGEXP_LIKE:
        return re.search(str(p.value), str(v)) is not None
    raise EvalError(f"predicate {t} not supported in row filters")


def _coerce_like(template: Any, v: Any) -> Any:
    if isinstance(template, (int, float)) and isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return v
    return v


def _loose_eq(a: Any, b: Any) -> bool:
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    return a == b


# --------------------------------------------------------------------------
# builtin scalar functions (ref: pinot-common/.../function/scalar/*)
# --------------------------------------------------------------------------

# ---- arithmetic (operator canonical forms) ----

@scalar_function()
def plus(a, b):
    return a + b


@scalar_function()
def minus(a, b):
    return a - b


@scalar_function()
def times(a, b):
    return a * b


@scalar_function()
def divide(a, b):
    return a / b


@scalar_function(name="mod")
def _mod(a, b):
    return a % b


@scalar_function(name="abs")
def _abs(a):
    return abs(a)


@scalar_function(name="ceil", aliases=["ceiling"])
def _ceil(a):
    return float(math.ceil(a))


@scalar_function(name="floor")
def _floor(a):
    return float(math.floor(a))


@scalar_function(name="exp")
def _exp(a):
    return math.exp(a)


@scalar_function(name="ln")
def _ln(a):
    return math.log(a)


@scalar_function(name="log10")
def _log10(a):
    return math.log10(a)


@scalar_function(name="log2")
def _log2(a):
    return math.log2(a)


@scalar_function(name="sqrt")
def _sqrt(a):
    return math.sqrt(a)


@scalar_function(name="power", aliases=["pow"])
def _power(a, b):
    return math.pow(a, b)


@scalar_function(name="round")
def _round(a, scale=0):
    return round(a, int(scale)) if scale else float(round(a))


@scalar_function(name="least")
def _least(*args):
    return min(args)


@scalar_function(name="greatest")
def _greatest(*args):
    return max(args)


# ---- string (ref: StringFunctions.java) ----

@scalar_function(name="upper")
def _upper(s):
    return str(s).upper()


@scalar_function(name="lower")
def _lower(s):
    return str(s).lower()


@scalar_function(name="trim")
def _trim(s):
    return str(s).strip()


@scalar_function(name="ltrim")
def _ltrim(s):
    return str(s).lstrip()


@scalar_function(name="rtrim")
def _rtrim(s):
    return str(s).rstrip()


@scalar_function(name="length")
def _length(s):
    return len(str(s))


@scalar_function(name="reverse")
def _reverse(s):
    return str(s)[::-1]


@scalar_function(name="substr", aliases=["substring"])
def _substr(s, start, end=None):
    # reference semantics: 0-based start; end exclusive; -1 end = rest
    s = str(s)
    start = int(start)
    if end is None or int(end) == -1:
        return s[start:]
    return s[start:int(end)]


@scalar_function(name="concat")
def _concat(a, b, sep=""):
    return f"{a}{sep}{b}"


@scalar_function(name="replace")
def _replace(s, find, sub):
    return str(s).replace(str(find), str(sub))


@scalar_function(name="lpad")
def _lpad(s, size, pad=" "):
    s = str(s)
    size = int(size)
    while len(s) < size:
        s = pad + s
    return s[-size:] if len(s) > size else s


@scalar_function(name="rpad")
def _rpad(s, size, pad=" "):
    s = str(s)
    size = int(size)
    while len(s) < size:
        s = s + pad
    return s[:size]


@scalar_function(name="strpos")
def _strpos(s, find, instance=1):
    s, find = str(s), str(find)
    pos = -1
    for _ in range(int(instance)):
        pos = s.find(find, pos + 1)
        if pos < 0:
            return -1
    return pos


@scalar_function(name="startswith", aliases=["startsWith"])
def _startswith(s, prefix):
    return str(s).startswith(str(prefix))


@scalar_function(name="split")
def _split(s, sep):
    return str(s).split(str(sep))


@scalar_function(name="hammingdistance", aliases=["hammingDistance"])
def _hamming(a, b):
    a, b = str(a), str(b)
    if len(a) != len(b):
        return -1
    return sum(1 for x, y in zip(a, b) if x != y)


# ---- datetime (ref: DateTimeFunctions.java) ----

@scalar_function(name="now")
def _now():
    import time as _t

    return int(_t.time() * 1000)


@scalar_function(name="toepochseconds", aliases=["toEpochSeconds"])
def _to_epoch_seconds(ms):
    return int(ms) // 1000


@scalar_function(name="toepochminutes", aliases=["toEpochMinutes"])
def _to_epoch_minutes(ms):
    return int(ms) // 60_000


@scalar_function(name="toepochhours", aliases=["toEpochHours"])
def _to_epoch_hours(ms):
    return int(ms) // 3_600_000


@scalar_function(name="toepochdays", aliases=["toEpochDays"])
def _to_epoch_days(ms):
    return int(ms) // 86_400_000


@scalar_function(name="fromepochseconds", aliases=["fromEpochSeconds"])
def _from_epoch_seconds(s):
    return int(s) * 1000


@scalar_function(name="fromepochminutes", aliases=["fromEpochMinutes"])
def _from_epoch_minutes(m):
    return int(m) * 60_000


@scalar_function(name="fromepochhours", aliases=["fromEpochHours"])
def _from_epoch_hours(h):
    return int(h) * 3_600_000


@scalar_function(name="fromepochdays", aliases=["fromEpochDays"])
def _from_epoch_days(d):
    return int(d) * 86_400_000


_JAVA_TO_STRFTIME = [
    ("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"), ("HH", "%H"),
    ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"),
]


def _to_strftime(java_fmt: str) -> str:
    out = java_fmt
    for j, s in _JAVA_TO_STRFTIME:
        out = out.replace(j, s)
    return out


@scalar_function(name="todatetime", aliases=["toDateTime"])
def _to_datetime(ms, fmt):
    dt = _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc)
    s = dt.strftime(_to_strftime(str(fmt)))
    if "%f" in _to_strftime(str(fmt)):
        # strftime %f is microseconds; java SSS is millis
        s = s.replace(dt.strftime("%f"), dt.strftime("%f")[:3])
    return s


@scalar_function(name="fromdatetime", aliases=["fromDateTime"])
def _from_datetime(s, fmt):
    dt = _dt.datetime.strptime(str(s), _to_strftime(str(fmt)))
    return int(dt.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000)


# fixed-width unit tables, shared with the device transform rewrites
# (engine/plan.py imports these so the host oracle and the device integer
# rewrite can never diverge on a unit's width)
TRUNC_UNIT_MS = {
    "millisecond": 1, "second": 1000, "minute": 60_000, "hour": 3_600_000,
    "day": 86_400_000, "week": 7 * 86_400_000,
}
TIME_UNIT_MS = {
    "MILLISECONDS": 1, "SECONDS": 1000, "MINUTES": 60_000,
    "HOURS": 3_600_000, "DAYS": 86_400_000,
}
_TRUNC_UNIT_MS = TRUNC_UNIT_MS


@scalar_function(name="datetrunc", aliases=["dateTrunc"])
def _date_trunc(unit, ms):
    u = str(unit).lower()
    if u in _TRUNC_UNIT_MS:
        q = _TRUNC_UNIT_MS[u]
        return (int(ms) // q) * q
    dt = _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc)
    if u == "month":
        dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    elif u == "quarter":
        dt = dt.replace(month=(dt.month - 1) // 3 * 3 + 1, day=1, hour=0,
                        minute=0, second=0, microsecond=0)
    elif u == "year":
        dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0,
                        microsecond=0)
    else:
        raise EvalError(f"datetrunc unit {unit!r}")
    return int(dt.timestamp() * 1000)


@scalar_function(name="year")
def _year(ms):
    return _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc).year


@scalar_function(name="month", aliases=["monthofyear", "monthOfYear"])
def _month(ms):
    return _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc).month


@scalar_function(name="dayofmonth", aliases=["dayOfMonth", "day"])
def _day_of_month(ms):
    return _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc).day


@scalar_function(name="dayofweek", aliases=["dayOfWeek"])
def _day_of_week(ms):
    # ISO: Monday=1..Sunday=7 (joda DateTimeField semantics)
    return _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc).isoweekday()


@scalar_function(name="hour")
def _hour(ms):
    return _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc).hour


@scalar_function(name="minute")
def _minute(ms):
    return _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc).minute


@scalar_function(name="second")
def _second(ms):
    return _dt.datetime.fromtimestamp(int(ms) / 1000.0, tz=_dt.timezone.utc).second


@scalar_function(name="timeconvert", aliases=["timeConvert"])
def _time_convert(value, from_unit, to_unit):
    ms = int(value) * TIME_UNIT_MS[str(from_unit).upper()]
    return ms // TIME_UNIT_MS[str(to_unit).upper()]


# ---- json (ref: JsonFunctions.java) ----

def _json_path_get(obj: Any, path: str) -> Any:
    """Subset of JsonPath: $.a.b[0].c"""
    if not path.startswith("$"):
        raise EvalError(f"json path must start with $: {path!r}")
    cur = obj
    for part in re.findall(r"\.([A-Za-z_][\w]*)|\[(\d+)\]", path):
        name, idx = part
        if cur is None:
            return None
        if name:
            if not isinstance(cur, dict):
                return None
            cur = cur.get(name)
        else:
            if not isinstance(cur, list) or int(idx) >= len(cur):
                return None
            cur = cur[int(idx)]
    return cur


@scalar_function(name="jsonpath", aliases=["jsonPath", "json_extract_scalar",
                                           "jsonextractscalar", "jsonExtractScalar"])
def _json_path(blob, path, result_type="STRING", default=None):
    obj = _json.loads(blob) if isinstance(blob, (str, bytes)) else blob
    v = _json_path_get(obj, str(path))
    if v is None:
        return default
    t = str(result_type).upper()
    if t in ("INT", "LONG"):
        return int(v)
    if t in ("FLOAT", "DOUBLE"):
        return float(v)
    if t == "STRING":
        return v if isinstance(v, str) else _json.dumps(v)
    return v


@scalar_function(name="jsonformat", aliases=["jsonFormat"])
def _json_format(obj):
    return _json.dumps(obj, separators=(",", ":"))


@scalar_function(name="tojsonmapstr", aliases=["toJsonMapStr"])
def _to_json_map_str(m):
    return _json.dumps(m, separators=(",", ":"))


# ---- array / multi-value (ref: ArrayFunctions) ----

@scalar_function(name="arraylength", aliases=["arrayLength", "cardinality"])
def _array_length(a):
    return len(a)


@scalar_function(name="arraymin", aliases=["arrayMin"])
def _array_min(a):
    return min(a)


@scalar_function(name="arraymax", aliases=["arrayMax"])
def _array_max(a):
    return max(a)


@scalar_function(name="arraysum", aliases=["arraySum"])
def _array_sum(a):
    return sum(a)


@scalar_function(name="arrayaverage", aliases=["arrayAverage"])
def _array_average(a):
    return sum(a) / len(a)


@scalar_function(name="arraydistinct", aliases=["arrayDistinct"])
def _array_distinct(a):
    out = []
    for x in a:
        if x not in out:
            out.append(x)
    return out


@scalar_function(name="valuein", aliases=["valueIn"])
def _value_in(a, *allowed):
    allow = set(allowed)
    return [x for x in a if x in allow]


@functools.lru_cache(maxsize=64)
def _decode_idset(serialized_idset: str) -> frozenset:
    import base64

    from pinot_tpu.common import serde

    return frozenset(serde.loads(base64.b64decode(serialized_idset)))


@scalar_function(name="inidset", aliases=["inIdSet", "in_id_set"])
def _in_id_set(value, serialized_idset):
    """Membership test against an IDSET() aggregation result (ref:
    InIdSetTransformFunction consuming IdSetAggregationFunction's base64
    payload) -> 1/0 like the reference's boolean-as-int transforms. The
    decoded set is cached: row-level eval calls this once per row."""
    v = value.item() if hasattr(value, "item") else value
    return 1 if v in _decode_idset(serialized_idset) else 0


# --------------------------------------------------------------------------
# geospatial (ref: pinot-core geospatial/transform/function/*; geography is
# carried through strings with the EWKT "SRID=4326;" prefix rather than the
# reference's serialized-bytes + SRID flag)
# --------------------------------------------------------------------------

def _parse_geo(v):
    from pinot_tpu.utils import geo

    return geo.parse_ewkt(v)


@scalar_function(name="stpoint", aliases=["ST_Point", "st_point"])
def _st_point(x, y, is_geography=0):
    from pinot_tpu.utils import geo

    g = geo.point(float(x), float(y), bool(is_geography))
    return (geo.GEOG_PREFIX + g.wkt()) if g.geography else g.wkt()


@scalar_function(name="stgeomfromtext", aliases=["ST_GeomFromText"])
def _st_geom_from_text(wkt):
    return _parse_geo(wkt).wkt()


@scalar_function(name="stgeogfromtext", aliases=["ST_GeogFromText"])
def _st_geog_from_text(wkt):
    from pinot_tpu.utils import geo

    g = geo.from_wkt(str(wkt), geography=True)
    return geo.GEOG_PREFIX + g.wkt()


@scalar_function(name="stastext", aliases=["ST_AsText"])
def _st_as_text(v):
    return _parse_geo(v).wkt()


@scalar_function(name="stdistance", aliases=["ST_Distance"])
def _st_distance(a, b):
    from pinot_tpu.utils import geo

    return geo.distance(_parse_geo(a), _parse_geo(b))


@scalar_function(name="stcontains", aliases=["ST_Contains"])
def _st_contains(outer, inner):
    from pinot_tpu.utils import geo

    return 1 if geo.contains(_parse_geo(outer), _parse_geo(inner)) else 0


@scalar_function(name="stwithin", aliases=["ST_Within"])
def _st_within(inner, outer):
    from pinot_tpu.utils import geo

    return 1 if geo.contains(_parse_geo(outer), _parse_geo(inner)) else 0


@scalar_function(name="starea", aliases=["ST_Area"])
def _st_area(g):
    from pinot_tpu.utils import geo

    return geo.area(_parse_geo(g))


@scalar_function(name="stx", aliases=["ST_X"])
def _st_x(g):
    return _parse_geo(g).x


@scalar_function(name="sty", aliases=["ST_Y"])
def _st_y(g):
    return _parse_geo(g).y
