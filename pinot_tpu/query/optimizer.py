"""Query optimizer: filter rewrites before planning.

Re-design of ``pinot-core/.../query/optimizer/QueryOptimizer.java`` +
``filter/*``: flatten nested AND/OR, rewrite LIKE to REGEXP_LIKE, merge EQ
children of an OR into one IN, merge overlapping ranges on the same column,
and fold constant arithmetic.
"""

from __future__ import annotations

import re
from dataclasses import replace
from typing import Any, List, Optional

from pinot_tpu.query.expressions import (
    Expr,
    FilterNode,
    FilterOp,
    Function,
    Literal,
    OrderByExpr,
    Predicate,
    PredicateType,
    fold_constants,
)
from pinot_tpu.query.parser import ParsedQuery


def like_to_regex(pattern: str) -> str:
    """SQL LIKE pattern -> anchored regex (ref: RegexpPatternConverterUtils):
    ``%`` -> ``.*``, ``_`` -> ``.``, everything else escaped."""
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


# -- filter rewrites --------------------------------------------------------

def _flatten(node: FilterNode) -> FilterNode:
    """Flatten nested AND(AND(..)..) / OR(OR(..)..)
    (ref: FlattenAndOrFilterOptimizer)."""
    if node.op in (FilterOp.AND, FilterOp.OR):
        children: List[FilterNode] = []
        for c in node.children:
            c = _flatten(c)
            if c.op is node.op:
                children.extend(c.children)
            else:
                children.append(c)
        if len(children) == 1:
            return children[0]
        return FilterNode(node.op, children=children)
    if node.op is FilterOp.NOT:
        return FilterNode.not_(_flatten(node.children[0]))
    return node


def _rewrite_like(node: FilterNode) -> FilterNode:
    if node.predicate is not None:
        p = node.predicate
        if p.type is PredicateType.LIKE:
            return FilterNode.pred(replace(
                p, type=PredicateType.REGEXP_LIKE,
                values=(like_to_regex(str(p.value)),)))
        return node
    return FilterNode(node.op,
                      children=tuple(_rewrite_like(c) for c in node.children),
                      predicate=node.predicate)


def _merge_eq_in(node: FilterNode) -> FilterNode:
    """OR(EQ(c,a), EQ(c,b), ...) -> IN(c, a, b, ...)
    (ref: MergeEqInFilterOptimizer)."""
    if node.op is FilterOp.OR:
        by_col = {}
        rest: List[FilterNode] = []
        for c in node.children:
            c = _merge_eq_in(c)
            p = c.predicate
            if p is not None and p.type in (PredicateType.EQ, PredicateType.IN):
                by_col.setdefault(p.lhs, []).extend(p.values)
            else:
                rest.append(c)
        merged: List[FilterNode] = []
        for lhs, values in by_col.items():
            uniq = tuple(dict.fromkeys(values))
            ptype = PredicateType.EQ if len(uniq) == 1 else PredicateType.IN
            merged.append(FilterNode.pred(Predicate(ptype, lhs, values=uniq)))
        children = merged + rest
        if len(children) == 1:
            return children[0]
        return FilterNode.or_(children)
    if node.op in (FilterOp.AND, FilterOp.NOT):
        return FilterNode(node.op,
                          children=tuple(_merge_eq_in(c) for c in node.children),
                          predicate=node.predicate)
    return node


def _merge_ranges(node: FilterNode) -> FilterNode:
    """AND of ranges on the same expr -> one range
    (ref: MergeRangeFilterOptimizer)."""
    if node.op is FilterOp.AND:
        by_col = {}
        rest: List[FilterNode] = []
        for c in node.children:
            c = _merge_ranges(c)
            p = c.predicate
            if p is not None and p.type is PredicateType.RANGE:
                by_col.setdefault(p.lhs, []).append(p)
            else:
                rest.append(c)
        merged: List[FilterNode] = []
        for lhs, preds in by_col.items():
            if len(preds) == 1:
                merged.append(FilterNode.pred(preds[0]))
                continue
            try:
                lo, lo_inc = None, False
                hi, hi_inc = None, False
                for p in preds:
                    if p.lower is not None and (lo is None or p.lower > lo
                                                or (p.lower == lo and not p.lower_inclusive)):
                        lo, lo_inc = p.lower, p.lower_inclusive
                    if p.upper is not None and (hi is None or p.upper < hi
                                                or (p.upper == hi and not p.upper_inclusive)):
                        hi, hi_inc = p.upper, p.upper_inclusive
                merged.append(FilterNode.pred(Predicate(
                    PredicateType.RANGE, lhs, lower=lo, upper=hi,
                    lower_inclusive=lo_inc, upper_inclusive=hi_inc)))
            except TypeError:
                # mixed-type bounds (b > 1 AND b > 'x'): not mergeable; the
                # predicate evaluator reports the type error per-predicate
                merged.extend(FilterNode.pred(p) for p in preds)
        children = merged + rest
        if len(children) == 1:
            return children[0]
        return FilterNode.and_(children)
    if node.op in (FilterOp.OR, FilterOp.NOT):
        return FilterNode(node.op,
                          children=tuple(_merge_ranges(c) for c in node.children),
                          predicate=node.predicate)
    return node


def _fold_filter(node: FilterNode) -> FilterNode:
    if node.predicate is not None:
        p = node.predicate
        folded = fold_constants(p.lhs)
        if folded is not p.lhs:
            return FilterNode.pred(replace(p, lhs=folded))
        return node
    return FilterNode(node.op, children=tuple(_fold_filter(c) for c in node.children),
                      predicate=node.predicate)


def optimize_filter(node: Optional[FilterNode]) -> Optional[FilterNode]:
    if node is None:
        return None
    node = _fold_filter(node)
    node = _flatten(node)
    node = _rewrite_like(node)
    node = _merge_eq_in(node)
    node = _merge_ranges(node)
    return _flatten(node)


def optimize(parsed: ParsedQuery) -> ParsedQuery:
    # group_by/order_by are folded in build_query_context AFTER ordinal
    # resolution ('ORDER BY 1 + 1' must not collapse into ordinal 2)
    parsed.where = optimize_filter(parsed.where)
    parsed.having = optimize_filter(parsed.having)
    parsed.select = [(fold_constants(e), a) for e, a in parsed.select]
    return parsed
