"""EXPLAIN PLAN: logical operator tree for a compiled query.

Re-design of the reference's explain support (``EXPLAIN PLAN FOR <sql>``,
``query/reduce/ExplainPlanDataTableReducer`` + per-operator
``toExplainString``): rows of (Operator, Operator_Id, Parent_Id) matching
the reference's response shape. The tree is LOGICAL — built from the
QueryContext alone, since physical strategy selection (device kernel vs
Pallas vs host vs star-tree, index choices) is per-segment; the execution
notes column of each operator names the candidate strategies instead.
"""

from __future__ import annotations

from typing import List, Optional

from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.expressions import FilterNode, FilterOp


def explain_rows(ctx: QueryContext) -> List[List]:
    """[[operator, operator_id, parent_id], ...] (ref: the EXPLAIN
    resultTable schema Operator/Operator_Id/Parent_Id)."""
    rows: List[List] = []
    next_id = [0]

    def emit(text: str, parent: int) -> int:
        oid = next_id[0]
        next_id[0] += 1
        rows.append([text, oid, parent])
        return oid

    sel = ", ".join(str(e) for e in ctx.select_expressions)
    root = emit(
        f"BROKER_REDUCE(limit:{ctx.limit}"
        + (f",offset:{ctx.offset}" if ctx.offset else "")
        + (",sort:" + ", ".join(
            f"{ob.expr} {'ASC' if ob.ascending else 'DESC'}"
            for ob in ctx.order_by) if ctx.order_by else "")
        + (",having:true" if ctx.having is not None else "")
        + ")", -1)

    if ctx.is_group_by:
        combine = emit("COMBINE_GROUP_BY(sharded psum over device mesh)",
                       root)
        agg = emit(
            "GROUP_BY(groupKeys:"
            + ", ".join(str(e) for e in ctx.group_by)
            + ", aggregations:"
            + ", ".join(str(f) for f in ctx.aggregations) + ")", combine)
    elif ctx.is_aggregation:
        combine = emit("COMBINE_AGGREGATE(sharded psum over device mesh)",
                       root)
        agg = emit("AGGREGATE(aggregations:"
                   + ", ".join(str(f) for f in ctx.aggregations) + ")",
                   combine)
    elif ctx.distinct:
        combine = emit("COMBINE_DISTINCT", root)
        agg = emit(f"DISTINCT(keyColumns:{sel})", combine)
    else:
        combine = emit("COMBINE_SELECT", root)
        agg = emit(f"SELECT(selectList:{sel})", combine)

    project_cols = sorted(set(ctx.referenced_columns()))
    proj = emit("PROJECT(" + ", ".join(project_cols) + ")", agg)
    doc = emit("DOC_ID_SET", proj)
    _emit_filter(ctx.filter, doc, emit)
    return rows


def _emit_filter(node: Optional[FilterNode], parent: int, emit) -> None:
    if node is None:
        emit("FILTER_MATCH_ENTIRE_SEGMENT", parent)
        return
    if node.op is FilterOp.AND:
        fid = emit("FILTER_AND", parent)
        for c in node.children:
            _emit_filter(c, fid, emit)
        return
    if node.op is FilterOp.OR:
        fid = emit("FILTER_OR", parent)
        for c in node.children:
            _emit_filter(c, fid, emit)
        return
    if node.op is FilterOp.NOT:
        fid = emit("FILTER_NOT", parent)
        _emit_filter(node.children[0], fid, emit)
        return
    p = node.predicate
    emit(f"FILTER_{p.type.name}(predicate:{p})", parent)


EXPLAIN_COLUMNS = (["Operator", "Operator_Id", "Parent_Id"],
                   ["STRING", "INT", "INT"])
