"""Minion role: polls the controller task queue and runs task executors.

Re-design of ``pinot-minion/.../BaseMinionStarter.java:69`` +
``taskfactory/TaskFactoryRegistry.java``: the minion registers as a MINION
instance, claims WAITING tasks from the task manager, dispatches to the
executor registry (minion/tasks.py), and reports COMPLETED/ERROR.
"""

from __future__ import annotations

import logging
import os
import threading

from typing import Dict, Optional

from pinot_tpu.controller.state import InstanceInfo
from pinot_tpu.controller.tasks import COMPLETED, ERROR, PinotTaskConfig
from pinot_tpu.minion.tasks import TASK_EXECUTORS, BaseTaskExecutor, MinionContext

log = logging.getLogger(__name__)


class MinionInstance:
    """One minion worker (ref: BaseMinionStarter lifecycle)."""

    def __init__(self, instance_id: str, controller,
                 work_dir: str = "/tmp/pinot_tpu_minion",
                 executors: Optional[Dict[str, BaseTaskExecutor]] = None):
        self.instance_id = instance_id
        self.controller = controller
        self.ctx = MinionContext(controller=controller,
                                 work_dir=os.path.join(work_dir, instance_id))
        os.makedirs(self.ctx.work_dir, exist_ok=True)
        self.executors = dict(TASK_EXECUTORS if executors is None else executors)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tasks_succeeded = 0  # race-ok: single_writer
        self.tasks_failed = 0  # race-ok: single_writer
        controller.store.register_instance(InstanceInfo(instance_id, "MINION"))

    # -- lifecycle -----------------------------------------------------------
    def start(self, poll_interval_s: float = 0.2) -> None:
        def loop():
            while not self._stop.is_set():
                if not self.run_one_task():
                    self._stop.wait(poll_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"minion-{self.instance_id}")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self.controller.store.set_instance_alive(self.instance_id, False)

    # -- work loop -----------------------------------------------------------
    def run_one_task(self) -> bool:
        """Claim and run one task; returns False when the queue is empty."""
        task = self.controller.task_manager.poll(self.instance_id)
        if task is None:
            return False
        self._run(task)
        return True

    def _run(self, task: PinotTaskConfig) -> None:
        executor = self.executors.get(task.task_type)
        tm = self.controller.task_manager
        if executor is None:
            tm.report(task.task_id, ERROR,
                      error=f"no executor for {task.task_type}")
            self.tasks_failed += 1
            return
        try:
            outputs = executor.execute(task, self.ctx)
            tm.report(task.task_id, COMPLETED, output_segments=outputs)
            self.tasks_succeeded += 1
        except Exception as exc:
            log.exception("task %s failed", task.task_id)
            tm.report(task.task_id, ERROR, error=f"{type(exc).__name__}: {exc}")
            self.tasks_failed += 1
