"""Minion task executors: MergeRollup, RealtimeToOffline, Purge.

Re-design of the reference's builtin minion tasks
(``pinot-plugins/pinot-minion-tasks/pinot-minion-builtin-tasks/`` —
``MergeRollupTaskExecutor``, ``RealtimeToOfflineSegmentsTaskExecutor``,
``PurgeTaskExecutor``) over the segment processing framework
(segment/processing.py). Each executor: download input segments → run the
processor → upload outputs → apply the segment-replacement protocol
(delete inputs for merge; advance the window watermark for RT→offline).
"""

from __future__ import annotations

import logging
import os

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from pinot_tpu.controller.tasks import (
    CONVERT_TO_RAW_TASK,
    MERGE_ROLLUP_TASK,
    PURGE_TASK,
    REALTIME_TO_OFFLINE_TASK,
    SEGMENT_GENERATION_AND_PUSH_TASK,
    PinotTaskConfig,
)
from pinot_tpu.segment.immutable import ImmutableSegment, load_segment
from pinot_tpu.segment.processing import (
    MergeType,
    SegmentProcessorConfig,
    SegmentProcessorFramework,
)
from pinot_tpu.spi.table import TableType, raw_table_name, table_name_with_type

log = logging.getLogger(__name__)


@dataclass
class MinionContext:
    """What an executor needs from the cluster (ref: MinionContext.java)."""

    controller: object            # Controller (task_manager, add_segment, …)
    work_dir: str

    @property
    def store(self):
        return self.controller.store

    @property
    def task_manager(self):
        return self.controller.task_manager


class BaseTaskExecutor:
    """Ref: BaseTaskExecutor/BaseMultipleSegmentsConversionExecutor."""

    task_type = "base"

    def execute(self, task: PinotTaskConfig, ctx: MinionContext) -> List[str]:
        """Returns output segment names. Raise to mark the task ERROR."""
        raise NotImplementedError

    # -- shared helpers ------------------------------------------------------
    def _download(self, task: PinotTaskConfig,
                  ctx: MinionContext) -> List[ImmutableSegment]:
        """Resolve input segments via their deep-store download URLs
        through the PinotFS registry (ref: downloadSegmentFromDeepStore)."""
        import os

        from pinot_tpu.spi.filesystem import fetch_segment

        segs = []
        for name in task.input_segments:
            md = ctx.store.get_segment_metadata(task.table, name)
            if md is None or not md.download_url:
                raise FileNotFoundError(
                    f"segment {name} of {task.table} has no download url")
            local = fetch_segment(md.download_url,
                                  os.path.join(ctx.work_dir, "downloads"))
            segs.append(load_segment(local))
        return segs

    def _schema_and_config(self, ctx: MinionContext, table: str):
        cfg = ctx.store.get_table_config(table)
        schema = ctx.store.get_schema(raw_table_name(table))
        if cfg is None or schema is None:
            raise KeyError(f"missing table config/schema for {table}")
        return schema, cfg

    def _upload(self, ctx: MinionContext, table: str,
                seg_dirs: List[str]) -> List[str]:
        names = []
        for d in seg_dirs:
            seg = load_segment(d)
            ctx.controller.add_segment(table, seg.metadata,
                                       f"file://{os.path.abspath(d)}")
            names.append(seg.segment_name)
        return names


class MergeRollupTaskExecutor(BaseTaskExecutor):
    """Merge + optionally roll up a time bucket of offline segments, then
    atomically replace the inputs (ref: MergeRollupTaskExecutor.java)."""

    task_type = MERGE_ROLLUP_TASK

    def execute(self, task: PinotTaskConfig, ctx: MinionContext) -> List[str]:
        schema, cfg = self._schema_and_config(ctx, task.table)
        segments = self._download(task, ctx)
        merge_type = MergeType[task.configs.get("mergeType", "CONCAT").upper()]
        agg_types = {k[len("aggregationType."):]: v
                     for k, v in task.configs.items()
                     if k.startswith("aggregationType.")}
        # Partition rows by time bucket instead of clamping to the task
        # window: inputs may *straddle* the bucket boundary, and deleting
        # them after a window clamp would drop their out-of-window rows.
        # Ref: MergeRollupTaskGenerator sets PARTITION_BUCKET_TIME_PERIOD —
        # spilled-over rows land in their own per-bucket output segments.
        ws = int(task.configs["windowStartMs"])
        we = int(task.configs["windowEndMs"])
        bucket_ms = int(task.configs.get("bucketTimeMs", we - ws))
        proc = SegmentProcessorFramework(segments, SegmentProcessorConfig(
            schema=schema, table_config=cfg, merge_type=merge_type,
            aggregation_types=agg_types,
            bucket_time_ms=bucket_ms,
            # the task id in the name keeps retries of a partially-failed
            # bucket from overwriting the prior attempt's outputs (which
            # may hold rows of inputs that were already deleted)
            segment_name_prefix=f"merged_{raw_table_name(task.table)}"
                                f"_{task.configs['windowStartMs']}"
                                f"_{task.task_id[-8:]}",
            max_docs_per_segment=int(
                task.configs.get("maxNumRecordsPerSegment", "5000000")),
        ))
        out_dirs = proc.process(os.path.join(ctx.work_dir, task.task_id))
        # lineage replace protocol: outputs hidden while uploading, then the
        # COMPLETED flip atomically swaps visibility — queries never see
        # inputs and outputs together (ref: SegmentReplacementProtocol via
        # start/endReplaceSegments; controller/lineage.py)
        out_names = [os.path.basename(d) for d in out_dirs]
        entry_id = ctx.controller.start_replace_segments(
            task.table, list(task.input_segments), out_names)
        try:
            names = self._upload(ctx, task.table, out_dirs)
            ctx.controller.end_replace_segments(task.table, entry_id)
        except Exception:
            ctx.controller.revert_replace_segments(task.table, entry_id)
            raise
        # inputs are lineage-hidden now; physical deletion reclaims space
        for name in task.input_segments:
            ctx.controller.delete_segment(task.table, name)
        return names


class RealtimeToOfflineSegmentsTaskExecutor(BaseTaskExecutor):
    """Build offline segments from a committed realtime window and push them
    to the companion OFFLINE table; advance the window watermark on success
    (ref: RealtimeToOfflineSegmentsTaskExecutor.java preProcess/postProcess)."""

    task_type = REALTIME_TO_OFFLINE_TASK

    def execute(self, task: PinotTaskConfig, ctx: MinionContext) -> List[str]:
        raw = raw_table_name(task.table)
        offline_table = table_name_with_type(raw, TableType.OFFLINE)
        if ctx.store.get_table_config(offline_table) is None:
            raise KeyError(f"RT->offline needs companion table {offline_table}")
        schema, cfg = self._schema_and_config(ctx, task.table)
        offline_cfg = ctx.store.get_table_config(offline_table)
        segments = self._download(task, ctx)
        ws = int(task.configs["windowStartMs"])
        we = int(task.configs["windowEndMs"])
        merge_type = MergeType[task.configs.get("mergeType", "CONCAT").upper()]
        agg_types = {k[len("aggregationType."):]: v
                     for k, v in task.configs.items()
                     if k.startswith("aggregationType.")}
        proc = SegmentProcessorFramework(segments, SegmentProcessorConfig(
            schema=schema, table_config=offline_cfg, merge_type=merge_type,
            aggregation_types=agg_types,
            window_start_ms=ws, window_end_ms=we,
            segment_name_prefix=f"rt2off_{raw}_{ws}",
            max_docs_per_segment=int(
                task.configs.get("maxNumRecordsPerSegment", "5000000")),
        ))
        out_dirs = proc.process(os.path.join(ctx.work_dir, task.task_id))
        names = self._upload(ctx, offline_table, out_dirs)
        ctx.task_manager.set_watermark_ms(task.table,
                                          REALTIME_TO_OFFLINE_TASK, we)
        return names


class PurgeTaskExecutor(BaseTaskExecutor):
    """Rewrite a segment dropping rows the record purger matches
    (ref: PurgeTaskExecutor.java + RecordPurgerFactory)."""

    task_type = PURGE_TASK

    # table raw name -> row predicate (True = purge the row); the in-process
    # stand-in for the reference's RecordPurgerFactory plugin registry
    PURGERS: Dict[str, Callable[[dict], bool]] = {}

    def execute(self, task: PinotTaskConfig, ctx: MinionContext) -> List[str]:
        schema, cfg = self._schema_and_config(ctx, task.table)
        purger = self.PURGERS.get(raw_table_name(task.table))
        if purger is None:
            raise KeyError(f"no record purger registered for {task.table}")
        segments = self._download(task, ctx)
        (in_name,) = task.input_segments
        proc = SegmentProcessorFramework(segments, SegmentProcessorConfig(
            schema=schema, table_config=cfg, merge_type=MergeType.CONCAT,
            record_filter=purger,
            segment_name_prefix=f"purged_{in_name}",
        ))
        out_dirs = proc.process(os.path.join(ctx.work_dir, task.task_id))
        names = self._upload(ctx, task.table, out_dirs)
        ctx.controller.delete_segment(task.table, in_name)
        return names


class ConvertToRawIndexTaskExecutor(BaseTaskExecutor):
    """Rebuild a segment with the configured columns stored RAW
    (no-dictionary) and refresh-push it under the SAME name
    (ref: ConvertToRawIndexTaskExecutor.java — a segment conversion, not
    a merge; the custom map records completion so the generator stops)."""

    task_type = CONVERT_TO_RAW_TASK

    def execute(self, task: PinotTaskConfig, ctx: MinionContext) -> List[str]:
        from dataclasses import replace as dc_replace

        from pinot_tpu.segment.creator import SegmentBuilder
        from pinot_tpu.segment.processing import read_columnar

        schema, cfg = self._schema_and_config(ctx, task.table)
        cols_to_convert = [c.strip() for c in
                           task.configs.get("columnsToConvert", "").split(",")
                           if c.strip()]
        (in_name,) = task.input_segments
        (segment,) = self._download(task, ctx)

        columns = read_columnar(segment)
        indexing = dc_replace(
            cfg.indexing_config,
            no_dictionary_columns=sorted(
                set(cfg.indexing_config.no_dictionary_columns)
                | set(cols_to_convert)))
        out_dir = os.path.join(ctx.work_dir, task.task_id)
        builder = SegmentBuilder(schema, in_name, table_name=cfg.table_name,
                                 indexing_config=indexing)
        builder.build(columns, out_dir)
        names = self._upload(ctx, task.table, [os.path.join(out_dir,
                                                            in_name)])
        # record completion in the segment's custom map — SORTED so the
        # generator's changed-config comparison is order-insensitive
        md = ctx.store.get_segment_metadata(task.table, in_name)
        if md is not None:
            md.custom["convertToRawDone"] = \
                ",".join(sorted(cols_to_convert)) or "*"
            ctx.store.set_segment_metadata(md)
        return names


class SegmentGenerationAndPushTaskExecutor(BaseTaskExecutor):
    """Run a batch segment-generation job inside the minion and push the
    results (ref: SegmentGenerationAndPushTaskExecutor.java driving the
    standalone job runner)."""

    task_type = SEGMENT_GENERATION_AND_PUSH_TASK

    def execute(self, task: PinotTaskConfig, ctx: MinionContext) -> List[str]:
        import json as _json

        from pinot_tpu.controller.tasks import ingested_files_path
        from pinot_tpu.ingestion.batchjob import (
            SegmentGenerationJobRunner,
            SegmentGenerationJobSpec,
        )

        schema, cfg = self._schema_and_config(ctx, task.table)
        files = _json.loads(task.configs.get("inputFiles", "[]"))
        if not files:
            raise ValueError("SegmentGenerationAndPushTask without "
                             "inputFiles")
        out_dir = os.path.join(ctx.work_dir, task.task_id)
        spec = SegmentGenerationJobSpec(
            output_dir_uri=out_dir,
            table_name=cfg.table_name,
            data_format=task.configs.get("inputFormat") or None,
            segment_name_prefix=f"{cfg.table_name}_{task.task_id}")
        runner = SegmentGenerationJobRunner(spec, schema=schema,
                                            table_config=cfg)
        seg_dirs = runner.run_files(files)
        names = self._upload(ctx, task.table, seg_dirs)
        # record success AFTER upload, with the GENERATION-TIME mtimes (a
        # re-stat here would bind a later rewrite's mtime to the content
        # that was actually read, or crash on a deleted landing file)
        recorded = _json.loads(task.configs.get("inputFileMtimes", "{}"))

        def apply(d):
            d = dict(d or {})
            d.update(recorded)
            return d

        ctx.store.update(ingested_files_path(task.table), apply)
        return names


TASK_EXECUTORS: Dict[str, BaseTaskExecutor] = {
    e.task_type: e for e in (MergeRollupTaskExecutor(),
                             RealtimeToOfflineSegmentsTaskExecutor(),
                             PurgeTaskExecutor(),
                             ConvertToRawIndexTaskExecutor(),
                             SegmentGenerationAndPushTaskExecutor())
}
