"""Minion role: background task workers (merge/rollup, realtime->offline,
purge) driven by controller task generation.

Ref: pinot-minion/.../BaseMinionStarter.java:69 (role lifecycle),
pinot-plugins/pinot-minion-tasks/pinot-minion-builtin-tasks/ (builtin
executors), pinot-core/.../segment/processing/framework/ (the processing
engine, re-designed in segment/processing.py).
"""

from pinot_tpu.minion.tasks import (
    TASK_EXECUTORS,
    BaseTaskExecutor,
    MergeRollupTaskExecutor,
    MinionContext,
    PurgeTaskExecutor,
    RealtimeToOfflineSegmentsTaskExecutor,
)
from pinot_tpu.minion.worker import MinionInstance

__all__ = [
    "BaseTaskExecutor",
    "MergeRollupTaskExecutor",
    "MinionContext",
    "MinionInstance",
    "PurgeTaskExecutor",
    "RealtimeToOfflineSegmentsTaskExecutor",
    "TASK_EXECUTORS",
]
