"""Python client: broker connection + result-set model.

Re-design of the reference's java client
(``pinot-clients/pinot-java-client/.../Connection.java`` +
``JsonAsyncHttpPinotClientTransport.java`` + ``ResultSetGroup``): a
connection holds one or more broker URLs (round-robin, the static
broker-selector mode; ZK-dynamic selection maps to watching the cluster
state store), posts SQL to ``POST /query/sql``, and wraps the JSON
response in the same ResultSetGroup/ResultSet accessors the java client
exposes — so reference client code translates line for line::

    conn = connect(["localhost:8099"])
    results = conn.execute("SELECT count(*) FROM baseballStats")
    results.result_set.get_long(0, 0)
"""

from __future__ import annotations

import itertools
import json
import urllib.request

from typing import Any, Dict, List, Optional, Sequence


class PinotClientError(Exception):
    """Transport failures and server-side query exceptions
    (ref: PinotClientException)."""


class ResultSet:
    """One result table (ref: ResultTableResultSet)."""

    def __init__(self, result_table: Dict[str, Any]):
        schema = result_table.get("dataSchema", {})
        self.column_names: List[str] = schema.get("columnNames", [])
        self.column_types: List[str] = schema.get("columnDataTypes", [])
        self.rows: List[List[Any]] = result_table.get("rows", [])

    @property
    def row_count(self) -> int:
        return len(self.rows)

    @property
    def column_count(self) -> int:
        return len(self.column_names)

    def get_value(self, row: int, col: int) -> Any:
        return self.rows[row][col]

    def get_int(self, row: int, col: int) -> int:
        return int(self.rows[row][col])

    get_long = get_int

    def get_double(self, row: int, col: int) -> float:
        return float(self.rows[row][col])

    def get_string(self, row: int, col: int) -> str:
        return str(self.rows[row][col])

    def __iter__(self):
        return iter(self.rows)


class ResultSetGroup:
    """The parsed broker response (ref: ResultSetGroup.java)."""

    def __init__(self, response: Dict[str, Any]):
        self.raw = response
        rt = response.get("resultTable")
        self.result_set: Optional[ResultSet] = (
            ResultSet(rt) if rt is not None else None)
        self.exceptions: List[Dict[str, Any]] = \
            response.get("exceptions", [])

    @property
    def result_set_count(self) -> int:
        return 1 if self.result_set is not None else 0

    def get_result_set(self, index: int = 0) -> ResultSet:
        if index != 0 or self.result_set is None:
            raise IndexError(f"no result set {index}")
        return self.result_set

    # query execution stats (ref: ExecutionStats)
    @property
    def stats(self) -> Dict[str, Any]:
        return {k: v for k, v in self.raw.items()
                if k not in ("resultTable", "exceptions")}


class Connection:
    """Ref: Connection.java — execute() round-robins the broker list."""

    def __init__(self, broker_urls: Sequence[str], timeout_s: float = 60.0,
                 fail_on_exceptions: bool = True):
        if not broker_urls:
            raise ValueError("at least one broker url is required")
        self._brokers = [self._normalize(u) for u in broker_urls]
        self._rr = itertools.cycle(range(len(self._brokers)))
        self.timeout_s = timeout_s
        self.fail_on_exceptions = fail_on_exceptions

    @staticmethod
    def _normalize(url: str) -> str:
        if not url.startswith(("http://", "https://")):
            url = "http://" + url
        return url.rstrip("/")

    def execute(self, sql: str) -> ResultSetGroup:
        broker = self._brokers[next(self._rr)]
        body = json.dumps({"sql": sql}).encode("utf-8")
        req = urllib.request.Request(
            f"{broker}/query/sql", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                payload = json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            # reached the broker, got an error status: surface the body
            detail = ""
            try:
                detail = e.read().decode("utf-8", "replace")[:500]
            except OSError:
                pass
            raise PinotClientError(
                f"broker {broker} returned {e.code}: {detail}") from e
        except OSError as e:
            raise PinotClientError(f"broker {broker} unreachable: {e}") from e
        except ValueError as e:  # JSONDecodeError: 200 with a non-JSON body
            raise PinotClientError(
                f"broker {broker} returned a non-JSON response: {e}") from e
        group = ResultSetGroup(payload)
        if self.fail_on_exceptions and group.exceptions:
            raise PinotClientError(
                f"query failed: {group.exceptions[:3]}")
        return group


def connect(broker_urls: Sequence[str], **kw) -> Connection:
    """Ref: ConnectionFactory.fromHostList."""
    return Connection(broker_urls, **kw)
