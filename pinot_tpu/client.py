"""Python client: broker connection + result-set model.

Re-design of the reference's java client
(``pinot-clients/pinot-java-client/.../Connection.java`` +
``JsonAsyncHttpPinotClientTransport.java`` + ``ResultSetGroup``): a
connection holds one or more broker URLs (round-robin, the static
broker-selector mode; ZK-dynamic selection maps to watching the cluster
state store), posts SQL to ``POST /query/sql``, and wraps the JSON
response in the same ResultSetGroup/ResultSet accessors the java client
exposes — so reference client code translates line for line::

    conn = connect(["localhost:8099"])
    results = conn.execute("SELECT count(*) FROM baseballStats")
    results.result_set.get_long(0, 0)
"""

from __future__ import annotations

import itertools
import json
import urllib.request

from typing import Any, Dict, List, Optional, Sequence


class PinotClientError(Exception):
    """Transport failures and server-side query exceptions
    (ref: PinotClientException)."""


class ResultSet:
    """One result table (ref: ResultTableResultSet)."""

    def __init__(self, result_table: Dict[str, Any]):
        schema = result_table.get("dataSchema", {})
        self.column_names: List[str] = schema.get("columnNames", [])
        self.column_types: List[str] = schema.get("columnDataTypes", [])
        self.rows: List[List[Any]] = result_table.get("rows", [])

    @property
    def row_count(self) -> int:
        return len(self.rows)

    @property
    def column_count(self) -> int:
        return len(self.column_names)

    def get_value(self, row: int, col: int) -> Any:
        return self.rows[row][col]

    def get_int(self, row: int, col: int) -> int:
        return int(self.rows[row][col])

    get_long = get_int

    def get_double(self, row: int, col: int) -> float:
        return float(self.rows[row][col])

    def get_string(self, row: int, col: int) -> str:
        return str(self.rows[row][col])

    def __iter__(self):
        return iter(self.rows)


class ResultSetGroup:
    """The parsed broker response (ref: ResultSetGroup.java)."""

    def __init__(self, response: Dict[str, Any]):
        self.raw = response
        rt = response.get("resultTable")
        self.result_set: Optional[ResultSet] = (
            ResultSet(rt) if rt is not None else None)
        self.exceptions: List[Dict[str, Any]] = \
            response.get("exceptions", [])

    @property
    def result_set_count(self) -> int:
        return 1 if self.result_set is not None else 0

    def get_result_set(self, index: int = 0) -> ResultSet:
        if index != 0 or self.result_set is None:
            raise IndexError(f"no result set {index}")
        return self.result_set

    # query execution stats (ref: ExecutionStats)
    @property
    def stats(self) -> Dict[str, Any]:
        return {k: v for k, v in self.raw.items()
                if k not in ("resultTable", "exceptions")}


def _normalize_url(url: str) -> str:
    if not url.startswith(("http://", "https://")):
        url = "http://" + url
    return url.rstrip("/")


class DynamicBrokerSelector:
    """Live broker discovery from the controller's cluster state
    (ref: DynamicBrokerSelector — the java client watches ZK's broker
    external view; here the controller's /instances resource serves the
    same list). Results cache for ``refresh_s``; a controller outage or a
    bad response falls back to the last good list rather than erroring."""

    def __init__(self, controller_url: str, refresh_s: float = 10.0,
                 timeout_s: float = 10.0):
        self.controller_url = _normalize_url(controller_url)
        self.refresh_s = refresh_s
        self.timeout_s = timeout_s
        self._cached: List[str] = []
        self._fetched_at = 0.0

    def brokers(self, force: bool = False) -> List[str]:
        import time

        if (not force and self._cached
                and time.time() - self._fetched_at < self.refresh_s):
            return self._cached
        try:
            with urllib.request.urlopen(f"{self.controller_url}/instances",
                                        timeout=self.timeout_s) as r:
                payload = json.loads(r.read().decode("utf-8"))
        except (OSError, ValueError):
            return self._cached  # controller down: last good list serves
        urls = [f"http://{i.get('host', 'localhost')}:{i['port']}"
                for i in payload.get("instances", [])
                if i.get("type", "").upper().startswith("BROKER")
                and i.get("alive", True) and i.get("port")]
        if urls:
            self._cached = urls
            self._fetched_at = time.time()
        return self._cached


class Connection:
    """Ref: Connection.java — execute() round-robins the broker list,
    failing over to the next broker on transport errors (``retries``
    attempts total; broker-side query errors are NOT retried)."""

    def __init__(self, broker_urls: Sequence[str] = (),
                 timeout_s: float = 60.0,
                 fail_on_exceptions: bool = True,
                 selector: Optional[DynamicBrokerSelector] = None,
                 retries: int = 3, backoff_s: float = 0.1):
        if not broker_urls and selector is None:
            raise ValueError("broker urls or a broker selector is required")
        self._static = [self._normalize(u) for u in broker_urls]
        self._selector = selector
        self._rr = itertools.count()
        self.timeout_s = timeout_s
        self.fail_on_exceptions = fail_on_exceptions
        self.retries = max(retries, 1)
        self.backoff_s = backoff_s

    _normalize = staticmethod(_normalize_url)

    def _broker_list(self, force_refresh: bool = False) -> List[str]:
        if self._selector is not None:
            dynamic = self._selector.brokers(force=force_refresh)
            if dynamic:
                return dynamic
        return self._static

    def execute(self, sql: str) -> ResultSetGroup:
        import time

        last: Optional[Exception] = None
        for attempt in range(self.retries):
            brokers = self._broker_list(force_refresh=attempt > 0)
            if not brokers:
                raise PinotClientError("no live brokers discovered")
            broker = brokers[next(self._rr) % len(brokers)]
            try:
                return self._post(broker, sql)
            except PinotClientError:
                raise  # broker reached; its answer is final
            except OSError as e:
                last = e  # unreachable: fail over to the next broker
                if attempt + 1 < self.retries:
                    time.sleep(self.backoff_s * (2 ** attempt))
        raise PinotClientError(
            f"all brokers unreachable after {self.retries} attempts: "
            f"{last}") from last

    def _post(self, broker: str, sql: str) -> ResultSetGroup:
        body = json.dumps({"sql": sql}).encode("utf-8")
        req = urllib.request.Request(
            f"{broker}/query/sql", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                payload = json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            # reached the broker, got an error status: surface the body
            detail = ""
            try:
                detail = e.read().decode("utf-8", "replace")[:500]
            except OSError:
                pass
            raise PinotClientError(
                f"broker {broker} returned {e.code}: {detail}") from e
        except ValueError as e:  # JSONDecodeError: 200 with a non-JSON body
            raise PinotClientError(
                f"broker {broker} returned a non-JSON response: {e}") from e
        group = ResultSetGroup(payload)
        if self.fail_on_exceptions and group.exceptions:
            raise PinotClientError(
                f"query failed: {group.exceptions[:3]}")
        return group


def connect(broker_urls: Sequence[str], **kw) -> Connection:
    """Ref: ConnectionFactory.fromHostList."""
    return Connection(broker_urls, **kw)


def connect_with_controller(controller_url: str, **kw) -> Connection:
    """Ref: ConnectionFactory.fromZookeeper — dynamic broker discovery
    from the cluster's authority instead of a static host list."""
    return Connection(selector=DynamicBrokerSelector(controller_url), **kw)
