"""Broker JSON response model.

Re-design of ``pinot-common/.../response/broker/BrokerResponseNative.java``:
resultTable + exceptions + execution stats, serialized in the reference's
JSON layout so clients written against Pinot's response shape keep working.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pinot_tpu.engine.results import QueryStats, ResultTable


@dataclass
class BrokerResponse:
    result_table: Optional[ResultTable] = None
    exceptions: List[Dict[str, Any]] = field(default_factory=list)
    stats: QueryStats = field(default_factory=QueryStats)
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    time_used_ms: float = 0.0
    trace_info: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "exceptions": self.exceptions,
            "numServersQueried": self.num_servers_queried,
            "numServersResponded": self.num_servers_responded,
            "numSegmentsQueried": self.stats.num_segments_queried,
            "numSegmentsProcessed": self.stats.num_segments_processed,
            "numSegmentsMatched": self.stats.num_segments_matched,
            "numSegmentsPrunedByServer": self.stats.num_segments_pruned,
            "numDocsScanned": self.stats.num_docs_scanned,
            "totalDocs": self.stats.total_docs,
            "numGroupsLimitReached": self.stats.num_groups_limit_reached,
            "timeUsedMs": round(self.time_used_ms, 3),
        }
        if self.result_table is not None:
            d["resultTable"] = self.result_table.to_dict()
        if self.trace_info:
            d["traceInfo"] = self.trace_info
        return d

    @property
    def has_exceptions(self) -> bool:
        return bool(self.exceptions)

    def add_exception(self, code: int, message: str) -> None:
        # ref: QueryException error codes
        self.exceptions.append({"errorCode": code, "message": message})
