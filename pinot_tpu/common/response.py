"""Broker JSON response model.

Re-design of ``pinot-common/.../response/broker/BrokerResponseNative.java``:
resultTable + exceptions + execution stats, serialized in the reference's
JSON layout so clients written against Pinot's response shape keep working.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pinot_tpu.engine.results import QueryStats, ResultTable


@dataclass
class BrokerResponse:
    result_table: Optional[ResultTable] = None
    exceptions: List[Dict[str, Any]] = field(default_factory=list)
    stats: QueryStats = field(default_factory=QueryStats)
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    time_used_ms: float = 0.0
    # broker-side phase timings (COMPILATION/ROUTING/SCATTER_GATHER/REDUCE);
    # server phases arrive merged inside stats.phase_ms
    phase_times_ms: Dict[str, float] = field(default_factory=dict)
    trace_info: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "exceptions": self.exceptions,
            "numServersQueried": self.num_servers_queried,
            "numServersResponded": self.num_servers_responded,
            # loud partial-result flag (ref: BrokerResponseNative
            # partialResult): true when a scattered-to server returned no
            # usable DataTable — the result stands on fewer servers
            "partialResult": (self.num_servers_responded
                              < self.num_servers_queried),
            "numSegmentsQueried": self.stats.num_segments_queried,
            "numSegmentsProcessed": self.stats.num_segments_processed,
            "numSegmentsMatched": self.stats.num_segments_matched,
            "numSegmentsPrunedByServer": self.stats.num_segments_pruned,
            "numDocsScanned": self.stats.num_docs_scanned,
            "totalDocs": self.stats.total_docs,
            "numGroupsLimitReached": self.stats.num_groups_limit_reached,
            "timeUsedMs": round(self.time_used_ms, 3),
            # broker + (summed) server phase timings in one map
            "phaseTimesMs": {
                **{k: round(v, 3) for k, v in self.phase_times_ms.items()},
                **{k: round(v, 3) for k, v in self.stats.phase_ms.items()},
            },
        }
        if self.stats.staging:
            # HBM residency counters merged across servers (counters sum,
            # *Bytes keys max — see QueryStats.merge)
            d["staging"] = self.stats.staging
        if self.stats.decisions:
            # path-decision ledger (common/tracing.py): every decline of
            # a faster rung this query took, keyed
            # "point:declined->chosen:reason", summed across servers
            d["decisions"] = self.stats.decisions
        if self.result_table is not None:
            d["resultTable"] = self.result_table.to_dict()
        if self.trace_info:
            d["traceInfo"] = self.trace_info
        return d

    @property
    def has_exceptions(self) -> bool:
        return bool(self.exceptions)

    def add_exception(self, code: int, message: str) -> None:
        # ref: QueryException error codes
        self.exceptions.append({"errorCode": code, "message": message})
