"""DataTable: the server->broker intermediate-result wire format.

Re-design of ``pinot-core/.../common/datatable/DataTableImplV3.java:43`` +
``ObjectSerDeUtils`` (custom serde for aggregation intermediate objects):
one self-describing payload carrying either merged scalar-aggregation
states, a group-by table, selection rows, or distinct rows — plus the data
schema, per-server execution stats, and exceptions. Values round-trip
through a tagged encoding covering the intermediate-state types (tuples for
AVG/MINMAXRANGE, frozensets for DISTINCTCOUNT, bytes, non-finite floats).

JSON framing keeps the format debuggable and language-neutral; bulk
selection payloads can later swap to Arrow IPC without changing consumers.
"""

from __future__ import annotations

import enum
import json

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pinot_tpu.engine.results import DataSchema, QueryStats


class ResponseType(enum.Enum):
    AGGREGATION = "AGGREGATION"
    GROUP_BY = "GROUP_BY"
    SELECTION = "SELECTION"
    DISTINCT = "DISTINCT"


# --------------------------------------------------------------------------
# tagged value encoding (ref: ObjectSerDeUtils object-type registry)
# --------------------------------------------------------------------------

def encode_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        if v != v or v in (float("inf"), float("-inf")):
            return {"__t": "f", "v": repr(v)}
        return v
    if isinstance(v, bytes):
        return {"__t": "b", "v": v.hex()}
    if isinstance(v, tuple):
        return {"__t": "t", "v": [encode_value(x) for x in v]}
    if isinstance(v, frozenset):
        return {"__t": "s", "v": sorted((encode_value(x) for x in v),
                                        key=lambda e: json.dumps(e))}
    if isinstance(v, (list,)):
        return {"__t": "l", "v": [encode_value(x) for x in v]}
    if hasattr(v, "item"):  # numpy scalar
        return encode_value(v.item())
    raise TypeError(f"cannot encode {type(v).__name__} for the wire")


def decode_value(v: Any) -> Any:
    if isinstance(v, dict) and "__t" in v:
        t = v["__t"]
        if t == "f":
            return float(v["v"])
        if t == "b":
            return bytes.fromhex(v["v"])
        if t == "t":
            return tuple(decode_value(x) for x in v["v"])
        if t == "s":
            return frozenset(decode_value(x) for x in v["v"])
        if t == "l":
            return [decode_value(x) for x in v["v"]]
        raise ValueError(f"unknown value tag {t!r}")
    return v


# --------------------------------------------------------------------------
# the DataTable
# --------------------------------------------------------------------------

@dataclass
class DataTable:
    """One server's reply for one (sub)query."""

    response_type: ResponseType
    # AGGREGATION: {"states": [state per agg]}
    # GROUP_BY:    {"groups": [[key tuple, [state per agg]], ...],
    #               "schema_types": {col: type label}}
    # SELECTION:   {"schema": DataSchema dict, "rows": [...],
    #               "num_hidden": trailing order-by-only columns}
    # DISTINCT:    {"schema": DataSchema dict, "rows": [...]}
    payload: Dict[str, Any]
    stats: QueryStats = field(default_factory=QueryStats)
    exceptions: List[str] = field(default_factory=list)

    # -- framing -------------------------------------------------------------
    def to_bytes(self) -> bytes:
        return json.dumps({
            "type": self.response_type.value,
            "payload": self.payload,
            "stats": self.stats.to_dict(),
            "exceptions": self.exceptions,
        }, separators=(",", ":")).encode("utf-8")

    @classmethod
    def from_bytes(cls, raw: bytes) -> "DataTable":
        d = json.loads(raw.decode("utf-8"))
        st = d.get("stats", {})
        stats = QueryStats(
            num_segments_queried=st.get("numSegmentsQueried", 0),
            num_segments_processed=st.get("numSegmentsProcessed", 0),
            num_segments_matched=st.get("numSegmentsMatched", 0),
            num_segments_pruned=st.get("numSegmentsPrunedByServer", 0),
            num_docs_scanned=st.get("numDocsScanned", 0),
            total_docs=st.get("totalDocs", 0),
            num_groups_limit_reached=st.get("numGroupsLimitReached", False),
            phase_ms=st.get("phaseTimesMs", {}),
            trace=st.get("trace", []),
        )
        return cls(ResponseType(d["type"]), d["payload"], stats,
                   d.get("exceptions", []))

    # -- typed constructors --------------------------------------------------
    @classmethod
    def for_aggregation(cls, states: List[Any], stats: QueryStats) -> "DataTable":
        return cls(ResponseType.AGGREGATION,
                   {"states": [encode_value(s) for s in states]}, stats)

    @classmethod
    def for_group_by(cls, groups: Dict[tuple, List[Any]],
                     schema_types: Dict[str, str],
                     stats: QueryStats) -> "DataTable":
        return cls(ResponseType.GROUP_BY, {
            "groups": [[encode_value(k), [encode_value(s) for s in states]]
                       for k, states in groups.items()],
            "schema_types": schema_types,
        }, stats)

    @classmethod
    def for_selection(cls, schema: DataSchema, rows: List[List[Any]],
                      stats: QueryStats, num_hidden: int = 0) -> "DataTable":
        return cls(ResponseType.SELECTION, {
            "schema": schema.to_dict(),
            "rows": [[encode_value(c) for c in r] for r in rows],
            "num_hidden": num_hidden,
        }, stats)

    @classmethod
    def for_distinct(cls, schema: DataSchema,
                     rows: List[List[Any]], stats: QueryStats) -> "DataTable":
        return cls(ResponseType.DISTINCT, {
            "schema": schema.to_dict(),
            "rows": [[encode_value(c) for c in r] for r in rows],
        }, stats)

    @classmethod
    def for_exception(cls, message: str,
                      response_type: ResponseType = ResponseType.AGGREGATION
                      ) -> "DataTable":
        return cls(response_type, {}, QueryStats(), [message])

    # -- typed readers -------------------------------------------------------
    def agg_states(self) -> List[Any]:
        return [decode_value(s) for s in self.payload["states"]]

    def group_by_groups(self) -> Dict[tuple, List[Any]]:
        return {decode_value(k): [decode_value(s) for s in states]
                for k, states in self.payload["groups"]}

    def schema_types(self) -> Dict[str, str]:
        return self.payload.get("schema_types", {})

    def data_schema(self) -> DataSchema:
        d = self.payload["schema"]
        return DataSchema(d["columnNames"], d["columnDataTypes"])

    def rows(self) -> List[List[Any]]:
        return [[decode_value(c) for c in r] for r in self.payload["rows"]]

    @property
    def num_hidden(self) -> int:
        return self.payload.get("num_hidden", 0)
