"""DataTable: the server->broker intermediate-result wire format.

Re-design of ``pinot-core/.../common/datatable/DataTableImplV3.java:43`` +
``ObjectSerDeUtils`` (custom serde for aggregation intermediate objects):
one self-describing payload carrying either merged scalar-aggregation
states, a group-by table, selection rows, or distinct rows — plus the data
schema, per-server execution stats, and exceptions. Values round-trip
through a tagged encoding covering the intermediate-state types (tuples for
AVG/MINMAXRANGE, frozensets for DISTINCTCOUNT, bytes, non-finite floats).

Framing is binary columnar (magic ``PDT3``): header + stats/exceptions
sections + a per-type payload where selection/distinct/group-by data ships
as typed columns — numeric columns as raw little-endian buffers, string
columns as offset+heap pairs, heterogeneous state columns through the
tagged object serde (common/serde.py, the ObjectSerDeUtils analogue).
``from_bytes`` sniffs the magic and still accepts the legacy JSON framing,
so mixed-version servers interoperate.

Decode is COLUMNAR-NATIVE: the wire's typed buffers stay numpy arrays
(i64/f64 zero-copy via ``np.frombuffer``, strings as heap+offsets) behind
the ``Column`` accessors — the broker's vectorized reduce consumes
``columns()`` / ``group_columns()`` without boxing a single numeric cell.
``rows()`` / ``group_by_groups()`` remain as lazy compatibility views, and
``payload`` materializes its legacy dict shape on first access only.
"""

from __future__ import annotations

import enum
import json
import struct

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common import serde
from pinot_tpu.engine.results import DataSchema, QueryStats

MAGIC = b"PDT3"


class ResponseType(enum.Enum):
    AGGREGATION = "AGGREGATION"
    GROUP_BY = "GROUP_BY"
    SELECTION = "SELECTION"
    DISTINCT = "DISTINCT"


# stable wire ordinals: never renumber, append only (declaration order must
# not leak into the binary framing or mixed-version decode breaks)
_WIRE_ORDINAL = {
    ResponseType.AGGREGATION: 0,
    ResponseType.GROUP_BY: 1,
    ResponseType.SELECTION: 2,
    ResponseType.DISTINCT: 3,
}
_WIRE_TYPE = {v: k for k, v in _WIRE_ORDINAL.items()}


# --------------------------------------------------------------------------
# tagged value encoding (ref: ObjectSerDeUtils object-type registry)
# --------------------------------------------------------------------------

def encode_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        if v != v or v in (float("inf"), float("-inf")):
            return {"__t": "f", "v": repr(v)}
        return v
    if isinstance(v, bytes):
        return {"__t": "b", "v": v.hex()}
    if isinstance(v, tuple):
        return {"__t": "t", "v": [encode_value(x) for x in v]}
    if isinstance(v, frozenset):
        return {"__t": "s", "v": sorted((encode_value(x) for x in v),
                                        key=lambda e: json.dumps(e))}
    if isinstance(v, (list,)):
        return {"__t": "l", "v": [encode_value(x) for x in v]}
    if hasattr(v, "item"):  # numpy scalar
        return encode_value(v.item())
    raise TypeError(f"cannot encode {type(v).__name__} for the wire")


def decode_value(v: Any) -> Any:
    if isinstance(v, dict) and "__t" in v:
        t = v["__t"]
        if t == "f":
            return float(v["v"])
        if t == "b":
            return bytes.fromhex(v["v"])
        if t == "t":
            return tuple(decode_value(x) for x in v["v"])
        if t == "s":
            return frozenset(decode_value(x) for x in v["v"])
        if t == "l":
            return [decode_value(x) for x in v["v"]]
        raise ValueError(f"unknown value tag {t!r}")
    return v


# --------------------------------------------------------------------------
# columnar sections (binary framing)
# --------------------------------------------------------------------------

# Column-kind dispatch table. graftlint's ``wire`` family holds every
# dispatcher (a function referencing two or more kinds) to the FULL table:
# adding a kind without updating encode, decode, and every Column accessor
# fails lint instead of silently mis-framing new columns.
_COL_I64 = 0
_COL_F64 = 1
_COL_STR = 2
_COL_OBJ = 3

# non-kind groupings (tuples, not wire ordinals — excluded from the lint's
# kind table, which only collects int-valued _COL_* constants)
_COL_NUMERIC = (_COL_I64, _COL_F64)


class Column:
    """One typed wire column, kept in its decoded-buffer form.

    i64/f64: a zero-copy numpy view over the received bytes. str: the
    utf-8 heap + offsets (python strings decode lazily, once). obj: the
    serde-decoded python objects (tuples/frozensets/bytes/None/mixed).
    ``tolist()`` is the boxed compatibility view; the vectorized reduce
    never calls it for numeric columns.
    """

    __slots__ = ("kind", "n", "_arr", "_heap", "_offsets", "_vals", "_safe")

    def __init__(self, kind: int, n: int, arr: Optional[np.ndarray] = None,
                 heap: Optional[bytes] = None,
                 offsets: Optional[np.ndarray] = None,
                 vals: Optional[list] = None):
        self.kind = kind
        self.n = n
        self._arr = arr
        self._heap = heap
        self._offsets = offsets
        self._vals = vals
        self._safe: Optional[bool] = None

    # -- constructors --------------------------------------------------------
    @classmethod
    def from_encoded(cls, values: List[Any]) -> "Column":
        """Payload-shaped (tagged-encoding) cells -> a typed Column; the
        sniff mirrors ``_encode_column`` so constructor-built and
        wire-decoded tables expose identical column kinds."""
        vals = [decode_value(v) for v in values]
        vals = [v.item() if hasattr(v, "item") else v for v in vals]
        if vals and all(type(v) is int for v in vals) \
                and all(-(1 << 63) <= v < (1 << 63) for v in vals):
            return cls(_COL_I64, len(vals),
                       arr=np.asarray(vals, dtype="<i8"), vals=vals)
        if vals and all(isinstance(v, float) for v in vals):
            return cls(_COL_F64, len(vals),
                       arr=np.asarray(vals, dtype="<f8"), vals=vals)
        if vals and all(type(v) is str for v in vals):
            return cls(_COL_STR, len(vals), vals=vals)
        return cls(_COL_OBJ, len(vals), vals=vals)

    # -- typed accessors -----------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self.kind in _COL_NUMERIC

    @property
    def is_string(self) -> bool:
        return self.kind == _COL_STR

    @property
    def json_safe(self) -> bool:
        """Every boxed cell already satisfies the payload's JSON-shape
        invariant (i64/str always; f64 unless non-finite; obj never —
        tuples/sets/bytes need wrapping). Computed from the ARRAY for f64,
        never by scanning boxed cells."""
        if self._safe is None:
            if self.kind == _COL_F64:
                self._safe = bool(np.isfinite(self._arr).all())
            elif self.kind == _COL_I64 or self.kind == _COL_STR:
                self._safe = True
            elif self.kind == _COL_OBJ:
                self._safe = False
            else:
                raise ValueError(f"unknown column kind {self.kind}")
        return self._safe

    def array(self) -> np.ndarray:
        """The column as a numpy array: numeric -> the (zero-copy) wire
        buffer; str -> a unicode array (decoded once); obj -> object
        array. Sortable for every kind except obj (caller's guard)."""
        if self.kind == _COL_I64 or self.kind == _COL_F64:
            return self._arr
        if self.kind == _COL_STR:
            return np.asarray(self._strings(), dtype=object if self.n == 0
                              else None)
        if self.kind == _COL_OBJ:
            a = np.empty(self.n, dtype=object)
            for i, v in enumerate(self._vals):
                a[i] = v
            return a
        raise ValueError(f"unknown column kind {self.kind}")

    def tolist(self) -> list:
        """Boxed DECODED values (the ``rows()`` view), cached."""
        if self._vals is None:
            if self.kind == _COL_I64:
                self._vals = [int(v) for v in self._arr]
            elif self.kind == _COL_F64:
                self._vals = [float(v) for v in self._arr]
            elif self.kind == _COL_STR:
                self._vals = self._strings()
            elif self.kind == _COL_OBJ:
                self._vals = []
            else:
                raise ValueError(f"unknown column kind {self.kind}")
        return self._vals

    def take_boxed(self, indices) -> list:
        """Box ONLY the cells at ``indices`` (the trimmed-output path —
        a LIMIT-sized materialization, never the full column)."""
        if self._vals is not None:
            return [self._vals[int(i)] for i in indices]
        if self.kind == _COL_I64:
            return [int(v) for v in self._arr.take(indices)]
        if self.kind == _COL_F64:
            return [float(v) for v in self._arr.take(indices)]
        if self.kind == _COL_STR:
            off, heap = self._offsets, self._heap
            return [heap[off[i]:off[i + 1]].decode("utf-8")
                    for i in (int(i) for i in indices)]
        if self.kind == _COL_OBJ:
            return [self._vals[int(i)] for i in indices]
        raise ValueError(f"unknown column kind {self.kind}")

    def encoded_list(self) -> list:
        """Payload-shaped cells (tagged encoding applied where the boxed
        value would violate the JSON-shape invariant)."""
        if self.json_safe:
            return self.tolist()
        return [encode_value(v) for v in self.tolist()]

    def encode_parts(self, parts: list) -> None:
        """Append the wire form of this column as buffer PARTS (the typed
        fast path of ``_encode_column``): numeric and decoded-string
        columns frame their existing buffers directly — memoryviews over
        the arrays, no intermediate bytearray assembly — and the final
        ``b"".join`` in ``DataTable.to_bytes`` is the only copy."""
        if self.kind == _COL_I64:
            parts.append(bytes([_COL_I64]))
            parts.append(np.ascontiguousarray(self._arr, dtype="<i8").data)
        elif self.kind == _COL_F64:
            parts.append(bytes([_COL_F64]))
            parts.append(np.ascontiguousarray(self._arr, dtype="<f8").data)
        elif self.kind == _COL_STR:
            parts.append(bytes([_COL_STR]))
            if self._heap is not None:
                # wire-decoded: the heap + offsets ARE the wire form
                parts.append(struct.pack("<I", len(self._heap)))
                parts.append(self._heap)
                parts.append(np.ascontiguousarray(self._offsets,
                                                  dtype="<u4").data)
            else:
                _encode_str_parts(parts, self.tolist())
        elif self.kind == _COL_OBJ:
            parts.append(bytes([_COL_OBJ]))
            buf = bytearray()  # serde is inherently byte-at-a-time
            for v in self._vals:
                serde.pack_obj(v, buf)
            parts.append(bytes(buf))
        else:
            raise ValueError(f"unknown column kind {self.kind}")

    def _strings(self) -> List[str]:
        if self._vals is not None:
            return self._vals
        off = self._offsets
        heap = self._heap
        self._vals = [heap[off[i]:off[i + 1]].decode("utf-8")
                      for i in range(self.n)]
        return self._vals


def _encode_str_column(out: bytearray, vals: List[str]) -> None:
    """Heap+offsets body of a string column (kind byte is the caller's)."""
    parts = [v.encode("utf-8") for v in vals]
    heap = b"".join(parts)
    offsets = np.cumsum([0] + [len(p) for p in parts]).astype("<u4")
    out.extend(struct.pack("<I", len(heap)))
    out.extend(heap)
    out.extend(offsets.tobytes())


def _encode_str_parts(parts: list, vals: List[str]) -> None:
    """Heap+offsets body of a string column as buffer parts: each encoded
    string is its own part (the heap never assembles on the python heap —
    the final join IS the heap) followed by the offsets buffer."""
    enc = [v.encode("utf-8") for v in vals]
    offsets = np.cumsum([0] + [len(p) for p in enc]).astype("<u4")
    parts.append(struct.pack("<I", int(offsets[-1])))
    parts.extend(enc)
    parts.append(offsets.data)


def _encode_column(out: bytearray, values: List[Any]) -> None:
    """One typed column: numeric homogeneity -> raw buffers, strings ->
    offsets+heap, anything else (tuples/sets/bytes/None/mixed) -> tagged
    objects. The type sniff treats numpy scalars as their python values."""
    vals = [v.item() if hasattr(v, "item") else v for v in values]
    if vals and all(type(v) is int for v in vals) \
            and all(-(1 << 63) <= v < (1 << 63) for v in vals):
        out.append(_COL_I64)
        out.extend(np.asarray(vals, dtype="<i8").tobytes())
        return
    if vals and all(isinstance(v, float) for v in vals):
        out.append(_COL_F64)
        out.extend(np.asarray(vals, dtype="<f8").tobytes())
        return
    if vals and all(type(v) is str for v in vals):
        out.append(_COL_STR)
        _encode_str_column(out, vals)
        return
    out.append(_COL_OBJ)
    for v in vals:
        serde.pack_obj(v, out)


def _decode_column(buf: bytes, off: int, n: int) -> Tuple[Column, int]:
    """-> (Column, new offset). Numeric buffers are ZERO-COPY numpy views
    over ``buf``; strings stay heap+offsets; obj cells decode through the
    tagged serde. Nothing is boxed here — ``Column.tolist()`` is the lazy
    boxing point for compatibility consumers."""
    kind = buf[off]
    off += 1
    if kind == _COL_I64:
        a = np.frombuffer(buf, dtype="<i8", count=n, offset=off)
        return Column(_COL_I64, n, arr=a), off + 8 * n
    if kind == _COL_F64:
        a = np.frombuffer(buf, dtype="<f8", count=n, offset=off)
        return Column(_COL_F64, n, arr=a), off + 8 * n
    if kind == _COL_STR:
        (heap_len,) = struct.unpack_from("<I", buf, off)
        off += 4
        raw = buf[off:off + heap_len]
        off += heap_len
        offsets = np.frombuffer(buf, dtype="<u4", count=n + 1, offset=off)
        off += 4 * (n + 1)
        return Column(_COL_STR, n, heap=raw, offsets=offsets), off
    if kind == _COL_OBJ:
        vals = []
        for _ in range(n):
            v, off = serde.unpack_obj(buf, off)
            vals.append(v)
        return Column(_COL_OBJ, n, vals=vals), off
    raise ValueError(f"unknown column kind {kind}")


def _put_section(parts: list, raw: bytes) -> None:
    parts.append(struct.pack("<I", len(raw)))
    parts.append(raw)


def _get_section(buf: bytes, off: int) -> tuple:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return buf[off:off + n], off + n


# --------------------------------------------------------------------------
# the DataTable
# --------------------------------------------------------------------------

class DataTable:
    """One server's reply for one (sub)query.

    ``payload`` keeps the legacy JSON-shaped dict contract:
      AGGREGATION: {"states": [state per agg]}
      GROUP_BY:    {"groups": [[key tuple, [state per agg]], ...],
                    "schema_types": {col: type label}}
      SELECTION:   {"schema": DataSchema dict, "rows": [...],
                    "num_hidden": trailing order-by-only columns}
      DISTINCT:    {"schema": DataSchema dict, "rows": [...]}
    but on a wire-decoded table the row/group section lives as typed
    ``Column`` buffers until something touches ``payload`` — the
    vectorized reduce reads ``columns()`` / ``group_columns()`` and the
    boxed dict never materializes.
    """

    __slots__ = ("response_type", "stats", "exceptions", "wire_decoded",
                 "_payload", "_cols", "_key_cols", "_agg_cols", "_n_rows")

    def __init__(self, response_type: ResponseType,
                 payload: Optional[Dict[str, Any]],
                 stats: Optional[QueryStats] = None,
                 exceptions: Optional[List[str]] = None):
        self.response_type = response_type
        self._payload: Dict[str, Any] = payload if payload is not None else {}
        self.stats = stats if stats is not None else QueryStats()
        self.exceptions = exceptions if exceptions is not None else []
        # True on tables that arrived THROUGH the wire (from_bytes /
        # legacy JSON): the broker's device reduce keys off it — a table
        # that crossed a process boundary already paid D2H, so the host
        # merge is its natural frame
        self.wire_decoded = False
        self._cols: Optional[List[Column]] = None
        self._key_cols: Optional[List[Column]] = None
        self._agg_cols: Optional[List[Column]] = None
        self._n_rows: Optional[int] = None

    def __repr__(self) -> str:
        return (f"DataTable({self.response_type.value}, "
                f"rows={self.num_rows()}, "
                f"exceptions={len(self.exceptions)})")

    # -- payload compatibility ----------------------------------------------
    @property
    def payload(self) -> Dict[str, Any]:
        """The legacy dict view; materializes boxed rows/groups from the
        columnar buffers on first access (compat + JSON framing only —
        the array-native reduce never touches it)."""
        self._materialize()
        return self._payload

    def _materialize(self) -> None:
        p = self._payload
        if self._cols is not None and "rows" not in p:
            cols = [c.encoded_list() for c in self._cols]
            p["rows"] = [[c[i] for c in cols]
                         for i in range(self._n_rows or 0)]
        if self._key_cols is not None and "groups" not in p:
            keys = [c.tolist() for c in self._key_cols]
            aggs = [c.encoded_list() for c in self._agg_cols]
            p["groups"] = [
                [encode_value(tuple(kc[i] for kc in keys)),
                 [ac[i] for ac in aggs]]
                for i in range(self._n_rows or 0)]

    # -- framing -------------------------------------------------------------
    def to_buffers(self) -> List[Any]:
        """The wire form as an ordered list of buffer parts (bytes /
        memoryviews over the live column arrays). Layout:
        magic | u8 type-ordinal | stats json section | exceptions json
        section | per-type payload. Zero-copy: typed column buffers are
        framed directly (``Column.encode_parts``); nothing assembles an
        intermediate bytearray. A transport that can writev/scatter sends
        the parts as-is; ``to_bytes`` is the single-buffer join."""
        parts: List[Any] = [MAGIC, bytes([_WIRE_ORDINAL[self.response_type]])]
        _put_section(parts, json.dumps(
            self.stats.to_dict(), separators=(",", ":")).encode("utf-8"))
        _put_section(parts, json.dumps(
            self.exceptions, separators=(",", ":")).encode("utf-8"))
        t = self.response_type
        if t is ResponseType.AGGREGATION:
            states = [decode_value(s) for s in self._payload["states"]] \
                if self._payload else []
            buf = bytearray()
            serde.pack_obj(len(states), buf)
            for s in states:
                serde.pack_obj(s, buf)
            parts.append(bytes(buf))
        elif t is ResponseType.GROUP_BY:
            _put_section(parts, json.dumps(
                self._payload.get("schema_types", {}),
                separators=(",", ":")).encode("utf-8"))
            key_cols, agg_cols = (self.group_columns()
                                  if self._payload or self._key_cols
                                  else ([], []))
            n = key_cols[0].n if key_cols else 0
            parts.append(struct.pack("<IHH", n, len(key_cols),
                                     len(agg_cols)))
            for c in key_cols:
                c.encode_parts(parts)
            for c in agg_cols:
                c.encode_parts(parts)
        else:  # SELECTION / DISTINCT
            schema = self._payload.get(
                "schema", {"columnNames": [], "columnDataTypes": []}) \
                if self._payload else {"columnNames": [],
                                       "columnDataTypes": []}
            cols = self.columns() if self._payload or self._cols else []
            n_rows = cols[0].n if cols else 0
            _put_section(parts, json.dumps(
                schema, separators=(",", ":")).encode("utf-8"))
            parts.append(struct.pack("<IHH", n_rows, len(cols),
                                     self.num_hidden))
            for c in cols:
                c.encode_parts(parts)
        return parts

    def to_bytes(self) -> bytes:
        """Single-buffer wire form: ONE join over ``to_buffers`` parts."""
        return b"".join(self.to_buffers())

    @classmethod
    def from_bytes(cls, raw: bytes) -> "DataTable":
        if not raw.startswith(MAGIC):
            return cls._from_json_bytes(raw)
        off = len(MAGIC)
        rtype = _WIRE_TYPE[raw[off]]
        off += 1
        stats_raw, off = _get_section(raw, off)
        exc_raw, off = _get_section(raw, off)
        stats = cls._stats_from_dict(json.loads(stats_raw.decode("utf-8")))
        exceptions = json.loads(exc_raw.decode("utf-8"))
        if rtype is ResponseType.AGGREGATION:
            n, off = serde.unpack_obj(raw, off)
            states = []
            for _ in range(n):
                s, off = serde.unpack_obj(raw, off)
                states.append(s)
            dt = cls(rtype, {"states": [encode_value(s) for s in states]},
                     stats, exceptions)
            dt.wire_decoded = True
            return dt
        dt = cls(rtype, {}, stats, exceptions)
        dt.wire_decoded = True
        if rtype is ResponseType.GROUP_BY:
            st_raw, off = _get_section(raw, off)
            dt._payload["schema_types"] = json.loads(st_raw.decode("utf-8"))
            n, arity, n_aggs = struct.unpack_from("<IHH", raw, off)
            off += 8
            key_cols = []
            for _ in range(arity):
                col, off = _decode_column(raw, off, n)
                key_cols.append(col)
            agg_cols = []
            for _ in range(n_aggs):
                col, off = _decode_column(raw, off, n)
                agg_cols.append(col)
            dt._key_cols, dt._agg_cols, dt._n_rows = key_cols, agg_cols, n
        else:
            schema_raw, off = _get_section(raw, off)
            dt._payload["schema"] = json.loads(schema_raw.decode("utf-8"))
            n_rows, n_cols, num_hidden = struct.unpack_from(
                "<IHH", raw, off)
            off += 8
            cols = []
            for _ in range(n_cols):
                col, off = _decode_column(raw, off, n_rows)
                cols.append(col)
            dt._cols, dt._n_rows = cols, n_rows
            if rtype is ResponseType.SELECTION:
                dt._payload["num_hidden"] = num_hidden
        return dt

    @staticmethod
    def _stats_from_dict(st: Dict[str, Any]) -> QueryStats:
        return QueryStats(
            num_segments_queried=st.get("numSegmentsQueried", 0),
            num_segments_processed=st.get("numSegmentsProcessed", 0),
            num_segments_matched=st.get("numSegmentsMatched", 0),
            num_segments_pruned=st.get("numSegmentsPrunedByServer", 0),
            num_docs_scanned=st.get("numDocsScanned", 0),
            total_docs=st.get("totalDocs", 0),
            num_groups_limit_reached=st.get("numGroupsLimitReached", False),
            num_servers_queried=st.get("numServersQueried", 0),
            num_servers_responded=st.get("numServersResponded", 0),
            group_by_rung=st.get("groupByRung"),
            startree_tree_index=st.get("startreeTreeIndex"),
            reduce_path=st.get("reducePath"),
            staging=st.get("staging", {}),
            launch=st.get("launch", {}),
            phase_ms=st.get("phaseTimesMs", {}),
            trace=st.get("trace", []),
            spans=st.get("spans", []),
            decisions=st.get("decisions", {}),
        )

    @classmethod
    def _from_json_bytes(cls, raw: bytes) -> "DataTable":
        """Legacy JSON framing (kept for mixed-version interop + debug)."""
        d = json.loads(raw.decode("utf-8"))
        dt = cls(ResponseType(d["type"]), d["payload"],
                 cls._stats_from_dict(d.get("stats", {})),
                 d.get("exceptions", []))
        dt.wire_decoded = True
        return dt

    def to_json_bytes(self) -> bytes:
        """The debuggable JSON framing (not the serving default)."""
        return json.dumps({
            "type": self.response_type.value,
            "payload": self.payload,
            "stats": self.stats.to_dict(),
            "exceptions": self.exceptions,
        }, separators=(",", ":")).encode("utf-8")

    # -- typed constructors --------------------------------------------------
    @classmethod
    def for_aggregation(cls, states: List[Any], stats: QueryStats) -> "DataTable":
        return cls(ResponseType.AGGREGATION,
                   {"states": [encode_value(s) for s in states]}, stats)

    @classmethod
    def for_group_by(cls, groups: Dict[tuple, List[Any]],
                     schema_types: Dict[str, str],
                     stats: QueryStats) -> "DataTable":
        return cls(ResponseType.GROUP_BY, {
            "groups": [[encode_value(k), [encode_value(s) for s in states]]
                       for k, states in groups.items()],
            "schema_types": schema_types,
        }, stats)

    @classmethod
    def for_selection(cls, schema: DataSchema, rows: List[List[Any]],
                      stats: QueryStats, num_hidden: int = 0,
                      sorted_rows: bool = False) -> "DataTable":
        """``sorted_rows``: the server already ordered the (trimmed) rows
        by the query's ORDER BY — the broker's merge can treat the block
        as pre-sorted (ref: SelectionOperatorUtils sorted-block merge).
        Rides the schema section so the binary layout is unchanged."""
        sd = schema.to_dict()
        if sorted_rows:
            sd["sorted"] = True
        return cls(ResponseType.SELECTION, {
            "schema": sd,
            "rows": [[encode_value(c) for c in r] for r in rows],
            "num_hidden": num_hidden,
        }, stats)

    @classmethod
    def for_distinct(cls, schema: DataSchema,
                     rows: List[List[Any]], stats: QueryStats) -> "DataTable":
        return cls(ResponseType.DISTINCT, {
            "schema": schema.to_dict(),
            "rows": [[encode_value(c) for c in r] for r in rows],
        }, stats)

    @classmethod
    def for_exception(cls, message: str,
                      response_type: ResponseType = ResponseType.AGGREGATION
                      ) -> "DataTable":
        return cls(response_type, {}, QueryStats(), [message])

    # -- columnar readers (the array-native reduce path) ---------------------
    def columns(self) -> List[Column]:
        """SELECTION/DISTINCT columns (visible + hidden) as typed Columns.
        Zero-copy when the table was wire-decoded; constructor-built and
        legacy-JSON tables sniff their boxed payload rows into typed
        arrays (same kinds the wire encoder would have chosen)."""
        if self._cols is None:
            rows = self._payload.get("rows", [])
            n_cols = len(self._payload.get(
                "schema", {}).get("columnNames", ())) or \
                (len(rows[0]) if rows else 0)
            self._cols = [Column.from_encoded([r[c] for r in rows])
                          for c in range(n_cols)]
            self._n_rows = len(rows)
        return self._cols

    def group_columns(self) -> Tuple[List[Column], List[Column]]:
        """GROUP_BY (key columns, aggregation-state columns)."""
        if self._key_cols is None:
            groups = self.group_by_groups() if self._payload else {}
            keys = list(groups.keys())
            vals = list(groups.values())
            arity = len(keys[0]) if keys else 0
            n_aggs = len(vals[0]) if vals else 0
            self._key_cols = [
                Column.from_encoded([encode_value(k[i]) for k in keys])
                for i in range(arity)]
            self._agg_cols = [
                Column.from_encoded([encode_value(v[a]) for v in vals])
                for a in range(n_aggs)]
            self._n_rows = len(keys)
        return self._key_cols, self._agg_cols

    def num_rows(self) -> int:
        """Row/group count without materializing the boxed payload."""
        if self._n_rows is not None:
            return self._n_rows
        if self.response_type is ResponseType.GROUP_BY:
            return len(self._payload.get("groups", ()))
        if self.response_type is ResponseType.AGGREGATION:
            return 1 if self._payload.get("states") else 0
        return len(self._payload.get("rows", ()))

    @property
    def selection_sorted(self) -> bool:
        """True when the producing server ordered this block by the
        query's ORDER BY (see ``for_selection(sorted_rows=True)``)."""
        return bool(self._payload.get("schema", {}).get("sorted"))

    # -- typed readers -------------------------------------------------------
    def agg_states(self) -> List[Any]:
        return [decode_value(s) for s in self._payload["states"]]

    def group_by_groups(self) -> Dict[tuple, List[Any]]:
        if self._key_cols is not None and "groups" not in self._payload:
            keys = [c.tolist() for c in self._key_cols]
            aggs = [c.tolist() for c in self._agg_cols]
            return {tuple(kc[i] for kc in keys): [ac[i] for ac in aggs]
                    for i in range(self._n_rows or 0)}
        return {decode_value(k): [decode_value(s) for s in states]
                for k, states in self._payload["groups"]}

    def schema_types(self) -> Dict[str, str]:
        return self._payload.get("schema_types", {})

    def data_schema(self) -> DataSchema:
        d = self._payload["schema"]
        return DataSchema(d["columnNames"], d["columnDataTypes"])

    def rows(self) -> List[List[Any]]:
        """Boxed row view — LAZY: wire-decoded tables build rows from the
        typed columns on demand (and only box each column once)."""
        if self._cols is not None and "rows" not in self._payload:
            cols = [c.tolist() for c in self._cols]
            return [[c[i] for c in cols] for i in range(self._n_rows or 0)]
        return [[decode_value(c) for c in r]
                for r in self._payload["rows"]]

    @property
    def num_hidden(self) -> int:
        return self._payload.get("num_hidden", 0)
