"""DataTable: the server->broker intermediate-result wire format.

Re-design of ``pinot-core/.../common/datatable/DataTableImplV3.java:43`` +
``ObjectSerDeUtils`` (custom serde for aggregation intermediate objects):
one self-describing payload carrying either merged scalar-aggregation
states, a group-by table, selection rows, or distinct rows — plus the data
schema, per-server execution stats, and exceptions. Values round-trip
through a tagged encoding covering the intermediate-state types (tuples for
AVG/MINMAXRANGE, frozensets for DISTINCTCOUNT, bytes, non-finite floats).

Framing is binary columnar (magic ``PDT3``): header + stats/exceptions
sections + a per-type payload where selection/distinct/group-by data ships
as typed columns — numeric columns as raw little-endian buffers, string
columns as offset+heap pairs, heterogeneous state columns through the
tagged object serde (common/serde.py, the ObjectSerDeUtils analogue).
``from_bytes`` sniffs the magic and still accepts the legacy JSON framing,
so mixed-version servers interoperate.
"""

from __future__ import annotations

import enum
import json
import struct

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.common import serde
from pinot_tpu.engine.results import DataSchema, QueryStats

MAGIC = b"PDT3"


class ResponseType(enum.Enum):
    AGGREGATION = "AGGREGATION"
    GROUP_BY = "GROUP_BY"
    SELECTION = "SELECTION"
    DISTINCT = "DISTINCT"


# stable wire ordinals: never renumber, append only (declaration order must
# not leak into the binary framing or mixed-version decode breaks)
_WIRE_ORDINAL = {
    ResponseType.AGGREGATION: 0,
    ResponseType.GROUP_BY: 1,
    ResponseType.SELECTION: 2,
    ResponseType.DISTINCT: 3,
}
_WIRE_TYPE = {v: k for k, v in _WIRE_ORDINAL.items()}


# --------------------------------------------------------------------------
# tagged value encoding (ref: ObjectSerDeUtils object-type registry)
# --------------------------------------------------------------------------

def encode_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        if v != v or v in (float("inf"), float("-inf")):
            return {"__t": "f", "v": repr(v)}
        return v
    if isinstance(v, bytes):
        return {"__t": "b", "v": v.hex()}
    if isinstance(v, tuple):
        return {"__t": "t", "v": [encode_value(x) for x in v]}
    if isinstance(v, frozenset):
        return {"__t": "s", "v": sorted((encode_value(x) for x in v),
                                        key=lambda e: json.dumps(e))}
    if isinstance(v, (list,)):
        return {"__t": "l", "v": [encode_value(x) for x in v]}
    if hasattr(v, "item"):  # numpy scalar
        return encode_value(v.item())
    raise TypeError(f"cannot encode {type(v).__name__} for the wire")


def decode_value(v: Any) -> Any:
    if isinstance(v, dict) and "__t" in v:
        t = v["__t"]
        if t == "f":
            return float(v["v"])
        if t == "b":
            return bytes.fromhex(v["v"])
        if t == "t":
            return tuple(decode_value(x) for x in v["v"])
        if t == "s":
            return frozenset(decode_value(x) for x in v["v"])
        if t == "l":
            return [decode_value(x) for x in v["v"]]
        raise ValueError(f"unknown value tag {t!r}")
    return v


# --------------------------------------------------------------------------
# columnar sections (binary framing)
# --------------------------------------------------------------------------

_COL_I64 = 0
_COL_F64 = 1
_COL_STR = 2
_COL_OBJ = 3


def _encode_column(out: bytearray, values: List[Any]) -> None:
    """One typed column: numeric homogeneity -> raw buffers, strings ->
    offsets+heap, anything else (tuples/sets/bytes/None/mixed) -> tagged
    objects. The type sniff treats numpy scalars as their python values."""
    vals = [v.item() if hasattr(v, "item") else v for v in values]
    if vals and all(type(v) is int for v in vals) \
            and all(-(1 << 63) <= v < (1 << 63) for v in vals):
        out.append(_COL_I64)
        out.extend(np.asarray(vals, dtype="<i8").tobytes())
        return
    if vals and all(isinstance(v, float) for v in vals):
        out.append(_COL_F64)
        out.extend(np.asarray(vals, dtype="<f8").tobytes())
        return
    if vals and all(type(v) is str for v in vals):
        parts = [v.encode("utf-8") for v in vals]
        heap = b"".join(parts)
        offsets = np.cumsum([0] + [len(p) for p in parts]).astype("<u4")
        out.append(_COL_STR)
        out.extend(struct.pack("<I", len(heap)))
        out.extend(heap)
        out.extend(offsets.tobytes())
        return
    out.append(_COL_OBJ)
    for v in vals:
        serde.pack_obj(v, out)


def _decode_column(buf: bytes, off: int, n: int) -> tuple:
    """-> (values, new offset, json_safe). ``json_safe`` means every value
    already satisfies the payload's JSON-shape invariant, so the caller can
    skip the per-cell ``encode_value`` pass (i64/str always; f64 unless a
    non-finite slipped in; obj never — tuples/sets/bytes need wrapping)."""
    kind = buf[off]
    off += 1
    if kind == _COL_I64:
        a = np.frombuffer(buf, dtype="<i8", count=n, offset=off)
        return [int(v) for v in a], off + 8 * n, True
    if kind == _COL_F64:
        a = np.frombuffer(buf, dtype="<f8", count=n, offset=off)
        return ([float(v) for v in a], off + 8 * n,
                bool(np.isfinite(a).all()))
    if kind == _COL_STR:
        (heap_len,) = struct.unpack_from("<I", buf, off)
        off += 4
        raw = buf[off:off + heap_len]
        off += heap_len
        offsets = np.frombuffer(buf, dtype="<u4", count=n + 1, offset=off)
        off += 4 * (n + 1)
        vals = [raw[offsets[i]:offsets[i + 1]].decode("utf-8")
                for i in range(n)]
        return vals, off, True
    if kind == _COL_OBJ:
        vals = []
        for _ in range(n):
            v, off = serde.unpack_obj(buf, off)
            vals.append(v)
        return vals, off, False
    raise ValueError(f"unknown column kind {kind}")


def _put_section(out: bytearray, raw: bytes) -> None:
    out.extend(struct.pack("<I", len(raw)))
    out.extend(raw)


def _get_section(buf: bytes, off: int) -> tuple:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return buf[off:off + n], off + n


# --------------------------------------------------------------------------
# the DataTable
# --------------------------------------------------------------------------

@dataclass
class DataTable:
    """One server's reply for one (sub)query."""

    response_type: ResponseType
    # AGGREGATION: {"states": [state per agg]}
    # GROUP_BY:    {"groups": [[key tuple, [state per agg]], ...],
    #               "schema_types": {col: type label}}
    # SELECTION:   {"schema": DataSchema dict, "rows": [...],
    #               "num_hidden": trailing order-by-only columns}
    # DISTINCT:    {"schema": DataSchema dict, "rows": [...]}
    payload: Dict[str, Any]
    stats: QueryStats = field(default_factory=QueryStats)
    exceptions: List[str] = field(default_factory=list)

    # -- framing -------------------------------------------------------------
    def to_bytes(self) -> bytes:
        """Binary columnar framing (see module doc). Layout:
        magic | u8 type-ordinal | stats json section | exceptions json
        section | per-type payload."""
        out = bytearray(MAGIC)
        out.append(_WIRE_ORDINAL[self.response_type])
        _put_section(out, json.dumps(
            self.stats.to_dict(), separators=(",", ":")).encode("utf-8"))
        _put_section(out, json.dumps(
            self.exceptions, separators=(",", ":")).encode("utf-8"))
        t = self.response_type
        if t is ResponseType.AGGREGATION:
            states = [decode_value(s) for s in self.payload["states"]] \
                if self.payload else []
            serde.pack_obj(len(states), out)
            for s in states:
                serde.pack_obj(s, out)
        elif t is ResponseType.GROUP_BY:
            groups = self.group_by_groups() if self.payload else {}
            _put_section(out, json.dumps(
                (self.payload or {}).get("schema_types", {}),
                separators=(",", ":")).encode("utf-8"))
            keys = list(groups.keys())
            vals = list(groups.values())
            n = len(keys)
            arity = len(keys[0]) if keys else 0
            n_aggs = len(vals[0]) if vals else 0
            out.extend(struct.pack("<IHH", n, arity, n_aggs))
            for k in range(arity):
                _encode_column(out, [key[k] for key in keys])
            for a in range(n_aggs):
                _encode_column(out, [v[a] for v in vals])
        else:  # SELECTION / DISTINCT
            rows = self.rows() if self.payload else []
            schema = self.payload.get("schema", {"columnNames": [],
                                                 "columnDataTypes": []}) \
                if self.payload else {"columnNames": [], "columnDataTypes": []}
            _put_section(out, json.dumps(
                schema, separators=(",", ":")).encode("utf-8"))
            n_cols = len(schema["columnNames"])
            out.extend(struct.pack("<IHH", len(rows), n_cols,
                                   self.num_hidden))
            for c in range(n_cols):
                _encode_column(out, [r[c] for r in rows])
        return bytes(out)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "DataTable":
        if not raw.startswith(MAGIC):
            return cls._from_json_bytes(raw)
        off = len(MAGIC)
        rtype = _WIRE_TYPE[raw[off]]
        off += 1
        stats_raw, off = _get_section(raw, off)
        exc_raw, off = _get_section(raw, off)
        stats = cls._stats_from_dict(json.loads(stats_raw.decode("utf-8")))
        exceptions = json.loads(exc_raw.decode("utf-8"))
        if rtype is ResponseType.AGGREGATION:
            n, off = serde.unpack_obj(raw, off)
            states = []
            for _ in range(n):
                s, off = serde.unpack_obj(raw, off)
                states.append(s)
            payload = {"states": [encode_value(s) for s in states]}
        elif rtype is ResponseType.GROUP_BY:
            st_raw, off = _get_section(raw, off)
            schema_types = json.loads(st_raw.decode("utf-8"))
            n, arity, n_aggs = struct.unpack_from("<IHH", raw, off)
            off += 8
            key_cols = []
            for _ in range(arity):
                col, off, _safe = _decode_column(raw, off, n)
                key_cols.append(col)
            agg_cols = []
            for _ in range(n_aggs):
                col, off, safe = _decode_column(raw, off, n)
                agg_cols.append(col if safe
                                else [encode_value(v) for v in col])
            payload = {
                "groups": [
                    [encode_value(tuple(kc[i] for kc in key_cols)),
                     [ac[i] for ac in agg_cols]]
                    for i in range(n)],
                "schema_types": schema_types,
            }
        else:
            schema_raw, off = _get_section(raw, off)
            schema = json.loads(schema_raw.decode("utf-8"))
            n_rows, n_cols, num_hidden = struct.unpack_from("<IHH", raw, off)
            off += 8
            cols = []
            for _ in range(n_cols):
                col, off, safe = _decode_column(raw, off, n_rows)
                cols.append(col if safe
                            else [encode_value(v) for v in col])
            rows = [[cols[c][i] for c in range(n_cols)]
                    for i in range(n_rows)]
            payload = {"schema": schema, "rows": rows}
            if rtype is ResponseType.SELECTION:
                payload["num_hidden"] = num_hidden
        return cls(rtype, payload, stats, exceptions)

    @staticmethod
    def _stats_from_dict(st: Dict[str, Any]) -> QueryStats:
        return QueryStats(
            num_segments_queried=st.get("numSegmentsQueried", 0),
            num_segments_processed=st.get("numSegmentsProcessed", 0),
            num_segments_matched=st.get("numSegmentsMatched", 0),
            num_segments_pruned=st.get("numSegmentsPrunedByServer", 0),
            num_docs_scanned=st.get("numDocsScanned", 0),
            total_docs=st.get("totalDocs", 0),
            num_groups_limit_reached=st.get("numGroupsLimitReached", False),
            num_servers_queried=st.get("numServersQueried", 0),
            num_servers_responded=st.get("numServersResponded", 0),
            group_by_rung=st.get("groupByRung"),
            startree_tree_index=st.get("startreeTreeIndex"),
            staging=st.get("staging", {}),
            launch=st.get("launch", {}),
            phase_ms=st.get("phaseTimesMs", {}),
            trace=st.get("trace", []),
            spans=st.get("spans", []),
            decisions=st.get("decisions", {}),
        )

    @classmethod
    def _from_json_bytes(cls, raw: bytes) -> "DataTable":
        """Legacy JSON framing (kept for mixed-version interop + debug)."""
        d = json.loads(raw.decode("utf-8"))
        return cls(ResponseType(d["type"]), d["payload"],
                   cls._stats_from_dict(d.get("stats", {})),
                   d.get("exceptions", []))

    def to_json_bytes(self) -> bytes:
        """The debuggable JSON framing (not the serving default)."""
        return json.dumps({
            "type": self.response_type.value,
            "payload": self.payload,
            "stats": self.stats.to_dict(),
            "exceptions": self.exceptions,
        }, separators=(",", ":")).encode("utf-8")

    # -- typed constructors --------------------------------------------------
    @classmethod
    def for_aggregation(cls, states: List[Any], stats: QueryStats) -> "DataTable":
        return cls(ResponseType.AGGREGATION,
                   {"states": [encode_value(s) for s in states]}, stats)

    @classmethod
    def for_group_by(cls, groups: Dict[tuple, List[Any]],
                     schema_types: Dict[str, str],
                     stats: QueryStats) -> "DataTable":
        return cls(ResponseType.GROUP_BY, {
            "groups": [[encode_value(k), [encode_value(s) for s in states]]
                       for k, states in groups.items()],
            "schema_types": schema_types,
        }, stats)

    @classmethod
    def for_selection(cls, schema: DataSchema, rows: List[List[Any]],
                      stats: QueryStats, num_hidden: int = 0) -> "DataTable":
        return cls(ResponseType.SELECTION, {
            "schema": schema.to_dict(),
            "rows": [[encode_value(c) for c in r] for r in rows],
            "num_hidden": num_hidden,
        }, stats)

    @classmethod
    def for_distinct(cls, schema: DataSchema,
                     rows: List[List[Any]], stats: QueryStats) -> "DataTable":
        return cls(ResponseType.DISTINCT, {
            "schema": schema.to_dict(),
            "rows": [[encode_value(c) for c in r] for r in rows],
        }, stats)

    @classmethod
    def for_exception(cls, message: str,
                      response_type: ResponseType = ResponseType.AGGREGATION
                      ) -> "DataTable":
        return cls(response_type, {}, QueryStats(), [message])

    # -- typed readers -------------------------------------------------------
    def agg_states(self) -> List[Any]:
        return [decode_value(s) for s in self.payload["states"]]

    def group_by_groups(self) -> Dict[tuple, List[Any]]:
        return {decode_value(k): [decode_value(s) for s in states]
                for k, states in self.payload["groups"]}

    def schema_types(self) -> Dict[str, str]:
        return self.payload.get("schema_types", {})

    def data_schema(self) -> DataSchema:
        d = self.payload["schema"]
        return DataSchema(d["columnNames"], d["columnDataTypes"])

    def rows(self) -> List[List[Any]]:
        return [[decode_value(c) for c in r] for r in self.payload["rows"]]

    @property
    def num_hidden(self) -> int:
        return self.payload.get("num_hidden", 0)
