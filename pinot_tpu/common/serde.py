"""Compact binary object serde for aggregation intermediate states.

Re-design of ``pinot-core/.../common/ObjectSerDeUtils.java`` (the custom
serializer registry for HLL/TDigest/Bitmap/IdSet intermediate objects): a
tagged, length-delimited binary encoding covering every intermediate-state
type the combine/reduce phases ship between server and broker — ints,
doubles (non-finite included), strings, bytes (sketch payloads), tuples
(AVG/MINMAXRANGE states), frozensets (DISTINCTCOUNT), lists, None, bools.

Unlike the reference there is no per-type registry index negotiated out of
band: each value is self-describing (one tag byte), so a DataTable payload
can be decoded without the query context. Varint lengths keep small states
small; numeric homogeneity is the DataTable's columnar layer's job, not
this one's.
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple

# tag bytes
_T_NONE = 0x00
_T_FALSE = 0x01
_T_TRUE = 0x02
_T_INT = 0x03        # zigzag varint
_T_FLOAT = 0x04      # f64 big-endian (covers nan/inf exactly)
_T_STR = 0x05        # varint len + utf8
_T_BYTES = 0x06      # varint len + raw
_T_TUPLE = 0x07      # varint n + items
_T_FROZENSET = 0x08  # varint n + items
_T_LIST = 0x09       # varint n + items


def _write_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    shift = 0
    n = 0
    while True:
        b = buf[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def pack_obj(v: Any, out: bytearray) -> None:
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        out.append(_T_INT)
        _write_varint(out, (v << 1) if v >= 0 else ((-v << 1) | 1))
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out.extend(struct.pack(">d", v))
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        out.append(_T_STR)
        _write_varint(out, len(raw))
        out.extend(raw)
    elif isinstance(v, bytes):
        out.append(_T_BYTES)
        _write_varint(out, len(v))
        out.extend(v)
    elif isinstance(v, tuple):
        out.append(_T_TUPLE)
        _write_varint(out, len(v))
        for x in v:
            pack_obj(x, out)
    elif isinstance(v, frozenset):
        out.append(_T_FROZENSET)
        _write_varint(out, len(v))
        for x in sorted(v, key=lambda e: (str(type(e)), str(e))):
            pack_obj(x, out)
    elif isinstance(v, list):
        out.append(_T_LIST)
        _write_varint(out, len(v))
        for x in v:
            pack_obj(x, out)
    elif hasattr(v, "item"):  # numpy scalar
        pack_obj(v.item(), out)
    else:
        raise TypeError(f"cannot serialize {type(v).__name__} for the wire")


def unpack_obj(buf: bytes, off: int = 0) -> Tuple[Any, int]:
    tag = buf[off]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_TRUE:
        return True, off
    if tag == _T_FALSE:
        return False, off
    if tag == _T_INT:
        z, off = _read_varint(buf, off)
        return (-(z >> 1) if z & 1 else (z >> 1)), off
    if tag == _T_FLOAT:
        return struct.unpack_from(">d", buf, off)[0], off + 8
    if tag == _T_STR:
        n, off = _read_varint(buf, off)
        return buf[off:off + n].decode("utf-8"), off + n
    if tag == _T_BYTES:
        n, off = _read_varint(buf, off)
        return bytes(buf[off:off + n]), off + n
    if tag in (_T_TUPLE, _T_FROZENSET, _T_LIST):
        n, off = _read_varint(buf, off)
        items: List[Any] = []
        for _ in range(n):
            x, off = unpack_obj(buf, off)
            items.append(x)
        if tag == _T_TUPLE:
            return tuple(items), off
        if tag == _T_FROZENSET:
            return frozenset(items), off
        return items, off
    raise ValueError(f"unknown serde tag 0x{tag:02x}")


def dumps(v: Any) -> bytes:
    out = bytearray()
    pack_obj(v, out)
    return bytes(out)


def loads(raw: bytes) -> Any:
    v, off = unpack_obj(raw, 0)
    if off != len(raw):
        raise ValueError(f"trailing bytes after object ({len(raw) - off})")
    return v
