"""Continuous telemetry: windowed histograms, SLO burn, flight recorder.

Re-design of the reference's continuous operational telemetry — the
per-phase latency histograms and per-table quantile meters every
broker/server exports (``AbstractMetrics`` + the yammer ``Histogram``
types behind ``BrokerQueryPhase``/``ServerQueryPhase``, SIGMOD'18 §6:
operating Pinot at LinkedIn leans on exactly these) — plus two layers the
reference leaves to external systems (inGraphs/ThirdEye):

- **SLO burn tracking**: per-table latency/error objectives
  (``pinot.broker.slo.<table>.p99.ms`` / ``.error.pct``) with
  multi-window burn rates, so "is the error budget burning NOW" is a
  gauge, not a dashboard query someone has to run.
- **An anomaly-triggered flight recorder**: a process-wide bounded ring
  of recent span roots + decision-ledger deltas + residency/scheduler/
  admission snapshots that freezes into a timestamped post-mortem JSON
  bundle when an anomaly trigger fires (sliding p99 far above its EWMA
  baseline, a rejection burst, an eviction/demotion storm, a
  pallas-decline burst) — the black box for the next convoy collapse or
  ``pallas_kernels: 0`` round.

Cost model: the record path is lock-light — one bisect + a few integer
increments under a tiny uncontended lock, no allocation beyond the
bucket increment, and NEVER a device sync (the graftlint ``sync`` family
gates gauge callbacks for that). Quantiles, rotation merges, exposition,
and anomaly evaluation all happen on the scrape/sampler side.

Everything hangs off one process-wide :data:`TELEMETRY` center (the
flight recorder is explicitly process-wide, like the decision LEDGER);
tests may instantiate private :class:`Telemetry` objects.
"""

from __future__ import annotations

import json
import os
import threading
import time

from bisect import bisect_left
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

# --------------------------------------------------------------------------
# log-bucketed histogram
# --------------------------------------------------------------------------

# Log-spaced bucket upper bounds (ms): 0.01 ms .. ~70 s at ratio 2^(1/4)
# (~19% per step, so quantile estimates carry <= ~19% relative error), plus
# an overflow bucket. Shared across every histogram: snapshot/merge are
# O(len(BUCKET_BOUNDS_MS)) and exposition emits one `le` per bound.
_GROWTH = 2.0 ** 0.25
_N_BOUNDS = 92
BUCKET_BOUNDS_MS: Tuple[float, ...] = tuple(
    round(0.01 * _GROWTH ** i, 6) for i in range(_N_BOUNDS))


class Histogram:
    """Thread-safe log-bucketed histogram (values in ms).

    ``record`` is the hot path: bisect + four increments under a tiny
    lock. Everything analytical (quantiles, merge, exposition rows) walks
    the fixed bucket array — O(buckets), scrape-side only."""

    __slots__ = ("counts", "count", "sum", "max", "_lock")

    def __init__(self):
        self.counts = [0] * (_N_BOUNDS + 1)  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock
        self.sum = 0.0  # guarded-by: _lock
        self.max = 0.0  # guarded-by: _lock
        self._lock = threading.Lock()

    def record(self, ms: float) -> None:
        i = bisect_left(BUCKET_BOUNDS_MS, ms)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += ms
            if ms > self.max:
                self.max = ms

    def clear(self) -> None:
        with self._lock:
            for i in range(len(self.counts)):
                self.counts[i] = 0
            self.count = 0
            self.sum = 0.0
            self.max = 0.0

    def merge(self, other: "Histogram") -> None:
        """Add ``other``'s state into this histogram (window merges)."""
        with other._lock:
            counts = list(other.counts)
            count, total, mx = other.count, other.sum, other.max
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.count += count
            self.sum += total
            if mx > self.max:
                self.max = mx

    # -- analytics (scrape-side) --------------------------------------------
    def _copy(self) -> Tuple[List[int], int, float, float]:
        with self._lock:
            return list(self.counts), self.count, self.sum, self.max

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1) with linear interpolation inside
        the containing log bucket; relative error bounded by the bucket
        growth ratio. 0.0 when empty."""
        counts, count, _s, mx = self._copy()
        return _bucket_quantile(counts, count, mx, q)

    def quantiles(self, qs: Tuple[float, ...]) -> List[float]:
        counts, count, _s, mx = self._copy()
        return [_bucket_quantile(counts, count, mx, q) for q in qs]

    def count_over(self, threshold_ms: float) -> int:
        """Estimated number of recorded values above ``threshold_ms``
        (interpolated inside the bucket containing the threshold) — the
        numerator of the latency-SLO burn fraction."""
        counts, count, _s, _m = self._copy()
        if count == 0:
            return 0
        i = bisect_left(BUCKET_BOUNDS_MS, threshold_ms)
        over = sum(counts[i + 1:])
        inbucket = counts[i] if i < len(counts) else 0
        if inbucket:
            lo = BUCKET_BOUNDS_MS[i - 1] if i > 0 else 0.0
            hi = BUCKET_BOUNDS_MS[i] if i < _N_BOUNDS else threshold_ms
            frac_over = 0.0 if hi <= lo else \
                max(0.0, min(1.0, (hi - threshold_ms) / (hi - lo)))
            over += int(round(inbucket * frac_over))
        return min(over, count)

    @property
    def mean(self) -> float:
        with self._lock:
            return self.sum / self.count if self.count else 0.0

    def snapshot(self) -> Dict[str, Any]:
        counts, count, total, mx = self._copy()
        out: Dict[str, Any] = {
            "count": count,
            "sumMs": round(total, 3),
            "maxMs": round(mx, 3),
            "meanMs": round(total / count, 3) if count else 0.0,
        }
        for label, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            out[label] = round(_bucket_quantile(counts, count, mx, q), 3)
        return out

    def bucket_rows(self) -> List[Tuple[str, int]]:
        """Prometheus ``_bucket`` rows: (le, CUMULATIVE count), +Inf last."""
        counts, _c, _s, _m = self._copy()
        rows: List[Tuple[str, int]] = []
        cum = 0
        for i, bound in enumerate(BUCKET_BOUNDS_MS):
            cum += counts[i]
            rows.append((repr(bound), cum))
        rows.append(("+Inf", cum + counts[-1]))
        return rows


def _bucket_quantile(counts: List[int], count: int, observed_max: float,
                     q: float) -> float:
    if count == 0:
        return 0.0
    rank = q * count
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            if i >= _N_BOUNDS:  # overflow bucket: best estimate is the max
                return observed_max
            lo = BUCKET_BOUNDS_MS[i - 1] if i > 0 else 0.0
            hi = BUCKET_BOUNDS_MS[i]
            frac = (rank - cum) / c
            return lo + frac * (hi - lo)
        cum += c
    return observed_max


class WindowedHistogram:
    """A lifetime :class:`Histogram` plus a ring of rotating sub-windows
    giving sliding quantiles over the last ``window_s * num_windows``
    seconds (default 30 s x 10 = 5 min). Rotation happens lazily on
    record/read — no timer thread; an idle histogram costs nothing."""

    def __init__(self, window_s: float = 30.0, num_windows: int = 10,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self.num_windows = max(1, int(num_windows))
        self._clock = clock
        self.lifetime = Histogram()
        self._ring: List[Histogram] = [Histogram()
                                       for _ in range(self.num_windows)]
        self._cur = 0  # guarded-by: _rot_lock
        self._cur_start = clock()  # guarded-by: _rot_lock
        self._rot_lock = threading.Lock()

    def _rotate_locked(self, now: float) -> None:
        elapsed = now - self._cur_start
        if elapsed < self.window_s:
            return
        steps = int(elapsed // self.window_s)
        if steps >= self.num_windows:  # whole horizon expired
            for h in self._ring:
                h.clear()
            self._cur_start = now
            return
        for _ in range(steps):
            self._cur = (self._cur + 1) % self.num_windows
            self._ring[self._cur].clear()
        self._cur_start += steps * self.window_s

    def _current(self) -> Histogram:
        now = self._clock()
        with self._rot_lock:
            self._rotate_locked(now)
            return self._ring[self._cur]

    def record(self, ms: float) -> None:
        self._current().record(ms)
        self.lifetime.record(ms)

    def sliding(self) -> Histogram:
        """Merged view of the live sub-windows (the last ~window_s *
        num_windows seconds) — a fresh Histogram the caller owns."""
        now = self._clock()
        with self._rot_lock:
            self._rotate_locked(now)
            ring = list(self._ring)
        merged = Histogram()
        for h in ring:
            merged.merge(h)
        return merged

    def snapshot(self) -> Dict[str, Any]:
        out = {"lifetime": self.lifetime.snapshot(),
               "sliding": self.sliding().snapshot(),
               "windowS": self.window_s,
               "numWindows": self.num_windows}
        return out


class WindowCounter:
    """Rotating per-window event counter (same ring discipline as
    :class:`WindowedHistogram`) — the error half of the SLO burn math."""

    def __init__(self, window_s: float = 30.0, num_windows: int = 10,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self.num_windows = max(1, int(num_windows))
        self._clock = clock
        self.total = 0  # guarded-by: _lock
        self._ring = [0] * self.num_windows  # guarded-by: _lock
        self._cur = 0  # guarded-by: _lock
        self._cur_start = clock()  # guarded-by: _lock
        self._lock = threading.Lock()

    def _rotate_locked(self, now: float) -> None:
        elapsed = now - self._cur_start
        if elapsed < self.window_s:
            return
        steps = int(elapsed // self.window_s)
        if steps >= self.num_windows:
            for i in range(self.num_windows):
                self._ring[i] = 0
            self._cur_start = now
            return
        for _ in range(steps):
            self._cur = (self._cur + 1) % self.num_windows
            self._ring[self._cur] = 0
        self._cur_start += steps * self.window_s

    def add(self, n: int = 1) -> None:
        now = self._clock()
        with self._lock:
            self._rotate_locked(now)
            self._ring[self._cur] += n
            self.total += n

    def in_window(self, last_n_windows: Optional[int] = None) -> int:
        """Events inside the most recent ``last_n_windows`` sub-windows
        (None = the whole ring horizon)."""
        n = self.num_windows if last_n_windows is None \
            else min(int(last_n_windows), self.num_windows)
        now = self._clock()
        with self._lock:
            self._rotate_locked(now)
            return sum(self._ring[(self._cur - i) % self.num_windows]
                       for i in range(n))


class TimeRing:
    """Bounded (timestamp, value) ring at few-second resolution — the
    history behind gauges that used to be instants (staged bytes, queue
    depths, arrival EWMA, rejection counters)."""

    def __init__(self, slots: int = 150):
        self._ring: "deque" = deque(maxlen=max(2, int(slots)))  # guarded-by: _lock
        self._lock = threading.Lock()

    def append(self, value: float, ts: Optional[float] = None) -> None:
        with self._lock:
            self._ring.append((time.time() if ts is None else ts,
                               float(value)))

    def values(self) -> List[List[float]]:
        with self._lock:
            return [[round(t, 3), v] for t, v in self._ring]

    def last(self) -> Optional[float]:
        with self._lock:
            return self._ring[-1][1] if self._ring else None


# --------------------------------------------------------------------------
# SLO burn tracking
# --------------------------------------------------------------------------

# q-objective -> allowed over-threshold fraction: a p99 objective budgets
# 1% of requests over the threshold
_P99_ALLOWED = 0.01
# multi-window burn evaluation: "short" = the last 2 sub-windows (~1 min
# at the default 30 s window), "long" = the full ring horizon (~5 min)
SHORT_WINDOWS = 2


class SloTracker:
    """Per-table latency/error objectives + multi-window burn rates.

    burn_rate = (observed bad fraction) / (allowed bad fraction): 1.0
    burns exactly the error budget, >1 is over-burn (the multi-window
    alerting form from the SRE workbook). Latency badness comes from the
    broker front-door histograms (``count_over`` the p99 objective);
    error badness from per-table windowed error/total counters."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 window_s: float = 30.0, num_windows: int = 10):
        self._clock = clock
        self._window_s = window_s
        self._num_windows = num_windows
        self._lock = threading.Lock()
        # table -> {"p99_ms": float|None, "error_pct": float|None}
        self._objectives: Dict[str, Dict[str, Optional[float]]] = {}  # guarded-by: _lock
        # table -> (total WindowCounter, error WindowCounter)
        self._counters: Dict[str, Tuple[WindowCounter, WindowCounter]] = {}  # guarded-by: _lock

    def set_objective(self, table: str, p99_ms: Optional[float] = None,
                      error_pct: Optional[float] = None,
                      freshness_ms: Optional[float] = None) -> None:
        with self._lock:
            obj = self._objectives.setdefault(
                table, {"p99_ms": None, "error_pct": None})
            if p99_ms is not None:
                obj["p99_ms"] = float(p99_ms)
            if error_pct is not None:
                obj["error_pct"] = float(error_pct)
            if freshness_ms is not None:
                obj["freshness_ms"] = float(freshness_ms)

    def objectives(self) -> Dict[str, Dict[str, Optional[float]]]:
        with self._lock:
            return {t: dict(o) for t, o in self._objectives.items()}

    def _counters_for(self, table: str) -> Tuple[WindowCounter, WindowCounter]:
        with self._lock:
            pair = self._counters.get(table)
            if pair is None:
                pair = (WindowCounter(self._window_s, self._num_windows,
                                      self._clock),
                        WindowCounter(self._window_s, self._num_windows,
                                      self._clock))
                self._counters[table] = pair
            return pair

    def note_request(self, table: str, error: bool) -> None:
        total, errors = self._counters_for(table)
        total.add(1)
        if error:
            errors.add(1)

    @staticmethod
    def _burn(bad: float, total: float, allowed: float) -> Optional[float]:
        if total <= 0 or allowed <= 0:
            return None
        return round((bad / total) / allowed, 4)

    def burn_rates(self, table: str,
                   latency_histo: Optional[WindowedHistogram],
                   freshness_histo: Optional[WindowedHistogram] = None
                   ) -> Dict[str, Any]:
        """Every objective x both windows for one table."""
        with self._lock:
            obj = dict(self._objectives.get(table) or {})
        out: Dict[str, Any] = {"objectives": obj}
        p99_ms = obj.get("p99_ms")
        if p99_ms and latency_histo is not None:
            lat: Dict[str, Any] = {}
            for name, windows in (("short", SHORT_WINDOWS), ("long", None)):
                # merge the relevant sub-windows; "short" approximates the
                # last ~minute by scaling the full sliding view only when
                # per-window merge is unavailable — here we merge exactly
                h = self._sliding_subset(latency_histo, windows)
                over = h.count_over(p99_ms)
                lat[name] = {
                    "requests": h.count,
                    "overThreshold": over,
                    "badFraction": round(over / h.count, 4) if h.count else 0.0,
                    "burnRate": self._burn(over, h.count, _P99_ALLOWED),
                }
            out["latency"] = lat
        err_pct = obj.get("error_pct")
        if err_pct:
            total, errors = self._counters_for(table)
            err: Dict[str, Any] = {}
            for name, windows in (("short", SHORT_WINDOWS), ("long", None)):
                t = total.in_window(windows)
                e = errors.in_window(windows)
                err[name] = {
                    "requests": t,
                    "errors": e,
                    "badFraction": round(e / t, 4) if t else 0.0,
                    "burnRate": self._burn(e, t, err_pct / 100.0),
                }
            out["errors"] = err
        fresh_ms = obj.get("freshness_ms")
        if fresh_ms and freshness_histo is not None:
            # ingest-to-queryable: each histogram sample is one row's
            # append->first-covering-watermark latency; "bad" rows took
            # longer than the objective to become queryable
            fr: Dict[str, Any] = {}
            for name, windows in (("short", SHORT_WINDOWS), ("long", None)):
                h = self._sliding_subset(freshness_histo, windows)
                over = h.count_over(fresh_ms)
                fr[name] = {
                    "rows": h.count,
                    "overThreshold": over,
                    "badFraction": round(over / h.count, 4) if h.count
                    else 0.0,
                    "burnRate": self._burn(over, h.count, _P99_ALLOWED),
                }
            out["freshness"] = fr
        return out

    @staticmethod
    def _sliding_subset(wh: WindowedHistogram,
                        last_n: Optional[int]) -> Histogram:
        if last_n is None:
            return wh.sliding()
        now = wh._clock()
        with wh._rot_lock:
            wh._rotate_locked(now)
            picks = [wh._ring[(wh._cur - i) % wh.num_windows]
                     for i in range(min(last_n, wh.num_windows))]
        merged = Histogram()
        for h in picks:
            merged.merge(h)
        return merged


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

# anomaly-event kinds -> (burst threshold, burst window seconds): a burst
# freezes the recorder into a post-mortem bundle. Conservative defaults —
# a handful of rejections is load shedding working; a burst is an incident.
DEFAULT_BURSTS: Dict[str, Tuple[int, float]] = {
    "rejection": (8, 5.0),
    "eviction": (64, 5.0),
    "demotion": (64, 5.0),
    "pallas_decline": (32, 5.0),
}
# windowed-p99 anomaly: sliding p99 > factor x its own EWMA baseline
P99_SPIKE_FACTOR = 3.0
P99_SPIKE_MIN_COUNT = 32
P99_EWMA_ALPHA = 0.2


class FlightRecorder:
    """Process-wide black box: a bounded ring of recent span roots (the
    slow-log retention machinery feeds it), rolling decision-ledger
    marks, and registered state providers (residency / scheduler /
    admission snapshots) — frozen into a timestamped JSON bundle when an
    anomaly trigger fires.

    Trigger paths NEVER freeze synchronously: callers may hold engine
    locks (an eviction storm is noted under the residency lock), so a
    trip only records a pending trigger; the telemetry sampler — or an
    explicit ``process_pending()`` — performs the freeze outside every
    caller lock."""

    def __init__(self, span_ring: int = 64, ledger_ring: int = 150,
                 bundle_ring: int = 8, min_freeze_interval_s: float = 10.0,
                 out_dir: Optional[str] = None):
        self._lock = threading.Lock()
        self._spans: "deque" = deque(maxlen=span_ring)  # guarded-by: _lock
        self._ledger_marks: "deque" = deque(maxlen=ledger_ring)  # guarded-by: _lock
        self._providers: Dict[str, Callable[[], Any]] = {}  # guarded-by: _lock
        self._events: Dict[str, "deque"] = {}  # guarded-by: _lock
        self._event_totals: Dict[str, int] = {}  # guarded-by: _lock
        self._pending: List[Tuple[str, float]] = []  # guarded-by: _lock
        self._last_freeze = 0.0  # guarded-by: _lock
        self.bursts = dict(DEFAULT_BURSTS)
        self.bundles: "deque" = deque(maxlen=bundle_ring)  # guarded-by: _lock
        self.frozen = 0  # guarded-by: _lock
        self.min_freeze_interval_s = float(min_freeze_interval_s)
        self.out_dir = out_dir

    # -- feeds ---------------------------------------------------------------
    def note_query(self, entry: Dict[str, Any]) -> None:
        """A completed query with a retained span tree (QueryRegistry.end
        forwards entries that carry spans)."""
        with self._lock:
            self._spans.append(entry)

    def note_ledger_mark(self, snapshot: Dict[str, int],
                         ts: Optional[float] = None) -> None:
        with self._lock:
            self._ledger_marks.append(
                (time.time() if ts is None else ts, snapshot))

    def register_provider(self, name: str, fn: Callable[[], Any]) -> None:
        """State snapshots to include in every bundle (residency /
        scheduler / admission). Called only at freeze time — they may be
        arbitrarily heavy."""
        with self._lock:
            self._providers[name] = fn

    def note_event(self, kind: str, n: int = 1) -> None:
        """One anomaly-relevant event (rejection / eviction / demotion /
        pallas_decline). Cheap: timestamp appends + a burst check; a trip
        records a PENDING trigger only (see class docstring)."""
        spec = self.bursts.get(kind)
        now = time.monotonic()
        with self._lock:
            self._event_totals[kind] = self._event_totals.get(kind, 0) + n
            if spec is None:
                return
            threshold, window_s = spec
            dq = self._events.get(kind)
            if dq is None:
                dq = self._events[kind] = deque(maxlen=4 * threshold)
            for _ in range(n):
                dq.append(now)
            recent = sum(1 for t in dq if now - t <= window_s)
            if recent >= threshold:
                self._trip_locked(f"{kind}_burst", now)

    def note_p99_spike(self, key: str) -> None:
        """p99-anomaly trip from the sampler's baseline check."""
        with self._lock:
            self._trip_locked(f"p99_spike:{key}", time.monotonic())

    def _trip_locked(self, trigger: str, now: float) -> None:
        if now - self._last_freeze < self.min_freeze_interval_s:
            return
        if any(t == trigger for t, _ts in self._pending):
            return
        self._pending.append((trigger, now))

    # -- freeze --------------------------------------------------------------
    def process_pending(self, extra: Optional[Dict[str, Any]] = None
                        ) -> List[Dict[str, Any]]:
        """Freeze every pending trigger into a bundle (sampler thread /
        tests). Runs outside all caller locks by construction."""
        with self._lock:
            pending, self._pending = self._pending, []
        return [self.freeze(trigger, extra=extra)
                for trigger, _ts in pending]

    def freeze(self, trigger: str,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Assemble + persist one post-mortem bundle NOW."""
        with self._lock:
            spans = list(self._spans)
            marks = list(self._ledger_marks)
            providers = dict(self._providers)
            totals = dict(self._event_totals)
            self._last_freeze = time.monotonic()
        decisions: Dict[str, Any] = {}
        if marks:
            newest_ts, newest = marks[-1]
            oldest_ts, oldest = marks[0]
            decisions = {
                "sinceS": round(newest_ts - oldest_ts, 3),
                "delta": {k: v - oldest.get(k, 0)
                          for k, v in newest.items()
                          if v - oldest.get(k, 0)},
                "total": newest,
            }
        snapshots: Dict[str, Any] = {}
        for name, fn in providers.items():
            try:
                snapshots[name] = fn()
            except Exception as e:  # a broken provider must not kill the box
                snapshots[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
        bundle: Dict[str, Any] = {
            "trigger": trigger,
            "ts": time.time(),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
            "spanRoots": spans,
            "decisions": decisions,
            "snapshots": snapshots,
            "eventTotals": totals,
        }
        if extra:
            bundle.update(extra)
        path = self._persist(bundle)
        if path:
            bundle["path"] = path
        with self._lock:
            self.bundles.append(bundle)
            self.frozen += 1
        return bundle

    def _persist(self, bundle: Dict[str, Any]) -> Optional[str]:
        out_dir = self.out_dir
        if out_dir is None:
            import tempfile

            out_dir = os.path.join(tempfile.gettempdir(),
                                   "pinot_tpu_flightrecorder")
        try:
            os.makedirs(out_dir, exist_ok=True)
            trigger = "".join(c if c.isalnum() else "_"
                              for c in bundle["trigger"])[:48]
            path = os.path.join(
                out_dir, f"flight_{int(bundle['ts'] * 1e3)}_{trigger}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(bundle, f, default=str)
            return path
        except OSError:
            return None

    def snapshot(self) -> Dict[str, Any]:
        """``/debug/flightrecorder`` body: bundle index + the last bundle
        in full + live ring occupancy."""
        with self._lock:
            bundles = list(self.bundles)
            pending = [t for t, _ts in self._pending]
            return {
                "frozen": self.frozen,
                "pendingTriggers": pending,
                "spanRingSize": len(self._spans),
                "eventTotals": dict(self._event_totals),
                "bundles": [{"trigger": b["trigger"], "ts": b["ts"],
                             "iso": b["iso"], "path": b.get("path"),
                             "spanRoots": len(b["spanRoots"])}
                            for b in bundles],
                "last": bundles[-1] if bundles else None,
            }


# --------------------------------------------------------------------------
# the telemetry center
# --------------------------------------------------------------------------

class Telemetry:
    """One per process (:data:`TELEMETRY`): the (table, phase) histogram
    registry, the gauge-history rings + their sampler thread, the SLO
    tracker, and the flight recorder."""

    def __init__(self, window_s: float = 30.0, num_windows: int = 10,
                 resolution_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = window_s
        self.num_windows = num_windows
        self.resolution_s = resolution_s  # guarded-by-writes: _lock
        self._clock = clock
        self._lock = threading.Lock()
        # writes-only guard: the record path reads with a GIL-atomic
        # dict.get and only takes the lock to insert a new key
        self._histos: Dict[Tuple[str, str], WindowedHistogram] = {}  # guarded-by-writes: _lock
        self._rings: Dict[str, TimeRing] = {}  # guarded-by: _lock
        self._tracked: Dict[str, Callable[[], float]] = {}  # guarded-by: _lock
        self._p99_baseline: Dict[Tuple[str, str], float] = {}  # guarded-by: _lock
        self.slo = SloTracker(clock=clock, window_s=window_s,
                              num_windows=num_windows)
        self.recorder = FlightRecorder()  # guarded-by-writes: _lock
        self.p99_spike_factor = P99_SPIKE_FACTOR  # guarded-by-writes: _lock
        self._sampler: Optional[threading.Thread] = None  # guarded-by: _lock
        self._sampler_stop = threading.Event()

    # -- configuration -------------------------------------------------------
    def configure(self, config=None) -> None:
        """Apply config keys (window/resolution/recorder bounds) and parse
        per-table SLO objectives from the RAW key strings —
        ``pinot.broker.slo.<table>.p99.ms`` / ``.error.pct`` — so table
        names survive the relaxed-key normalization verbatim."""
        import re

        from pinot_tpu.spi.config import CommonConstants, PinotConfiguration

        cfg = config if config is not None else PinotConfiguration()
        # the sampler loop reads these each tick; serialize the writes so
        # a live reconfigure publishes whole values (reads stay lock-free)
        with self._lock:
            self.resolution_s = max(0.25, cfg.get_float(
                CommonConstants.TELEMETRY_RESOLUTION_S_KEY,
                self.resolution_s))
            self.recorder.min_freeze_interval_s = cfg.get_float(
                CommonConstants.FLIGHT_MIN_INTERVAL_S_KEY,
                self.recorder.min_freeze_interval_s)
            out_dir = cfg.get_str(CommonConstants.FLIGHT_DIR_KEY, "")
            if out_dir:
                self.recorder.out_dir = out_dir
            self.p99_spike_factor = cfg.get_float(
                CommonConstants.FLIGHT_P99_FACTOR_KEY, self.p99_spike_factor)
        # built from the declared SLO_KEY_PREFIX constant, so the doc'd
        # key namespace and the parse can never drift
        pat = re.compile(
            re.escape(CommonConstants.SLO_KEY_PREFIX) + r"(?P<table>.+)"
            r"\.(?P<kind>p99\.ms|error\.pct|freshness\.ms)$",
            re.IGNORECASE)
        for raw in cfg.keys():
            m = pat.match(raw)
            if m is None:
                continue
            table, kind = m.group("table"), m.group("kind").lower()
            try:
                value = float(cfg.get(raw))
            except (TypeError, ValueError):
                continue
            if kind == "p99.ms":
                self.slo.set_objective(table, p99_ms=value)
            elif kind == "freshness.ms":
                self.slo.set_objective(table, freshness_ms=value)
            else:
                self.slo.set_objective(table, error_pct=value)

    # -- histograms ----------------------------------------------------------
    def histo(self, table: str, phase: str) -> WindowedHistogram:
        key = (table or "", phase)
        h = self._histos.get(key)  # lock-free hit: THE record hot path
        if h is None:
            with self._lock:
                h = self._histos.get(key)
                if h is None:
                    h = WindowedHistogram(self.window_s, self.num_windows,
                                          clock=self._clock)
                    self._histos[key] = h
        return h

    def observe(self, table: str, phase: str, ms: float) -> None:
        """THE record path: one dict probe + one histogram record."""
        self.histo(table, phase).record(ms)

    def note_broker_query(self, table: str, ms: float, error: bool) -> None:
        """Broker front-door completion: latency histogram + SLO counters."""
        self.observe(table, "broker", ms)
        self.slo.note_request(table or "", error)

    def note_rejection(self, table: str) -> None:
        self.recorder.note_event("rejection")

    def note_event(self, kind: str, n: int = 1) -> None:
        self.recorder.note_event(kind, n)

    # -- gauge-history rings -------------------------------------------------
    def track_gauge(self, name: str, fn: Callable[[], float],
                    start_sampler: bool = True) -> None:
        """Sample ``fn`` into a TimeRing every ``resolution_s`` seconds.
        The graftlint ``sync`` family gates these callbacks: they must
        never materialize device values (scrape-time device sync)."""
        with self._lock:
            self._tracked[name] = fn
            if name not in self._rings:
                self._rings[name] = TimeRing()
        if start_sampler:
            self._ensure_sampler()

    def ring(self, name: str) -> Optional[TimeRing]:
        with self._lock:
            return self._rings.get(name)

    def _ensure_sampler(self) -> None:
        with self._lock:
            t = self._sampler
            if t is not None and t.is_alive():
                return
            self._sampler_stop = threading.Event()
            t = threading.Thread(target=self._sample_loop, daemon=True,
                                 name="telemetry-sampler")
            self._sampler = t
        t.start()

    def _sample_loop(self) -> None:
        stop = self._sampler_stop
        while not stop.wait(self.resolution_s):
            try:
                self.sample_now()
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "telemetry sample tick failed")

    def stop_sampler(self) -> None:
        self._sampler_stop.set()

    def sample_now(self) -> None:
        """One sampler tick, callable synchronously (tests / scrapes):
        sample tracked gauges into their rings, append a decision-ledger
        mark, evaluate the p99-anomaly baselines, and process pending
        flight-recorder triggers into bundles."""
        ts = time.time()
        with self._lock:
            tracked = list(self._tracked.items())
            rings = dict(self._rings)
        for name, fn in tracked:
            try:
                rings[name].append(float(fn()), ts)
            except Exception:
                pass  # a broken gauge must not kill the sampler
        from pinot_tpu.common.tracing import LEDGER

        self.recorder.note_ledger_mark(LEDGER.snapshot(), ts)
        self._check_p99_anomalies()
        self.recorder.process_pending()

    def _check_p99_anomalies(self) -> None:
        """Sliding p99 vs its own EWMA baseline, per (table, phase): a
        spike past ``p99_spike_factor`` x baseline trips the recorder."""
        with self._lock:
            histos = dict(self._histos)
        for key, wh in histos.items():
            sl = wh.sliding()
            if sl.count < P99_SPIKE_MIN_COUNT:
                continue
            p99 = sl.quantile(0.99)
            with self._lock:
                base = self._p99_baseline.get(key)
                if base is None:
                    self._p99_baseline[key] = p99
                    continue
                spiked = base > 0 and p99 > self.p99_spike_factor * base
                self._p99_baseline[key] = (P99_EWMA_ALPHA * p99
                                           + (1 - P99_EWMA_ALPHA) * base)
            if spiked:
                self.recorder.note_p99_spike(f"{key[0]}:{key[1]}")

    # -- views ---------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """``/debug/telemetry`` body: every (table, phase) histogram with
        lifetime AND sliding quantiles, the gauge-history rings, and the
        anomaly-event totals."""
        with self._lock:
            histos = dict(self._histos)
            rings = dict(self._rings)
        return {
            "resolutionS": self.resolution_s,
            "windowS": self.window_s,
            "numWindows": self.num_windows,
            "histograms": {f"{t or '_'}:{p}": h.snapshot()
                           for (t, p), h in sorted(histos.items())},
            "rings": {name: r.values() for name, r in sorted(rings.items())},
            "events": self.recorder.snapshot()["eventTotals"],
        }

    def slo_snapshot(self) -> Dict[str, Any]:
        """``/debug/slo`` body: per configured table, objectives + the
        short/long-window burn rates."""
        tables = self.slo.objectives()
        with self._lock:
            histos = dict(self._histos)
        return {
            "tables": {t: self.slo.burn_rates(
                t, histos.get((t, "broker")),
                freshness_histo=histos.get((t, "freshness")))
                for t in sorted(tables)},
        }

    def freshness_snapshot(self) -> Dict[str, Any]:
        """``/debug/freshness`` body: per table with a ``freshness``
        histogram, the ingest-to-queryable quantiles (sliding + lifetime)
        plus the freshness objective/burn when one is configured."""
        with self._lock:
            histos = {t: h for (t, p), h in self._histos.items()
                      if p == "freshness"}
        objectives = self.slo.objectives()
        out: Dict[str, Any] = {"tables": {}}
        for t in sorted(histos):
            h = histos[t]
            body: Dict[str, Any] = {"histogram": h.snapshot()}
            obj = (objectives.get(t) or {}).get("freshness_ms")
            if obj:
                body["objectiveMs"] = obj
                body["burn"] = self.slo.burn_rates(
                    t, None, freshness_histo=h).get("freshness")
            out["tables"][t] = body
        return out

    def burn_gauges(self) -> Dict[Tuple[str, str, str], float]:
        """(table, objective, window) -> burn rate, for the
        ``slo_burn_rate`` exposition family (None burns are omitted)."""
        out: Dict[Tuple[str, str, str], float] = {}
        snap = self.slo_snapshot()["tables"]
        for table, body in snap.items():
            for objective, key in (("p99", "latency"), ("error", "errors"),
                                   ("freshness", "freshness")):
                for window, cell in (body.get(key) or {}).items():
                    burn = cell.get("burnRate")
                    if burn is not None:
                        out[(table, objective, window)] = burn
        return out

    # -- exposition ----------------------------------------------------------
    def export_prometheus(self, prefix: str) -> str:
        """Real exposition-format families for the continuous layer:
        ``<prefix>query_phase_latency_ms`` histograms labeled
        (table, phase) with ``_bucket``/``_sum``/``_count``, plus
        ``<prefix>slo_burn_rate`` gauges."""
        with self._lock:
            histos = sorted(self._histos.items())
        lines: List[str] = []
        fam = f"{prefix}query_phase_latency_ms"
        if histos:
            lines.append(f"# HELP {fam} Query latency by (table, phase), "
                         f"log-bucketed (lifetime).")
            lines.append(f"# TYPE {fam} histogram")
            for (table, phase), wh in histos:
                labels = f'table="{table}",phase="{phase}"'
                h = wh.lifetime
                for le, cum in h.bucket_rows():
                    lines.append(f'{fam}_bucket{{{labels},le="{le}"}} {cum}')
                with h._lock:
                    total, count = h.sum, h.count
                lines.append(f"{fam}_sum{{{labels}}} {round(total, 3)}")
                lines.append(f"{fam}_count{{{labels}}} {count}")
        burns = self.burn_gauges()
        if burns:
            bfam = f"{prefix}slo_burn_rate"
            lines.append(f"# HELP {bfam} SLO burn rate (1.0 = burning the "
                         f"budget exactly) per table/objective/window.")
            lines.append(f"# TYPE {bfam} gauge")
            for (table, objective, window), burn in sorted(burns.items()):
                lines.append(
                    f'{bfam}{{table="{table}",objective="{objective}",'
                    f'window="{window}"}} {burn}')
        return "\n".join(lines) + ("\n" if lines else "")

    # -- test hygiene --------------------------------------------------------
    def reset(self) -> None:
        """Clear recorded state (histograms, rings, SLO counters, flight
        recorder rings/bundles). Objectives and tracked gauges survive;
        tests isolating the process-wide instance call this."""
        self.stop_sampler()
        with self._lock:
            self._histos.clear()
            self._rings.clear()
            self._tracked.clear()
            self._p99_baseline.clear()
            self.slo = SloTracker(clock=self._clock, window_s=self.window_s,
                                  num_windows=self.num_windows)
            out_dir = self.recorder.out_dir
            self.recorder = FlightRecorder(out_dir=out_dir)


TELEMETRY = Telemetry()


# mapping from the residency manager's meter mnemonics to anomaly-event
# kinds (the storm triggers); called from the engine's accounting paths,
# possibly under locks — note_event never freezes synchronously
_STORM_EVENTS = {
    "STAGING_EVICTIONS": "eviction",
    "STAGING_DEMOTIONS": "demotion",
    "STAGING_HOST_DROPS": "eviction",
}


def note_storm_event(meter_name: Optional[str], n: int = 1) -> None:
    if not meter_name or n <= 0:
        return
    kind = _STORM_EVENTS.get(meter_name)
    if kind is not None:
        TELEMETRY.note_event(kind, n)


def observe_ms(table: Optional[str], phase: str, ms: float) -> None:
    """Module-level record helper so instrumentation sites stay one line."""
    TELEMETRY.observe(table or "", phase, ms)
