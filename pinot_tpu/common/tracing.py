"""Query lifecycle tracing: span trees + the path-decision ledger.

Re-design of the reference's request-scoped tracing
(``TraceContext.java:46`` — per-operator trace trees attached to traced
requests — plus the ``ServerQueryPhase``/``BrokerQueryPhase`` timer
pyramid and the broker slow-query log), with one addition the reference
never had: a **decision ledger** that records WHY execution declined a
faster path.

Two data products ride together:

- **Span trees** (:class:`Span` / :class:`SpanRecorder`): a hierarchical
  record of the full query lifecycle — broker parse/route/scatter ->
  server admission queue -> scheduler queue -> residency lease ->
  launch-dispatcher queue + vmap batch -> per-segment kernel + D2H ->
  sharded combine -> broker reduce. Every span carries wall ms, an
  explicit queue-vs-work split (``queueMs``/``workMs``) where a queue
  exists, and structured attributes. Server trees ship on the DataTable
  wire (``QueryStats.spans``) and are re-parented under the broker root
  at reduce; the legacy flat ``traceInfo["entries"]`` view is EMITTED
  FROM the tree (each span close appends one flat entry), so pre-span
  consumers keep working.
- **The decision ledger**: every point where execution declines a faster
  rung emits a machine-readable ``(decision_point, chosen, declined,
  reason_code)`` record — pallas eligibility, star-tree fit, residency
  spill/slice, backend selection, host-engine fallbacks. Records
  aggregate into ``QueryStats.decisions`` (summed at merge) and into the
  process-level :data:`LEDGER` histogram surfaced on ``/metrics`` — the
  forensics the "why did pallas never fire" question needs.

Cost model: spans are recorded only when a recorder is attached to the
stats (``trace=true``, the ``pinot.server.query.trace.sample`` rate, or
a configured slow-query threshold); the off path pays one ``getattr``
per site. Reason-code counters are always on — they fire only at decline
points, which are off the resident fast path.
"""

from __future__ import annotations

import re
import threading
import time

from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

# span-dict keys the serializer owns; attributes must not collide
_RESERVED = ("name", "ms", "queueMs", "workMs", "children")


class Span:
    """One open span. Closed spans become plain dicts (wire-ready)."""

    __slots__ = ("name", "t0", "wall_ms", "queue_ms", "attrs", "children")

    def __init__(self, name: str, attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.t0 = time.perf_counter()
        self.wall_ms = 0.0
        self.queue_ms: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.children: List[Dict[str, Any]] = []

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name, "ms": round(self.wall_ms, 3)}
        if self.queue_ms is not None:
            # the explicit queue-vs-work split: queueMs is time spent
            # WAITING at this level, workMs the remainder
            d["queueMs"] = round(self.queue_ms, 3)
            d["workMs"] = round(max(self.wall_ms - self.queue_ms, 0.0), 3)
        for k, v in self.attrs.items():
            if k not in _RESERVED:
                d[k] = v
        if self.children:
            d["children"] = self.children
        return d


class SpanRecorder:
    """Per-query span collector. One per :class:`QueryStats`;
    thread-confined — segment fan-out workers record into their private
    stats' recorders, and ``QueryStats.merge`` re-parents their finished
    spans under the caller's currently-open span.

    ``sink`` is the completed-top-level-span list (normally the stats'
    own ``spans`` field, so finished trees land directly on the wire
    payload); ``legacy`` is the flat entry list (``QueryStats.trace``) —
    every span close appends one ``{"operator", "ms", ...attrs}`` entry,
    preserving the pre-span-tree ``traceInfo["entries"]`` contract."""

    __slots__ = ("spans", "_stack", "_legacy")

    def __init__(self, sink: Optional[List[Dict[str, Any]]] = None,
                 legacy: Optional[List[Dict[str, Any]]] = None):
        self.spans: List[Dict[str, Any]] = sink if sink is not None else []
        self._stack: List[Span] = []
        self._legacy = legacy

    # -- open/close ----------------------------------------------------------
    def span_begin(self, name: str, **attrs: Any) -> Span:
        """Open a child of the current span (or a new root). MUST reach
        ``span_end`` on every path, exception edges included — the
        graftlint ``spanpair`` obligation gates manual pairs; prefer the
        ``span()`` context manager."""
        sp = Span(name, attrs)
        self._stack.append(sp)
        return sp

    def span_end(self, span: Span, queue_ms: Optional[float] = None,
                 **attrs: Any) -> Optional[Dict[str, Any]]:
        """Close ``span`` (idempotent: a second close is a no-op). A
        still-open child left behind by an error path is swept closed
        into ``span`` first, so exception edges can never leave a
        dangling open span below a closed parent."""
        if span not in self._stack:
            return None
        while self._stack[-1] is not span:
            self.span_end(self._stack[-1])
        self._stack.pop()
        span.wall_ms = (time.perf_counter() - span.t0) * 1e3
        if queue_ms is not None:
            span.queue_ms = queue_ms
        if attrs:
            span.attrs.update(attrs)
        d = span.to_dict()
        target = self._stack[-1].children if self._stack else self.spans
        target.append(d)
        if self._legacy is not None:
            self._legacy.append({"operator": span.name,
                                 "ms": round(span.wall_ms, 3), **span.attrs})
        return d

    @contextmanager
    def span(self, name: str, **attrs: Any):
        sp = self.span_begin(name, **attrs)
        try:
            yield sp
        finally:
            self.span_end(sp)

    def close_all(self) -> None:
        """Close every open span, outermost last (query teardown /
        exception edge)."""
        if self._stack:
            self.span_end(self._stack[0])

    @property
    def open_depth(self) -> int:
        return len(self._stack)

    # -- pre-measured / adopted spans ---------------------------------------
    def add_completed(self, name: str, wall_ms: float,
                      queue_ms: Optional[float] = None,
                      **attrs: Any) -> Dict[str, Any]:
        """Attach an already-measured span (e.g. a queue wait that ended
        before the recorder existed) as a child of the current span."""
        sp = Span(name, attrs)
        sp.wall_ms = wall_ms
        sp.queue_ms = queue_ms
        d = sp.to_dict()
        target = self._stack[-1].children if self._stack else self.spans
        target.append(d)
        if self._legacy is not None:
            self._legacy.append({"operator": name, "ms": round(wall_ms, 3),
                                 **attrs})
        return d

    def adopt(self, span_dicts: List[Dict[str, Any]]) -> None:
        """Re-parent completed span dicts (a worker stats' trees, a
        server's wire trees) under the currently-open span."""
        target = self._stack[-1].children if self._stack else self.spans
        target.extend(span_dicts)


# --------------------------------------------------------------------------
# QueryStats attachment (the stats object stays a plain dataclass; the
# recorder rides as a private attribute so untraced queries allocate nothing)
# --------------------------------------------------------------------------

def stats_tracer(stats: Any) -> Optional[SpanRecorder]:
    """The stats' recorder, or None (untraced: zero-allocation path)."""
    return getattr(stats, "_recorder", None)


def start_trace(stats: Any) -> SpanRecorder:
    """Attach a recorder to ``stats`` (idempotent). Completed roots land
    in ``stats.spans`` (the wire field); flat entries in ``stats.trace``."""
    rec = getattr(stats, "_recorder", None)
    if rec is None:
        rec = SpanRecorder(sink=stats.spans, legacy=stats.trace)
        stats._recorder = rec
    return rec


class _NullSpanCm:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpanCm()


def maybe_span(stats: Any, name: str, **attrs: Any):
    """Context manager that records a span when ``stats`` is traced and
    is a shared no-op singleton otherwise (the off-path cost is one
    ``getattr``)."""
    rec = getattr(stats, "_recorder", None)
    if rec is None:
        return _NULL_SPAN
    return rec.span(name, **attrs)


def attach_root_child(stats: Any, name: str, wall_ms: float,
                      queue_ms: Optional[float] = None, front: bool = False,
                      **attrs: Any) -> None:
    """Retroactively attach a pre-measured child to the stats' FINISHED
    root span (the scheduler-queue wait is measured by the server tier
    after the executor already closed the tree). The root's wall time
    grows to keep the tree self-consistent (children must account inside
    the root)."""
    if not stats.spans:
        return
    root = stats.spans[0]
    sp = Span(name, attrs)
    sp.wall_ms = wall_ms
    sp.queue_ms = queue_ms
    child = sp.to_dict()
    kids = root.setdefault("children", [])
    if front:
        kids.insert(0, child)
    else:
        kids.append(child)
    root["ms"] = round(root.get("ms", 0.0) + wall_ms, 3)
    stats.trace.append({"operator": name, "ms": round(wall_ms, 3), **attrs})


def flatten_spans(span_dicts: List[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """Span trees -> legacy flat entries (pre-order), for consumers that
    want the old shape derived from the tree rather than the emitted
    legacy list."""
    out: List[Dict[str, Any]] = []

    def walk(d: Dict[str, Any]) -> None:
        e = {"operator": d["name"], "ms": d["ms"]}
        for k, v in d.items():
            if k not in ("name", "ms", "children"):
                e[k] = v
        out.append(e)
        for c in d.get("children", ()):
            walk(c)

    for d in span_dicts:
        walk(d)
    return out


def build_broker_root(phase_ms: Dict[str, float],
                      server_spans: List[Dict[str, Any]],
                      total_ms: float,
                      admission_wait_ms: float = 0.0,
                      reduce_folds: Optional[List[Dict[str, Any]]] = None
                      ) -> Dict[str, Any]:
    """Assemble the broker root span from the measured broker phases
    (COMPILATION/ROUTING/SCATTER_GATHER/REDUCE), re-parenting the
    per-server trees under the ScatterGather child — the reduce-side half
    of the reference's per-server ``traceInfo`` keying.

    ``reduce_folds`` is the reduce-as-arrivals split: one Fold child per
    folded DataTable (its work overlapped the gather wait, so the folds'
    wall time lives INSIDE ScatterGather; the Reduce child keeps the
    final merge/trim/HAVING pass and carries a foldMs rollup)."""
    children: List[Dict[str, Any]] = []
    if admission_wait_ms > 0:
        children.append({"name": "Admission",
                         "ms": round(admission_wait_ms, 3),
                         "queueMs": round(admission_wait_ms, 3),
                         "workMs": 0.0})
    for phase, name in (("COMPILATION", "Compile"), ("ROUTING", "Routing")):
        if phase in phase_ms:
            children.append({"name": name,
                             "ms": round(phase_ms[phase], 3)})
    sg: Dict[str, Any] = {
        "name": "ScatterGather",
        "ms": round(phase_ms.get("SCATTER_GATHER", 0.0), 3)}
    if server_spans:
        sg["children"] = list(server_spans)
    children.append(sg)
    if "REDUCE" in phase_ms:
        reduce_span: Dict[str, Any] = {"name": "Reduce",
                                       "ms": round(phase_ms["REDUCE"], 3)}
        if reduce_folds:
            reduce_span["foldMs"] = round(
                sum(f.get("ms", 0.0) for f in reduce_folds), 3)
            reduce_span["children"] = list(reduce_folds)
        children.append(reduce_span)
    return {"name": "BrokerQuery", "ms": round(total_ms, 3),
            "children": children}


# --------------------------------------------------------------------------
# path-decision ledger
# --------------------------------------------------------------------------

# Ordered (substring, reason_code) classification of decline messages.
# More specific substrings FIRST. Every PlanError / pallas ineligibility
# message in the engine maps to a stable code here; the normalizing
# fallback below keeps even unlisted messages classified (never
# "unknown" for a non-empty message) — the bench loud-fails on "unknown".
_DECLINE_RULES: Tuple[Tuple[str, str], ...] = (
    ("mutable segment", "mutable_segment"),
    ("star-tree group key space", "startree_group_space_over_limit"),
    ("no pre-agg pairs", "startree_no_preagg_pair"),
    ("star-tree param", "startree_param_drift"),
    ("group key space", "group_space_over_limit"),
    ("not device-supported", "agg_not_device_supported"),
    ("DISTINCTCOUNTHLL argument", "hll_arg_not_column"),
    ("DISTINCTCOUNTHLL needs", "hll_needs_sv_dict"),
    ("HLL register space", "hll_register_space_over_limit"),
    ("DISTINCTCOUNT argument", "distinctcount_arg_not_column"),
    ("DISTINCTCOUNT on raw", "distinctcount_raw_column"),
    ("DISTINCTCOUNT on MV", "distinctcount_mv_column"),
    ("DISTINCTCOUNT cardinality", "distinctcount_cardinality_over_limit"),
    ("MV aggregation argument", "mv_agg_arg_not_column"),
    ("needs a numeric MV column", "mv_agg_not_numeric"),
    ("group-by on virtual column", "group_virtual_column"),
    ("group-by on MV column", "group_mv_column"),
    ("raw int group-by span", "group_raw_span_over_limit"),
    ("group-by on raw float", "group_raw_float_column"),
    ("group-by expression span", "group_expression_span_over_limit"),
    ("group-by expression", "group_expression_unbounded"),
    ("expression predicate", "expression_predicate"),
    ("virtual column predicate", "virtual_column_predicate"),
    ("JSON_MATCH on MV", "json_match_mv_column"),
    ("on raw column -> host", "raw_predicate_unsupported"),
    ("raw MV column predicate", "raw_mv_predicate"),
    ("predicate", "predicate_unsupported"),
    ("non-numeric literal", "value_literal_non_numeric"),
    ("virtual column in value", "value_virtual_column"),
    ("in value expression", "value_column_not_numeric_sv"),
    ("transform", "transform_unsupported"),
    ("cannot compile value", "value_expression_uncompilable"),
    ("live groups exceed the compact cap", "compact_cap_overflow"),
    ("doc axis", "capacity_mesh_mismatch"),
    # pallas eligibility (engine/pallas_kernels.py _Ineligible messages)
    ("unpackable column", "pallas_unpackable_column"),
    ("lut with too many runs", "pallas_lut_too_many_runs"),
    ("raw group key", "pallas_raw_group_key"),
    ("non-numeric/MV agg value column", "pallas_value_not_numeric_sv"),
    ("no stats for int value bound", "pallas_no_int_stats"),
    ("i64-staged value column", "pallas_i64_value_column"),
    ("i64 sum bound over i64", "pallas_i64_sum_bound_over_i64"),
    ("i64 column in float expression", "pallas_i64_in_float_expr"),
    ("missing agg value", "pallas_missing_agg_value"),
    ("int expr bound exceeds i32", "pallas_expression_bound_over_i32"),
    ("agg value", "pallas_agg_value_op_unsupported"),
    ("mv aggregation", "pallas_mv_aggregation"),
    ("int min/max not f32-exact", "pallas_minmax_not_f32_exact"),
)

# Reason codes recorded DIRECTLY at decline sites (never routed through
# classify_decline's message table). The graftlint ``decline`` family
# checks every ``decline("...")`` literal in engine/pallas_kernels.py
# against this registry plus _DECLINE_RULES' code column, so a new
# decline site can never reach the ledger as an unregistered code.
DIRECT_DECLINE_CODES = frozenset({
    "pallas_too_many_groups",
    "pallas_distinct_agg",
    "pallas_docs_over_i32",
    "pallas_column_not_packable",
    "pallas_value_layout_unsupported",
    "pallas_disabled_on_backend",
    "pallas_shape_blocked",
    "pallas_exec_failed",
    "pallas_build_failed",
})

# Reason codes the broker-side ROUTING decision point records
# (broker/routing.py): a prune that fired, or why a configured pruner
# could not help. Registered for the same reason as DIRECT_DECLINE_CODES:
# every reason reaching the ledger must be a known, stable code —
# test_cluster_routing scans routing.py's record sites against this set.
ROUTING_DECISION_REASONS = frozenset({
    "partition_prune",
    "time_prune",
    "no_filter",
    "no_partition_predicate",
    "no_partition_metadata",
    "partition_all_match",
    "no_time_bound",
    "time_all_match",
})

# Reason codes the STAR-TREE decision point records
# (engine/startree_exec.py: pick_star_tree's note()/decline() sites and
# _matching_ids' reason strings). Same contract as
# ROUTING_DECISION_REASONS: every reason literal in startree_exec.py must
# be registered here — test_startree's conformance test scans the source —
# so a new decline site can never reach the ledger unregistered. The
# CHOSEN-tree success records ("startree:scan-><rung>:tree<i>") carry the
# dynamic reason matched by STARTREE_TREE_REASON instead.
STARTREE_DECISION_REASONS = frozenset({
    "startree_upsert_valid_docs",
    "startree_filter_or_not_shape",
    "startree_group_expression",
    "startree_group_off_split_order",
    "startree_filter_non_dimension",
    "startree_predicate_type_unsupported",
    "startree_agg_not_pairable",
    "startree_expression_agg_no_pair",
    "startree_missing_function_pair",
    "startree_no_fitting_tree",
    "startree_raw_dimension",
    "startree_dictid_overflow_noncontiguous",
    # recorded from engine/executor.py _try_star_tree: the host walker
    # refused a tree the pick accepted (defensive disagreement) -> scan
    "startree_walker_declined",
})

# the chosen-tree ledger reason: which of the segment's trees served
STARTREE_TREE_REASON = re.compile(r"tree\d+\Z")

# Reason codes the broker GATHER point records (broker/broker.py) when a
# scattered-to server fails to produce a usable DataTable — the loud
# accounting behind every partial result.
GATHER_DECISION_REASONS = frozenset({
    "server_not_connected",
    "server_timeout",
    "server_error",
})

# Reason codes the broker REDUCE point records (broker/reduce.py) when
# the vectorized (array-native) merge cannot prove bit-exactness against
# the row-path oracle and falls back to it. Same contract as
# ROUTING_DECISION_REASONS: every reason literal at a reduce.py record
# site must be registered here — test_reduce_vectorized scans the source.
REDUCE_DECISION_REASONS = frozenset({
    "reduce_group_key_not_sortable",
    "reduce_distinct_key_not_sortable",
    "reduce_order_key_not_sortable",
    "reduce_column_kind_mismatch",
    "reduce_nan_numeric_state",
    "reduce_nan_order_key",
    "reduce_i64_sum_bound",
})

# Reason codes the broker REDUCE point records (broker/reduce.py
# ``_decline_device`` sites) when the DEVICE group-by merge
# (parallel/reduce_device.py) cannot prove bit-exactness or has no
# substrate, and the query falls back ONE rung to the vectorized host
# path ("reduce:device->host:<reason>"). Distinct prefix from
# REDUCE_DECISION_REASONS: that set explains vectorized->oracle falls.
REDUCE_DEVICE_REASONS = frozenset({
    "reduce_device_mesh_unavailable",
    "reduce_device_obj_state",
    "reduce_device_cross_process",
    "reduce_device_rows_over_capacity",
    "reduce_device_nan_key",
    "reduce_device_key_space_overflow",
    "reduce_device_f64_sum_order",
    "reduce_device_i64_sum_bound",
    "reduce_device_kernel_error",
})

# Reason codes the KERNEL PREFLIGHT seeds into the per-shape pallas
# blocklist (tools/preflight.py): one code per lowering-model rule. A
# blocked shape then declines with ``pallas_preflight_<rule>`` instead of
# the generic ``pallas_shape_blocked``, so the ledger says WHICH lowering
# constraint the shape was predicted to violate — before any chip saw it.
PALLAS_PREFLIGHT_REASONS = frozenset({
    "pallas_preflight_tile_align",
    "pallas_preflight_vmem_budget",
    "pallas_preflight_smem_budget",
    "pallas_preflight_groups_bound",
    "pallas_preflight_grid_bound",
    "pallas_preflight_dtype_unsupported",
    "pallas_preflight_limb_planes",
})


# --------------------------------------------------------------------------
# unified reason registry: ONE lookup + ONE conformance harness for every
# reason namespace above (they were five hand-rolled frozensets with four
# near-duplicate source-scanning tests; the namespaces keep their public
# frozenset names — plenty of code imports them — but registration,
# lookup, and conformance scanning now go through here).
# --------------------------------------------------------------------------

class ReasonNamespace:
    """One decision-point reason namespace: the registered code set plus
    everything the generic conformance harness needs to scan its source
    module — regexes whose group(1) captures a reason literal at a record
    site, an optional prefix that makes EVERY quoted ``"<prefix>..."``
    literal in the module a reason, an optional pattern for allowed
    dynamic reasons (``tree<i>``), and a floor on sites found (a scan
    that finds nothing means the patterns drifted, not that the module
    conformed)."""

    __slots__ = ("name", "codes", "module", "literal_patterns", "prefix",
                 "dynamic", "min_sites", "exact")

    def __init__(self, name: str, codes: frozenset, module: str,
                 literal_patterns: Tuple[str, ...] = (),
                 prefix: Optional[str] = None,
                 dynamic: Optional["re.Pattern"] = None,
                 min_sites: int = 1, exact: bool = False):
        self.name = name
        self.codes = codes
        self.module = module
        self.literal_patterns = literal_patterns
        self.prefix = prefix
        self.dynamic = dynamic
        self.min_sites = min_sites
        self.exact = exact

    def scan_source(self) -> set:
        """All reason literals found at this namespace's record sites (by
        pattern and/or prefix) in its module's source. A namespace rooted
        at a package (the module has ``__path__``) scans every ``.py``
        beneath it — ``race_ok`` waivers live wherever shared state
        lives, not in one module."""
        import importlib
        import os

        mod = importlib.import_module(self.module)
        paths: List[str] = []
        if hasattr(mod, "__path__"):
            for root, _dirs, files in os.walk(list(mod.__path__)[0]):
                paths.extend(os.path.join(root, f) for f in sorted(files)
                             if f.endswith(".py"))
        else:
            paths.append(mod.__file__.rstrip("c"))
        found: set = set()
        for path in paths:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            for pat in self.literal_patterns:
                found |= set(re.findall(pat, src))
            if self.prefix:
                found |= set(
                    re.findall(rf'"({self.prefix}[a-z0-9_]+)"', src))
        return found

    def conformance(self) -> Tuple[set, set]:
        """(literals found, unregistered literals) — the generic
        source-scanning conformance check. Dynamic reasons matching
        ``dynamic`` are allowed without registration."""
        found = self.scan_source()
        bad = {r for r in found - self.codes
               if not (self.dynamic and self.dynamic.fullmatch(r))}
        return found, bad


_REASON_REGISTRY: Dict[str, ReasonNamespace] = {}


def _register_reasons(ns: ReasonNamespace) -> None:
    _REASON_REGISTRY[ns.name] = ns


def reason_registry(name: Optional[str] = None):
    """The unified reason-namespace registry. With ``name``, the one
    :class:`ReasonNamespace`; without, the full ``{name: namespace}``
    dict. Every reason code that can reach the ledger from a registered
    decision point lives in exactly one namespace here."""
    if name is None:
        return dict(_REASON_REGISTRY)
    return _REASON_REGISTRY[name]


def registered_reason_codes() -> frozenset:
    """Union of every namespace's code set."""
    out: set = set()
    for ns in _REASON_REGISTRY.values():
        out |= ns.codes
    return frozenset(out)


# the five pre-existing namespaces + the preflight namespace, registered
# through the one harness (tests/test_reasons.py parameterizes over this
# registry — the four per-module conformance tests collapsed into it)
_register_reasons(ReasonNamespace(
    "pallas", DIRECT_DECLINE_CODES | frozenset(
        code for _needle, code in _DECLINE_RULES
        if code.startswith("pallas_")),
    "pinot_tpu.engine.pallas_kernels",
    literal_patterns=(r'decline\("([a-z0-9_]+)"\)',),
    min_sites=3))
_register_reasons(ReasonNamespace(
    "routing", ROUTING_DECISION_REASONS, "pinot_tpu.broker.routing",
    literal_patterns=(r'declined\("([a-z_]+)"\)',
                      r'"pruned", "all_servers",\s*\n?\s*"([a-z_]+)"'),
    min_sites=4))
_register_reasons(ReasonNamespace(
    "gather", GATHER_DECISION_REASONS, "pinot_tpu.broker.broker",
    literal_patterns=(r'"full_result",\s*\n?\s*"([a-z_]+)"',),
    min_sites=3, exact=True))
_register_reasons(ReasonNamespace(
    "startree", STARTREE_DECISION_REASONS,
    "pinot_tpu.engine.startree_exec",
    prefix="startree_", dynamic=STARTREE_TREE_REASON, min_sites=10))
_register_reasons(ReasonNamespace(
    "reduce", REDUCE_DECISION_REASONS, "pinot_tpu.broker.reduce",
    literal_patterns=(r'_decline\(\s*"([a-z0-9_]+)"',), min_sites=3))
_register_reasons(ReasonNamespace(
    "reduce_device", REDUCE_DEVICE_REASONS, "pinot_tpu.broker.reduce",
    literal_patterns=(r'_decline_device\(\s*"([a-z0-9_]+)"',),
    min_sites=4, exact=True))
_register_reasons(ReasonNamespace(
    "pallas_preflight", PALLAS_PREFLIGHT_REASONS,
    "pinot_tpu.tools.preflight",
    literal_patterns=(r'_Rule\(\s*"([a-z0-9_]+)"',), min_sites=5,
    exact=True))
# realtime serving tier (PR-17): consuming-segment device declines,
# broker hybrid time-boundary routing, and the seal swap
MUTABLE_DECLINE_REASONS = frozenset({
    "mutable_empty_watermark",   # nothing published yet: host answers
    "mutable_hll_lut_unstable",  # HLL register LUTs go stale as the
                                 # dictionary grows mid-consume
    "mutable_exec_failed",       # staging/kernel raised: host fallback
    # the consuming-segment index rung (PR-18), recorded through
    # _decline_rung/_chose_rung — declines fall to the full chunk scan
    # (NOT to host), so these ride the "index" decision point with the
    # mutable device scan as the chosen side
    "mutable_index_unsupported_shape",  # OR/NOT, non-EQ/IN/RANGE, MV,
                                        # dictionary-less, or upsert
    "mutable_index_over_threshold",     # broad match: the chunk scan wins
    "mutable_index_exec_failed",        # gather kernel raised: chunk scan
    "mutable_index_served",             # gather served the snapshot
})
HYBRID_ROUTE_REASONS = frozenset({
    "hybrid_single_table",    # only one physical table: no split
    "hybrid_no_time_column",  # split predicate inexpressible
    "hybrid_no_boundary",     # boundary not published: realtime serves all
    "hybrid_time_split",      # offline <= boundary < realtime
})
SEAL_SWAP_REASONS = frozenset({
    "seal_swap",      # local consumer committed: mutable -> immutable
    "seal_download",  # replica download of a sealed segment
})
_register_reasons(ReasonNamespace(
    "mutable", MUTABLE_DECLINE_REASONS,
    "pinot_tpu.engine.mutable_staging",
    literal_patterns=(
        r'_decline\(\s*[a-zA-Z_][a-zA-Z0-9_]*\s*,\s*"([a-z0-9_]+)"',
        r'_decline_rung\(\s*[a-zA-Z_][a-zA-Z0-9_]*\s*,\s*"([a-z0-9_]+)"',
        r'_chose_rung\(\s*[a-zA-Z_][a-zA-Z0-9_]*\s*,\s*"([a-z0-9_]+)"',),
    min_sites=3, exact=True))
_register_reasons(ReasonNamespace(
    "hybrid", HYBRID_ROUTE_REASONS, "pinot_tpu.broker.broker",
    literal_patterns=(r'_hybrid_route\(\s*stats,\s*"([a-z0-9_]+)"',),
    min_sites=4, exact=True))
_register_reasons(ReasonNamespace(
    "seal", SEAL_SWAP_REASONS, "pinot_tpu.server.data_manager",
    literal_patterns=(r'"(seal_[a-z0-9_]+)"',), min_sites=2, exact=True))
# index rung (PR-18): docId-gather over inverted/sorted/range indexes —
# every outcome on an index-candidate filter shape, chosen and declined
INDEX_DECISION_REASONS = frozenset({
    "index_served",              # gather rung served the segment
    "index_filter_shape",        # OR/NOT or non-column predicate
    "index_pred_type_unsupported",  # not EQ / IN / RANGE
    "index_missing_index",       # a predicate column has no usable index
    "index_selectivity_over_threshold",  # broad match: the scan wins
    "index_upsert_valid_docs",   # valid-doc bitmap ANDs the filter
    "index_plan_error",          # device plan/unpack declined -> scan
    "index_exec_failed",         # staging/kernel raised -> scan serves
})
_register_reasons(ReasonNamespace(
    "index", INDEX_DECISION_REASONS, "pinot_tpu.engine.index_exec",
    literal_patterns=(
        r'_decline\(\s*stats,\s*"([a-z0-9_]+)"',
        r'raise _Decline\(\s*"([a-z0-9_]+)"',
        r'_chose\(\s*stats,\s*"([a-z0-9_]+)"',),
    min_sites=6, exact=True))
# race waivers (PR-20): the ``threads`` lint family's ``# race-ok:``
# annotations. Each code names a concurrency DESIGN the reference also
# relies on, not a dismissal — the lint rejects any code not in this set,
# so the vocabulary can only grow through here, next to its meaning.
RACE_OK_REASONS = frozenset({
    "single_writer",         # one runtime thread performs every write;
                             # readers take GIL-atomic snapshots and
                             # tolerate one-batch staleness (the
                             # volatile-numDocsIndexed watermark pattern)
    "publish_once",          # reference assigned once at setup, never
                             # reassigned; readers null-check
    "delegates_locking",     # field holds an object that does its own
                             # locking; the mutator call the lint sees is
                             # the delegate's atomic op, and the reference
                             # itself never changes after __init__
    "quiesced_by_refcount",  # teardown mutation that runs only after the
                             # residency refcount proves no reader holds
                             # the object
})
_register_reasons(ReasonNamespace(
    "race_ok", RACE_OK_REASONS, "pinot_tpu",
    literal_patterns=(r'#\s*race-ok:\s*([a-z0-9_]+)',),
    min_sites=4, exact=True))


_SANITIZE = re.compile(r"[^a-z0-9]+")
_DIGITS = re.compile(r"\d+")


def classify_decline(message: str) -> str:
    """Decline message -> stable snake_case reason code. The table covers
    every engine decline message; the fallback strips runtime-variable
    digits and normalizes, so new messages stay machine-readable (and
    non-``unknown``) until classified properly."""
    for needle, code in _DECLINE_RULES:
        if needle in message:
            return code
    code = _SANITIZE.sub("_", _DIGITS.sub("", message).lower()).strip("_")
    return code[:64] if code else "unknown"


class DecisionLedger:
    """Always-on histogram of path-decision records, keyed on the full
    ``(decision_point, chosen, declined, reason_code)`` tuple. One
    process-level instance (:data:`LEDGER`) backs ``/metrics`` and the
    bench per-suite deltas; tests may instantiate private ledgers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, str, str, str], int] = {}  # guarded-by: _lock
        self._registries: List[Any] = []  # guarded-by-writes: _lock

    # the one labeled prometheus family every decline lands in
    METRIC_FAMILY = "decision_declined_total"

    def record(self, point: str, chosen: str, declined: str,
               reason: str) -> None:
        key = (point, chosen, declined, reason)
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            regs = list(self._registries)
        for reg in regs:
            reg.labeled_meter(self.METRIC_FAMILY,
                              point=point, reason=reason).mark()
        if point == "pallas":
            # pallas-decline burst is a flight-recorder anomaly trigger:
            # a storm of declines is how "pallas_kernels: 0" looks live
            from pinot_tpu.common.telemetry import TELEMETRY

            TELEMETRY.note_event("pallas_decline")

    def bind_metrics(self, registry: Any) -> None:
        """Surface the histogram on a MetricsRegistry as ONE labeled
        ``decision_declined_total{point=...,reason=...}`` family on
        ``/metrics`` (one name-mangled counter per cell pre-dates labeled
        families; see spi/metrics.py labeled_meter)."""
        with self._lock:
            if registry not in self._registries:
                self._registries.append(registry)
            existing = dict(self._counts)
        registry.set_help(self.METRIC_FAMILY,
                          "Path decisions where execution declined a "
                          "faster rung, by decision point and reason.")
        for (point, _c, _d, reason), n in existing.items():
            registry.labeled_meter(self.METRIC_FAMILY,
                                   point=point, reason=reason).mark(n)

    def snapshot(self) -> Dict[str, int]:
        """``"point:declined->chosen:reason" -> count`` (the same key
        shape ``QueryStats.decisions`` uses)."""
        with self._lock:
            return {decision_key(p, c, d, r): n
                    for (p, c, d, r), n in self._counts.items()}

    def reason_histogram(self) -> Dict[str, int]:
        """reason_code -> count across all decision points."""
        out: Dict[str, int] = {}
        with self._lock:
            for (_p, _c, _d, r), n in self._counts.items():
                out[r] = out.get(r, 0) + n
        return out

    def delta(self, mark: Dict[str, int]) -> Dict[str, int]:
        """Per-suite histogram since ``mark`` (a prior ``snapshot()``)."""
        now = self.snapshot()
        return {k: v - mark.get(k, 0) for k, v in now.items()
                if v - mark.get(k, 0)}


def decision_key(point: str, chosen: str, declined: str,
                 reason: str) -> str:
    return f"{point}:{declined}->{chosen}:{reason}"


def parse_decision_key(key: str) -> Tuple[str, str, str, str]:
    """Inverse of :func:`decision_key` -> (point, chosen, declined,
    reason)."""
    point, rest = key.split(":", 1)
    path, reason = rest.rsplit(":", 1)
    declined, chosen = path.split("->", 1)
    return point, chosen, declined, reason


LEDGER = DecisionLedger()


def record_decision(stats: Any, point: str, chosen: str, declined: str,
                    reason: str) -> None:
    """One ledger record: execution declined ``declined`` in favor of
    ``chosen`` at ``point`` because ``reason``. Lands in the per-query
    ``QueryStats.decisions`` dict (summed across segments/shards/servers
    at merge) AND the process :data:`LEDGER` histogram — both always on;
    a decline is never silent."""
    if stats is not None:
        key = decision_key(point, chosen, declined, reason)
        stats.decisions[key] = stats.decisions.get(key, 0) + 1
    LEDGER.record(point, chosen, declined, reason)


# --------------------------------------------------------------------------
# query registry: /debug/queries + slow-query log
# --------------------------------------------------------------------------

class QueryRegistry:
    """Backing store for ``/debug/queries``: the currently-running query
    set, a ring buffer of the last N completed, and a slow-query log
    (``pinot.server.query.slow.threshold.ms``) that retains the full
    span tree for over-threshold queries — the executor force-records
    spans for every query while the threshold is configured, and ships
    them on the wire only when the query was actually traced/sampled, so
    a slow query's forensics survive even when sampling missed it."""

    def __init__(self, ring_size: int = 128, slow_log_size: int = 32,
                 slow_threshold_ms: float = 0.0):
        self.ring_size = max(1, int(ring_size))
        self.slow_log_size = max(1, int(slow_log_size))
        self.slow_threshold_ms = float(slow_threshold_ms)
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        self._running: Dict[int, Dict[str, Any]] = {}  # guarded-by: _lock
        self._completed: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._slow: List[Dict[str, Any]] = []  # guarded-by: _lock
        self.slow_queries = 0  # guarded-by: _lock

    @property
    def force_trace(self) -> bool:
        """True when every query must record spans so the slow log can
        retain trees sampling missed."""
        return self.slow_threshold_ms > 0

    def begin(self, ctx: Any, stats: Any = None) -> Dict[str, Any]:
        token: Dict[str, Any] = {
            "sql": getattr(ctx, "sql", None),
            "table": getattr(ctx, "table_name", None),
            "requestId": getattr(ctx, "request_id", None),
            "phase": "executing",
            "t0": time.perf_counter(),
            "stats": stats,
        }
        with self._lock:
            self._seq += 1
            token["id"] = self._seq
            self._running[token["id"]] = token
        return token

    def phase(self, token: Dict[str, Any], phase: str) -> None:
        token["phase"] = phase

    def end(self, token: Dict[str, Any], error: Any = None) -> float:
        elapsed_ms = (time.perf_counter() - token["t0"]) * 1e3
        stats = token.get("stats")
        entry: Dict[str, Any] = {
            "id": token["id"],
            "sql": token["sql"],
            "table": token["table"],
            "elapsedMs": round(elapsed_ms, 3),
        }
        if token.get("requestId"):
            entry["requestId"] = token["requestId"]
        if error is not None:
            entry["error"] = f"{type(error).__name__}: {error}"[:200]
        if stats is not None and stats.decisions:
            entry["decisions"] = dict(stats.decisions)
        slow = self.slow_threshold_ms > 0 \
            and elapsed_ms >= self.slow_threshold_ms
        if slow and stats is not None and stats.spans:
            # copy the LIST (dicts shared): the executor may clear the
            # stats' wire field when the query wasn't actually traced
            entry["spans"] = list(stats.spans)
        with self._lock:
            self._running.pop(token["id"], None)
            self._completed.append(entry)
            if len(self._completed) > self.ring_size:
                del self._completed[0]
            if slow:
                self.slow_queries += 1
                self._slow.append(entry)
                if len(self._slow) > self.slow_log_size:
                    del self._slow[0]
        if stats is not None and stats.spans:
            # flight-recorder feed: every completed query whose span tree
            # was recorded (traced / sampled / slow-log-forced) lands in
            # the black box's bounded ring — copied like the slow log, so
            # the executor clearing the wire field can't empty it
            from pinot_tpu.common.telemetry import TELEMETRY

            fr = dict(entry)
            fr.setdefault("spans", list(stats.spans))
            TELEMETRY.recorder.note_query(fr)
        return elapsed_ms

    def snapshot(self) -> Dict[str, Any]:
        """``/debug/queries`` body."""
        now = time.perf_counter()
        with self._lock:
            running = list(self._running.values())
            completed = list(self._completed)
            slow = list(self._slow)
            slow_n = self.slow_queries
        run_out = []
        for t in running:
            lease = getattr(t.get("stats"), "_staging_lease", None)
            run_out.append({
                "id": t["id"],
                "sql": t["sql"],
                "table": t["table"],
                "phase": t["phase"],
                "elapsedMs": round((now - t["t0"]) * 1e3, 3),
                "pinsHeld": len(lease._pinned) if lease is not None else 0,
            })
        return {
            "running": run_out,
            "completed": completed,
            "slow": slow,
            "slowThresholdMs": self.slow_threshold_ms,
            "slowQueries": slow_n,
        }
