"""Single-flight execution: concurrent identical work shares one run.

The request-tier analogue of the launch dispatcher's dedup (PR 3
coalesces *device launches*; this coalesces whole executions above them):
the first caller for a key becomes the LEADER and runs the function; every
caller that arrives while the leader is in flight becomes a FOLLOWER and
blocks on the leader's future, receiving the same result object (or the
same exception). The flight table holds only in-flight work — results are
never cached, so staleness is bounded by one execution and invalidation
reduces to "don't join a flight whose key embeds an old generation".

Used by:

- ``broker/broker.py``: concurrent identical dashboard queries (same
  normalized SQL + principal + cluster-state generation) share one
  scatter/gather/reduce, before any fan-out happens;
- ``engine/executor.py``: concurrent identical per-segment kernel
  launches (same cached plan + same staged resident) share one device
  program + one D2H fetch — the per-segment half of the LaunchKernel
  coalescing contract.
"""

from __future__ import annotations

import threading

from concurrent.futures import Future
from typing import Any, Callable, Dict, Hashable, Optional, Tuple

__all__ = ["SingleFlight"]


class SingleFlight:
    """In-flight dedup table. ``do(key, fn)`` returns ``(result,
    coalesced)`` — ``coalesced`` True when this caller rode another
    caller's execution. A ``key`` of None disables coalescing for that
    call (the caller decided the work isn't shareable)."""

    __slots__ = ("_lock", "_flights", "leaders", "hits")

    def __init__(self):
        self._lock = threading.Lock()
        self._flights: Dict[Hashable, Future] = {}  # guarded-by: _lock
        # cumulative counters; readers go through snapshot()
        self.leaders = 0  # guarded-by-writes: _lock
        self.hits = 0  # guarded-by-writes: _lock

    def do(self, key: Optional[Hashable],
           fn: Callable[[], Any]) -> Tuple[Any, bool]:
        if key is None:
            return fn(), False
        with self._lock:
            fut = self._flights.get(key)
            leader = fut is None
            if leader:
                fut = Future()
                self._flights[key] = fut
                self.leaders += 1
            else:
                self.hits += 1
        if not leader:
            return fut.result(), True
        try:
            result = fn()
        except BaseException as e:
            # drop the flight BEFORE resolving: a caller arriving after
            # the failure must start fresh, not join a dead flight
            with self._lock:
                self._flights.pop(key, None)
            fut.set_exception(e)
            raise
        with self._lock:
            self._flights.pop(key, None)
        fut.set_result(result)
        return result, False

    def inflight(self) -> int:
        with self._lock:
            return len(self._flights)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"leaders": self.leaders, "hits": self.hits,
                    "inflight": len(self._flights)}
