"""Named numeric-exactness bounds: the wide-bound constants that license
device and vectorized sums.

Every bit-exactness proof in the engine/broker/parallel tiers compares
against ONE of these named constants — never a raw ``1 << 62`` /
``1 << 53`` literal (the graftlint ``exactness`` family bans the raw
forms and checks each guard pairs with the dtype it protects). Each
constant carries its derivation so the guard and the arithmetic it
licenses can be audited side by side.
"""

from __future__ import annotations

# i64 fold headroom: a signed-64 accumulator overflows at 2^63, and the
# limb-reassembly carry chain (engine/pallas_kernels.py) shifts partial
# rows by up to 62 bits — so any fold whose total absolute mass stays
# strictly under 2^62 keeps a 2x safety margin under the overflow line.
I64_FOLD_BOUND = 1 << 62

# f64 exact-integer bound: float64 carries a 53-bit mantissa, so every
# integer with |v| < 2^53 is exactly representable and integral partial
# sums under this mass are order-independent (device psum order may
# differ from the host reduceat order without changing a bit).
F64_EXACT_INT_BOUND = float(1 << 53)

# composite-key space budget: group-by key columns encode injectively
# into one non-negative i64 composite per row; capping the composite
# space strictly under 2^62 keeps every live code below the pad
# sentinel (and leaves the same 2x margin as the fold bound).
I64_KEY_SPACE_BOUND = 1 << 62

# i64 max as pad/sentinel key: live composite keys are non-negative and
# < I64_KEY_SPACE_BOUND, so i64 max sorts strictly after every live key
# on the device sort-merge rung.
I64_PAD_SENTINEL = (1 << 63) - 1
