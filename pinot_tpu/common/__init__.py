"""Shared wire/metadata layer (ref: pinot-common): the DataTable
server->broker payload, broker response model."""

from pinot_tpu.common.datatable import (
    DataTable,
    ResponseType,
    decode_value,
    encode_value,
)
from pinot_tpu.common.response import BrokerResponse

__all__ = ["DataTable", "ResponseType", "decode_value", "encode_value",
           "BrokerResponse"]
