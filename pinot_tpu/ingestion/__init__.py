"""Ingestion: record transformers, stream SPI, realtime consumption
(ref: pinot-spi stream/, pinot-segment-local recordtransformer/,
pinot-core data/manager/realtime/)."""

from pinot_tpu.ingestion.stream import (
    JsonMessageDecoder,
    MemoryStream,
    MessageBatch,
    PartitionLevelConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamMessageDecoder,
    StreamMetadataProvider,
    StreamOffset,
    create_consumer_factory,
    create_decoder,
    register_decoder,
    register_stream_type,
)
from pinot_tpu.ingestion import socketstream  # registers stream.type=socket
from pinot_tpu.ingestion import kafkawire  # registers stream.type=kafka
from pinot_tpu.ingestion.transformers import (
    CompositeTransformer,
    ComplexTypeTransformer,
    DataTypeTransformer,
    ExpressionTransformer,
    FilterTransformer,
    NullValueTransformer,
    RecordTransformer,
    SanitizationTransformer,
    transform_rows,
)
from pinot_tpu.ingestion.realtime import (
    CompletionReply,
    CompletionResponse,
    ConsumerState,
    LocalCompletionProtocol,
    RealtimeSegmentDataManager,
    SegmentCompletionProtocol,
)

__all__ = [
    "JsonMessageDecoder", "MemoryStream", "MessageBatch",
    "PartitionLevelConsumer", "StreamConsumerFactory", "StreamMessage",
    "StreamMessageDecoder", "StreamMetadataProvider", "StreamOffset",
    "create_consumer_factory", "create_decoder", "register_decoder",
    "register_stream_type",
    "CompositeTransformer", "ComplexTypeTransformer", "DataTypeTransformer",
    "ExpressionTransformer", "FilterTransformer", "NullValueTransformer",
    "RecordTransformer", "SanitizationTransformer", "transform_rows",
    "CompletionReply", "CompletionResponse", "ConsumerState",
    "LocalCompletionProtocol", "RealtimeSegmentDataManager",
    "SegmentCompletionProtocol",
]
