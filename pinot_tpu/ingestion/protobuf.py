"""Protobuf input format: length-delimited messages + compiled descriptors.

Re-design of the reference's protobuf plugin
(``pinot-plugins/pinot-input-format/pinot-protobuf/.../ProtoBufRecordReader.java``
+ ``ProtoBufRecordExtractor``): the data file holds varint-length-delimited
serialized messages; the reader loads a ``FileDescriptorSet`` (the output of
``protoc --descriptor_set_out``) named by ``descriptorFile``, resolves
``protoClassName``, and extracts scalar / repeated-scalar / enum fields
into rows. Nested messages flatten into dicts (the extractor's
recursive-message behavior).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional, Sequence

from pinot_tpu.spi.readers import (
    GenericRow,
    RecordReader,
    RecordReaderConfig,
)


def load_message_class(descriptor_file: str, message_name: str):
    """FileDescriptorSet + fully-qualified message name -> message class."""
    from google.protobuf import (
        descriptor_pb2,
        descriptor_pool,
        message_factory,
    )

    fds = descriptor_pb2.FileDescriptorSet()
    with open(descriptor_file, "rb") as f:
        fds.ParseFromString(f.read())
    pool = descriptor_pool.DescriptorPool()
    for fd in fds.file:
        pool.Add(fd)
    desc = pool.FindMessageTypeByName(message_name)
    return message_factory.GetMessageClass(desc)


def write_delimited(path: str, messages) -> None:
    """Serialize messages varint-length-delimited (writeDelimitedTo).
    Protobuf's wire varint IS unsigned LEB128 — the same codec the
    DataTable serde uses, so it is shared (common/serde._write_varint)."""
    from pinot_tpu.common.serde import _write_varint

    out = bytearray()
    for m in messages:
        raw = m.SerializeToString()
        _write_varint(out, len(raw))
        out += raw
    with open(path, "wb") as f:
        f.write(bytes(out))


def _message_to_dict(msg) -> Dict[str, Any]:
    """Walk DESCRIPTOR fields, not ListFields(): proto3 scalars at their
    default value (qty=0, name='') serialize as ABSENT, and the reference
    extractor still surfaces the default, not null
    (ProtoBufRecordExtractor getField semantics)."""
    out: Dict[str, Any] = {}
    for fd in msg.DESCRIPTOR.fields:
        if fd.label == fd.LABEL_REPEATED:
            value = getattr(msg, fd.name)
            if fd.type == fd.TYPE_MESSAGE:
                out[fd.name] = [_message_to_dict(v) for v in value]
            else:
                out[fd.name] = list(value)
        elif fd.type == fd.TYPE_MESSAGE:
            out[fd.name] = (_message_to_dict(getattr(msg, fd.name))
                            if msg.HasField(fd.name) else None)
        elif fd.type == fd.TYPE_ENUM:
            out[fd.name] = fd.enum_type.values_by_number[
                getattr(msg, fd.name)].name
        else:
            out[fd.name] = getattr(msg, fd.name)
    return out


class ProtoBufRecordReader(RecordReader):
    """Ref: ProtoBufRecordReader — config keys ``descriptorFile`` and
    ``protoClassName`` (fully-qualified message name)."""

    def init(self, data_file: str,
             fields_to_read: Optional[Sequence[str]] = None,
             config: Optional[RecordReaderConfig] = None) -> None:
        cfg = config or {}
        desc = cfg.get("descriptorFile")
        name = cfg.get("protoClassName")
        if not desc or not name:
            raise ValueError("protobuf reader needs 'descriptorFile' and "
                             "'protoClassName' in the reader config")
        self._cls = load_message_class(str(desc), str(name))
        self._path = data_file
        self._fields = list(fields_to_read) if fields_to_read else None

    def __iter__(self) -> Iterator[GenericRow]:
        from pinot_tpu.common.serde import _read_varint

        with open(self._path, "rb") as f:
            buf = f.read()
        pos = 0
        while pos < len(buf):
            try:
                size, pos = _read_varint(buf, pos)
            except IndexError:
                raise ValueError(
                    f"{self._path}: truncated length varint at byte {pos}")
            if pos + size > len(buf):
                # a short tail must be LOUD: a mid-transfer truncation that
                # lands on a field boundary would otherwise parse as a
                # valid message with trailing fields silently dropped
                raise ValueError(
                    f"{self._path}: truncated message at byte {pos} "
                    f"(need {size}, have {len(buf) - pos})")
            msg = self._cls()
            msg.ParseFromString(buf[pos:pos + size])
            pos += size
            row = _message_to_dict(msg)
            if self._fields is not None:
                row = {k: row.get(k) for k in self._fields}
            yield GenericRow(row)

    def rewind(self) -> None:
        pass  # iteration re-reads the file
