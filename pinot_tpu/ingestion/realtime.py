"""Realtime segment consumption: the consume loop + commit state machine.

Re-design of ``pinot-core/.../data/manager/realtime/LLRealtimeSegmentDataManager.java:100``:
a per-partition consumer drains ``MessageBatch``es from the stream into a
host-resident :class:`MutableSegment` (decode -> transform -> index), tracks
offsets, and on reaching the flush threshold negotiates the commit with the
controller through the segment-completion protocol
(``SegmentCompletionProtocol.java:54``): segmentConsumed -> HOLD / CATCHUP /
COMMIT -> build immutable segment -> split commit (upload file, then commit
metadata). The committed stream offset range recorded in segment metadata is
the checkpoint (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import enum
import logging
import os
import threading
import time

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from pinot_tpu.ingestion.stream import (
    StreamConsumerFactory,
    StreamMessageDecoder,
    StreamOffset,
    create_consumer_factory,
    create_decoder,
)
from pinot_tpu.common.telemetry import observe_ms
from pinot_tpu.ingestion.transformers import CompositeTransformer
from pinot_tpu.segment.metadata import SegmentMetadata
from pinot_tpu.segment.mutable import MutableSegment
from pinot_tpu.spi.data import Schema
from pinot_tpu.spi.table import TableConfig

log = logging.getLogger(__name__)


class ConsumerState(enum.Enum):
    """Ref: LLRealtimeSegmentDataManager.State:101."""

    INITIAL_CONSUMING = "INITIAL_CONSUMING"
    CATCHING_UP = "CATCHING_UP"
    HOLDING = "HOLDING"
    COMMITTING = "COMMITTING"
    COMMITTED = "COMMITTED"
    RETAINING = "RETAINING"
    DISCARDED = "DISCARDED"
    ERROR = "ERROR"


class CompletionResponse(enum.Enum):
    """Controller replies (ref: SegmentCompletionProtocol responses)."""

    HOLD = "HOLD"
    CATCHUP = "CATCHUP"
    COMMIT = "COMMIT"
    KEEP = "KEEP"
    DISCARD = "DISCARD"
    NOT_LEADER = "NOT_LEADER"


@dataclass
class CompletionReply:
    response: CompletionResponse
    # for CATCHUP: the offset to catch up to
    target_offset: Optional[StreamOffset] = None


class SegmentCompletionProtocol:
    """Client side of the controller commit FSM (ref:
    protocols/SegmentCompletionProtocol.java:54 message types)."""

    def segment_consumed(self, segment_name: str, instance: str,
                         offset: StreamOffset) -> CompletionReply:
        raise NotImplementedError

    def segment_commit_start(self, segment_name: str, instance: str,
                             offset: StreamOffset) -> CompletionReply:
        raise NotImplementedError

    def segment_commit_upload(self, segment_name: str, instance: str,
                              segment_dir: str) -> str:
        """Upload the built segment; returns the deep-store location."""
        raise NotImplementedError

    def segment_commit_end(self, segment_name: str, instance: str,
                           offset: StreamOffset, location: str,
                           metadata: SegmentMetadata) -> CompletionReply:
        raise NotImplementedError

    def segment_stopped_consuming(self, segment_name: str, instance: str,
                                  reason: str) -> None:
        pass


class LocalCompletionProtocol(SegmentCompletionProtocol):
    """Single-replica protocol: the caller always commits (standalone /
    quickstart mode — no controller FSM in the loop)."""

    def segment_consumed(self, segment_name, instance, offset):
        return CompletionReply(CompletionResponse.COMMIT)

    def segment_commit_start(self, segment_name, instance, offset):
        return CompletionReply(CompletionResponse.COMMIT)

    def segment_commit_upload(self, segment_name, instance, segment_dir):
        return segment_dir

    def segment_commit_end(self, segment_name, instance, offset, location,
                           metadata):
        return CompletionReply(CompletionResponse.COMMIT)


@dataclass
class ConsumptionResult:
    state: ConsumerState
    rows_indexed: int
    rows_dropped: int
    final_offset: StreamOffset
    segment_dir: Optional[str] = None
    metadata: Optional[SegmentMetadata] = None


class RealtimeSegmentDataManager:
    """One consuming segment of one stream partition.

    Synchronous core (``consume_until``/``run_once``) + an optional
    background thread (``start``/``stop``) mirroring the reference's
    PartitionConsumer thread (run():590).
    """

    def __init__(self, segment_name: str, table_config: TableConfig,
                 schema: Schema, partition: int,
                 start_offset: StreamOffset,
                 protocol: Optional[SegmentCompletionProtocol] = None,
                 instance_id: str = "server_0",
                 output_dir: str = "/tmp/pinot_tpu_segments",
                 consumer_factory: Optional[StreamConsumerFactory] = None,
                 on_committed: Optional[Callable[["RealtimeSegmentDataManager",
                                                  SegmentMetadata, str], None]] = None,
                 on_terminal: Optional[Callable[["RealtimeSegmentDataManager"],
                                                None]] = None):
        sc = table_config.stream_config
        if sc is None:
            raise ValueError("table has no stream config")
        self.segment_name = segment_name
        self.table_config = table_config
        self.schema = schema
        self.partition = partition
        self.instance_id = instance_id
        self.output_dir = output_dir
        self.protocol = protocol or LocalCompletionProtocol()
        self.on_committed = on_committed
        self.on_terminal = on_terminal

        factory = consumer_factory or create_consumer_factory(sc)
        self._consumer = factory.create_partition_consumer(partition)
        self._decoder: StreamMessageDecoder = create_decoder(sc.decoder)
        self._transformer = CompositeTransformer.for_table(table_config, schema)

        self.segment = MutableSegment(
            schema, segment_name,
            capacity=max(sc.segment_flush_threshold_rows, 1),
            indexing_config=table_config.indexing_config)
        self.start_offset = start_offset
        self.current_offset = start_offset  # race-ok: single_writer
        self.flush_threshold_rows = sc.segment_flush_threshold_rows
        self.flush_threshold_ms = sc.segment_flush_threshold_millis
        self._start_time_ms = int(time.time() * 1000)

        # row-level upsert hook: called as fn(row, doc_id) after a row is
        # indexed (ref: RealtimeTableDataManager addRecord wiring)
        self.upsert_hook = None
        self.state = ConsumerState.INITIAL_CONSUMING  # race-ok: single_writer
        self.rows_indexed = 0  # race-ok: single_writer
        self.rows_dropped = 0  # race-ok: single_writer
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- consume core -------------------------------------------------------
    def _index_batch(self, limit_offset: Optional[StreamOffset] = None) -> int:
        batch = self._consumer.fetch_messages(self.current_offset)
        n = 0
        for msg in batch.messages:
            if limit_offset is not None and msg.offset >= limit_offset:
                break
            row = self._decoder.decode(msg)
            if row is not None:
                row = self._transformer.transform(row)
            if row is None:
                self.rows_dropped += 1
            else:
                if not self.segment.index(row):
                    break
                self.rows_indexed += 1
                if self.upsert_hook is not None:
                    try:
                        self.upsert_hook(row, self.segment.num_docs - 1)
                    except Exception:
                        # the row IS indexed: advance past it before
                        # surfacing, or the resilient retry would replay
                        # it and double-index (exactly-once contract)
                        self.current_offset = StreamOffset(
                            msg.offset.value + 1)
                        raise
            n += 1
            self.current_offset = StreamOffset(msg.offset.value + 1)
        return n

    def _threshold_reached(self) -> bool:
        if self.rows_indexed >= self.flush_threshold_rows:
            return True
        age = int(time.time() * 1000) - self._start_time_ms
        return age >= self.flush_threshold_ms and self.rows_indexed > 0

    def run_once(self) -> ConsumerState:
        """One iteration of the consume/commit state machine
        (ref: PartitionConsumer.run():590-705)."""
        if self.state in (ConsumerState.INITIAL_CONSUMING,
                          ConsumerState.CATCHING_UP):
            limit = (self._catchup_target
                     if self.state is ConsumerState.CATCHING_UP else None)
            self._index_batch(limit)
            if self.state is ConsumerState.CATCHING_UP:
                if (self._catchup_target is not None
                        and self.current_offset >= self._catchup_target):
                    self.state = ConsumerState.HOLDING
            elif self._threshold_reached():
                self.state = ConsumerState.HOLDING

        if self.state is ConsumerState.HOLDING:
            reply = self.protocol.segment_consumed(
                self.segment_name, self.instance_id, self.current_offset)
            if reply.response is CompletionResponse.COMMIT:
                self.state = ConsumerState.COMMITTING
            elif reply.response is CompletionResponse.CATCHUP:
                self._catchup_target = reply.target_offset
                self.state = ConsumerState.CATCHING_UP
            elif reply.response is CompletionResponse.KEEP:
                self.state = ConsumerState.RETAINING
            elif reply.response is CompletionResponse.DISCARD:
                self.state = ConsumerState.DISCARDED
            # HOLD: stay HOLDING, retry next tick

        if self.state is ConsumerState.COMMITTING:
            self._commit()
        return self.state

    _catchup_target: Optional[StreamOffset] = None  # race-ok: single_writer

    def _commit(self) -> None:
        """Split commit (ref: commitSegment:939 + SplitSegmentCommitter):
        build -> upload -> metadata flip."""
        try:
            reply = self.protocol.segment_commit_start(
                self.segment_name, self.instance_id, self.current_offset)
            if reply.response is not CompletionResponse.COMMIT:
                self.state = ConsumerState.HOLDING
                return
            md, seg_dir = self.build_segment()
            location = self.protocol.segment_commit_upload(
                self.segment_name, self.instance_id, seg_dir)
            end = self.protocol.segment_commit_end(
                self.segment_name, self.instance_id, self.current_offset,
                location, md)
            if end.response is CompletionResponse.COMMIT:
                self.state = ConsumerState.COMMITTED
                self._committed_metadata = md
                self._committed_dir = seg_dir
                if self.on_committed is not None:
                    self.on_committed(self, md, seg_dir)
            else:
                self.state = ConsumerState.HOLDING
        except Exception:
            log.exception("commit failed for %s", self.segment_name)
            self.state = ConsumerState.ERROR

    _committed_metadata: Optional[SegmentMetadata] = None  # race-ok: single_writer
    _committed_dir: Optional[str] = None  # race-ok: single_writer

    def build_segment(self):
        """Ref: buildSegmentForCommit:754 — mutable -> immutable conversion.
        Stream offsets land in segment custom metadata (the checkpoint).
        Seal stamps the default star-tree set (ref: RealtimeSegmentConverter
        carrying StarTreeIndexConfigs into the converted segment) so the
        committed segment is eligible for the startree_device rung from its
        first query, and records the seal wall-time for the bench."""
        from dataclasses import replace as _dc_replace

        t0 = time.perf_counter()
        os.makedirs(self.output_dir, exist_ok=True)
        idx = self.segment.indexing
        if not idx.star_tree_index_configs and not idx.enable_default_star_tree:
            idx = _dc_replace(idx, enable_default_star_tree=True)
        md = self.segment.build_immutable(self.output_dir,
                                          indexing_config=idx)
        md.custom.update({
            "segment.realtime.startOffset": str(self.start_offset),
            "segment.realtime.endOffset": str(self.current_offset),
            "segment.realtime.partition": self.partition,
        })
        seg_dir = os.path.join(self.output_dir, self.segment_name)
        md.save(os.path.join(seg_dir, "metadata.json"))
        self.seal_wall_ms = (time.perf_counter() - t0) * 1e3
        observe_ms(self.table_config.table_name, "seal", self.seal_wall_ms)
        return md, seg_dir

    #: wall-clock of the last mutable->immutable build (bench `realtime`)
    seal_wall_ms: Optional[float] = None  # race-ok: single_writer

    #: consume-loop error streak (resets on success, trips ERROR at max)
    _consecutive_errors: int = 0  # race-ok: single_writer

    def _run_once_resilient(self) -> ConsumerState:
        """run_once with transient-failure absorption: a throwing consumer
        (network flap, broker hiccup) must not kill the consumption thread —
        offsets are only advanced after successful indexing, so retrying the
        same fetch is exactly-once safe (ref: the transient vs permanent
        consumer-exception split in LLRealtimeSegmentDataManager;
        FlakyConsumerRealtimeClusterIntegrationTest is the contract)."""
        try:
            st = self.run_once()
            self._consecutive_errors = 0
            return st
        except Exception:
            self._consecutive_errors = getattr(
                self, "_consecutive_errors", 0) + 1
            log.exception("[%s] consume iteration failed (attempt %d)",
                          self.segment_name, self._consecutive_errors)
            if self._consecutive_errors >= self.MAX_CONSUME_ERRORS:
                self.state = ConsumerState.ERROR
            return self.state

    MAX_CONSUME_ERRORS = 100

    # -- synchronous drive (tests, quickstart) ------------------------------
    def consume_until_committed(self, max_iters: int = 10_000) -> ConsumptionResult:
        for _ in range(max_iters):
            st = self._run_once_resilient()
            if st in (ConsumerState.COMMITTED, ConsumerState.RETAINING,
                      ConsumerState.DISCARDED, ConsumerState.ERROR):
                break
            err = getattr(self, "_consecutive_errors", 0)
            if err > 0:
                # linear backoff (capped): the sync driver must not burn
                # the whole error budget inside a sub-second outage
                time.sleep(min(0.01 * err, 0.1))
        return ConsumptionResult(
            self.state, self.rows_indexed, self.rows_dropped,
            self.current_offset, self._committed_dir, self._committed_metadata)

    # -- background thread (server runtime) ---------------------------------
    def start(self, tick_seconds: float = 0.05) -> None:
        def loop():
            while not self._stop.is_set():
                st = self._run_once_resilient()
                if st in (ConsumerState.COMMITTED, ConsumerState.RETAINING,
                          ConsumerState.DISCARDED, ConsumerState.ERROR):
                    break
                err = getattr(self, "_consecutive_errors", 0)
                if err > 0:
                    # exponential backoff capped at 5s: 100 consecutive
                    # errors span ~8 minutes, so an outage shorter than
                    # that resumes instead of flipping to ERROR
                    self._stop.wait(min(tick_seconds * (2 ** min(err, 10)),
                                        5.0))
                elif st is ConsumerState.HOLDING:
                    self._stop.wait(tick_seconds)
                elif not self._has_new_data():
                    self._stop.wait(tick_seconds)
            self._consumer.close()
            if self.on_terminal is not None and not self._stop.is_set():
                try:
                    self.on_terminal(self)
                except Exception:
                    log.exception("on_terminal failed for %s",
                                  self.segment_name)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"consumer-{self.segment_name}")
        self._thread.start()

    def _has_new_data(self) -> bool:
        try:
            return self._peek_new_data()
        except Exception:
            return False  # transient fetch failure: back off, retry later

    def _peek_new_data(self) -> bool:
        batch = self._consumer.fetch_messages(self.current_offset,
                                              max_messages=1)
        return batch.message_count > 0

    def stop(self, reason: str = "shutdown") -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self.state not in (ConsumerState.COMMITTED,
                              ConsumerState.DISCARDED):
            self.protocol.segment_stopped_consuming(
                self.segment_name, self.instance_id, reason)
