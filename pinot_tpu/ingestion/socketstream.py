"""Socket-transport stream plugin: a kafka-shaped partitioned log over HTTP.

The VERDICT r3 gap "nothing kafka-shaped over a real transport": this
module is the pinot-kafka-2.0 analogue (ref:
``pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0/
KafkaPartitionLevelConsumer.java`` + ``KafkaStreamMetadataProvider.java``)
built on a standalone broker process reachable over real sockets:

- :class:`StreamBrokerServer` — the embedded-Kafka-broker analogue
  (ref: KafkaStarterUtils / StreamDataServerStartable): an HTTP server
  holding partitioned append-only logs; producers POST records, consumers
  GET offset-addressed fetches. Runs in any process; consumers only need
  its URL.
- :class:`SocketStreamConsumerFactory` — the stream-SPI plugin
  (``stream.type = "socket"``): partition discovery + earliest/latest
  offsets via the metadata endpoint, offset-addressed batch fetch with
  resume — the exact consume/checkpoint contract the realtime FSM drives.

Table config:
    streamType: socket
    topic: <topic>
    properties: {"stream.socket.broker.url": "http://host:port"}
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from pinot_tpu.ingestion.stream import (
    MessageBatch,
    PartitionLevelConsumer,
    StreamConsumerFactory,
    StreamMessage,
    StreamMetadataProvider,
    StreamOffset,
    register_stream_type,
)
from pinot_tpu.spi.table import StreamIngestionConfig

BROKER_URL_PROP = "stream.socket.broker.url"


# --------------------------------------------------------------------------
# broker server
# --------------------------------------------------------------------------

class _Topic:
    def __init__(self, num_partitions: int):
        self.partitions: List[List[Dict[str, Any]]] = [
            [] for _ in range(num_partitions)]
        self.lock = threading.Lock()


class StreamBrokerServer:
    """Standalone partitioned-log broker over HTTP (real sockets)."""

    def __init__(self, port: int = 0):
        self._topics: Dict[str, _Topic] = {}
        self._lock = threading.Lock()
        broker = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _json(self, code: int, payload) -> None:
                raw = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n).decode()) if n else {}

            def do_POST(self):
                try:
                    parts = self.path.strip("/").split("/")
                    if len(parts) == 2 and parts[0] == "topics":
                        body = self._body()
                        broker.create_topic(
                            parts[1], int(body.get("numPartitions", 1)))
                        self._json(200, {"status": "created"})
                    elif (len(parts) == 3 and parts[0] == "topics"
                          and parts[2] == "produce"):
                        body = self._body()
                        off = broker.produce(
                            parts[1], int(body.get("partition", 0)),
                            body["records"])
                        self._json(200, {"nextOffset": off})
                    else:
                        self._json(404, {"error": "no such endpoint"})
                except KeyError as e:
                    self._json(404, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    self._json(500, {"error": str(e)[:200]})

            def do_GET(self):
                try:
                    url = urllib.parse.urlparse(self.path)
                    parts = url.path.strip("/").split("/")
                    q = urllib.parse.parse_qs(url.query)
                    if (len(parts) == 3 and parts[0] == "topics"
                            and parts[2] == "metadata"):
                        self._json(200, broker.metadata(parts[1]))
                    elif (len(parts) == 3 and parts[0] == "topics"
                          and parts[2] == "fetch"):
                        self._json(200, broker.fetch(
                            parts[1], int(q["partition"][0]),
                            int(q["offset"][0]),
                            int(q.get("max", ["5000"])[0])))
                    else:
                        self._json(404, {"error": "no such endpoint"})
                except KeyError as e:
                    self._json(404, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    self._json(500, {"error": str(e)[:200]})

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_port
        self.url = f"http://localhost:{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- broker ops ----------------------------------------------------------
    def create_topic(self, topic: str, num_partitions: int = 1) -> None:
        """Create — or EXPAND — a topic. Re-creating with a larger count
        appends empty partitions (kafka alter-topic semantics: partition
        counts only grow; existing partitions and offsets are untouched,
        which is what lets consumers survive the expansion)."""
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                self._topics[topic] = _Topic(num_partitions)
                return
            with t.lock:
                while len(t.partitions) < num_partitions:
                    t.partitions.append([])

    def _topic(self, topic: str) -> _Topic:
        t = self._topics.get(topic)
        if t is None:
            raise KeyError(f"no such topic {topic!r}")
        return t

    def produce(self, topic: str, partition: int,
                records: List[Any]) -> int:
        t = self._topic(topic)
        with t.lock:
            log = t.partitions[partition]
            import time

            now = int(time.time() * 1000)
            for r in records:
                log.append({"payload": r, "ts": now})
            return len(log)

    def metadata(self, topic: str) -> Dict[str, Any]:
        t = self._topic(topic)
        with t.lock:
            return {"numPartitions": len(t.partitions),
                    "earliest": [0] * len(t.partitions),
                    "latest": [len(p) for p in t.partitions]}

    def fetch(self, topic: str, partition: int, offset: int,
              max_messages: int) -> Dict[str, Any]:
        t = self._topic(topic)
        with t.lock:
            log = t.partitions[partition]
            chunk = log[offset:offset + max_messages]
            return {"messages": [
                {"payload": m["payload"], "offset": offset + i,
                 "ts": m["ts"]} for i, m in enumerate(chunk)],
                "nextOffset": offset + len(chunk)}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "StreamBrokerServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="stream-broker")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


# --------------------------------------------------------------------------
# client plugin (the stream SPI implementation)
# --------------------------------------------------------------------------

def _get_json(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def produce(broker_url: str, topic: str, records: List[Any],
            partition: int = 0, timeout: float = 10.0) -> int:
    """Producer-side helper (tests/quickstarts publish through this)."""
    body = json.dumps({"partition": partition,
                       "records": records}).encode()
    req = urllib.request.Request(
        f"{broker_url}/topics/{topic}/produce", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())["nextOffset"]


def create_topic(broker_url: str, topic: str, num_partitions: int = 1,
                 timeout: float = 10.0) -> None:
    body = json.dumps({"numPartitions": num_partitions}).encode()
    req = urllib.request.Request(
        f"{broker_url}/topics/{topic}", data=body,
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=timeout).read()


class SocketPartitionConsumer(PartitionLevelConsumer):
    """Ref: KafkaPartitionLevelConsumer.fetchMessages — offset-addressed
    fetch over the wire; resuming from a committed offset is just fetching
    from it."""

    def __init__(self, broker_url: str, topic: str, partition: int):
        self._base = (f"{broker_url}/topics/{topic}/fetch"
                      f"?partition={partition}")

    def fetch_messages(self, start: StreamOffset,
                       max_messages: int = 5000,
                       timeout_ms: int = 5000) -> MessageBatch:
        d = _get_json(f"{self._base}&offset={start.value}"
                      f"&max={max_messages}",
                      timeout=max(timeout_ms / 1000.0, 0.5))
        msgs = [StreamMessage(payload=m["payload"],
                              offset=StreamOffset(int(m["offset"])),
                              timestamp_ms=int(m.get("ts", 0)))
                for m in d["messages"]]
        return MessageBatch(msgs, StreamOffset(int(d["nextOffset"])))


class SocketStreamMetadataProvider(StreamMetadataProvider):
    """Ref: KafkaStreamMetadataProvider — partition discovery + offsets."""

    def __init__(self, broker_url: str, topic: str):
        self._url = f"{broker_url}/topics/{topic}/metadata"

    def _meta(self) -> Dict[str, Any]:
        return _get_json(self._url)

    def partition_count(self) -> int:
        return int(self._meta()["numPartitions"])

    def earliest_offset(self, partition: int) -> StreamOffset:
        return StreamOffset(int(self._meta()["earliest"][partition]))

    def latest_offset(self, partition: int) -> StreamOffset:
        return StreamOffset(int(self._meta()["latest"][partition]))


class SocketStreamConsumerFactory(StreamConsumerFactory):
    """``stream.type = "socket"`` (ref: KafkaConsumerFactory)."""

    def __init__(self, config: StreamIngestionConfig):
        super().__init__(config)
        url = config.properties.get(BROKER_URL_PROP)
        if not url:
            raise ValueError(
                f"socket stream needs {BROKER_URL_PROP!r} in properties")
        self._url = url.rstrip("/")

    def create_partition_consumer(self, partition: int) -> SocketPartitionConsumer:
        return SocketPartitionConsumer(self._url, self.config.topic,
                                       partition)

    def create_metadata_provider(self) -> SocketStreamMetadataProvider:
        return SocketStreamMetadataProvider(self._url, self.config.topic)


register_stream_type("socket", SocketStreamConsumerFactory)
